"""Fleet KV page tier tests — the fast in-process zone.

Layers (spawn-heavy cross-process proofs live in test_kvpool_proc.py):

- units: the pack_arrays/unpack_arrays binary ndarray codec (bit-exact
  across dtypes, 0-d scalars, non-contiguous input, empty arrays) and
  the page-chain codec over real prefill pages, f32 AND int8+rank-4-
  scale layouts;
- the pool service: push/fetch/NACK/partial-chain over a real socket,
  counters, client-side push dedup, dead-pool degradation;
- staleness hardening (ISSUE 16 satellite): a store eviction surfaces
  through drain_evicted_hashes and SharedPrefixIndex.forget drops the
  stranded claim, counting pages_stale — the regression for hints
  silently outliving worker-side eviction;
- the loop tier: two in-process ServingLoops sharing one pool — cold
  serve on A, pool-transferred serve on B bit-equal to the oracle; and
  the armed-but-idle guard (zero new jit traces, <5% host overhead per
  decode round);
- export: kvpool occupancy/capacity gauges merge by MAX while counters
  SUM, and per-replica kvstore occupancies still SUM.
"""

import numpy as np
import pytest

import jax

from rocket_tpu.models.generate import ContinuousBatcher, _spec_round
from rocket_tpu.serve import Completed, Request, ServingLoop
from rocket_tpu.serve.kvpool import (
    KVPagePool,
    KVPoolClient,
    decode_page_chain,
    encode_page_chain,
    register_kvpool_source,
)
from rocket_tpu.serve.kvstore import (
    PrefixKVStore,
    SharedPrefixIndex,
    page_hashes,
)
from rocket_tpu.utils.framing import pack_arrays, unpack_arrays

pytestmark = [pytest.mark.kvpool, pytest.mark.serving]

B, P, TOTAL, NDRAFT, PAGE = 3, 12, 24, 4, 4


def _lm(seed=1, **kw):
    from rocket_tpu.models.transformer import (TransformerConfig,
                                               TransformerLM)
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64, **kw
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


def _models(int8=False):
    kw = {"kv_cache_int8": True} if int8 else {}
    model, params = _lm(seed=1, **kw)
    draft, _ = _lm(seed=1, **kw)
    _, dparams = _lm(seed=7, **kw)
    return model, draft, params, dparams


def _bat(models, **kw):
    model, draft, params, dparams = models
    return ContinuousBatcher(model, draft, params, dparams,
                             total_len=TOTAL, n_draft=NDRAFT,
                             eos_token=None, **kw)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(8, P)).astype(np.int32)


def _chain(models, prompt):
    """(hashes, pages) for one prompt's prefilled full pages — hashed
    over the handoff buffer (prompt + first emitted token), the same
    rule as PrefixKVStore.insert."""
    host = _bat(models).prefill_handoff(prompt[None, :]).to_host()
    pages = host.split_pages(PAGE)
    hashes = page_hashes(
        np.asarray(host.buf)[0], PAGE,
        limit=int(np.asarray(host.n_tok)[0]) - 1,
    )[:len(pages)]
    return hashes, pages


# -- units: the binary ndarray codec -------------------------------------


class TestPackArrays:
    def test_round_trip_bit_exact_across_dtypes(self):
        rng = np.random.default_rng(0)
        arrays = [
            rng.standard_normal((2, 3, 4, 5)).astype(np.float32),
            (rng.standard_normal((1, 8, 4, 1)) * 10).astype(np.int8),
            rng.standard_normal((1, 8, 4, 1)).astype(np.float32),  # scales
            np.asarray(17, np.int32),                 # 0-d cache_index
            np.arange(6, dtype=np.int64),
            np.array([], dtype=np.float16),
            np.array([[True, False], [False, True]]),
        ]
        out = unpack_arrays(pack_arrays(arrays))
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert a.shape == b.shape and a.dtype == b.dtype
            assert np.array_equal(a, b)
            assert b.tobytes() == a.tobytes()  # bit-exact, NaN-safe

    def test_non_contiguous_input_and_owned_output(self):
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        view = base[:, ::2]                  # non-contiguous
        (out,) = unpack_arrays(pack_arrays([view]))
        assert np.array_equal(out, view)
        # default decode COPIES: the page must not pin the frame alive,
        # and consumers may mutate it
        out[0, 0] = -1.0                     # writable => owned

    def test_no_per_array_pickle_overhead(self):
        # the whole point: payload section is the raw buffer bytes, so
        # blob size is header + exactly sum(nbytes)
        arrays = [np.zeros((64, 64), np.float32), np.zeros(7, np.int8)]
        blob = pack_arrays(arrays)
        payload = sum(a.nbytes for a in arrays)
        assert payload <= len(blob) <= payload + 128


# -- units: the page-chain codec -----------------------------------------


class TestPageChainCodec:
    @pytest.mark.parametrize("int8", [False, True])
    def test_round_trip_bit_exact(self, prompts, int8):
        hashes, pages = _chain(_models(int8), prompts[0])
        assert len(pages) >= 2
        blob = encode_page_chain(hashes, pages)
        h2, p2 = decode_page_chain(blob)
        assert h2 == hashes and len(p2) == len(pages)
        for a, b in zip(pages, p2):
            la = jax.tree_util.tree_leaves((a.tokens, a.cache_t, a.cache_d))
            lb = jax.tree_util.tree_leaves((b.tokens, b.cache_t, b.cache_d))
            for x, y in zip(la, lb):
                x, y = np.asarray(x), np.asarray(y)
                assert x.shape == y.shape and x.dtype == y.dtype
                assert np.array_equal(x, y)
        if int8:
            leaves = [np.asarray(leaf) for p in p2 for leaf in
                      jax.tree_util.tree_leaves((p.cache_t, p.cache_d))]
            assert any(a.ndim == 4 and a.dtype == np.int8 for a in leaves)
            # int8 payload travels with its rank-4 f32 scale leaves
            assert any(a.ndim == 4 and a.dtype == np.float32
                       for a in leaves)

    def test_int8_wire_is_smaller(self, prompts):
        _, pages_f32 = _chain(_models(False), prompts[0])
        h8, pages_i8 = _chain(_models(True), prompts[0])
        f32 = len(encode_page_chain([b"x"] * len(pages_f32), pages_f32))
        i8 = len(encode_page_chain(h8, pages_i8))
        assert i8 < f32 * 0.6  # ~2.7x smaller at real layer shapes

    def test_length_mismatch_raises(self, prompts):
        hashes, pages = _chain(_models(), prompts[0])
        with pytest.raises(ValueError):
            encode_page_chain(hashes[:-1], pages)


# -- the pool service ----------------------------------------------------


class TestKVPagePool:
    def test_push_fetch_partial_nack_and_counters(self, prompts):
        models = _models()
        hashes, pages = _chain(models, prompts[0])
        pool = KVPagePool(page_tokens=PAGE, capacity_bytes=1 << 22)
        try:
            cli = KVPoolClient.connect(pool.address)
            assert cli.push(hashes, pages) == len(pages)
            # client-side dedup: an identical chain never re-crosses
            assert cli.push(hashes, pages) == 0
            assert pool.snapshot()["pushes"] == 1.0

            got = cli.fetch(hashes)
            assert got is not None and len(got) == len(pages)
            assert np.array_equal(
                np.asarray(got[0].tokens), np.asarray(pages[0].tokens))
            # a longer chain fetches its stored prefix (partial hit)
            part = cli.fetch(list(hashes) + [b"\x00" * 16])
            assert part is not None and len(part) == len(pages)
            # total miss => NACK => None, and the pool counts it
            assert cli.fetch([b"\x01" * 16]) is None
            snap = pool.snapshot()
            assert snap["fetch_hits"] == 2.0 and snap["nacks"] == 1.0
            assert snap["bytes_in"] > 0 and snap["bytes_out"] > 0
            assert snap["bytes_moved"] == snap["bytes_in"] \
                + snap["bytes_out"]
            assert snap["pages"] == float(len(pages))
            csnap = cli.snapshot()
            assert csnap["hits"] == 2.0 and csnap["nacks"] == 1.0
            assert csnap["bytes_moved"] > 0
            cli.close()
        finally:
            pool.close()

    def test_nack_clears_push_dedup(self, prompts):
        # pool-side eviction means "pushed before" no longer implies
        # "present": after any NACK the client must re-push on request
        hashes, pages = _chain(_models(), prompts[0])
        pool = KVPagePool(page_tokens=PAGE, capacity_bytes=1 << 22)
        try:
            cli = KVPoolClient.connect(pool.address)
            assert cli.push(hashes, pages) == len(pages)
            assert cli.fetch([b"\x02" * 16]) is None  # NACK
            pool._store._table.clear()                # simulate eviction
            pool._store.occupancy_bytes = 0
            assert cli.push(hashes, pages) == len(pages)  # re-pushed
            cli.close()
        finally:
            pool.close()

    def test_dead_pool_degrades_not_raises(self, prompts):
        hashes, pages = _chain(_models(), prompts[0])
        pool = KVPagePool(page_tokens=PAGE)
        cli = KVPoolClient.connect(pool.address, timeout=2.0)
        pool.close()
        # first call eats the socket error, marks dead; later calls
        # short-circuit — never an exception on the serving path
        assert cli.fetch(hashes) is None
        assert cli.push(hashes, pages) == 0
        assert cli.fetch(hashes) is None
        cli.close()

    def test_match_hashes_same_discipline_as_lookup(self, prompts):
        models = _models()
        hashes, pages = _chain(models, prompts[0])
        store = PrefixKVStore(page_tokens=PAGE)
        store.put_pages(hashes, pages)
        m = store.match_hashes(list(hashes))
        assert m is not None and m.hashes == list(hashes)
        # matched entries are pinned until release — same as lookup
        assert all(store._table[h].pins == 1 for h in hashes)
        store.release(m)
        assert all(store._table[h].pins == 0 for h in hashes)
        m2 = store.match_hashes([b"\x03" * 16])
        assert m2 is None and store.misses == 1


# -- staleness hardening (satellite) -------------------------------------


class TestStalenessFeedback:
    def test_eviction_surfaces_through_drain(self, prompts):
        models = _models()
        ha, pa = _chain(models, prompts[0])
        hb, pb = _chain(models, prompts[1])
        # capacity for one chain only: storing B must evict A's pages
        # (same-chain puts cannot self-evict — own-chain pinning)
        cap = int(sum(p.nbytes for p in pa))
        store = PrefixKVStore(page_tokens=PAGE, capacity_bytes=cap)
        store.put_pages(ha, pa)
        assert store.drain_evicted_hashes() == []
        store.put_pages(hb, pb)
        assert store.evictions > 0
        evicted = store.drain_evicted_hashes()
        assert evicted and set(evicted) <= set(ha)
        assert store.drain_evicted_hashes() == []  # return-and-clear

    def test_forget_degrades_hint_and_counts_stale(self, prompts):
        """Regression: a worker-side eviction must NOT strand the
        supervisor-side hint — forget() drops the claim so best_replica
        degrades to None (=> cold prefill), counting pages_stale."""
        idx = SharedPrefixIndex(page_tokens=PAGE)
        toks = prompts[0]
        hashes = page_hashes(toks, PAGE, limit=toks.shape[0] - 1)
        idx.note("r0", hashes)
        assert idx.best_replica(toks) == "r0"
        # the replica evicts the chain root; its STEP ships the delta
        dropped = idx.forget("r0", [hashes[0]])
        assert dropped == 1 and idx.pages_stale == 1
        assert idx.best_replica(toks) is None  # hint gone, not an error
        assert idx.snapshot()["pages_stale"] == 1.0

    def test_forget_is_per_replica(self, prompts):
        idx = SharedPrefixIndex(page_tokens=PAGE)
        toks = prompts[0]
        hashes = page_hashes(toks, PAGE, limit=toks.shape[0] - 1)
        idx.note("r0", hashes)
        idx.note("r1", hashes)
        idx.forget("r0", hashes)
        assert idx.best_replica(toks) == "r1"  # other replica unaffected
        # forgetting unknown claims is a no-op, not an error
        assert idx.forget("r0", hashes) == 0


# -- the loop tier: cross-loop transfer + armed-but-idle guard -----------


def _tiny_loop(**kw):
    from rocket_tpu.testing.workers import build_tiny_loop
    return build_tiny_loop(**kw)


class TestLoopPoolTier:
    def test_two_loops_share_pages_bit_equal(self):
        from rocket_tpu.testing.workers import P as WP
        rng = np.random.default_rng(42)
        prompt = rng.integers(1, 60, size=WP).astype(np.int32)

        oracle = _tiny_loop()
        oracle.submit(Request(rid="o", prompt=prompt))
        ref = {r.rid: r for r in oracle.run_until_idle()}["o"]
        oracle.close()
        assert isinstance(ref, Completed)

        pool = KVPagePool(page_tokens=3, capacity_bytes=1 << 22)
        try:
            a = _tiny_loop(kvstore_page_tokens=3, kvpool_addr=pool.address)
            b = _tiny_loop(kvstore_page_tokens=3, kvpool_addr=pool.address)
            a.submit(Request(rid="a", prompt=prompt))
            ra = {r.rid: r for r in a.run_until_idle()}["a"]
            assert np.array_equal(ra.tokens, ref.tokens)   # cold == oracle
            assert pool.snapshot()["pages_pushed"] > 0     # retire pushed

            b.submit(Request(rid="b", prompt=prompt))
            rb = {r.rid: r for r in b.run_until_idle()}["b"]
            # B never prefilled this prompt: pages came through the pool
            assert np.array_equal(rb.tokens, ref.tokens)
            assert b.counters.pool_hits >= 1
            assert b.counters.pool_hit_tokens > 0
            assert pool.snapshot()["bytes_out"] > 0
            a.close()
            b.close()
        finally:
            pool.close()

    def test_pool_miss_degrades_to_cold_prefill(self):
        from rocket_tpu.testing.workers import P as WP
        rng = np.random.default_rng(7)
        prompt = rng.integers(1, 60, size=WP).astype(np.int32)
        pool = KVPagePool(page_tokens=3)
        try:
            loop = _tiny_loop(kvstore_page_tokens=3,
                              kvpool_addr=pool.address)
            loop.submit(Request(rid="x", prompt=prompt))
            res = {r.rid: r for r in loop.run_until_idle()}["x"]
            assert isinstance(res, Completed)     # NACK => cold, no error
            assert loop.counters.pool_nacks >= 1
            assert loop.counters.pool_hits == 0
            loop.close()
        finally:
            pool.close()

    def test_kvpool_requires_kvstore(self):
        with pytest.raises(ValueError):
            ServingLoop(lambda: None, max_batch=1, kvpool=object())

    def test_armed_but_idle_zero_traces_and_low_overhead(self):
        import time as _time
        from rocket_tpu.testing.workers import B as WB, P as WP
        rng = np.random.default_rng(3)
        prompts8 = rng.integers(1, 60, size=(WB, WP)).astype(np.int32)
        rounds = 8

        def round_times(loop):
            for i in range(WB):
                loop.submit(Request(rid=i, prompt=prompts8[i]))
            loop.run_round()  # admits + settles
            out = []
            for _ in range(rounds):
                t0 = _time.perf_counter()
                loop.run_round()
                out.append(_time.perf_counter() - t0)
            loop.run_until_idle()
            return out

        bare_loop = _tiny_loop(kvstore_page_tokens=3)
        bare = round_times(bare_loop)
        bare_loop.close()

        pool = KVPagePool(page_tokens=3)
        try:
            traces_before = _spec_round._cache_size()
            armed_loop = _tiny_loop(kvstore_page_tokens=3,
                                    kvpool_addr=pool.address)
            armed = round_times(armed_loop)
            # the pool added ZERO traced step bodies
            assert _spec_round._cache_size() == traces_before
            armed_loop.close()
        finally:
            pool.close()
        b = float(np.median(bare))
        w = float(np.median(armed))
        # <5% relative plus an absolute floor for scheduler noise on
        # tiny CPU rounds — the pool client is untouched mid-decode
        assert w <= b * 1.05 + 5e-4, (
            f"pool-armed round {w * 1e3:.3f}ms vs bare {b * 1e3:.3f}ms")


# -- export / merge semantics --------------------------------------------


class TestKVPoolExport:
    def test_register_source_and_prometheus_names(self, prompts):
        from rocket_tpu.observe.export import collect, unregister_source
        from rocket_tpu.observe.export import prometheus_text
        hashes, pages = _chain(_models(), prompts[0])
        pool = KVPagePool(page_tokens=PAGE)
        try:
            name = register_kvpool_source(pool)
            cli = KVPoolClient.connect(pool.address)
            cli.push(hashes, pages)
            snap = collect()
            assert snap["serve_kvpool/pushes"] == 1.0
            assert snap["serve_kvpool/occupancy_bytes"] > 0
            text = prometheus_text({k: v for k, v in snap.items()
                                    if k.startswith("serve_kvpool/")})
            assert "rocket_tpu_serve_kvpool_bytes_moved" in text
            cli.close()
        finally:
            unregister_source("serve_kvpool")
            pool.close()

    def test_merge_pool_gauges_max_counters_sum(self):
        from rocket_tpu.observe.export import merge_counters
        a = {"serve_kvpool/fetches": 3.0,
             "serve_kvpool/occupancy_bytes": 100.0,
             "serve_kvpool/capacity_bytes": 1000.0,
             "serve_kvstore/occupancy_bytes": 40.0}
        b = {"serve_kvpool/fetches": 2.0,
             "serve_kvpool/occupancy_bytes": 70.0,
             "serve_kvpool/capacity_bytes": 1000.0,
             "serve_kvstore/occupancy_bytes": 60.0}
        m = merge_counters([a, b])
        assert m["serve_kvpool/fetches"] == 5.0            # counter: SUM
        assert m["serve_kvpool/occupancy_bytes"] == 100.0  # gauge: MAX
        assert m["serve_kvpool/capacity_bytes"] == 1000.0  # one pool
        # per-replica kvstore occupancies are DISTINCT stores: still SUM
        assert m["serve_kvstore/occupancy_bytes"] == 100.0
