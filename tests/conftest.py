"""Test configuration: force an 8-fake-device CPU backend.

SURVEY §4: multi-device behavior is tested without a cluster via
``--xla_force_host_platform_device_count=8`` — the TPU-world equivalent of a
fake backend.  Must run before the first ``import jax`` in any test module.
"""

import os
import tempfile

# Neutralize the axon TPU tunnel for tests: sitecustomize imports jax at
# interpreter start, so plain env vars are too late — but backend selection
# is lazy until the first jax.devices(), so switching the platform via
# jax.config still works here.
os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic warm-start tier: any test arming the persistent compile cache
# without an explicit dir must land in a fresh per-session tmp dir, never
# the repo's shared experiments/compile_cache/ — a populated shared cache
# changes what LATER sessions' compiles return (a cache-retrieved
# executable reports alias_size_in_bytes=0 in memory_analysis(), breaking
# the donation guards in test_ladder_shapes.py) and would make tier-1
# results depend on who ran before.  Tests that probe dir resolution
# override this env var themselves.
os.environ.setdefault(
    "ROCKET_TPU_COMPILE_CACHE",
    tempfile.mkdtemp(prefix="rocket_tpu_test_compile_cache_"))
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (multi-process rendezvous)"
    )
    config.addinivalue_line(
        "markers",
        "resilience: fault-tolerance / chaos tests (see docs/reliability.md; "
        "long sweeps run with -m 'slow and resilience')",
    )
    config.addinivalue_line(
        "markers",
        "serving: serving-robustness tests (rocket_tpu.serve — deadlines, "
        "backpressure, watchdog recovery; see docs/reliability.md)",
    )
    config.addinivalue_line(
        "markers",
        "tracing: structured-tracing / flight-recorder tests "
        "(rocket_tpu.observe.trace|recorder; see docs/observability.md)",
    )
    config.addinivalue_line(
        "markers",
        "fleet: multi-replica serving fleet tests (rocket_tpu.serve "
        "router/fleet — routing, lane handoff, replica self-healing; "
        "see docs/reliability.md; the thousand-request trace is slow)",
    )
    config.addinivalue_line(
        "markers",
        "elastic: elastic-restore / preemption-persistence tests "
        "(mesh-stamped manifests, reshard-on-restore, emergency tier; "
        "see docs/reliability.md)",
    )
    config.addinivalue_line(
        "markers",
        "goodput: goodput-ledger / retrace-sentinel / metrics-export tests "
        "(rocket_tpu.observe.ledger|export; see docs/observability.md "
        "\"Goodput & metrics export\")",
    )
    config.addinivalue_line(
        "markers",
        "kvcache: prefix-cache tier tests (rocket_tpu.serve.kvstore — "
        "page hashing, LRU eviction, cached-prefix bit-equality, session "
        "affinity; see docs/performance.md \"Prefix cache\")",
    )
    config.addinivalue_line(
        "markers",
        "procfleet: process-backed fleet tests (rocket_tpu.serve "
        "procfleet/wire/worker/autoscale — wire protocol, worker "
        "subprocess, kill -9 salvage, goodput-driven autoscaling; see "
        "docs/reliability.md \"Process fleet & autoscaling\"; the "
        "full kill-mid-burst and autoscale bursts are slow)",
    )
    config.addinivalue_line(
        "markers",
        "kvpool: fleet KV page-tier tests (rocket_tpu.serve.kvpool — "
        "binary page codec, pool push/fetch/NACK, cross-process page "
        "transfer, disaggregated prefill; see docs/performance.md "
        "\"Fleet KV tier\"; spawn-heavy cases live in "
        "tests/test_kvpool_proc.py on the heavy tail)",
    )
    config.addinivalue_line(
        "markers",
        "trainserve: train-while-serve tests (rocket_tpu.persist.publish "
        "/ rocket_tpu.serve feed|loop swap path — verified publication, "
        "live hot-swap, rejected torn publish, bounded rollback, "
        "kill-mid-swap heal; see docs/reliability.md \"Live weight "
        "updates\"; spawn-heavy acceptance cases live on the heavy tail)",
    )
    config.addinivalue_line(
        "markers",
        "tenants: multi-tenant serving tests (rocket_tpu.serve "
        "queue/loop/loadgen — SLO classes, weighted-fair admission, "
        "batch preemption with bit-equal resume, trace-replay harness; "
        "see docs/reliability.md \"Multi-tenant serving\"; spawn-heavy "
        "cases live in tests/test_tenants_proc.py on the heavy tail)",
    )
    config.addinivalue_line(
        "markers",
        "warmstart: warm-start tier tests (rocket_tpu.tune "
        "compile_cache/warmup — persistent compile cache, AOT "
        "executable reuse, pre-warmed/standby spawns; see "
        "docs/performance.md \"Warm start & compile cache\"; "
        "spawn-heavy cases ride the heavy tail of collection ordering)",
    )


# Fast-first ordering: the handful of files below carry the long
# compile-heavy tails (full-model forwards, pipeline schedules, real
# subprocess probes).  Running them LAST means the budgeted tier-1
# sweep fails fast on the broad cheap coverage, and on a slow shared
# host a timeout truncates into the heavy tail instead of silently
# dropping whole subsystems.  Stable sort — relative order inside each
# group is unchanged, and an untimed run still executes everything.
_HEAVY_TAIL = (
    "test_models.py",
    "test_pipeline_parallel.py",
    "test_checkpoint.py",
    "test_tune.py",
    "test_multi_optimizer.py",
    "test_ladder_shapes.py",
    "test_mpmd.py",
    "test_procfleet.py",
    "test_kvpool_proc.py",
    "test_trainserve.py",
    "test_tenants_proc.py",
    "test_tracing_proc.py",
    "test_zero_offload.py",
)


# The newest spawn-heavy file runs LAST of all: when the timed tier-1
# budget truncates, the cut lands on the newest coverage first and the
# long-standing seed suite still runs to completion.
_TAIL_END = ("test_trainserve.py", "test_tenants_proc.py",
             "test_tracing_proc.py", "test_zero_offload.py")


def pytest_collection_modifyitems(config, items):
    # warmstart-marked items spawn worker subprocesses — heavy-tail them
    # alongside the listed files so tier-1 truncation behavior holds.
    def tier(item):
        name = item.fspath.basename
        if name in _TAIL_END:
            # _TAIL_END is newest-last: truncation cuts newest coverage
            # first regardless of alphabetical collection order.
            return 2 + _TAIL_END.index(name)
        if name in _HEAVY_TAIL or item.get_closest_marker("warmstart"):
            return 1
        return 0

    items.sort(key=tier)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake cpu devices, got {devs}"
    return devs
