"""Unit tests for the capsule protocol (blackboard, events, priority, LIFO)."""

import numpy as np
import pytest

from rocket_tpu.core import Attributes, Capsule, Dispatcher, Events
from rocket_tpu.parallel import MeshSpec
from rocket_tpu.runtime import Runtime


class Recorder(Capsule):
    def __init__(self, log, name, **kwargs):
        super().__init__(**kwargs)
        self._log = log
        self._name = name

    def setup(self, attrs=None):
        super().setup(attrs)
        self._log.append(("setup", self._name))

    def launch(self, attrs=None):
        self._log.append(("launch", self._name))

    def destroy(self, attrs=None):
        self._log.append(("destroy", self._name))
        super().destroy(attrs)


class TestAttributes:
    def test_missing_key_reads_none(self):
        attrs = Attributes()
        assert attrs.anything is None

    def test_dot_write_read_delete(self):
        attrs = Attributes()
        attrs.batch = 42
        assert attrs["batch"] == 42 and attrs.batch == 42
        del attrs.batch
        assert attrs.batch is None

    def test_nested_dict_promotion(self):
        attrs = Attributes(looper={"state": {"loss": 1.0}})
        assert isinstance(attrs.looper.state, Attributes)
        assert attrs.looper.state.loss == 1.0
        attrs.tracker = {"scalars": []}
        assert attrs.tracker.scalars == []

    def test_is_pytree(self):
        import jax

        attrs = Attributes(a=np.ones(3), b={"c": np.zeros(2)})
        doubled = jax.tree_util.tree_map(lambda x: x * 2, attrs)
        assert isinstance(doubled, Attributes)
        assert float(doubled.a[0]) == 2.0
        assert isinstance(doubled.b, Attributes)


class TestDispatchOrdering:
    def test_priority_descending_and_destroy_reversed(self):
        log = []
        rt = Runtime()
        caps = [
            Recorder(log, "low", priority=100),
            Recorder(log, "high", priority=1100),
            Recorder(log, "mid", priority=1000),
        ]
        tree = Dispatcher(caps)
        tree.bind(rt)
        tree.setup()
        tree.launch()
        tree.destroy()
        assert [n for e, n in log if e == "setup"] == ["high", "mid", "low"]
        assert [n for e, n in log if e == "launch"] == ["high", "mid", "low"]
        assert [n for e, n in log if e == "destroy"] == ["low", "mid", "high"]

    def test_dispatch_event_routing(self):
        log = []
        cap = Recorder(log, "x")
        cap.bind(Runtime())
        cap.dispatch(Events.SETUP)
        cap.dispatch(Events.LAUNCH)
        assert log == [("setup", "x"), ("launch", "x")]

    def test_non_capsule_child_rejected(self):
        with pytest.raises(TypeError):
            Dispatcher([object()])


class TestStatefulRegistry:
    def test_lifo_registration(self):
        rt = Runtime()
        a = Capsule(statefull=True, priority=1100)
        b = Capsule(statefull=True, priority=1000)
        tree = Dispatcher([a, b])
        tree.bind(rt)
        tree.setup()
        assert rt.checkpointables == [a, b]
        tree.destroy()
        assert rt.checkpointables == []

    def test_out_of_order_destroy_allowed(self):
        # Identity-keyed deregistration: destroy order is free (the
        # reference needed LIFO because accelerate matched by position).
        rt = Runtime()
        a = Capsule(statefull=True)
        b = Capsule(statefull=True)
        a.bind(rt)
        b.bind(rt)
        a.setup()
        b.setup()
        a.destroy()
        assert rt.checkpointables == [b]
        with pytest.raises(RuntimeError, match="double destroy"):
            a.bind(rt) or setattr(a, "_registered", True) or a.destroy()

    def test_unbound_capsule_raises(self):
        with pytest.raises(RuntimeError, match="no runtime"):
            Capsule(statefull=True).setup()


class TestRuntime:
    def test_mesh_axes_and_dp_size(self, devices):
        rt = Runtime(mesh=MeshSpec(data=2, fsdp=2, tensor=2))
        assert rt.mesh.shape["data"] == 2
        assert rt.data_parallel_size == 4
        assert rt.device_count == 8

    def test_dedupe_registry(self):
        rt = Runtime()
        obj = object()
        assert rt.register_unique("module", obj)
        assert not rt.register_unique("module", obj)
        rt.deregister_unique("module", obj)
        assert rt.register_unique("module", obj)
