"""Int8 KV-cache decode (TransformerConfig.kv_cache_int8) — the oracle
discipline from the autotuner ISSUE:

- SHORT prompts, plain cache: greedy decode must be TOKEN-IDENTICAL to
  the bf16-cache oracle, solo and under the ContinuousBatcher with a
  mid-batch admit, and through cached beam search (the beam gather must
  carry the rank-4 scale leaves with the payload);
- LONG prompts, rolling cache: teacher-forced perplexity through the
  int8 cache stays within a documented tolerance (5% relative) of the
  bf16 cache — the regime where quantization error accumulates over
  many cache reads;
- layout: the cache pytree gains int8 payload + [B, slots, KV, 1] f32
  scale leaves, which is what the decode bench's MBU bytes model reads.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from rocket_tpu.models.generate import (
    ContinuousBatcher,
    beam_search_cached,
    decode_cache_shapes,
    generate,
    zero_cache,
)
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM


def _cfg(style="gpt2", **kw):
    if style == "gpt2":
        base = dict(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
            norm="layernorm", mlp="gelu", positions="learned",
            tie_embeddings=True, use_bias=True, attention="dot",
        )
    else:  # llama: RoPE + GQA
        base = dict(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, n_kv_heads=2,
            max_seq=64, attention="dot",
        )
    base.update(kw)
    return TransformerConfig(**base)


def _params(model, prompt, seed=1):
    return nn.meta.unbox(
        model.init(jax.random.PRNGKey(seed), {"tokens": prompt})["params"]
    )


@pytest.mark.parametrize("style", ["gpt2", "llama"])
def test_int8_kv_greedy_matches_bf16_cache_oracle(devices, style):
    """Same params, same prompt: the int8-cache greedy decode must emit
    exactly the bf16-cache tokens on short prompts."""
    cfg = _cfg(style)
    model = TransformerLM(cfg)
    model8 = TransformerLM(dataclasses.replace(cfg, kv_cache_int8=True))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(2, 8)), jnp.int32
    )
    params = _params(model, prompt)
    want = generate(model, params, prompt, max_new_tokens=12,
                    temperature=0.0)
    got = generate(model8, params, prompt, max_new_tokens=12,
                   temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_int8_kv_cache_layout(devices):
    """The cache pytree under kv_cache_int8: int8 payload, rank-4 f32
    scales (per row/slot/kv-head), scalar index — the scale rank is the
    contract the batcher's cache-shuffling helpers key on."""
    cfg = _cfg("llama", kv_cache_int8=True)
    model = TransformerLM(cfg)
    prompt = jnp.zeros((2, 4), jnp.int32)
    shapes = decode_cache_shapes(model, _params(model, prompt), prompt)
    leaves = {
        "/".join(str(k.key) for k in path): leaf
        for path, leaf in jax.tree_util.tree_leaves_with_path(shapes)
    }
    ks = [v for name, v in leaves.items() if name.endswith("cached_k")]
    scales = [v for name, v in leaves.items()
              if name.endswith("cached_k_scale")]
    assert ks and scales and len(ks) == len(scales) == cfg.n_layers
    for k, s in zip(ks, scales):
        assert k.dtype == jnp.int8
        assert s.dtype == jnp.float32
        assert s.shape == k.shape[:-1] + (1,)  # [B, slots, KV, 1]


def _teacher_forced_ppl(model, params, tokens):
    """Perplexity of ``tokens`` decoded one position at a time through
    the model's KV cache — every cache slot is written and re-read the
    way real decode does it."""
    B, T = tokens.shape
    cache = zero_cache(model, params, tokens[:, :1])
    total = jnp.zeros((B,), jnp.float32)
    for t in range(T - 1):
        pos = jnp.full((B, 1), t, jnp.int32)
        out, mutated = model.apply(
            {"params": params, "cache": cache},
            {"tokens": tokens[:, t:t + 1], "positions": pos},
            decode=True, mutable=["cache"],
        )
        cache = mutated["cache"]
        logp = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))
        total = total - logp[jnp.arange(B), tokens[:, t + 1]]
    return float(jnp.exp(jnp.mean(total / (T - 1))))


def test_int8_kv_rolling_long_prompt_perplexity_tolerance(devices):
    """Rolling cache, sequence far past the window: every slot gets
    overwritten repeatedly and every read dequantizes — teacher-forced
    perplexity must stay within 5% (relative) of the bf16 cache."""
    cfg = _cfg(
        "gpt2", max_seq=256, attention_window=16,
        decode_rolling_cache=True, decode_rolling_slack=8,
    )
    model = TransformerLM(cfg)
    model8 = TransformerLM(dataclasses.replace(cfg, kv_cache_int8=True))
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, size=(2, 48)), jnp.int32
    )
    params = _params(model, tokens[:, :8])
    ppl = _teacher_forced_ppl(model, params, tokens)
    ppl8 = _teacher_forced_ppl(model8, params, tokens)
    assert abs(ppl8 - ppl) / ppl < 0.05, (ppl, ppl8)


def test_int8_kv_rolling_generate_runs_past_window(devices):
    """End-to-end rolling generate with an int8 cache: a prompt longer
    than the window decodes, emits in-vocab tokens, and matches the
    bf16-cache tokens on this seed."""
    cfg = _cfg(
        "gpt2", max_seq=256, attention_window=32,
        decode_rolling_cache=True, decode_rolling_slack=16,
    )
    model = TransformerLM(cfg)
    model8 = TransformerLM(dataclasses.replace(cfg, kv_cache_int8=True))
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, size=(2, 70)), jnp.int32
    )
    params = _params(model, prompt[:, :8])
    want = generate(model, params, prompt, max_new_tokens=20,
                    temperature=0.0)
    got = generate(model8, params, prompt, max_new_tokens=20,
                   temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_int8_kv_beam_search_cached_matches(devices):
    """Beam search reorders cache rows each step; the scale leaves must
    travel with their payload (same src_beam gather) or scores drift."""
    cfg = _cfg("gpt2")
    model = TransformerLM(cfg)
    model8 = TransformerLM(dataclasses.replace(cfg, kv_cache_int8=True))
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, size=(1, 6)), jnp.int32
    )
    params = _params(model, prompt, seed=2)
    want = beam_search_cached(model, params, prompt, 8, 63, beam_size=3)
    got = beam_search_cached(model8, params, prompt, 8, 63, beam_size=3)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))


def test_int8_kv_continuous_batcher_mid_admit_matches(devices):
    """The batcher with kv_cache_int8=True must reproduce the bf16
    batcher's tokens row for row — including a row admitted mid-batch,
    whose prefill scatters int8 pages + scales into a live cache."""
    cfg = _cfg("gpt2")
    model = TransformerLM(cfg)
    prompt0 = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(2, 5)), jnp.int32
    )
    params = _params(model, prompt0)
    admit_prompt = jnp.asarray(
        np.random.default_rng(6).integers(0, 64, size=(1, 4)), jnp.int32
    )

    def run(**kw):
        bat = ContinuousBatcher(model, model, params, params,
                                total_len=20, n_draft=3, **kw)
        bat.start(prompt0)
        for _ in range(3):
            bat.step()
        bat.admit(0, admit_prompt, preempt=True)
        for _ in range(3):
            bat.step()
        return [bat.row_tokens(r) for r in range(2)]

    base = run()
    quant = run(kv_cache_int8=True)
    for (t0, n0), (t1, n1) in zip(base, quant):
        assert n0 == n1
        np.testing.assert_array_equal(
            np.asarray(t0)[:n0], np.asarray(t1)[:n1]
        )


def test_set_kv_cache_int8_rejects_live_batch(devices):
    """Flipping the cache layout mid-flight would discard every row's
    KV state — the batcher must refuse after start()."""
    cfg = _cfg("gpt2")
    model = TransformerLM(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 64, size=(1, 5)), jnp.int32
    )
    params = _params(model, prompt)
    bat = ContinuousBatcher(model, model, params, params,
                            total_len=16, n_draft=2)
    bat.set_kv_cache_int8(True)  # before start: fine
    assert bat._model.config.kv_cache_int8
    assert bat._draft_model.config.kv_cache_int8
    bat.start(prompt)
    with pytest.raises(ValueError, match="after start"):
        bat.set_kv_cache_int8(False)


def test_serving_loop_kv_cache_int8_knob(devices):
    """ServingLoop(kv_cache_int8=True) applies the layout to the initial
    batcher AND to a factory rebuild — recovery must not silently drop
    quantization."""
    from rocket_tpu.serve import ServingLoop

    cfg = _cfg("gpt2")
    model = TransformerLM(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(8).integers(0, 64, size=(1, 5)), jnp.int32
    )
    params = _params(model, prompt)

    def factory():
        return ContinuousBatcher(model, model, params, params,
                                 total_len=12, n_draft=2)

    loop = ServingLoop(factory, max_batch=1, kv_cache_int8=True)
    try:
        assert loop._bat._model.config.kv_cache_int8
        rebuilt = loop._build_batcher()
        assert rebuilt._model.config.kv_cache_int8
    finally:
        loop.close()
