"""Data-layer unit tests: sources, collate, padding masks, determinism."""

import numpy as np
import pytest

from rocket_tpu.data import ArraySource, ConcatSource, DataLoader, MapSource
from rocket_tpu.data.toys import mnist, synthetic_lm_tokens, synthetic_mnist


def _source(n=10):
    return ArraySource(
        {"x": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
         "y": np.arange(n, dtype=np.int32)}
    )


class TestSources:
    def test_array_source(self):
        src = _source()
        assert len(src) == 10
        sample = src[2]
        np.testing.assert_array_equal(sample["x"], [6, 7, 8])
        assert sample["y"] == 2

    def test_array_source_mismatched_lengths(self):
        with pytest.raises(ValueError, match="leading dim"):
            ArraySource({"a": np.zeros(3), "b": np.zeros(4)})

    def test_map_source(self):
        src = MapSource(_source(), lambda s: {**s, "y2": s["y"] * 2})
        assert src[3]["y2"] == 6

    def test_concat_source(self):
        src = ConcatSource([_source(4), _source(6)])
        assert len(src) == 10
        assert src[4]["y"] == 0  # first item of second source
        assert src[-1]["y"] == 5


class TestLoader:
    def test_batching_and_padding_mask(self):
        # 10 samples, batch 4 -> 3 batches, last padded with 2 wrap-around rows
        loader = DataLoader(_source(10), batch_size=4)
        batches = list(loader.iterate())
        assert len(batches) == 3
        assert all(b["x"].shape == (4, 3) for b in batches)  # static shapes
        np.testing.assert_array_equal(
            np.asarray(batches[-1]["_valid"]), [True, True, False, False]
        )
        # wrap-around pad repeats the epoch head
        np.testing.assert_array_equal(
            np.asarray(batches[-1]["y"])[2:], [0, 1]
        )

    def test_drop_last(self):
        loader = DataLoader(_source(10), batch_size=4, drop_last=True)
        assert len(loader) == 2
        assert len(list(loader.iterate())) == 2

    def test_shuffle_determinism_per_epoch(self):
        loader = DataLoader(_source(32), batch_size=8, shuffle=True, seed=1)
        a = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        b = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        c = [np.asarray(b["y"]) for b in loader.iterate(epoch=3)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))

    def test_prefetch_equals_sync(self):
        loader_a = DataLoader(_source(20), batch_size=4, prefetch=0)
        loader_b = DataLoader(_source(20), batch_size=4, prefetch=3)
        for x, y in zip(loader_a.iterate(), loader_b.iterate()):
            np.testing.assert_array_equal(np.asarray(x["y"]), np.asarray(y["y"]))

    def test_producer_error_propagates(self):
        class Bad(ArraySource):
            def __getitem__(self, i):
                if i == 5:
                    raise RuntimeError("boom")
                return super().__getitem__(i)

        loader = DataLoader(
            Bad({"x": np.zeros((8, 2), np.float32)}), batch_size=4, prefetch=2
        )
        with pytest.raises(RuntimeError, match="boom"):
            list(loader.iterate())


class TestToys:
    def test_synthetic_mnist_shapes(self):
        train, test = synthetic_mnist(n_train=64, n_test=16)
        assert train["image"].shape == (64, 28, 28, 1)
        assert train["image"].dtype == np.float32
        assert train["label"].max() <= 9

    def test_mnist_falls_back_to_synthetic(self):
        train, _ = mnist(n_train=32, n_test=8)
        assert train["image"].shape[0] == 32

    def test_lm_tokens_structure(self):
        data = synthetic_lm_tokens(n_docs=8, seq_len=32, vocab=64)
        assert data["tokens"].shape == (8, 32)
        assert data["tokens"].max() < 64
