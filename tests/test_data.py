"""Data-layer unit tests: sources, collate, padding masks, determinism."""

import numpy as np
import pytest

from rocket_tpu.data import (
    ArraySource,
    ConcatSource,
    DataLoader,
    GeneratorSource,
    MapSource,
)
from rocket_tpu.data.toys import mnist, synthetic_lm_tokens, synthetic_mnist


def _source(n=10):
    return ArraySource(
        {"x": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
         "y": np.arange(n, dtype=np.int32)}
    )


class TestSources:
    def test_array_source(self):
        src = _source()
        assert len(src) == 10
        sample = src[2]
        np.testing.assert_array_equal(sample["x"], [6, 7, 8])
        assert sample["y"] == 2

    def test_array_source_mismatched_lengths(self):
        with pytest.raises(ValueError, match="leading dim"):
            ArraySource({"a": np.zeros(3), "b": np.zeros(4)})

    def test_map_source(self):
        src = MapSource(_source(), lambda s: {**s, "y2": s["y"] * 2})
        assert src[3]["y2"] == 6

    def test_concat_source(self):
        src = ConcatSource([_source(4), _source(6)])
        assert len(src) == 10
        assert src[4]["y"] == 0  # first item of second source
        assert src[-1]["y"] == 5

    def test_token_file_source(self, tmp_path):
        from rocket_tpu.data.source import TokenFileSource

        tokens = np.arange(100, dtype=np.uint16)
        raw = tmp_path / "train.bin"
        tokens.tofile(raw)
        src = TokenFileSource(str(raw), seq_len=16)
        assert len(src) == 6  # (100-16)//16 + 1
        row = src[1]["tokens"]
        assert row.dtype == np.int32
        np.testing.assert_array_equal(row, np.arange(16, 32))

        npy = tmp_path / "train.npy"
        np.save(npy, tokens)
        src2 = TokenFileSource(str(npy), seq_len=16, stride=8)
        assert len(src2) == 11  # (100-16)//8 + 1
        np.testing.assert_array_equal(src2[2]["tokens"], np.arange(16, 32))
        np.testing.assert_array_equal(src2[-1]["tokens"], np.arange(80, 96))

    def test_token_file_source_through_loader(self, tmp_path):
        from rocket_tpu.data.source import TokenFileSource

        raw = tmp_path / "t.bin"
        np.arange(4096, dtype=np.uint16).tofile(raw)
        src = TokenFileSource(str(raw), seq_len=64)
        loader = DataLoader(src, batch_size=8, shuffle=True, seed=1)
        batches = list(loader.iterate())
        assert batches and batches[0]["tokens"].shape == (8, 64)

    def test_token_file_source_vocab_check_catches_tail(self, tmp_path):
        # Corruption past the head sample must still fail fast: plant the
        # out-of-range id only in the final tokens of a >1M-token file.
        from rocket_tpu.data.source import TokenFileSource

        arr = np.zeros(1_500_000, dtype=np.uint16)
        arr[-1] = 60000
        raw = tmp_path / "tail.bin"
        arr.tofile(raw)
        with pytest.raises(ValueError, match="vocab_size"):
            TokenFileSource(str(raw), seq_len=16, vocab_size=50257)
        # without vocab_size it loads fine
        assert len(TokenFileSource(str(raw), seq_len=16)) > 0


class TestLoader:
    def test_batching_and_padding_mask(self):
        # 10 samples, batch 4 -> 3 batches, last padded with 2 wrap-around rows
        loader = DataLoader(_source(10), batch_size=4)
        batches = list(loader.iterate())
        assert len(batches) == 3
        assert all(b["x"].shape == (4, 3) for b in batches)  # static shapes
        np.testing.assert_array_equal(
            np.asarray(batches[-1]["_valid"]), [True, True, False, False]
        )
        # wrap-around pad repeats the epoch head
        np.testing.assert_array_equal(
            np.asarray(batches[-1]["y"])[2:], [0, 1]
        )

    def test_drop_last(self):
        loader = DataLoader(_source(10), batch_size=4, drop_last=True)
        assert len(loader) == 2
        assert len(list(loader.iterate())) == 2

    def test_shuffle_determinism_per_epoch(self):
        loader = DataLoader(_source(32), batch_size=8, shuffle=True, seed=1)
        a = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        b = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        c = [np.asarray(b["y"]) for b in loader.iterate(epoch=3)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))

    def test_prefetch_equals_sync(self):
        loader_a = DataLoader(_source(20), batch_size=4, prefetch=0)
        loader_b = DataLoader(_source(20), batch_size=4, prefetch=3)
        for x, y in zip(loader_a.iterate(), loader_b.iterate()):
            np.testing.assert_array_equal(np.asarray(x["y"]), np.asarray(y["y"]))

    def test_producer_error_propagates(self):
        class Bad(ArraySource):
            def __getitem__(self, i):
                if i == 5:
                    raise RuntimeError("boom")
                return super().__getitem__(i)

        loader = DataLoader(
            Bad({"x": np.zeros((8, 2), np.float32)}), batch_size=4, prefetch=2
        )
        with pytest.raises(RuntimeError, match="boom"):
            list(loader.iterate())


class TestDevicePrefetch:
    """The device-transfer stage (loader ``device_prefetch``) and the
    prefetch thread's lifecycle contract."""

    @pytest.mark.parametrize("prefetch", [0, 2])
    @pytest.mark.parametrize("depth", [0, 1, 2])
    def test_depths_yield_identical_batches(self, prefetch, depth):
        ref = DataLoader(_source(20), batch_size=4, prefetch=0,
                         device_prefetch=0)
        loader = DataLoader(_source(20), batch_size=4, prefetch=prefetch,
                            device_prefetch=depth)
        got = list(loader.iterate())
        want = list(ref.iterate())
        assert len(got) == len(want)
        for x, y in zip(want, got):
            np.testing.assert_array_equal(np.asarray(x["y"]), np.asarray(y["y"]))
            np.testing.assert_array_equal(
                np.asarray(x["_valid"]), np.asarray(y["_valid"])
            )

    def test_negative_depth_rejected(self):
        with pytest.raises(ValueError, match="device_prefetch"):
            DataLoader(_source(8), batch_size=4, device_prefetch=-1)

    def test_prefetch_thread_joined_on_early_exit(self):
        import threading

        loader = DataLoader(_source(64), batch_size=4, prefetch=3,
                            device_prefetch=2)
        before = set(threading.enumerate())
        it = loader.iterate()
        next(it)
        next(it)
        it.close()  # abandoned mid-epoch: close() must join the producer
        leaked = [t for t in set(threading.enumerate()) - before if t.is_alive()]
        assert not leaked

    def test_producer_error_leaves_no_thread(self):
        import threading

        class Bad(ArraySource):
            def __getitem__(self, i):
                if i == 5:
                    raise RuntimeError("boom")
                return super().__getitem__(i)

        loader = DataLoader(
            Bad({"x": np.zeros((8, 2), np.float32)}), batch_size=4, prefetch=2
        )
        before = set(threading.enumerate())
        with pytest.raises(RuntimeError, match="boom"):
            list(loader.iterate())
        leaked = [t for t in set(threading.enumerate()) - before if t.is_alive()]
        assert not leaked

    def test_depth0_early_exit_closes_upstream(self):
        """``device_prefetch=0`` shares the cleanup contract of the staged
        path: abandoning the epoch mid-way must still close the upstream
        prefetch thread instead of deferring shutdown to GC."""
        import threading

        loader = DataLoader(_source(64), batch_size=4, prefetch=3,
                            device_prefetch=0)
        before = set(threading.enumerate())
        it = loader.iterate()
        next(it)
        it.close()
        leaked = [t for t in set(threading.enumerate()) - before if t.is_alive()]
        assert not leaked

    def test_placement_fixed_across_mid_epoch_mesh_change(self, devices):
        """The batch sharding resolves ONCE per epoch: a ``mesh_context``
        opened after the epoch started (resolved to host) must not flip
        later batches onto devices mid-epoch."""
        import jax

        from rocket_tpu.parallel.context import mesh_context
        from rocket_tpu.parallel.mesh import data_parallel_mesh

        loader = DataLoader(_source(32), batch_size=8, device_prefetch=0)
        it = loader.iterate()
        first = next(it)
        assert not isinstance(first["x"], jax.Array)  # no mesh at epoch start
        with mesh_context(data_parallel_mesh()):
            second = next(it)  # mesh opened mid-epoch: placement unchanged
        assert not isinstance(second["x"], jax.Array)

    def test_to_device_honors_active_mesh(self, devices):
        """No explicit sharding wired in: inside a ``mesh_context`` the
        loader assembles global arrays laid out over the data axes; with no
        mesh active, batches stay as host numpy (clean fallback)."""
        import jax

        from rocket_tpu.parallel.context import mesh_context
        from rocket_tpu.parallel.mesh import data_parallel_mesh

        loader = DataLoader(_source(32), batch_size=8, prefetch=2,
                            device_prefetch=2)
        host = next(iter(loader.iterate()))
        assert isinstance(np.asarray(host["x"]), np.ndarray)
        assert not isinstance(host["x"], jax.Array)

        mesh = data_parallel_mesh()
        with mesh_context(mesh):
            placed = next(iter(loader.iterate()))
        assert isinstance(placed["x"], jax.Array)
        assert len(placed["x"].sharding.device_set) == len(jax.devices())
        # rank-1 leaves (labels, the _valid mask) re-rank the spec cleanly
        assert isinstance(placed["_valid"], jax.Array)
        np.testing.assert_array_equal(np.asarray(placed["x"]),
                                      np.asarray(host["x"]))


def _stream_source(n=10):
    """Length-free stream of the same samples as _source(n)."""

    def gen():
        for i in range(n):
            yield {"x": np.arange(i * 3, i * 3 + 3, dtype=np.float32),
                   "y": np.int32(i)}

    return GeneratorSource(gen)


class TestStreamingLoader:
    def test_streaming_batches_and_partial_mask(self):
        loader = DataLoader(_stream_source(10), batch_size=4)
        assert loader.streaming and loader.num_batches is None
        with pytest.raises(TypeError, match="no length"):
            len(loader)
        batches = list(loader.iterate())
        assert len(batches) == 3
        assert all(b["x"].shape == (4, 3) for b in batches)  # static shapes
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b["y"]) for b in batches[:2]]),
            np.arange(8),
        )
        np.testing.assert_array_equal(
            np.asarray(batches[-1]["_valid"]), [True, True, False, False]
        )
        np.testing.assert_array_equal(np.asarray(batches[-1]["y"])[:2], [8, 9])

    def test_streaming_drop_last(self):
        loader = DataLoader(_stream_source(10), batch_size=4, drop_last=True)
        assert len(list(loader.iterate())) == 2

    def test_streaming_resume_replays_stream(self):
        """iterate(skip_batches=k) equals the tail of the full iteration —
        the checkpointable cursor is just the batch index (VERDICT r2
        missing #3 / next #6)."""
        loader = DataLoader(_stream_source(20), batch_size=4, prefetch=0)
        full = [np.asarray(b["y"]) for b in loader.iterate(epoch=0)]
        resumed = [
            np.asarray(b["y"])
            for b in loader.iterate(epoch=0, skip_batches=2)
        ]
        assert len(resumed) == len(full) - 2
        for x, y in zip(full[2:], resumed):
            np.testing.assert_array_equal(x, y)

    def test_streaming_shuffle_buffer_deterministic(self):
        loader = DataLoader(
            _stream_source(32), batch_size=8, shuffle=True, seed=1,
            shuffle_buffer=8,
        )
        a = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        b = [np.asarray(b["y"]) for b in loader.iterate(epoch=2)]
        c = [np.asarray(b["y"]) for b in loader.iterate(epoch=3)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))
        # a permutation: every sample appears exactly once
        np.testing.assert_array_equal(np.sort(np.concatenate(a)), np.arange(32))

    def test_streaming_epoch_fn_reseeds(self):
        src = GeneratorSource(
            lambda: iter(range(4)),
            epoch_fn=lambda e: iter(range(e, e + 4)),
        )
        loader = DataLoader(
            src, batch_size=4,
            collate_fn=lambda xs: {"v": np.asarray(xs)},
        )
        b0 = next(loader.iterate(epoch=0))
        b5 = next(loader.iterate(epoch=5))
        np.testing.assert_array_equal(np.asarray(b0["v"]), [0, 1, 2, 3])
        np.testing.assert_array_equal(np.asarray(b5["v"]), [5, 6, 7, 8])

    @pytest.mark.parametrize("n,drop_last,want", [
        (7, False, 2),   # batch 1 partial: p0 holds a FULL local slice (4,6)
        (7, True, 1),    # ...and must still drop it with drop_last
        (5, False, 2),   # p1 holds zero rows of the partial batch
        (8, False, 2),   # ends exactly on a boundary
    ])
    def test_streaming_per_process_batch_counts_agree(
        self, monkeypatch, n, drop_last, want
    ):
        """Every process must emit the SAME number of batches (device
        assembly is collective) no matter how the trailing remainder's rows
        fall across processes — including a process holding a full local
        slice of a partial global batch, or none of it."""
        import rocket_tpu.data.loader as loader_mod

        counts, masks = [], []
        for p in range(2):
            monkeypatch.setattr(loader_mod.jax, "process_count", lambda: 2)
            monkeypatch.setattr(
                loader_mod.jax, "process_index", lambda p=p: p
            )
            loader = DataLoader(
                _stream_source(n), batch_size=4, drop_last=drop_last,
                prefetch=0,
            )
            batches = list(loader.iterate())
            counts.append(len(batches))
            masks.append([np.asarray(b["_valid"]) for b in batches])
        assert counts == [want, want], counts
        if not drop_last and n % 4 != 0:
            # global valid rows of the final batch == n % 4
            total_valid = sum(int(m[-1].sum()) for m in masks)
            assert total_valid == n % 4, masks

    def test_streaming_trains_through_looper(self, tmp_path, devices):
        """Full pipeline from a length-free stream: Looper infers
        repeats=None and runs until the stream's termination vote; the
        Module trains on every batch."""
        import rocket_tpu as rt
        from rocket_tpu.models.objectives import lm_cross_entropy
        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, size=(24, 16)).astype(np.int32)

        def gen():
            for row in tokens:
                yield {"tokens": row}

        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=1, n_heads=2, max_seq=16,
            attention="dot",
        )
        seen = []

        class Spy(rt.Capsule):
            def launch(self, attrs=None):
                if attrs is not None and attrs.batch is not None:
                    seen.append(int(np.asarray(attrs.batch["_valid"]).sum()))

        mod = rt.Module(
            TransformerLM(cfg),
            capsules=[rt.Loss(lm_cross_entropy(), name="lm"),
                      rt.Optimizer(learning_rate=1e-2)],
        )
        looper = rt.Looper(
            capsules=[
                rt.Dataset(source=rt.GeneratorSource(gen), batch_size=8),
                Spy(statefull=False),
                mod,
            ],
            progress=False,
        )
        launcher = rt.Launcher(
            capsules=[looper], tag="stream", num_epochs=1,
            project_root=str(tmp_path),
        )
        launcher.launch()
        assert sum(seen) == 24  # every stream sample trained on exactly once


class TestToys:
    def test_synthetic_mnist_shapes(self):
        train, test = synthetic_mnist(n_train=64, n_test=16)
        assert train["image"].shape == (64, 28, 28, 1)
        assert train["image"].dtype == np.float32
        assert train["label"].max() <= 9

    def test_mnist_falls_back_to_synthetic(self):
        train, _ = mnist(n_train=32, n_test=8)
        assert train["image"].shape[0] == 32

    def test_lm_tokens_structure(self):
        data = synthetic_lm_tokens(n_docs=8, seq_len=32, vocab=64)
        assert data["tokens"].shape == (8, 32)
        assert data["tokens"].max() < 64


class TestStreamingStarvation:
    def test_starved_stream_raises_on_every_process(self, monkeypatch):
        """A stream whose trailing remainder can't give every process a
        sample must raise on ALL hosts — raising only on the starved
        process leaves its peers entering the collective assembly and
        deadlocking (VERDICT r3 weakness #7)."""
        import rocket_tpu.data.loader as loader_mod

        for p in range(4):
            monkeypatch.setattr(loader_mod.jax, "process_count", lambda: 4)
            monkeypatch.setattr(
                loader_mod.jax, "process_index", lambda p=p: p
            )
            loader = DataLoader(_stream_source(2), batch_size=4, prefetch=0)
            with pytest.raises(ValueError, match="all hosts"):
                list(loader.iterate())

    def test_stream_remainder_covering_every_process_still_pads(
        self, monkeypatch
    ):
        """remaining >= procs: every process got at least one sample, so
        the padded final batch forms on each."""
        import rocket_tpu.data.loader as loader_mod

        counts = []
        for p in range(4):
            monkeypatch.setattr(loader_mod.jax, "process_count", lambda: 4)
            monkeypatch.setattr(
                loader_mod.jax, "process_index", lambda p=p: p
            )
            loader = DataLoader(_stream_source(6), batch_size=4, prefetch=0)
            counts.append(len(list(loader.iterate())))
        assert counts == [2, 2, 2, 2]


class TestWorkerProcesses:
    def test_workers_match_in_process(self):
        """num_workers>0 yields bit-identical batches in identical order."""
        a = DataLoader(_source(37), batch_size=8, shuffle=True, seed=5)
        b = DataLoader(_source(37), batch_size=8, shuffle=True, seed=5,
                       num_workers=3)
        batches_a = list(a.iterate(epoch=2))
        batches_b = list(b.iterate(epoch=2))
        assert len(batches_a) == len(batches_b) == 5
        for x, y in zip(batches_a, batches_b):
            np.testing.assert_array_equal(np.asarray(x["x"]), np.asarray(y["x"]))
            np.testing.assert_array_equal(np.asarray(x["_valid"]),
                                          np.asarray(y["_valid"]))

    def test_workers_run_cpu_bound_transforms(self):
        """A MapSource transform executes inside the workers and results
        arrive in order."""
        src = MapSource(_source(16), lambda s: {**s, "y2": s["y"] * 2})
        loader = DataLoader(src, batch_size=4, num_workers=2, prefetch=0)
        batches = list(loader.iterate())
        got = np.concatenate([np.asarray(b["y2"]) for b in batches])
        np.testing.assert_array_equal(got, np.arange(16) * 2)

    def test_worker_error_propagates(self):
        class Bad(ArraySource):
            def __getitem__(self, i):
                if i == 5:
                    raise RuntimeError("boom-in-worker")
                return super().__getitem__(i)

        loader = DataLoader(
            Bad({"x": np.zeros((8, 2), np.float32)}), batch_size=4,
            num_workers=2,
        )
        with pytest.raises(RuntimeError, match="boom-in-worker"):
            list(loader.iterate())

    def test_workers_reject_streaming(self):
        with pytest.raises(ValueError, match="map-style"):
            DataLoader(_stream_source(8), batch_size=4, num_workers=2)

    def test_workers_mid_epoch_resume(self):
        loader = DataLoader(_source(32), batch_size=8, shuffle=True, seed=1,
                            num_workers=2, prefetch=0)
        full = [np.asarray(b["y"]) for b in loader.iterate(epoch=1)]
        resumed = [np.asarray(b["y"])
                   for b in loader.iterate(epoch=1, skip_batches=2)]
        for x, y in zip(full[2:], resumed):
            np.testing.assert_array_equal(x, y)

    def test_abandoned_iteration_reaps_workers(self):
        """Breaking out mid-epoch must terminate the forked pool (no
        zombie worker processes accumulating across truncated evals)."""
        import multiprocessing as mp
        import time

        before = len(mp.active_children())
        for _round in range(3):
            loader = DataLoader(_source(64), batch_size=4, num_workers=2)
            for batch in loader.iterate():
                break  # abandon immediately
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(mp.active_children()) <= before:
                break
            time.sleep(0.2)
        assert len(mp.active_children()) <= before, (
            before, len(mp.active_children())
        )


def test_mnist_download_gated_and_fallback(tmp_path, monkeypatch):
    """mnist(): download only when asked, graceful synthetic fallback
    when the network (or mirror) is unreachable, IDX round-trip when the
    files exist locally."""
    import gzip
    import struct

    from rocket_tpu.data import toys

    # unreachable mirror: download_mnist must return False, not raise
    monkeypatch.setattr(
        toys, "_MNIST_MIRRORS", ("http://127.0.0.1:9/",), raising=True
    )
    target = tmp_path / "dl"
    assert toys.download_mnist(str(target), timeout=0.2) is False

    # mnist() with download requested + dead network -> synthetic fallback
    train, test = toys.mnist(
        data_dir=str(target), download=True, n_train=32, n_test=16
    )
    assert train["image"].shape[0] == 32  # synthetic honored the kwargs

    # forge a tiny valid IDX set; mnist() must now read it (gz included)
    def write_idx(path, arr):
        with gzip.open(path, "wb") as f:
            f.write(struct.pack(">HBB", 0, 8, arr.ndim))
            f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
            f.write(arr.astype(np.uint8).tobytes())

    imgs = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28) % 255
    labels = np.asarray([3, 7], np.uint8)
    for stem in ("train", "t10k"):
        write_idx(target / f"{stem}-images-idx3-ubyte.gz", imgs)
        write_idx(target / f"{stem}-labels-idx1-ubyte.gz", labels)
    train, test = toys.mnist(data_dir=str(target))
    assert train["image"].shape == (2, 28, 28, 1)
    assert train["label"].tolist() == [3, 7]
    assert train["image"].max() <= 1.0
