"""Model-family tests: transformer LM (sharded), ResNet (batch_stats),
ViT, LoRA freezing."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import rocket_tpu as rt
import flax.linen as nn
from rocket_tpu.models.lora import freeze_non_lora, lora_labels
from rocket_tpu.models.objectives import cross_entropy, lm_cross_entropy
from rocket_tpu.models.resnet import ResNet
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.models.vit import ViT, ViTConfig
from rocket_tpu.parallel.mesh import MeshSpec


def _lm_batch(vocab=256, B=8, S=128, seed=0):
    rng = np.random.default_rng(seed)
    return {"tokens": jnp.asarray(rng.integers(0, vocab, size=(B, S)), jnp.int32)}


def _train_module(model, loss_fn, runtime, lr=1e-2, wrap=None):
    mod = rt.Module(
        model,
        capsules=[rt.Loss(loss_fn, name="obj"), rt.Optimizer(learning_rate=lr, wrap=wrap)],
    )
    mod.bind(runtime)
    mod.setup()
    return mod


def _run_steps(mod, batch, n=6):
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    losses = []
    for _ in range(n):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["obj"]))
    return losses


def test_transformer_sharded_training(devices):
    runtime = rt.Runtime(mesh=MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = TransformerConfig.tiny()
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    batch = jax.device_put(_lm_batch(), runtime.batch_sharding(ndim=2))
    losses = _run_steps(mod, batch)
    assert losses[-1] < losses[0]
    specs = {
        str(p.sharding.spec)
        for p in jax.tree_util.tree_leaves(mod.state.params)
        if hasattr(p, "sharding")
    }
    assert any("tensor" in s for s in specs), specs
    assert any("fsdp" in s for s in specs), specs
    mod.destroy()


def test_transformer_gpt2_style(devices):
    runtime = rt.Runtime()
    cfg = TransformerConfig.tiny(
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True,
    )
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    losses = _run_steps(mod, _lm_batch())
    assert losses[-1] < losses[0]
    mod.destroy()


def test_transformer_fused_qkv_matches_unfused(devices):
    """fused_qkv is a layout change only: transplanting the three separate
    q/k/v kernels (concatenated) into the fused projection must reproduce
    the unfused logits exactly."""
    cfg = TransformerConfig.tiny(n_kv_heads=2, attention="dot")
    cfg_f = TransformerConfig.tiny(n_kv_heads=2, attention="dot", fused_qkv=True)
    batch = _lm_batch(B=2, S=64)
    m, m_f = TransformerLM(cfg), TransformerLM(cfg_f)
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))

    def fuse(params):
        params = jax.tree_util.tree_map(lambda x: x, params)  # copy
        for blk in [k for k in params if k.startswith("block_")]:
            attn = params[blk]["attn"]
            qkv = jnp.concatenate(
                [attn.pop("q")["kernel"], attn.pop("k")["kernel"],
                 attn.pop("v")["kernel"]], axis=-1,
            )
            attn["qkv"] = {"kernel": qkv}
        return params

    fused_params = fuse(
        jax.tree_util.tree_map(lambda x: x, vs["params"])
    )
    out = m.apply(vs, batch)["logits"]
    out_f = m_f.apply({"params": fused_params}, batch)["logits"]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_f), atol=1e-5, rtol=1e-5
    )


def test_transformer_fused_qkv_trains(devices):
    runtime = rt.Runtime(mesh=MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = TransformerConfig.tiny(fused_qkv=True)
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    batch = jax.device_put(_lm_batch(), runtime.batch_sharding(ndim=2))
    losses = _run_steps(mod, batch)
    assert losses[-1] < losses[0]
    mod.destroy()


def test_transformer_fused_ce_matches_logits_path(devices):
    """fused_ce: the loss computed from token_nll (logits never built)
    equals the logits-path loss, and so do the parameter gradients."""
    from rocket_tpu.models.objectives import lm_cross_entropy as lm_ce

    base = dict(tie_embeddings=True, positions="learned", attention="dot")
    cfg = TransformerConfig.tiny(**base)
    cfg_f = TransformerConfig.tiny(fused_ce=True, **base)
    batch = _lm_batch(B=2, S=64)
    m, m_f = TransformerLM(cfg), TransformerLM(cfg_f)
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))
    loss_fn = lm_ce()

    def loss_logits(params):
        return loss_fn(m.apply({"params": params}, batch))

    def loss_fused(params):
        out = m_f.apply({"params": params}, batch)
        assert "logits" not in out and "token_nll" in out
        return loss_fn(out)

    l0, g0 = jax.value_and_grad(loss_logits)(vs["params"])
    l1, g1 = jax.value_and_grad(loss_fused)(vs["params"])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    flat0 = jax.tree_util.tree_leaves_with_path(g0)
    flat1 = dict(jax.tree_util.tree_leaves_with_path(g1))
    for path, leaf in flat0:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat1[path]), atol=2e-5, rtol=1e-4,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


def test_transformer_fused_ce_trains_sharded(devices):
    runtime = rt.Runtime(mesh=MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = TransformerConfig.tiny(tie_embeddings=True, fused_ce=True)
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    batch = jax.device_put(_lm_batch(), runtime.batch_sharding(ndim=2))
    losses = _run_steps(mod, batch)
    assert losses[-1] < losses[0]
    mod.destroy()


@pytest.mark.parametrize(
    "extra",
    [dict(remat=True), dict(scan_layers=True),
     dict(remat=True, scan_layers=True, fused_qkv=True)],
    ids=["remat", "scan", "remat+scan+fused_qkv"],
)
def test_transformer_fused_ce_composes(devices, extra):
    """fused_ce sits outside the block stack, so it must compose with the
    memory layouts (remat / scan) and fused_qkv; chunk size that does not
    divide the token count exercises the ragged tail."""
    runtime = rt.Runtime(mesh=MeshSpec(data=2, tensor=2, fsdp=2))
    cfg = TransformerConfig.tiny(
        tie_embeddings=True, fused_ce=True, fused_ce_chunk=48, **extra
    )
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    batch = jax.device_put(_lm_batch(), runtime.batch_sharding(ndim=2))
    losses = _run_steps(mod, batch, n=3)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    mod.destroy()


def test_transformer_scan_matches_unrolled(devices):
    """scan_layers is a layout change only: stacking the unrolled blocks'
    params along a leading 'layers' axis must reproduce the unrolled
    logits exactly (backs the docs/performance.md claim that the scan
    LAYOUT is sound and any TPU-backend scan anomaly is a backend issue)."""

    base = dict(attention="dot", positions="learned", tie_embeddings=True)
    cfg_u = TransformerConfig.tiny(n_kv_heads=2, **base)
    cfg_s = TransformerConfig.tiny(n_kv_heads=2, scan_layers=True, **base)
    batch = _lm_batch(B=2, S=64)
    m_u, m_s = TransformerLM(cfg_u), TransformerLM(cfg_s)
    vs = nn.meta.unbox(m_u.init(jax.random.PRNGKey(0), batch))

    params = {k: v for k, v in vs["params"].items()}
    block_keys = sorted(
        (k for k in params if k.startswith("block_")),
        key=lambda k: int(k.split("_")[1]),
    )
    blocks = [params.pop(k) for k in block_keys]
    params["blocks"] = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *blocks
    )
    out_u = m_u.apply(vs, batch)["logits"]
    out_s = m_s.apply({"params": params}, batch)["logits"]
    np.testing.assert_allclose(
        np.asarray(out_u), np.asarray(out_s), atol=2e-5, rtol=1e-5
    )


def test_transformer_gqa_scan_remat(devices):
    runtime = rt.Runtime()
    cfg = TransformerConfig.tiny(n_kv_heads=2, scan_layers=True, remat=True)
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    losses = _run_steps(mod, _lm_batch())
    assert losses[-1] < losses[0]
    # scan stacking: block params have a leading layers axis
    import flax

    params = flax.core.unfreeze(mod.state.params)
    leaf = jax.tree_util.tree_leaves(params["blocks"])[0]
    assert leaf.shape[0] == cfg.n_layers
    mod.destroy()


def test_resnet_batchnorm_mutable(devices):
    runtime = rt.Runtime()
    model = ResNet(stage_sizes=(1, 1), num_classes=4, width=8, small_images=True)
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(8, 16, 16, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 4, size=8), jnp.int32),
    }
    mod = _train_module(model, cross_entropy(labels_key="label"), runtime)
    attrs = rt.Attributes(looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()))
    attrs.batch = batch
    mod.launch(attrs)
    # snapshot to host NOW: the next launch donates the state buffers
    stats_before = np.asarray(
        jax.tree_util.tree_leaves(mod.state.mutable["batch_stats"])[0]
    )
    attrs.batch = batch
    mod.launch(attrs)
    stats_after = np.asarray(
        jax.tree_util.tree_leaves(mod.state.mutable["batch_stats"])[0]
    )
    # running stats actually update inside the jitted step
    assert not np.allclose(stats_before, stats_after)
    mod.destroy()


def test_vit_trains(devices):
    runtime = rt.Runtime()
    model = ViT(ViTConfig.tiny())
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=8), jnp.int32),
    }
    mod = _train_module(model, cross_entropy(labels_key="label"), runtime, lr=1e-3)
    losses = _run_steps(mod, batch, n=5)
    assert losses[-1] < losses[0]
    mod.destroy()


@pytest.mark.parametrize("family", ["resnet", "vit", "lm"])
def test_bf16_policy_threads_through_model_families(devices, family):
    """Under mixed_precision='bf16' the activations (captured intermediates)
    and output logits are ACTUALLY bf16 — no silent f32 re-cast inside the
    model families (VERDICT r1 weakness #5); params stay f32 masters."""
    from rocket_tpu.engine.precision import Policy
    from rocket_tpu.models.resnet import ResNet

    policy = Policy.from_string("bf16")
    rng = np.random.default_rng(0)
    if family == "resnet":
        # dtype comes from the policy (Module clones it in via the adapter's
        # apply_policy; here set directly) — the batch is NOT cast.
        model = ResNet(
            stage_sizes=(1, 1), num_classes=4, width=8, small_images=True,
            dtype=policy.compute_dtype,
        )
        batch = {"image": jnp.asarray(rng.normal(size=(4, 16, 16, 3)), jnp.float32)}
        probe = "BottleneckBlock_0"
    elif family == "vit":
        model = ViT(ViTConfig.tiny(), dtype=policy.compute_dtype)
        batch = {"image": jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)}
        probe = "block_0"
    else:
        model = TransformerLM(TransformerConfig.tiny())
        batch = _lm_batch(B=4, S=32)
        probe = None

    variables = dict(model.init(jax.random.PRNGKey(0), batch, train=False))
    params = variables.pop("params")
    cast_vars = {"params": policy.cast_to_compute(params), **variables}
    out, inter = model.apply(
        cast_vars, batch, train=False, capture_intermediates=True
    )
    assert out["logits"].dtype == jnp.bfloat16
    if probe is not None:
        flat = jax.tree_util.tree_leaves_with_path(inter["intermediates"])
        probed = [
            leaf for path, leaf in flat
            if probe in jax.tree_util.keystr(path)
            and hasattr(leaf, "dtype")
            and getattr(leaf, "ndim", 0) > 0  # skip f32 aux scalars (MoE)
        ]
        assert probed, f"no intermediates captured under {probe}"
        assert all(leaf.dtype == jnp.bfloat16 for leaf in probed), [
            leaf.dtype for leaf in probed
        ]


def test_bf16_policy_end_to_end_training(devices):
    """The full Module path under mixed_precision='bf16': the adapter clones
    the policy's compute dtype into the model (apply_policy), training
    converges even from RAW UINT8 images, eval logits are bf16, and the f32
    master params stay f32 in the TrainState."""
    runtime = rt.Runtime(mixed_precision="bf16")
    model = ResNet(stage_sizes=(1, 1), num_classes=4, width=8, small_images=True)
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, size=(8, 16, 16, 3)), jnp.uint8),
        "label": jnp.asarray(rng.integers(0, 4, size=8), jnp.int32),
    }
    mod = _train_module(model, cross_entropy(labels_key="label"), runtime)
    losses = _run_steps(mod, batch, n=6)
    assert losses[-1] < losses[0]
    assert all(
        leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(mod.state.params)
    )
    # eval path: uint8 in, bf16 compute out (apply_policy threaded the dtype)
    attrs = rt.Attributes(
        batch=batch, looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
    )
    mod.launch(attrs)
    assert attrs.batch["logits"].dtype == jnp.bfloat16
    # supervision leaves were not degraded by the engine
    assert attrs.batch["label"].dtype == jnp.int32
    mod.destroy()


def test_moe_expert_parallel_training(devices):
    """MoE transformer on an expert x tensor mesh: training converges, the
    expert weights actually shard over the 'expert' axis, and the Switch
    load-balancing aux is published and finite."""
    from rocket_tpu.models.moe import moe_aux_loss

    runtime = rt.Runtime(mesh=MeshSpec(data=2, expert=2, tensor=2))
    cfg = TransformerConfig.tiny(n_experts=4, moe_top_k=2)
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Loss(moe_aux_loss(), name="moe_aux", weight=0.01),
            rt.Optimizer(learning_rate=1e-2),
        ],
    )
    mod.bind(runtime)
    mod.setup()
    batch = jax.device_put(_lm_batch(), runtime.batch_sharding(ndim=2))
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    losses, auxes = [], []
    for _ in range(6):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["lm"]))
        auxes.append(float(attrs.step_logs["moe_aux"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(a) and 0.0 < a < cfg.n_experts for a in auxes)

    expert_specs = {
        jax.tree_util.keystr(p): str(leaf.sharding.spec)
        for p, leaf in jax.tree_util.tree_leaves_with_path(mod.state.params)
        if "moe" in jax.tree_util.keystr(p)
    }
    w_specs = [s for k, s in expert_specs.items() if "w_up" in k or "w_down" in k]
    assert w_specs and all("expert" in s for s in w_specs), expert_specs
    mod.destroy()


def test_moe_scan_layers(devices):
    """MoE composes with scan-stacked layers (aux accumulates through the
    scan's ys output)."""
    runtime = rt.Runtime()
    cfg = TransformerConfig.tiny(n_experts=2, moe_top_k=1, scan_layers=True)
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    attrs.batch = _lm_batch()
    mod.launch(attrs)
    assert np.isfinite(float(attrs.step_logs["obj"]))
    # eval path publishes moe_aux on the rewritten batch
    attrs2 = rt.Attributes(
        batch=_lm_batch(),
        looper=rt.Attributes(grad_enabled=False, state=rt.Attributes()),
    )
    mod.launch(attrs2)
    assert np.isfinite(float(attrs2.batch["moe_aux"]))
    mod.destroy()


def test_moe_all_tokens_routed_with_ample_capacity(devices):
    """With generous capacity every token's combine weights sum to ~1 — no
    silent token dropping at the default operating point."""
    import jax.numpy as jnp

    from rocket_tpu.models.moe import MoEMLP

    layer = MoEMLP(n_experts=4, mlp_dim=32, top_k=2, capacity_factor=4.0)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 8)), jnp.float32
    )
    variables = layer.init(jax.random.PRNGKey(0), x)
    y, aux = layer.apply(variables, x)
    assert y.shape == x.shape
    # zero input rows -> zero output (dispatch linearity sanity)
    y0, _ = layer.apply(variables, jnp.zeros_like(x))
    np.testing.assert_allclose(np.asarray(y0), 0.0, atol=1e-6)
    assert 0.0 < float(aux) < 4.0


def test_moe_sort_dispatch_matches_onehot_oracle(devices):
    """The scalable sort/scatter dispatch and the GShard one-hot einsum
    oracle produce the same outputs AND the same gradients — the seat
    assignment (slot-major, overflow dropping) is semantically identical
    (VERDICT r2 weak #6)."""
    import jax.numpy as jnp

    from rocket_tpu.models.moe import MoEMLP

    kw = dict(n_experts=8, mlp_dim=32, top_k=2, capacity_factor=1.0)
    sort_layer = MoEMLP(**kw, dispatch="sort")
    onehot_layer = MoEMLP(**kw, dispatch="onehot")
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 64, 16)), jnp.float32
    )
    variables = sort_layer.init(jax.random.PRNGKey(0), x)

    y_sort, aux_sort = sort_layer.apply(variables, x)
    y_hot, aux_hot = onehot_layer.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(y_sort), np.asarray(y_hot), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(float(aux_sort), float(aux_hot), rtol=1e-6)

    def loss(params, layer):
        y, aux = layer.apply(params, x)
        return jnp.sum(y ** 2) + aux

    g_sort = jax.grad(loss)(variables, sort_layer)
    g_hot = jax.grad(loss)(variables, onehot_layer)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-4
        ),
        g_sort,
        g_hot,
    )


def test_moe_sort_dispatch_memory_scales(devices):
    """At E=32 the one-hot path materializes O(B*S*E*C) dispatch/combine
    tensors; the sort path must stay well under that (the point of the
    rewrite).  Compared via XLA's compiled temp-memory analysis."""
    import jax.numpy as jnp

    from rocket_tpu.models.moe import MoEMLP

    kw = dict(n_experts=32, mlp_dim=64, top_k=2, capacity_factor=1.25)
    B, S, D = 4, 512, 32
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(B, S, D)), jnp.float32
    )

    def temp_bytes(layer):
        variables = layer.init(jax.random.PRNGKey(0), x)
        fn = jax.jit(lambda v, xx: layer.apply(v, xx)[0])
        mem = fn.lower(variables, x).compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    sort_bytes = temp_bytes(MoEMLP(**kw, dispatch="sort"))
    onehot_bytes = temp_bytes(MoEMLP(**kw, dispatch="onehot"))
    # one-hot: combine+dispatch are B*S*E*C*4 bytes each (C=40 here ->
    # ~10MB per tensor); sort path carries only [B,K*S] routing vectors
    # and the [E,C,D] buffers both paths share.
    assert sort_bytes < onehot_bytes / 2, (sort_bytes, onehot_bytes)


@pytest.mark.parametrize("style", ["gpt2", "llama"])
def test_generate_cached_matches_full_forward(devices, style):
    """KV-cache greedy decode must emit EXACTLY the tokens that repeated
    full forwards would: the cache is an optimization, not a semantics
    change.  Covers learned positions (gpt2 style) and RoPE + GQA (llama
    style)."""
    import jax.numpy as jnp

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    if style == "gpt2":
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=48,
            norm="layernorm", mlp="gelu", positions="learned",
            tie_embeddings=True, use_bias=True, attention="dot",
        )
    else:
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, n_kv_heads=2,
            max_seq=48, attention="dot",
        )
    model = TransformerLM(cfg)
    B, P, NEW = 2, 8, 6
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(B, P)), jnp.int32
    )
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )

    got = generate(model, params, prompt, max_new_tokens=NEW, temperature=0.0)
    assert got.shape == (B, P + NEW)

    # oracle: grow the sequence with full (uncached) forwards
    seq = prompt
    for _ in range(NEW):
        out = model.apply({"params": params}, {"tokens": seq})
        nxt = jnp.argmax(out["logits"][:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(seq))


def test_generate_with_bf16_cast_params(devices):
    """Serving casts weights to bf16 before decoding; the KV cache must
    follow the params' dtype (regression: generate derived cache shapes
    from a fresh f32 init, so bf16 k/v hit an f32 cache and
    dynamic_update_slice rejected the dtype mismatch)."""
    import jax.numpy as jnp

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=48,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    model = TransformerLM(cfg)
    B, P, NEW = 2, 8, 6
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(B, P)), jnp.int32
    )
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params,
    )
    got = generate(model, params, prompt, max_new_tokens=NEW, temperature=0.0)
    assert got.shape == (B, P + NEW)
    assert jnp.all((got >= 0) & (got < 64))


def test_generate_eos_token_freezes_finished_rows(devices):
    """After a row emits eos, every later position repeats eos (static
    shapes under jit; the host trims), and the pre-EOS prefix is
    bit-identical to the no-eos call."""
    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(2, 6)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    free = np.asarray(
        generate(model, params, prompt, max_new_tokens=20, temperature=0.0)
    )
    # pick an eos the free-running greedy output actually emits so the
    # freeze path is exercised
    eos = int(free[0, 6 + 2])
    got = np.asarray(
        generate(model, params, prompt, max_new_tokens=20, temperature=0.0,
                 eos_token=eos)
    )
    assert got.shape == free.shape
    for row in range(got.shape[0]):
        cont_free, cont = free[row, 6:], got[row, 6:]
        hits = np.nonzero(cont == eos)[0]
        if hits.size:
            first = hits[0]
            # identical before the first eos, frozen at eos after
            np.testing.assert_array_equal(cont[:first], cont_free[:first])
            assert np.all(cont[first:] == eos)
        else:
            np.testing.assert_array_equal(cont, cont_free)
    # row 0 must actually have frozen (we chose its own 3rd token)
    assert np.any(got[0, 6:] == eos)


def test_speculative_generate_matches_plain_greedy(devices):
    """Speculative decoding is an EXACTNESS contract: whatever the draft
    proposes (here: a differently-initialized model that disagrees
    often), the output must be identical to plain greedy decoding with
    the target alone."""
    from rocket_tpu.models.generate import generate, speculative_generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    draft_cfg = TransformerConfig(
        vocab_size=64, hidden=16, n_layers=1, n_heads=2, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(1, 8)), jnp.int32
    )
    model = TransformerLM(cfg)
    draft = TransformerLM(draft_cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    draft_params = nn.meta.unbox(
        draft.init(jax.random.PRNGKey(2), {"tokens": prompt})["params"]
    )

    want = generate(model, params, prompt, max_new_tokens=17,
                    temperature=0.0)
    for n_draft in (1, 3, 4):
        got = speculative_generate(
            model, params, draft, draft_params, prompt,
            max_new_tokens=17, n_draft=n_draft,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_speculative_generate_perfect_draft(devices):
    """With the target as its own draft every proposal is accepted — the
    degenerate upper bound must still be exact."""
    from rocket_tpu.models.generate import generate, speculative_generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, size=(1, 6)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    want = generate(model, params, prompt, max_new_tokens=12,
                    temperature=0.0)
    got, stats = speculative_generate(
        model, params, model, params, prompt, max_new_tokens=12, n_draft=4,
        return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # a perfect draft must accept EVERY proposal in EVERY round — this is
    # what catches draft-cache corruption that output exactness cannot
    # (the target re-verifies everything): 11 tokens after the prefill
    # one, 5 per round -> exactly 3 rounds, all drafts accepted
    assert stats["accepted"] == stats["drafted"], stats
    assert stats["rounds"] == 3, stats


def test_speculative_generate_eos_matches_generate_eos(devices):
    """speculative + eos must reproduce generate + eos exactly: prefix
    through the first eos, all-eos frozen tail after."""
    from rocket_tpu.models.generate import generate, speculative_generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(6).integers(0, 64, size=(1, 6)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    free = np.asarray(
        generate(model, params, prompt, max_new_tokens=16, temperature=0.0)
    )
    eos = int(free[0, 6 + 3])  # an eos the greedy run actually emits
    want = np.asarray(
        generate(model, params, prompt, max_new_tokens=16, temperature=0.0,
                 eos_token=eos)
    )
    got = np.asarray(
        speculative_generate(model, params, model, params, prompt,
                             max_new_tokens=16, n_draft=4, eos_token=eos)
    )
    np.testing.assert_array_equal(got, want)
    assert np.any(got[0, 6:] == eos)

    # prefill-token-is-eos branch: eos = the FIRST greedy token makes
    # the whole continuation a frozen all-eos tail in both functions
    eos0 = int(free[0, 6])
    want0 = np.asarray(
        generate(model, params, prompt, max_new_tokens=16, temperature=0.0,
                 eos_token=eos0)
    )
    got0 = np.asarray(
        speculative_generate(model, params, model, params, prompt,
                             max_new_tokens=16, n_draft=4, eos_token=eos0)
    )
    np.testing.assert_array_equal(got0, want0)
    assert np.all(got0[0, 6:] == eos0)


def test_accept_resample_first_token_marginal_is_target(devices):
    """The speculative-sampling theorem, tested on the extracted core:
    whatever the draft distribution q, the round's first emitted token
    (accepted d_1, or the rejection resample) is distributed exactly per
    the target's p — checked empirically on fixed p/q over 20k trials."""
    from rocket_tpu.models.generate import _accept_resample

    rng = np.random.default_rng(0)
    V, k, N = 6, 2, 20_000
    p0 = np.array([0.35, 0.05, 0.2, 0.1, 0.25, 0.05])
    p1 = np.array([0.1, 0.3, 0.1, 0.2, 0.2, 0.1])
    p2 = np.array([0.4, 0.1, 0.1, 0.1, 0.2, 0.1])
    q0 = np.array([0.1, 0.4, 0.1, 0.2, 0.1, 0.1])  # very unlike p0
    q1 = np.array([0.2, 0.2, 0.2, 0.2, 0.1, 0.1])
    p_rows = np.stack([p0, p1, p2]).astype(np.float32)
    q_rows = np.stack([q0, q1]).astype(np.float32)

    counts = np.zeros(V)
    for _ in range(N):
        drafts = np.array([rng.choice(V, p=q0), rng.choice(V, p=q1)])
        j, tok = _accept_resample(p_rows, q_rows, drafts, rng)
        first = int(drafts[0]) if j >= 1 else tok
        counts[first] += 1
    tv = 0.5 * np.abs(counts / N - p0).sum()
    assert tv < 0.03, (tv, counts / N)


def test_speculative_sample_identical_draft_accepts_everything(devices):
    """p == q makes the accept probability min(1, p/q) = 1: the target
    drafting for itself must accept every proposal, and the run must be
    reproducible from the seed."""
    from rocket_tpu.models.generate import speculative_sample
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(8).integers(0, 64, size=(1, 6)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    out, stats = speculative_sample(
        model, params, model, params, prompt, max_new_tokens=14,
        n_draft=4, temperature=0.9, seed=7, return_stats=True,
    )
    assert out.shape == (1, 20)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 64))
    assert stats["accepted"] == stats["drafted"], stats
    again = speculative_sample(
        model, params, model, params, prompt, max_new_tokens=14,
        n_draft=4, temperature=0.9, seed=7,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(again))


def test_speculative_generate_rejects_batch(devices):
    from rocket_tpu.models.generate import speculative_generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=16, n_layers=1, n_heads=2, max_seq=32,
        attention="dot", norm="layernorm", mlp="gelu",
        positions="learned", tie_embeddings=True, use_bias=True,
    )
    model = TransformerLM(cfg)
    prompt = jnp.zeros((2, 4), jnp.int32)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    with pytest.raises(ValueError, match="batch=1"):
        speculative_generate(model, params, model, params, prompt, 4)
    one = prompt[:1]
    with pytest.raises(ValueError, match="n_draft"):
        speculative_generate(model, params, model, params, one, 4,
                             n_draft=0)


def test_generate_sampling_shapes_and_jit(devices):
    """Temperature/top-k sampling path runs under jit and respects the
    vocab bound."""
    import functools

    import jax.numpy as jnp

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=32, hidden=16, n_layers=1, n_heads=2, max_seq=32,
        attention="dot",
    )
    model = TransformerLM(cfg)
    prompt = jnp.zeros((2, 4), jnp.int32)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    gen = jax.jit(functools.partial(
        generate, model, max_new_tokens=5, temperature=0.7, top_k=8
    ))
    got = gen(params, prompt, rng=jax.random.PRNGKey(3))
    assert got.shape == (2, 9)
    assert int(jnp.max(got)) < 32 and int(jnp.min(got)) >= 0


def test_lora_freezes_base_weights(devices):
    runtime = rt.Runtime()
    cfg = TransformerConfig.tiny(lora_rank=4)
    mod = _train_module(
        TransformerLM(cfg), lm_cross_entropy(), runtime, wrap=freeze_non_lora
    )
    mod.materialize(_lm_batch())
    before = jax.tree_util.tree_map(np.asarray, mod.state.params)
    _run_steps(mod, _lm_batch(), n=3)
    after = mod.state.params
    labels = lora_labels(after)
    flat_b = jax.tree_util.tree_leaves_with_path(before)
    flat_a = jax.tree_util.tree_leaves_with_path(after)
    flat_l = jax.tree_util.tree_leaves_with_path(labels)
    changed_lora = unchanged_base = 0
    for (pb, b), (pa, a), (pl, lab) in zip(flat_b, flat_a, flat_l):
        if lab == "train":
            if not np.allclose(np.asarray(b), np.asarray(a)):
                changed_lora += 1
        else:
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
            unchanged_base += 1
    assert changed_lora > 0 and unchanged_base > 0
    mod.destroy()


def test_seq2seq_trains_sharded(devices):
    """Encoder-decoder family: copy task loss decreases through the jitted
    step on a dp x tp x fsdp mesh; lm_cross_entropy reused with
    tokens_key='targets' (the decoder shift)."""
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    runtime = rt.Runtime(mesh=MeshSpec(data=2, fsdp=2, tensor=2))
    cfg = Seq2SeqConfig.tiny()
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, cfg.vocab_size, size=(8, 24)).astype(np.int32)
    batch = {
        "inputs": jnp.asarray(inputs),
        "targets": jnp.asarray(inputs[:, :16]),  # copy-prefix task
        "inputs_mask": jnp.ones((8, 24), jnp.int32),
    }
    mod = _train_module(
        EncoderDecoder(cfg), lm_cross_entropy(tokens_key="targets"), runtime
    )
    batch = jax.device_put(batch, runtime.batch_sharding(ndim=2))
    losses = _run_steps(mod, batch)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    specs = {
        str(p.sharding.spec)
        for p in jax.tree_util.tree_leaves(mod.state.params)
        if hasattr(p, "sharding")
    }
    assert any("tensor" in s for s in specs), specs
    mod.destroy()


def test_seq2seq_memory_mask_blocks_padding(devices):
    """Cross-attention must ignore masked input positions: changing tokens
    under the mask cannot change the logits."""
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    cfg = Seq2SeqConfig.tiny(attention="dot")
    rng = np.random.default_rng(1)
    inputs = rng.integers(0, cfg.vocab_size, size=(2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[:, 8:] = 0
    targets = rng.integers(0, cfg.vocab_size, size=(2, 8)).astype(np.int32)
    m = EncoderDecoder(cfg)
    batch = {
        "inputs": jnp.asarray(inputs),
        "targets": jnp.asarray(targets),
        "inputs_mask": jnp.asarray(mask),
    }
    vs = m.init(jax.random.PRNGKey(0), batch)
    out_a = m.apply(vs, batch)["logits"]
    scrambled = inputs.copy()
    scrambled[:, 8:] = rng.integers(0, cfg.vocab_size, size=(2, 4))
    batch2 = dict(batch, inputs=jnp.asarray(scrambled))
    out_b = m.apply(vs, batch2)["logits"]
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-6)


def test_seq2seq_fully_masked_row_stays_finite(devices):
    """An all-padding input row (wrap-around dummy in a final partial
    batch) must not poison the batch with softmax NaNs — the key mask
    fill is finite, degrading to uniform weights on dead rows."""
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    cfg = Seq2SeqConfig.tiny(attention="dot")
    rng = np.random.default_rng(2)
    batch = {
        "inputs": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32
        ),
        "targets": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32
        ),
        "inputs_mask": jnp.asarray(
            np.stack([np.ones(12), np.zeros(12)]), jnp.int32
        ),
    }
    m = EncoderDecoder(cfg)
    vs = m.init(jax.random.PRNGKey(0), batch)
    out = m.apply(vs, batch)["logits"]
    assert bool(jnp.isfinite(out).all())
    g = jax.grad(
        lambda p: m.apply({"params": p}, batch)["logits"].sum()
    )(nn.meta.unbox(vs)["params"])
    assert all(
        bool(jnp.isfinite(leaf).all())
        for leaf in jax.tree_util.tree_leaves(g)
    )


def test_seq2seq_generate_greedy_self_consistent(devices):
    """generate_seq2seq: encode-once + scan decode; greedy output must be
    the argmax of the teacher-forced logits over its own prefix."""
    from rocket_tpu.models.generate import generate_seq2seq
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    cfg = Seq2SeqConfig.tiny(attention="dot")
    rng = np.random.default_rng(3)
    inputs = jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 12)), jnp.int32)
    m = EncoderDecoder(cfg)
    vs = m.init(
        jax.random.PRNGKey(0),
        {"inputs": inputs, "targets": jnp.zeros((2, 4), jnp.int32)},
    )
    out = generate_seq2seq(m, vs, inputs, max_new_tokens=6, bos_id=1)
    assert out.shape == (2, 7) and int(out[0, 0]) == 1
    logits = m.apply(vs, {"inputs": inputs, "targets": out})["logits"]
    greedy = jnp.argmax(logits[:, :-1], axis=-1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(out[:, 1:]))


def test_seq2seq_dropout_trains(devices):
    """dropout > 0 must work through the setup-style encode/decode (the
    Dropout submodule is declared in setup, not inline)."""
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    cfg = Seq2SeqConfig.tiny(attention="dot", dropout=0.1)
    rng = np.random.default_rng(4)
    batch = {
        "inputs": jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32),
    }
    m = EncoderDecoder(cfg)
    vs = m.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        batch, train=True,
    )
    out = m.apply(vs, batch, train=True,
                  rngs={"dropout": jax.random.PRNGKey(2)})
    assert bool(jnp.isfinite(out["logits"]).all())


@pytest.mark.parametrize("policy", ["nothing", "dots", "dots_no_batch"])
def test_transformer_remat_policies(devices, policy):
    """Every remat policy produces the same (finite, decreasing) training
    as plain remat — the policy only changes the recompute/memory trade."""
    runtime = rt.Runtime()
    cfg = TransformerConfig.tiny(remat=True, remat_policy=policy)
    mod = _train_module(TransformerLM(cfg), lm_cross_entropy(), runtime)
    losses = _run_steps(mod, _lm_batch(B=4, S=64), n=3)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    mod.destroy()


def test_transformer_remat_policy_unknown_rejected(devices):
    cfg = TransformerConfig.tiny(remat=True, remat_policy="bogus")
    with pytest.raises(ValueError, match="remat_policy"):
        TransformerLM(cfg).init(
            jax.random.PRNGKey(0), _lm_batch(B=1, S=32)
        )


def test_transformer_remat_inside_pipeline_matches(devices):
    """remat composes with the pipeline (GPipe's backward otherwise holds
    every microbatch's activations): checkpointed stage fn must reproduce
    the unremat'd pipeline's loss AND parameter gradients exactly."""
    from rocket_tpu.models.objectives import lm_cross_entropy as lm_ce
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(pipe=2, data=4).build(jax.devices())
    base = dict(
        vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
        attention="dot", pipeline_microbatches=2,
    )
    batch = _lm_batch(vocab=64, B=4, S=16)
    results = {}
    with mesh_context(mesh):
        for remat in (False, True):
            cfg = TransformerConfig(**base, remat=remat,
                                    remat_policy="dots" if remat else "nothing")
            m = TransformerLM(cfg)
            if not results:
                vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))

            def loss(params, m=m):
                return lm_ce()(m.apply({"params": params}, batch, train=True))

            # jit is required: the remat'd per-layer unit inside the
            # pipeline (the cross-schedule bit-equality contract) cannot
            # be transposed eagerly inside shard_map — real training is
            # always jitted anyway
            value, grads = jax.jit(jax.value_and_grad(loss))(vs["params"])
            results[remat] = (float(value), grads)
    np.testing.assert_allclose(results[False][0], results[True][0], rtol=1e-6)
    flat_a = jax.tree_util.tree_leaves_with_path(results[False][1])
    flat_b = dict(jax.tree_util.tree_leaves_with_path(results[True][1]))
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_b[path]), atol=1e-5, rtol=1e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_lm_z_loss_parity_fused_vs_logits(devices):
    """z_loss on the fused path (token_lse from the model) equals z_loss
    computed from full logits — values AND parameter gradients."""
    base = dict(tie_embeddings=True, positions="learned", attention="dot")
    cfg = TransformerConfig.tiny(**base)
    cfg_f = TransformerConfig.tiny(fused_ce=True, **base)
    batch = _lm_batch(B=2, S=64)
    m, m_f = TransformerLM(cfg), TransformerLM(cfg_f)
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))
    loss_fn = lm_cross_entropy(z_loss=1e-3)

    def loss_logits(params):
        return loss_fn(m.apply({"params": params}, batch))

    def loss_fused(params):
        out = m_f.apply({"params": params}, batch)
        assert "token_lse" in out
        return loss_fn(out)

    l0, g0 = jax.value_and_grad(loss_logits)(vs["params"])
    l1, g1 = jax.value_and_grad(loss_fused)(vs["params"])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    flat1 = dict(jax.tree_util.tree_leaves_with_path(g1))
    for path, leaf in jax.tree_util.tree_leaves_with_path(g0):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat1[path]), atol=2e-5, rtol=1e-4,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


def test_lm_z_loss_penalizes_large_logits(devices):
    """The regularizer must grow with the softmax normalizer."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    tokens = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    plain = lm_cross_entropy()({"logits": logits, "tokens": tokens})
    reg = lm_cross_entropy(z_loss=1e-2)({"logits": logits, "tokens": tokens})
    reg_big = lm_cross_entropy(z_loss=1e-2)(
        {"logits": logits * 10.0, "tokens": tokens}
    )
    assert float(reg) > float(plain)
    assert float(reg_big) - float(
        lm_cross_entropy()({"logits": logits * 10.0, "tokens": tokens})
    ) > float(reg) - float(plain)


def test_seq2seq_fused_ce_matches_logits_path(devices):
    """Seq2seq fused_ce parity: loss (with z_loss) and grads equal the
    logits path, mirroring the LM family's contract."""
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    base = dict(attention="dot")
    cfg = Seq2SeqConfig.tiny(**base)
    cfg_f = Seq2SeqConfig.tiny(fused_ce=True, fused_ce_chunk=24, **base)
    rng = np.random.default_rng(5)
    batch = {
        "inputs": jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 10)), jnp.int32),
    }
    m, m_f = EncoderDecoder(cfg), EncoderDecoder(cfg_f)
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))
    loss_fn = lm_cross_entropy(tokens_key="targets", z_loss=1e-3)

    def loss_logits(params):
        return loss_fn(m.apply({"params": params}, batch))

    def loss_fused(params):
        out = m_f.apply({"params": params}, batch)
        assert "logits" not in out and "token_nll" in out
        return loss_fn(out)

    l0, g0 = jax.value_and_grad(loss_logits)(vs["params"])
    l1, g1 = jax.value_and_grad(loss_fused)(vs["params"])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    flat1 = dict(jax.tree_util.tree_leaves_with_path(g1))
    for path, leaf in jax.tree_util.tree_leaves_with_path(g0):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat1[path]), atol=2e-5, rtol=1e-4,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


def test_lm_ce_custom_logits_key_rejects_fused_default(devices):
    """A non-default logits_key targets a specific head; the fused-CE NLL
    (batch['token_nll']) would silently take precedence and score a
    different head — construction must fail unless nll_key=None."""
    import pytest

    with pytest.raises(ValueError, match="nll_key"):
        lm_cross_entropy(logits_key="aux_logits")
    # a coherent custom pairing (this head's OWN fused NLL) stays allowed
    fn_pair = lm_cross_entropy(logits_key="aux_logits", nll_key="aux_nll")
    paired = fn_pair({"aux_nll": jnp.full((2, 7), 0.5),
                      "tokens": jnp.zeros((2, 8), jnp.int32)})
    np.testing.assert_allclose(float(paired), 0.5, rtol=1e-6)
    # explicit opt-out is the supported logits-only spelling
    fn = lm_cross_entropy(logits_key="aux_logits", nll_key=None)
    logits = jnp.zeros((2, 8, 16), jnp.float32)
    tokens = jnp.zeros((2, 8), jnp.int32)
    out = fn({"aux_logits": logits, "tokens": tokens,
              "token_nll": jnp.full((2, 7), 99.0)})
    assert float(out) < 10.0  # scored aux_logits, not the planted NLL


def test_seq2seq_generate_rejects_overlong_encoder_input(devices):
    """Encoder inputs longer than max_seq would silently gather clamped
    learned position embeddings; generate_seq2seq must raise instead."""
    import pytest
    from rocket_tpu.models.generate import generate_seq2seq
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

    cfg = Seq2SeqConfig.tiny(positions="learned")
    m = EncoderDecoder(cfg)
    inputs = jnp.zeros((1, cfg.max_seq + 1), jnp.int32)
    batch = {"inputs": jnp.zeros((1, 4), jnp.int32),
             "targets": jnp.zeros((1, 4), jnp.int32)}
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), batch))
    with pytest.raises(ValueError, match="encoder inputs"):
        generate_seq2seq(m, vs, inputs, max_new_tokens=2, bos_id=1)


def test_top_p_nucleus_sampling(devices):
    """top_p keeps exactly the smallest prefix of the sorted distribution
    with cumulative mass >= p; everything outside never samples."""
    from rocket_tpu.models.generate import _sample

    # masses: .5, .25, .125, .0625, .0625  (index order 0..4)
    base = jnp.log(jnp.asarray([[0.5, 0.25, 0.125, 0.0625, 0.0625]]))
    rngs = jax.random.split(jax.random.PRNGKey(0), 300)
    # p=0.6: nucleus = {0, 1} (0.5 < 0.6, 0.5+0.25 >= 0.6)
    toks = np.asarray([
        int(_sample(base, r, 1.0, None, top_p=0.6)[0]) for r in rngs[:150]
    ])
    assert set(toks) <= {0, 1}, set(toks)
    assert {0, 1} <= set(toks)  # both in-nucleus tokens actually occur
    # p=1.0: full distribution survives
    toks_full = np.asarray([
        int(_sample(base, r, 1.0, None, top_p=1.0)[0]) for r in rngs[150:]
    ])
    assert len(set(toks_full)) >= 4
    # tiny p: degenerates to argmax-only support
    toks_tiny = np.asarray([
        int(_sample(base, r, 1.0, None, top_p=1e-6)[0]) for r in rngs[:50]
    ])
    assert set(toks_tiny) == {0}
    # composes with top_k (k truncates first)
    toks_k = np.asarray([
        int(_sample(base, r, 1.0, 1, top_p=1.0)[0]) for r in rngs[:50]
    ])
    assert set(toks_k) == {0}
    with pytest.raises(ValueError, match="top_p"):
        _sample(base, rngs[0], 1.0, None, top_p=1.5)


def test_generate_with_top_p_runs_under_jit(devices):
    from rocket_tpu.models.generate import generate

    cfg = TransformerConfig.tiny()
    m = TransformerLM(cfg)
    prompt = jnp.zeros((2, 4), jnp.int32)
    vs = nn.meta.unbox(m.init(jax.random.PRNGKey(0), {"tokens": prompt}))
    import functools

    fn = jax.jit(functools.partial(
        generate, m, max_new_tokens=5, temperature=0.9, top_p=0.9,
    ))
    out = fn(vs["params"], prompt, rng=jax.random.PRNGKey(1))
    assert out.shape == (2, 9)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < cfg.vocab_size))


class TestBeamSearch:
    def _model(self, seed=0):
        from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig

        cfg = Seq2SeqConfig.tiny()
        m = EncoderDecoder(cfg)
        batch = {"inputs": jnp.zeros((1, 6), jnp.int32),
                 "targets": jnp.zeros((1, 4), jnp.int32)}
        vs = nn.meta.unbox(m.init(jax.random.PRNGKey(seed), batch))
        return m, vs

    def test_beam_size_one_matches_greedy(self, devices):
        from rocket_tpu.models.generate import (
            beam_search_seq2seq, generate_seq2seq)

        m, vs = self._model()
        rng = np.random.default_rng(0)
        inputs = jnp.asarray(
            rng.integers(2, m.config.vocab_size, (3, 6)), jnp.int32)
        greedy = generate_seq2seq(m, vs, inputs, max_new_tokens=5, bos_id=1)
        # eos must be a token greedy never emitted, or the beam freezes
        # where greedy keeps going and the outputs legitimately differ
        emitted = set(np.asarray(greedy).ravel().tolist())
        eos = next(t for t in range(m.config.vocab_size - 1, -1, -1)
                   if t not in emitted)
        beam, _ = beam_search_seq2seq(
            m, vs, inputs, max_new_tokens=5, bos_id=1,
            eos_id=eos, beam_size=1,
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam))

    def test_beam_finds_better_path_than_greedy(self, devices):
        """The classic beam-search win, on a hand-crafted duck-typed
        model: greedy takes the locally-best first token into a uniform
        dead end; a width-2 beam keeps the runner-up whose continuation
        is peaked, and must return the higher-scoring sequence."""
        import dataclasses

        from rocket_tpu.models.generate import beam_search_seq2seq

        V = 8

        @dataclasses.dataclass
        class Cfg:
            vocab_size: int = V
            max_seq: int = 16
            positions: str = "rope"

        class TrapModel:
            config = Cfg()

            def apply(self, variables, *args, method=None):
                if method == "encode":
                    inputs = args[0]
                    return jnp.zeros((inputs.shape[0], 1, 4))
                buf = args[0]  # [B', T]
                Bp, T = buf.shape
                # step logits depend on the PREVIOUS token:
                # after BOS(1): token 2 -> logp ~ log .4 (trap),
                #               token 3 -> logp ~ log .35
                # after 2: uniform (dead end); after 3: peaked on 4 (.9)
                base = jnp.full((Bp, T, V), 0.0)
                prev = buf
                after_bos = jnp.asarray(
                    [0., 0., jnp.log(.4) + 10, jnp.log(.35) + 10]
                    + [0.] * (V - 4))
                after3 = jnp.zeros(V).at[4].set(5.0)
                logits = jnp.where(
                    (prev == 1)[:, :, None], after_bos[None, None],
                    jnp.where((prev == 3)[:, :, None],
                              after3[None, None], base),
                )
                return logits

        tokens, score = beam_search_seq2seq(
            TrapModel(), {"params": {}}, jnp.zeros((1, 3), jnp.int32),
            max_new_tokens=2, bos_id=1, eos_id=V - 1, beam_size=2,
            length_penalty=0.0,
        )
        toks = np.asarray(tokens)[0]
        # greedy would pick 2 (the trap); the beam must return 3 -> 4
        np.testing.assert_array_equal(toks, [1, 3, 4])
        assert np.isfinite(float(score[0]))

    def test_beam_score_matches_manual_logprob(self, devices):
        """The returned score must equal the sum of per-step log-probs of
        the returned sequence under the model (length_penalty=0)."""
        from rocket_tpu.models.generate import beam_search_seq2seq

        m, vs = self._model(seed=5)
        rng = np.random.default_rng(2)
        inputs = jnp.asarray(
            rng.integers(2, m.config.vocab_size, (2, 6)), jnp.int32)
        T = 4
        eos = m.config.vocab_size - 1
        tokens, score = beam_search_seq2seq(
            m, vs, inputs, max_new_tokens=T, bos_id=1, eos_id=eos,
            beam_size=4, length_penalty=0.0,
        )
        logits = m.apply({"params": vs["params"]}, np.asarray(tokens),
                         m.apply({"params": vs["params"]}, inputs, None,
                                 False, method="encode"),
                         None, False, method="decode")
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        toks = np.asarray(tokens)
        for b in range(toks.shape[0]):
            total, done = 0.0, False
            for t in range(T):
                nxt = toks[b, t + 1]
                if done:
                    assert nxt == 0  # frozen beams pad after eos
                    continue
                total += float(logp[b, t, nxt])
                if nxt == eos:
                    done = True
            np.testing.assert_allclose(total, float(score[b]), rtol=1e-4)

    def test_beam_eos_freezes_and_pads(self, devices):
        """Declare greedy's first token to BE eos: the best beam finishes
        at step one and stays padded thereafter."""
        from rocket_tpu.models.generate import (
            beam_search_seq2seq, generate_seq2seq)

        m, vs = self._model()
        inputs = jnp.ones((1, 6), jnp.int32)
        greedy = generate_seq2seq(m, vs, inputs, max_new_tokens=4, bos_id=1)
        eos = int(np.asarray(greedy)[0, 1])  # the model's favorite token
        tokens, _ = beam_search_seq2seq(
            m, vs, inputs, max_new_tokens=4, bos_id=1,
            eos_id=eos, beam_size=1,
        )
        toks = np.asarray(tokens)[0]
        assert toks[1] == eos and np.all(toks[2:] == 0), toks


def _spec_batched_setup(seed=0, B=4, P=8, vocab=64, draft_differs=True):
    """Tiny target+draft pair for the batched speculative tests.

    max_seq carries the n_draft slack speculative_generate_batched
    requires (the verify chunk writes past a nearly-finished row)."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=vocab, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    draft_cfg = TransformerConfig(
        vocab_size=vocab, hidden=16, n_layers=1, n_heads=2, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    ) if draft_differs else cfg
    prompt = jnp.asarray(
        np.random.default_rng(seed).integers(0, vocab, size=(B, P)),
        jnp.int32,
    )
    model, draft = TransformerLM(cfg), TransformerLM(draft_cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    draft_params = params if not draft_differs else nn.meta.unbox(
        draft.init(jax.random.PRNGKey(2), {"tokens": prompt})["params"]
    )
    return model, params, draft, draft_params, prompt


def test_speculative_batched_matches_plain_greedy(devices):
    """The batched device-side decoder carries the same exactness
    contract as the host loop — every row of a B>1 batch must equal
    plain greedy decoding, whatever the (disagreeing) draft proposes
    and however unevenly rows accept."""
    from rocket_tpu.models.generate import (
        generate, speculative_generate_batched)

    model, params, draft, draft_params, prompt = _spec_batched_setup(B=8)
    want = np.asarray(
        generate(model, params, prompt, max_new_tokens=17, temperature=0.0)
    )
    for n_draft in (1, 3, 4):
        got, stats = speculative_generate_batched(
            model, params, draft, draft_params, prompt,
            max_new_tokens=17, n_draft=n_draft, return_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        assert stats["drafted"].shape == (8,)
        assert np.all(stats["accepted"] <= stats["drafted"])


def test_speculative_batched_perfect_draft(devices):
    """Target drafting for itself accepts every proposal in every round
    — catches per-row cache corruption that output exactness alone
    cannot (the target re-verifies everything)."""
    from rocket_tpu.models.generate import (
        generate, speculative_generate_batched)

    model, params, _, _, prompt = _spec_batched_setup(
        B=4, draft_differs=False)
    want = np.asarray(
        generate(model, params, prompt, max_new_tokens=12, temperature=0.0)
    )
    got, stats = speculative_generate_batched(
        model, params, model, params, prompt, max_new_tokens=12,
        n_draft=4, return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert np.array_equal(stats["accepted"], stats["drafted"]), stats
    # 11 post-prefill tokens at 5 per round -> exactly 3 rounds, no row
    # should drag the others further
    assert stats["rounds"] == 3, stats


def test_speculative_batched_eos_matches_generate_eos(devices):
    """Per-row eos freezing: rows hit eos at different steps; each must
    match generate()'s fixed-length eos contract exactly."""
    from rocket_tpu.models.generate import (
        generate, speculative_generate_batched)

    model, params, draft, draft_params, prompt = _spec_batched_setup(B=8)
    free = np.asarray(
        generate(model, params, prompt, max_new_tokens=16, temperature=0.0)
    )
    # pick an eos some rows actually emit mid-stream (row 0's 4th token)
    eos = int(free[0, 8 + 3])
    want = np.asarray(
        generate(model, params, prompt, max_new_tokens=16, temperature=0.0,
                 eos_token=eos)
    )
    got = np.asarray(speculative_generate_batched(
        model, params, draft, draft_params, prompt, max_new_tokens=16,
        n_draft=4, eos_token=eos,
    ))
    np.testing.assert_array_equal(got, want)
    assert np.any(got[0, 8:] == eos)


def test_speculative_batched_validation(devices):
    from rocket_tpu.models.generate import speculative_generate_batched

    model, params, draft, draft_params, prompt = _spec_batched_setup(B=2)
    with pytest.raises(ValueError, match="n_draft"):
        speculative_generate_batched(
            model, params, draft, draft_params, prompt, 4, n_draft=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        speculative_generate_batched(
            model, params, draft, draft_params, prompt, 0)
    # max_seq=64, P=8: max_new 53 + n_draft 4 > 64 - the slack must be
    # rejected loudly, not clamp-corrupt the cache
    with pytest.raises(ValueError, match="max_seq"):
        speculative_generate_batched(
            model, params, draft, draft_params, prompt, 53, n_draft=4)


def test_accept_resample_rows_marginal_matches_host_core(devices):
    """The device-side vectorized accept/resample must realize the same
    speculative-sampling theorem as the host core: the round's first
    emitted token is distributed exactly per the target's p, whatever q.
    One vectorized call over N rows replaces the host's N-trial loop."""
    from rocket_tpu.models.generate import _accept_resample_rows

    rng = np.random.default_rng(0)
    V, k, N = 6, 2, 20_000
    p0 = np.array([0.35, 0.05, 0.2, 0.1, 0.25, 0.05])
    p1 = np.array([0.1, 0.3, 0.1, 0.2, 0.2, 0.1])
    p2 = np.array([0.4, 0.1, 0.1, 0.1, 0.2, 0.1])
    q0 = np.array([0.1, 0.4, 0.1, 0.2, 0.1, 0.1])  # very unlike p0
    q1 = np.array([0.2, 0.2, 0.2, 0.2, 0.1, 0.1])
    p_rows = jnp.asarray(
        np.broadcast_to(np.stack([p0, p1, p2]), (N, k + 1, V)), jnp.float32
    )
    q_rows = jnp.asarray(
        np.broadcast_to(np.stack([q0, q1]), (N, k, V)), jnp.float32
    )
    drafts = jnp.asarray(np.stack(
        [rng.choice(V, size=N, p=q0), rng.choice(V, size=N, p=q1)], axis=1
    ), jnp.int32)
    j, tok = jax.jit(_accept_resample_rows)(
        p_rows, q_rows, drafts, jax.random.PRNGKey(1)
    )
    first = np.where(np.asarray(j) >= 1, np.asarray(drafts[:, 0]),
                     np.asarray(tok))
    counts = np.bincount(first, minlength=V)
    tv = 0.5 * np.abs(counts / N - p0).sum()
    assert tv < 0.03, (tv, counts / N)


def test_speculative_sample_batched_contracts(devices):
    """End-to-end batched sampling: reproducible per key, in-vocab,
    identical draft accepts everything, eos tail frozen."""
    from rocket_tpu.models.generate import speculative_sample_batched

    model, params, draft, draft_params, prompt = _spec_batched_setup(B=4)
    out, stats = speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.8, rng=jax.random.PRNGKey(7), return_stats=True,
    )
    o = np.asarray(out)
    assert o.shape == (4, 20) and (o >= 0).all() and (o < 64).all()
    assert np.all(stats["accepted"] <= stats["drafted"])
    again = speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.8, rng=jax.random.PRNGKey(7),
    )
    np.testing.assert_array_equal(np.asarray(again), o)

    # p == q: min(1, p/q) = 1 — every proposal accepted in every round
    _, s2 = speculative_sample_batched(
        model, params, model, params, prompt, 12, n_draft=4,
        temperature=1.0, rng=jax.random.PRNGKey(3), return_stats=True,
    )
    assert np.array_equal(s2["accepted"], s2["drafted"]), s2

    # eos: prefix through the first eos, frozen all-eos tail after
    eos = int(o[0, 8 + 2])
    got = np.asarray(speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.8, rng=jax.random.PRNGKey(7), eos_token=eos,
    ))
    for row in range(4):
        hits = np.nonzero(got[row, 8:] == eos)[0]
        if hits.size:
            assert np.all(got[row, 8 + hits[0]:] == eos)

    with pytest.raises(ValueError, match="temperature"):
        speculative_sample_batched(
            model, params, draft, draft_params, prompt, 4, temperature=0.0)


def test_generate_under_tensor_sharded_params(devices):
    """Serving under GSPMD: generate() and the batched speculative
    decoder must run with params laid out over a tensor-parallel mesh
    (the multi-chip serving scenario) and reproduce the single-device
    outputs exactly."""
    from rocket_tpu.models.generate import (
        generate, speculative_generate_batched)
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.mesh import MeshSpec
    from rocket_tpu.parallel.sharding import DEFAULT_RULES

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, 64, size=(4, 8)), jnp.int32
    )
    model = TransformerLM(cfg)
    boxed = model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    params = nn.meta.unbox(boxed)
    want = np.asarray(generate(model, params, prompt, 12, temperature=0.0))

    mesh = MeshSpec(tensor=2, data=4).build(jax.devices())
    logical = nn.get_partition_spec(boxed)
    shardings = jax.tree_util.tree_map(
        lambda spec: jax.NamedSharding(
            mesh,
            DEFAULT_RULES.spec(*spec)
            if isinstance(spec, jax.sharding.PartitionSpec)
            else jax.sharding.PartitionSpec(),
        ),
        logical,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )
    sharded_params = jax.device_put(params, shardings)
    # at least one leaf must actually be split over the tensor axis
    assert any(
        not s.is_fully_replicated
        for s in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda a: a.sharding, sharded_params)
        )
    )
    with mesh_context(mesh):
        got = np.asarray(
            generate(model, sharded_params, prompt, 12, temperature=0.0)
        )
        np.testing.assert_array_equal(got, want)
        spec = np.asarray(speculative_generate_batched(
            model, sharded_params, model, sharded_params, prompt, 12,
            n_draft=4,
        ))
    np.testing.assert_array_equal(spec, want)


def test_sliding_window_decode_matches_full_forward(devices):
    """A TransformerLM with attention_window must generate the same
    greedy tokens through the KV-cache decode path as through repeated
    full (train-path) forwards — generation beyond the window included."""
    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=40,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
        attention_window=4,
    )
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, size=(2, 6)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    new = 20  # far past the window of 4

    toks = prompt
    for _ in range(new):  # ground truth: full windowed forward each step
        out = model.apply({"params": params}, {"tokens": toks}, train=False)
        nxt = jnp.argmax(out["logits"][:, -1], axis=-1).astype(jnp.int32)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)

    got = generate(model, params, prompt, new, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(toks))

    # mistral preset carries the window
    assert TransformerConfig.mistral_7b().attention_window == 4096


def test_speculative_batched_with_sliding_window(devices):
    """The batched decoder's per-row cache masking must compose with
    attention_window: windowed batched speculative decode stays
    bit-exact vs windowed plain greedy, past the window length."""
    from rocket_tpu.models.generate import (
        generate, speculative_generate_batched)
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    kw = dict(norm="layernorm", mlp="gelu", positions="learned",
              tie_embeddings=True, use_bias=True, attention="dot",
              attention_window=4)
    cfg = TransformerConfig(vocab_size=64, hidden=32, n_layers=2,
                            n_heads=4, max_seq=48, **kw)
    dcfg = TransformerConfig(vocab_size=64, hidden=16, n_layers=1,
                             n_heads=2, max_seq=48, **kw)
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 64, size=(4, 6)), jnp.int32
    )
    model, draft = TransformerLM(cfg), TransformerLM(dcfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    draft_params = nn.meta.unbox(
        draft.init(jax.random.PRNGKey(2), {"tokens": prompt})["params"]
    )
    want = np.asarray(
        generate(model, params, prompt, 20, temperature=0.0)
    )
    got = speculative_generate_batched(
        model, params, draft, draft_params, prompt, 20, n_draft=3,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_rolling_kv_cache_matches_full_cache(devices):
    """decode_rolling_cache: O(window) serving memory with bit-exact
    outputs.  Multi-layer, prompt and generation both far past the
    window — the chunked prefill plus slot-position masking must
    reproduce the full-cache windowed decode exactly, greedy and
    sampled, plain and through the batched speculative decoder."""
    import dataclasses

    from rocket_tpu.models.generate import (
        decode_cache_shapes, generate, speculative_generate_batched)
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    kw = dict(norm="layernorm", mlp="gelu", positions="learned",
              tie_embeddings=True, use_bias=True, attention="dot",
              attention_window=8)
    cfg = TransformerConfig(vocab_size=64, hidden=32, n_layers=2,
                            n_heads=4, max_seq=96, **kw)
    roll = dataclasses.replace(
        cfg, decode_rolling_cache=True, decode_rolling_slack=8)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(3, 20)), jnp.int32
    )
    model, rmodel = TransformerLM(cfg), TransformerLM(roll)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )

    want = np.asarray(generate(model, params, prompt, 40, temperature=0.0))
    got = np.asarray(generate(rmodel, params, prompt, 40, temperature=0.0))
    np.testing.assert_array_equal(got, want)

    # sampled path: chunked prefill must not perturb the rng stream
    key = jax.random.PRNGKey(9)
    w_s = np.asarray(generate(model, params, prompt, 24, rng=key,
                              temperature=0.9, top_k=20))
    g_s = np.asarray(generate(rmodel, params, prompt, 24, rng=key,
                              temperature=0.9, top_k=20))
    np.testing.assert_array_equal(g_s, w_s)

    # the whole point: window+slack slots, not max_seq
    shapes = decode_cache_shapes(rmodel, params, prompt)
    slots = {a.shape[1] for a in jax.tree_util.tree_leaves(shapes)
             if a.ndim == 4}
    assert slots == {16}, slots

    # batched speculative decode over rolling caches stays bit-exact
    droll = dataclasses.replace(roll, hidden=16, n_heads=2, n_layers=1)
    draft = TransformerLM(droll)
    dparams = nn.meta.unbox(
        draft.init(jax.random.PRNGKey(2), {"tokens": prompt})["params"]
    )
    spec = np.asarray(speculative_generate_batched(
        rmodel, params, draft, dparams, prompt, 40, n_draft=3))
    np.testing.assert_array_equal(spec, want)


def test_rolling_kv_cache_validation(devices):
    import dataclasses

    from rocket_tpu.models.generate import speculative_generate_batched
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    with pytest.raises(ValueError, match="decode_rolling_cache"):
        TransformerConfig(decode_rolling_cache=True)  # no window

    kw = dict(norm="layernorm", mlp="gelu", positions="learned",
              tie_embeddings=True, use_bias=True, attention="dot",
              attention_window=8, decode_rolling_cache=True,
              decode_rolling_slack=2)
    cfg = TransformerConfig(vocab_size=64, hidden=16, n_layers=1,
                            n_heads=2, max_seq=64, **kw)
    model = TransformerLM(cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    # a verify chunk of n_draft+1=4 > slack 2 must be rejected up front
    with pytest.raises(ValueError, match="decode_rolling_slack"):
        speculative_generate_batched(
            model, params, model, params, prompt, 8, n_draft=3)


def test_speculative_sample_batched_topk_and_nucleus(devices):
    """Truncated-distribution speculative sampling: top_k=1 collapses
    to greedy (must equal generate temperature=0 exactly); top_k/top_p
    runs stay reproducible and in-vocab; identical draft still accepts
    everything under the same truncation."""
    from rocket_tpu.models.generate import (
        generate, speculative_sample_batched)

    model, params, draft, draft_params, prompt = _spec_batched_setup(B=4)
    want = np.asarray(generate(model, params, prompt, 12, temperature=0.0))
    got = np.asarray(speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.7, top_k=1, rng=jax.random.PRNGKey(5),
    ))
    np.testing.assert_array_equal(got, want)

    out, stats = speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.8, top_k=8, top_p=0.9, rng=jax.random.PRNGKey(6),
        return_stats=True,
    )
    o = np.asarray(out)
    assert (o >= 0).all() and (o < 64).all()
    again = np.asarray(speculative_sample_batched(
        model, params, draft, draft_params, prompt, 12, n_draft=3,
        temperature=0.8, top_k=8, top_p=0.9, rng=jax.random.PRNGKey(6),
    ))
    np.testing.assert_array_equal(again, o)

    _, s2 = speculative_sample_batched(
        model, params, model, params, prompt, 12, n_draft=4,
        temperature=1.0, top_k=8, rng=jax.random.PRNGKey(3),
        return_stats=True,
    )
    assert np.array_equal(s2["accepted"], s2["drafted"]), s2

    with pytest.raises(ValueError, match="top_p"):
        speculative_sample_batched(
            model, params, draft, draft_params, prompt, 4,
            temperature=0.8, top_p=1.5)


def test_beam_search_decoder_only(devices):
    """Decoder-only beam search: beam_size=1 must reproduce greedy
    generate() exactly; wider beams return a (length-normalized) score
    at least as good as the greedy chain's, freeze at eos, and respect
    sliding-window configs (full forwards carry the same masking)."""
    from rocket_tpu.models.generate import beam_search, generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(13).integers(0, 64, size=(3, 8)), jnp.int32
    )
    model = TransformerLM(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(1), {"tokens": prompt})["params"]
    )
    greedy = np.asarray(generate(model, params, prompt, 12, temperature=0.0))
    # an eos the greedy chains never emit, so beam_size=1 (greedy by
    # construction) cannot diverge via early freezing on any platform
    eos = next(v for v in range(64) if v not in set(greedy[:, 8:].ravel()))

    b1, s1 = beam_search(model, params, prompt, 12, eos_id=eos,
                         beam_size=1, length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(b1), greedy)

    b4, s4 = beam_search(model, params, prompt, 12, eos_id=eos,
                         beam_size=4, length_penalty=0.0)
    assert b4.shape == (3, 20)
    assert np.all(np.isfinite(np.asarray(s4)))

    # eos freezing: force an eos the model actually emits mid-stream
    free_eos = int(greedy[0, 8 + 2])
    bt, _ = beam_search(model, params, prompt, 12, eos_id=free_eos,
                        beam_size=2)
    row = np.asarray(bt)[0, 8:]
    hits = np.nonzero(row == free_eos)[0]
    if hits.size:
        assert np.all(row[hits[0] + 1:] == 0)  # pad after the first eos

    with pytest.raises(ValueError, match="beam_size"):
        beam_search(model, params, prompt, 4, eos_id=eos, beam_size=0)
