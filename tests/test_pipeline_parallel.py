"""GPipe pipeline-parallel tests (parallel/pipeline.py): forward and
gradient equivalence vs the sequential layer stack on the 8-fake-device
mesh, with real ppermute scheduling over the 'pipe' axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.parallel.mesh import MeshSpec
from rocket_tpu.parallel.pipeline import (
    SCHEDULES,
    _chunk_apply,
    gpipe,
    interleave_order,
    pipeline,
    schedule_plan,
)


def _layer(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _stack(rng, n_layers, width):
    keys = jax.random.split(rng, n_layers)
    return {
        "w": jnp.stack([
            jax.random.normal(k, (width, width)) * 0.3 for k in keys
        ]),
        "b": jnp.zeros((n_layers, width)),
    }


@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (2, 3), (8, 2)])
def test_gpipe_matches_sequential_forward(devices, n_stages, n_micro):
    mesh = MeshSpec(pipe=n_stages, data=8 // n_stages).build(devices)
    width, micro_b, n_layers = 16, 4, 2 * n_stages
    rng = jax.random.PRNGKey(0)
    params = _stack(rng, n_layers, width)
    xs = jax.random.normal(jax.random.PRNGKey(1), (n_micro, micro_b, width))

    expected = _chunk_apply(_layer, params, xs)
    got = gpipe(_layer, params, xs, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_gpipe_gradients_match_sequential(devices):
    """jax.grad through the pipeline (ppermute transposes to the reverse
    rotation) equals the sequential gradient — training through a pipeline
    needs no hand-written backward schedule."""
    mesh = MeshSpec(pipe=4, data=2).build(devices)
    width, n_micro, micro_b, n_layers = 8, 4, 2, 8
    params = _stack(jax.random.PRNGKey(0), n_layers, width)
    xs = jax.random.normal(jax.random.PRNGKey(1), (n_micro, micro_b, width))
    target = jax.random.normal(jax.random.PRNGKey(2), xs.shape)

    def loss_pipe(p):
        return jnp.mean((gpipe(_layer, p, xs, mesh=mesh) - target) ** 2)

    def loss_seq(p):
        return jnp.mean((_chunk_apply(_layer, p, xs) - target) ** 2)

    # jit is required: the remat'd per-layer unit inside _chunk_apply
    # (the cross-schedule bit-equality contract) cannot be transposed
    # eagerly inside shard_map — real training is always jitted anyway
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_gpipe_single_stage_degenerates(devices):
    """pipe=1 falls back to the plain sequential scan (mesh degradation
    contract: size-1 axes are free)."""
    mesh = MeshSpec(data=8).build(devices)
    params = _stack(jax.random.PRNGKey(0), 4, 8)
    xs = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 8))
    np.testing.assert_allclose(
        np.asarray(gpipe(_layer, params, xs, mesh=mesh)),
        np.asarray(_chunk_apply(_layer, params, xs)),
        atol=1e-6,
    )


def test_gpipe_rejects_indivisible_layers(devices):
    mesh = MeshSpec(pipe=4, data=2).build(devices)
    params = _stack(jax.random.PRNGKey(0), 6, 8)  # 6 % 4 != 0
    xs = jnp.zeros((2, 2, 8))
    with pytest.raises(ValueError, match="divisible"):
        gpipe(_layer, params, xs, mesh=mesh)


def test_transformer_pipeline_matches_sequential(devices):
    """TransformerLM(pipeline_microbatches=4) over pipe=2 produces the SAME
    logits as the scan-stacked sequential model with transplanted params."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.sharding import DEFAULT_RULES

    mesh = MeshSpec(pipe=2, data=4).build(devices)
    base = dict(vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
                attention="dot")
    cfg_pipe = TransformerConfig(**base, pipeline_microbatches=4)
    cfg_seq = TransformerConfig(**base, scan_layers=True)
    batch = {
        "tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
        )
    }
    model_pipe = TransformerLM(cfg_pipe)
    model_seq = TransformerLM(cfg_seq)
    with mesh_context(mesh, DEFAULT_RULES):
        vars_pipe = model_pipe.init(jax.random.PRNGKey(0), batch, train=False)
        params_pipe = flax_unbox(vars_pipe["params"])
        # transplant: pipeline/blocks <-> blocks, rest identical
        params_seq = dict(params_pipe)
        params_seq["blocks"] = params_seq.pop("pipeline")["blocks"]
        out_pipe = model_pipe.apply({"params": params_pipe}, batch, train=False)
        out_seq = model_seq.apply({"params": params_seq}, batch, train=False)
    np.testing.assert_allclose(
        np.asarray(out_pipe["logits"]),
        np.asarray(out_seq["logits"]),
        atol=2e-4,
    )


def flax_unbox(tree):
    import flax.linen as nn

    return nn.meta.unbox(tree)


def test_transformer_pipeline_trains_through_module(devices):
    """Full framework path: jitted train step with dp x pp sharding; loss
    finite and decreasing, layer params sharded over 'pipe'."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    runtime = rt.Runtime(mesh=MeshSpec(pipe=2, data=4))
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
        attention="dot", pipeline_microbatches=2,
    )
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(learning_rate=1e-2),
        ],
    )
    mod.bind(runtime)
    mod.setup()
    batch = jax.device_put(
        {"tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
        )},
        runtime.batch_sharding(ndim=2),
    )
    attrs_proto = dict(looper=None)
    import rocket_tpu as rt2

    attrs = rt2.Attributes(
        looper=rt2.Attributes(grad_enabled=True, state=rt2.Attributes())
    )
    losses = []
    for _ in range(5):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["lm"]))
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]
    stage_specs = {
        jax.tree_util.keystr(p): str(leaf.sharding.spec)
        for p, leaf in jax.tree_util.tree_leaves_with_path(mod.state.params)
        if "pipeline" in jax.tree_util.keystr(p)
    }
    assert stage_specs and all(
        s.startswith("PartitionSpec('pipe'") for s in stage_specs.values()
    ), stage_specs
    mod.destroy()


def test_transformer_pipeline_packed_positions_and_segments(devices):
    """Per-example positions + segment_ids (packed sequences) flow through
    the pipeline rotation with their microbatch — logits match the
    sequential stack given identical params."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.sharding import DEFAULT_RULES

    mesh = MeshSpec(pipe=2, data=4).build(devices)
    base = dict(vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
                attention="dot")
    cfg_pipe = TransformerConfig(**base, pipeline_microbatches=4)
    cfg_seq = TransformerConfig(**base, scan_layers=True)
    rng = np.random.default_rng(0)
    B, S = 8, 16
    # two packed documents per row: positions restart at the boundary
    bounds = rng.integers(4, 12, size=B)
    positions = np.zeros((B, S), np.int32)
    segment_ids = np.zeros((B, S), np.int32)
    for i, c in enumerate(bounds):
        positions[i, :c] = np.arange(c)
        positions[i, c:] = np.arange(S - c)
        segment_ids[i, c:] = 1
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, size=(B, S)), jnp.int32),
        "positions": jnp.asarray(positions),
        "segment_ids": jnp.asarray(segment_ids),
    }
    model_pipe = TransformerLM(cfg_pipe)
    model_seq = TransformerLM(cfg_seq)
    with mesh_context(mesh, DEFAULT_RULES):
        vars_pipe = model_pipe.init(jax.random.PRNGKey(0), batch, train=False)
        params_pipe = flax_unbox(vars_pipe["params"])
        params_seq = dict(params_pipe)
        params_seq["blocks"] = params_seq.pop("pipeline")["blocks"]
        out_pipe = model_pipe.apply({"params": params_pipe}, batch, train=False)
        out_seq = model_seq.apply({"params": params_seq}, batch, train=False)
    np.testing.assert_allclose(
        np.asarray(out_pipe["logits"]),
        np.asarray(out_seq["logits"]),
        atol=2e-4,
    )


def test_transformer_pipeline_degrades_on_pipe1_mesh(devices):
    """pipeline_microbatches>0 on a pipe=1 mesh runs the degraded per-
    microbatch sequential path and still matches the scan stack."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.sharding import DEFAULT_RULES

    mesh = MeshSpec(data=8).build(devices)
    base = dict(vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
                attention="dot")
    cfg_pipe = TransformerConfig(**base, pipeline_microbatches=2)
    cfg_seq = TransformerConfig(**base, scan_layers=True)
    batch = {
        "tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
        )
    }
    model_pipe = TransformerLM(cfg_pipe)
    model_seq = TransformerLM(cfg_seq)
    with mesh_context(mesh, DEFAULT_RULES):
        vars_pipe = model_pipe.init(jax.random.PRNGKey(0), batch, train=False)
        params_pipe = flax_unbox(vars_pipe["params"])
        params_seq = dict(params_pipe)
        params_seq["blocks"] = params_seq.pop("pipeline")["blocks"]
        out_pipe = model_pipe.apply({"params": params_pipe}, batch, train=False)
        out_seq = model_seq.apply({"params": params_seq}, batch, train=False)
    np.testing.assert_allclose(
        np.asarray(out_pipe["logits"]),
        np.asarray(out_seq["logits"]),
        atol=2e-4,
    )


def test_transformer_pipeline_composes_with_fsdp_tensor(devices):
    """pipe=2 x fsdp=2 x tensor=2 in ONE mesh: the pipelined transformer
    still matches the sequential stack (constrain() degrades inside the
    manual gpipe region instead of crashing), and trains through Module."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    runtime = rt.Runtime(mesh=MeshSpec(data=1, pipe=2, fsdp=2, tensor=2))
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
        ffn_dim=64, attention="dot", pipeline_microbatches=2,
    )
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(learning_rate=1e-2),
        ],
    )
    mod.bind(runtime)
    mod.setup()
    batch = jax.device_put(
        {"tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
        )},
        runtime.batch_sharding(ndim=2),
    )
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    losses = []
    for _ in range(5):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["lm"]))
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]
    mod.destroy()


def test_gpipe_batch_sharded_microbatches(devices):
    """Microbatches sharded over the data axes compose with the pipe axis
    (dp x pp in one program)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = MeshSpec(pipe=2, data=4).build(devices)
    params = _stack(jax.random.PRNGKey(0), 4, 8)
    xs = jax.random.normal(jax.random.PRNGKey(1), (3, 8, 8))
    xs_sharded = jax.device_put(xs, NamedSharding(mesh, P(None, ("data",))))
    got = gpipe(
        _layer, params, xs_sharded, mesh=mesh, xs_spec=P(("data",))
    )
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(_chunk_apply(_layer, params, xs)),
        atol=1e-5,
    )


def test_transformer_pipeline_with_fused_knobs(devices):
    """Pipeline parallelism composes with fused_qkv and fused_ce (the
    fused loss sits outside the pipelined block stack)."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    runtime = rt.Runtime(mesh=MeshSpec(pipe=2, data=4))
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
        attention="dot", pipeline_microbatches=2,
        tie_embeddings=True, fused_qkv=True, fused_ce=True, fused_ce_chunk=24,
    )
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(learning_rate=1e-2),
        ],
    )
    mod.bind(runtime)
    mod.setup()
    batch = jax.device_put(
        {"tokens": jnp.asarray(
            np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
        )},
        runtime.batch_sharding(ndim=2),
    )
    attrs = rt.Attributes(
        batch=batch,
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
    )
    losses = []
    for _ in range(4):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["lm"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    mod.destroy()


def _fuse_module(runtime, cfg, fuse, lr=1e-2):
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerLM

    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(learning_rate=lr),
        ],
        fuse_accumulation=fuse,
    )
    mod.bind(runtime)
    mod.setup()
    return mod


def _launch_batches(mod, batches):
    import rocket_tpu as rt

    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    logs = []
    for b in batches:
        attrs.batch = b
        mod.launch(attrs)
        logs.append(attrs.step_logs)
    return logs


def test_fused_window_matches_micro_sync(devices):
    """Module(fuse_accumulation=True): ONE jitted call over the buffered
    window must train identically to the micro/sync pair — including
    per-slice objective averaging when loss masks vary across the window
    (VERDICT r3 next #5 parity requirement)."""
    import rocket_tpu as rt
    from rocket_tpu.models.transformer import TransformerConfig

    rng = np.random.default_rng(7)
    base = dict(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=32,
        attention="dot",
    )
    # masks differ per batch: slice-equal weighting is observable
    batches = []
    for i in range(4):
        tokens = rng.integers(0, 64, size=(8, 16))
        mask = np.ones((8, 16), np.float32)  # [B, S]; loss shifts it
        mask[:, : 3 * (i + 1)] = 0.0
        batches.append({
            "tokens": jnp.asarray(tokens, jnp.int32),
            "loss_mask": jnp.asarray(mask),
        })

    params = {}
    for fuse in (False, True):
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8), gradient_accumulation_steps=2
        )
        cfg = TransformerConfig(**base)
        mod = _fuse_module(runtime, cfg, fuse)
        logs = _launch_batches(mod, batches)
        if fuse:
            # mid-window launches run nothing
            assert logs[0] is None and logs[2] is None
            assert logs[1].synced and logs[3].synced
        else:
            assert not logs[0].synced and logs[1].synced
        assert mod.step == 2  # two effective steps either way
        params[fuse] = jax.tree_util.tree_map(np.asarray, mod.state.params)
        mod.destroy()

    flat_a = jax.tree_util.tree_leaves_with_path(params[False])
    flat_b = dict(jax.tree_util.tree_leaves_with_path(params[True]))
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            leaf, flat_b[path], atol=1e-6, rtol=1e-5,
            err_msg=jax.tree_util.keystr(path),
        )


def test_fused_window_drives_pipeline_with_scaled_microbatches(devices):
    """pipe=2 x accum=2 as ONE schedule: pipeline_microbatch_size keeps
    microbatch rows constant while the fused window doubles the microbatch
    count through a single GPipe pass; training matches the unfused
    pipeline run."""
    import rocket_tpu as rt
    from rocket_tpu.models.transformer import TransformerConfig

    rng = np.random.default_rng(3)
    batches = [
        jax.device_put(
            {"tokens": jnp.asarray(
                rng.integers(0, 64, size=(8, 16)), jnp.int32)},
        )
        for _ in range(4)
    ]
    base = dict(
        vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
        attention="dot",
    )
    params = {}
    for fuse in (False, True):
        runtime = rt.Runtime(
            mesh=MeshSpec(pipe=2, data=4), gradient_accumulation_steps=2
        )
        cfg = TransformerConfig(**base, pipeline_microbatch_size=4)
        mod = _fuse_module(runtime, cfg, fuse)
        sharded = [
            jax.device_put(b, runtime.batch_sharding(ndim=2))
            for b in batches
        ]
        logs = _launch_batches(mod, sharded)
        final = [l for l in logs if l is not None and l.synced]
        assert len(final) == 2
        assert all(np.isfinite(float(l["lm"])) for l in final)
        assert mod.step == 2
        params[fuse] = jax.tree_util.tree_map(np.asarray, mod.state.params)
        mod.destroy()
    flat_a = jax.tree_util.tree_leaves_with_path(params[False])
    flat_b = dict(jax.tree_util.tree_leaves_with_path(params[True]))
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            leaf, flat_b[path], atol=2e-5, rtol=1e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_fused_window_loss_logging_not_rescaled(devices):
    """The Loss capsule must report the window mean once, NOT divide the
    already-averaged fused value by accum again (r4 review finding)."""
    import rocket_tpu as rt
    from rocket_tpu.models.transformer import TransformerConfig

    rng = np.random.default_rng(1)
    batches = [
        {"tokens": jnp.asarray(rng.integers(0, 64, size=(8, 16)), jnp.int32)}
        for _ in range(2)
    ]
    state_vals = {}
    for fuse in (False, True):
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8), gradient_accumulation_steps=2
        )
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=32,
            attention="dot",
        )
        mod = _fuse_module(runtime, cfg, fuse)
        attrs = None
        import rocket_tpu as rt2

        attrs = rt2.Attributes(
            looper=rt2.Attributes(grad_enabled=True, state=rt2.Attributes())
        )
        for b in batches:
            attrs.batch = b
            mod.launch(attrs)
        state_vals[fuse] = float(attrs.looper.state["lm"])
        mod.destroy()
    # both paths log the same window-mean loss (one optimizer step each)
    np.testing.assert_allclose(
        state_vals[True], state_vals[False], rtol=1e-5
    )
    assert state_vals[True] > 1.0  # ~ln(64); the halved value would be ~2


def test_fused_window_rejects_mutable_collections(devices):
    """BatchNorm-style mutables update once per window under fusion —
    reject at materialize instead of training with silently different
    statistics."""
    import flax.linen as nn
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import cross_entropy

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, batch, train: bool = False):
            x = nn.Dense(8)(batch["x"])
            x = nn.BatchNorm(use_running_average=not train)(x)
            out = rt.Attributes(batch)
            out["logits"] = nn.Dense(4)(x)
            return out

    runtime = rt.Runtime(mesh=MeshSpec(data=8), gradient_accumulation_steps=2)
    mod = rt.Module(
        BNNet(),
        capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                  rt.Optimizer(learning_rate=1e-2)],
        fuse_accumulation=True,
    )
    mod.bind(runtime)
    mod.setup()
    batch = {"x": jnp.zeros((8, 4), jnp.float32),
             "label": jnp.zeros((8,), jnp.int32)}
    with pytest.raises(RuntimeError, match="mutable"):
        mod.materialize(batch)
    mod.destroy()


def test_pipeline_knobs_mutually_exclusive_at_construction(devices):
    from rocket_tpu.models.transformer import TransformerConfig

    with pytest.raises(ValueError, match="mutually exclusive"):
        TransformerConfig(
            pipeline_microbatches=2, pipeline_microbatch_size=4
        )


def test_fused_window_resume_restarts_window(devices, tmp_path):
    """fuse_accumulation: a checkpoint landing mid-window resumes by
    RESTARTING the window (documented contract — no grad_accum buffer
    exists to checkpoint); step counters stay consistent and training
    continues."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    rng = np.random.default_rng(0)
    data = {"tokens": rng.integers(0, 64, size=(64, 16)).astype(np.int32)}
    cfg_kw = dict(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=32,
        attention="dot",
    )

    def tree(epochs, resume=None):
        model = rt.Module(
            TransformerLM(TransformerConfig(**cfg_kw)),
            capsules=[rt.Loss(lm_cross_entropy(), name="lm"),
                      rt.Optimizer(learning_rate=1e-2)],
            fuse_accumulation=True,
        )
        looper = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=8, shuffle=True,
                           seed=5),
                model,
                # save_every=3 deliberately MISALIGNED with accum=2: the
                # snapshot at iter 2 lands mid-window
                rt.Checkpointer(save_every=3),
            ],
            progress=False,
        )
        launcher = rt.Launcher(
            capsules=[looper], tag="fw", num_epochs=epochs,
            project_root=str(tmp_path),
            gradient_accumulation_steps=2,
        )
        if resume:
            launcher.resume(resume)
        return launcher, model

    launcher, model = tree(epochs=1)
    launcher.launch()
    # 8 launches -> 4 effective steps; snapshots at iters 2 and 5
    assert model.step == 4
    ckpts = sorted((tmp_path / "fw" / "v0" / "weights").iterdir())
    assert [c.name for c in ckpts] == ["000002", "000005"]

    # resume from the MID-WINDOW snapshot (iter 2 = 1 effective step + 1
    # buffered launch that the snapshot could not capture)
    launcher2, model2 = tree(epochs=2, resume=str(ckpts[0]))
    launcher2.launch()
    # the partial window restarted: remaining launches of epoch 0 form
    # fresh windows; training completed both epochs with a sane count
    assert model2.step > model.step
    assert model2._window_buffer == []  # nothing stranded

# -- schedule-parameterized engine: 1F1B + interleaved ----------------------


def _sched_kwargs(schedule):
    return {"schedule": schedule,
            "n_chunks": 2 if schedule == "interleaved" else 1}


@pytest.mark.parametrize("schedule", ["1f1b", "interleaved"])
def test_schedules_bit_equal_to_gpipe_oracle(devices, schedule):
    """1F1B and interleaved(v=2) are BITWISE equal to the GPipe oracle in
    loss AND gradients — not allclose: the schedules share the per-layer
    compiled unit in _chunk_apply and a fixed accumulation order, so the
    only permitted difference is communication pattern."""
    mesh = MeshSpec(pipe=4, data=2).build(devices)
    width, n_micro, micro_b, n_layers = 8, 8, 2, 8
    params = _stack(jax.random.PRNGKey(0), n_layers, width)
    xs = jax.random.normal(jax.random.PRNGKey(1), (n_micro, micro_b, width))
    target = jax.random.normal(jax.random.PRNGKey(2), xs.shape)

    def make_loss(**kw):
        def loss(p):
            ys = pipeline(_layer, p, xs, mesh=mesh, **kw)
            return jnp.mean((ys - target) ** 2)
        return loss

    l_ref, g_ref = jax.jit(jax.value_and_grad(make_loss()))(params)
    l_got, g_got = jax.jit(
        jax.value_and_grad(make_loss(**_sched_kwargs(schedule)))
    )(params)
    assert np.array_equal(np.asarray(l_ref), np.asarray(l_got))
    mismatched = [
        jax.tree_util.keystr(path)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_ref),
            jax.tree_util.tree_leaves_with_path(g_got),
        )
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]
    assert not mismatched, mismatched


def test_schedule_plan_residency_and_bubble():
    """Analytic plan: 1F1B bounds live activations to min(P, M) <= P while
    GPipe stashes all M; interleaved(v) cuts the bubble fraction ~1/v."""
    P_, M, act = 4, 16, 1024
    gp = schedule_plan("gpipe", P_, M, micro_act_bytes=act)
    fb = schedule_plan("1f1b", P_, M, micro_act_bytes=act)
    il = schedule_plan("interleaved", P_, M, n_chunks=2, micro_act_bytes=act)
    assert gp["live_microbatches"] == M
    assert fb["live_microbatches"] == min(P_, M) <= P_
    assert il["live_microbatches"] == min(P_, M)
    assert fb["live_activation_bytes"] == fb["live_microbatches"] * act
    assert gp["bubble_fraction"] == (P_ - 1) / (M + P_ - 1)
    assert il["bubble_fraction"] == (P_ - 1) / (2 * M + P_ - 1)
    assert il["bubble_fraction"] < gp["bubble_fraction"]
    assert fb["bubble_fraction"] == gp["bubble_fraction"]
    # 1f1b at M < P cannot hold more than M
    assert schedule_plan("1f1b", 8, 2)["live_microbatches"] == 2


def test_schedule_plan_matches_memory_plan_accounting(devices):
    """The plan's live_activation_bytes composes with memory_plan()'s byte
    accounting: 1F1B's stash on the pipelined transformer is P/M of
    GPipe's, computed from the same micro activation size the bench
    records."""
    micro_act = 2 * 16 * 32 * 4  # micro_b x seq x hidden x f32
    gp = schedule_plan("gpipe", 2, 4, micro_act_bytes=micro_act)
    fb = schedule_plan("1f1b", 2, 4, micro_act_bytes=micro_act)
    assert gp["live_activation_bytes"] == 4 * micro_act
    assert fb["live_activation_bytes"] == 2 * micro_act
    assert fb["live_activation_bytes"] * 2 == gp["live_activation_bytes"]


def test_interleave_order_round_trips():
    """canonical -> stage-chunked permutation: stage p gets chunks
    k = c*P + p back to back, and applying the inverse restores the
    canonical layer order (the checkpoint layout is never permuted)."""
    order = interleave_order(8, n_stages=2, n_chunks=2)
    assert order.tolist() == [0, 1, 4, 5, 2, 3, 6, 7]
    inv = np.empty_like(order)
    inv[order] = np.arange(len(order))
    assert np.array_equal(np.arange(8), order[inv])


def test_pipeline_rejects_bad_interleave_chunking(devices):
    mesh = MeshSpec(pipe=4, data=2).build(devices)
    params = _stack(jax.random.PRNGKey(0), 8, 8)
    xs = jnp.zeros((8, 2, 8))
    # L=8 not divisible by P*v=12 — message names the remedy
    with pytest.raises(ValueError, match=r"pick n_chunks so L % \(P\*n_chunks\) == 0".replace("%", "%")):
        pipeline(_layer, params, xs, mesh=mesh,
                 schedule="interleaved", n_chunks=3)
    # M=3 not divisible by P=4 under interleaved
    with pytest.raises(ValueError, match="pad the microbatch count"):
        pipeline(_layer, params, jnp.zeros((3, 2, 8)), mesh=mesh,
                 schedule="interleaved", n_chunks=2)


def test_pipeline_rejects_schedule_misuse(devices):
    mesh = MeshSpec(pipe=2, data=4).build(devices)
    params = _stack(jax.random.PRNGKey(0), 4, 8)
    xs = jnp.zeros((2, 2, 8))
    with pytest.raises(ValueError, match="unknown schedule"):
        pipeline(_layer, params, xs, mesh=mesh, schedule="zigzag")
    with pytest.raises(ValueError, match="requires schedule='interleaved'"):
        pipeline(_layer, params, xs, mesh=mesh, schedule="1f1b", n_chunks=2)
    with pytest.raises(ValueError, match="n_chunks must be >= 1"):
        pipeline(_layer, params, xs, mesh=mesh,
                 schedule="interleaved", n_chunks=0)


def test_pipeline_rejects_xs_spec_length_mismatch(devices):
    from jax.sharding import PartitionSpec as P

    mesh = MeshSpec(pipe=2, data=4).build(devices)
    params = _stack(jax.random.PRNGKey(0), 4, 8)
    xs = (jnp.zeros((2, 4, 8)), jnp.zeros((2, 4), jnp.int32))
    with pytest.raises(ValueError, match="xs_spec has 3 specs, xs has 2"):
        pipeline(_layer, params, xs, mesh=mesh,
                 xs_spec=(P(("data",)), P(("data",)), P()))


@pytest.mark.parametrize("schedule", sorted(SCHEDULES))
def test_schedules_single_stage_degrade(devices, schedule):
    """pipe absent (n_stages == 1): every schedule falls back to the same
    sequential per-layer path, bit-equal to _chunk_apply."""
    mesh = MeshSpec(data=8).build(devices)
    params = _stack(jax.random.PRNGKey(0), 4, 8)
    xs = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 8))
    got = jax.jit(
        lambda p: pipeline(_layer, p, xs, mesh=mesh,
                           **_sched_kwargs(schedule))
    )(params)
    ref = jax.jit(lambda p: _chunk_apply(_layer, p, xs))(params)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


def test_config_schedule_validation():
    from rocket_tpu.models.transformer import TransformerConfig

    base = dict(vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32)
    with pytest.raises(ValueError, match="unknown"):
        TransformerConfig(**base, pipeline_microbatches=2,
                          pipeline_schedule="zigzag")
    with pytest.raises(ValueError, match="pipeline_chunks must be >= 1"):
        TransformerConfig(**base, pipeline_microbatches=2, pipeline_chunks=0)
    with pytest.raises(ValueError, match="requires"):
        TransformerConfig(**base, pipeline_microbatches=2, pipeline_chunks=2)
    with pytest.raises(ValueError, match="need pipelining on"):
        TransformerConfig(**base, pipeline_schedule="1f1b")
    # the valid spellings construct
    TransformerConfig(**base, pipeline_microbatches=2,
                      pipeline_schedule="1f1b")
    TransformerConfig(**base, pipeline_microbatches=2,
                      pipeline_schedule="interleaved", pipeline_chunks=2)


def test_transformer_schedules_bit_equal_through_module(devices):
    """Full framework path under each schedule: three jitted train steps
    produce IDENTICAL loss bits — the schedule knob changes communication
    and residency, never numerics."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
    )
    losses = {}
    for schedule in ("gpipe", "1f1b", "interleaved"):
        runtime = rt.Runtime(mesh=MeshSpec(pipe=2, data=4))
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=4, n_heads=4, max_seq=32,
            attention="dot", pipeline_microbatches=2,
            pipeline_schedule=schedule,
            pipeline_chunks=2 if schedule == "interleaved" else 1,
        )
        mod = rt.Module(
            TransformerLM(cfg),
            capsules=[rt.Loss(lm_cross_entropy(), name="lm"),
                      rt.Optimizer(learning_rate=1e-2)],
        )
        mod.bind(runtime)
        mod.setup()
        batch = jax.device_put({"tokens": tokens},
                               runtime.batch_sharding(ndim=2))
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
        )
        run = []
        for _ in range(3):
            attrs.batch = batch
            mod.launch(attrs)
            run.append(float(attrs.step_logs["lm"]))
        losses[schedule] = run
        mod.destroy()
    assert losses["1f1b"] == losses["gpipe"], losses
    assert losses["interleaved"] == losses["gpipe"], losses
    assert losses["gpipe"][-1] < losses["gpipe"][0]
