"""Worker for the 16k-token ring-attention training-step smoke test.

Runs in a FRESH interpreter (tests/test_ops.py spawns it): inside a long
pytest session the accumulated backend state (hundreds of compiled
executables and their thread pools) makes this largest-in-the-suite
program abort inside XLA:CPU — in a clean process it passes in seconds.
Same isolation pattern as multiproc_worker.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.parallel.mesh import MeshSpec

    S = 16_384
    runtime = rt.Runtime(mesh=MeshSpec(seq=8), mixed_precision="bf16")
    cfg = TransformerConfig(
        vocab_size=128, hidden=64, n_layers=1, n_heads=4,
        max_seq=S, attention="ring",
    )
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[rt.Loss(lm_cross_entropy(), name="lm"),
                  rt.Optimizer(learning_rate=1e-3)],
    )
    mod.bind(runtime)
    mod.setup()
    rng = np.random.default_rng(0)
    batch = jax.device_put(
        {"tokens": jnp.asarray(rng.integers(0, 128, (1, S)), jnp.int32)},
        runtime.batch_sharding(ndim=2, seq_dim=1),
    )
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    attrs.batch = batch
    mod.launch(attrs)
    loss = float(attrs.step_logs["lm"])
    assert np.isfinite(loss) and 3.0 < loss < 7.0, loss  # ~ln(128)=4.85
    assert int(mod.state.step) == 1
    mod.destroy()
    print("long-context-ok", loss)


if __name__ == "__main__":
    main()
