"""Resilience tests — chaos-driven proof of the fault-tolerance subsystem.

Covers the PR-2 acceptance criteria:

- SIGTERM at iteration k, rerun with ``resume("auto")`` → loss trajectory
  matches the uninterrupted run;
- corrupting the newest snapshot makes restore fall back to the previous
  valid one (and quarantine the broken dir as ``*.corrupt``);
- an injected NaN batch is skipped (optimizer state untouched) and
  training proceeds with finite loss;
- transient Source faults are absorbed by the retry path; persistent ones
  still surface;
- the skip-step guard adds ZERO extra traced step bodies on the happy path
  (bench-guard, instrumentation style of test_decode_hotpath.py).

Run the long chaos sweeps with ``pytest -m "slow and resilience"``.
"""

import os
import signal

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.persist import integrity
from rocket_tpu.testing import (
    FaultySource,
    NaNInjector,
    SigtermInjector,
    corrupt_snapshot,
)

from test_pipeline import MLP, synthetic_classification

pytestmark = pytest.mark.resilience


class LossRecorder(rt.Capsule):
    """Host-side per-iteration loss trace (sync read — test-only)."""

    def __init__(self):
        super().__init__(statefull=False, priority=400)
        self.losses = []

    def launch(self, attrs=None):
        if attrs is None or attrs.step_logs is None:
            return
        looper = attrs.looper
        if looper is not None and not looper.grad_enabled:
            return
        loss = attrs.step_logs.get("loss")
        if loss is not None:
            self.losses.append(float(loss))


def _tree(tmp_path, data, *, tag, epochs, pre_model=(), extra=(),
          save_every=100, resume=None, seed=0):
    """Standard chaos tree: 256 samples / batch 64 = 4 iterations per epoch.

    ``pre_model`` capsules mount between the Dataset and the Module (same
    priority 1000, stable sort keeps list order) — where a NaNInjector must
    sit to poison the batch the train step consumes.  ``extra`` capsules
    mount after the Module (sentinels, voters, SIGTERM injectors).
    """
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
    )
    recorder = LossRecorder()
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=7),
            *pre_model,
            model,
            *extra,
            recorder,
            rt.Checkpointer(save_every=save_every),
        ],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag=tag, num_epochs=epochs,
        project_root=str(tmp_path), seed=seed,
    )
    if resume is not None:
        launcher.resume(resume)
    return launcher, model, recorder


# -- checkpoint integrity ----------------------------------------------------


def test_manifest_and_commit_marker(tmp_path, devices):
    """Every Checkpointer snapshot carries a manifest and, once the async
    save drains, a commit marker; verify() accepts it."""
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(tmp_path, data, tag="mani", epochs=1, save_every=2)
    launcher.launch()  # destroy() waits -> commits finalized
    snaps = sorted((tmp_path / "mani" / "v0" / "weights").iterdir())
    assert [s.name for s in snaps] == ["000001", "000003"]
    for snap in snaps:
        assert (snap / integrity.MANIFEST_NAME).is_file()
        assert (snap / integrity.COMMIT_MARKER).is_file()
        ok, reason = integrity.verify(str(snap))
        assert ok, reason
        manifest = integrity.read_manifest(str(snap))
        assert manifest["schema"] == integrity.SCHEMA_VERSION
        assert manifest["iter_idx"] == int(snap.name)
        # at least the module item, with per-leaf structure + checksums
        assert any(k.startswith("module") for k in manifest["items"])
        for item in manifest["items"].values():
            assert item["structure"], "empty leaf structure"
            assert all("crc32" in rec for rec in item["structure"])


def test_corrupt_newest_falls_back_and_quarantines(tmp_path, devices):
    """Acceptance: corrupting the newest snapshot makes restore fall back to
    the previous valid one; the broken dir is renamed ``*.corrupt``."""
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(tmp_path, data, tag="fb", epochs=1, save_every=2)
    launcher.launch()
    weights = tmp_path / "fb" / "v0" / "weights"
    older, newest = sorted(weights.iterdir())  # 000001, 000003

    corrupt_snapshot(str(newest), mode="uncommit")
    ok, reason = integrity.verify(str(newest))
    assert not ok and "uncommitted" in reason

    # Explicit resume from the torn snapshot: quarantined, fallback restores
    # from 000001 (step 2, batch 2) -> 2 remaining iterations of epoch 0.
    launcher2, model2, rec2 = _tree(
        tmp_path, data, tag="fb", epochs=1, resume=str(newest),
    )
    launcher2.launch()
    assert len(rec2.losses) == 2  # resumed from the OLDER snapshot
    assert int(model2.state.step) == 4
    assert not newest.exists()
    assert (weights / f"{newest.name}{integrity.CORRUPT_SUFFIX}").exists()


def test_latest_valid_skips_torn_snapshot(tmp_path, devices):
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(tmp_path, data, tag="lv", epochs=1, save_every=2)
    launcher.launch()
    root = str(tmp_path / "lv")
    weights = tmp_path / "lv" / "v0" / "weights"
    older, newest = sorted(weights.iterdir())
    assert integrity.latest_valid(root) == str(newest)
    corrupt_snapshot(str(newest), mode="drop_item")
    assert integrity.latest_valid(root) == str(older)
    assert (weights / f"{newest.name}{integrity.CORRUPT_SUFFIX}").exists()


def test_deep_verify_catches_garbled_bytes(tmp_path, devices):
    """Bit rot that keeps marker+manifest intact passes shallow verify but
    fails the deep checksum pass."""
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(tmp_path, data, tag="gar", epochs=1, save_every=4)
    launcher.launch()
    snap = sorted((tmp_path / "gar" / "v0" / "weights").iterdir())[-1]
    ok, _ = integrity.verify(str(snap), deep=True)
    assert ok
    corrupt_snapshot(str(snap), mode="garble")
    ok, _ = integrity.verify(str(snap))
    assert ok, "shallow verify cannot see garbled bytes"
    ok, reason = integrity.verify(str(snap), deep=True)
    assert not ok and "corrupt" in reason


def test_legacy_snapshot_without_manifest_trusted(tmp_path, devices):
    """A pre-integrity snapshot (no manifest, no marker) is trusted with a
    warning on explicit restore — old runs stay restorable."""
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(tmp_path, data, tag="leg", epochs=1, save_every=4)
    launcher.launch()
    snap = sorted((tmp_path / "leg" / "v0" / "weights").iterdir())[-1]
    os.remove(snap / integrity.MANIFEST_NAME)
    os.remove(snap / integrity.COMMIT_MARKER)
    assert integrity.resolve_restore_path(str(snap)) == str(snap)
    assert snap.exists()  # trusted, NOT quarantined
    # ...but auto-resume scans stay strict: an unverifiable snapshot never
    # wins the newest-valid election.
    assert integrity.latest_valid(
        str(tmp_path / "leg"), do_quarantine=False
    ) != str(snap)


# -- preemption + auto-resume ------------------------------------------------


def test_sigterm_then_auto_resume_matches_uninterrupted(tmp_path, devices):
    """THE acceptance chaos test: SIGTERM at iteration k → rerun the same
    command with ``resume('auto')`` → the stitched loss trajectory equals
    the uninterrupted run's, and the final params match."""
    import jax

    data = synthetic_classification(n=256)  # 4 iters/epoch at bs 64

    # Reference: uninterrupted 2-epoch run.
    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="ref", epochs=2)
    launcher_a.launch()
    assert len(rec_a.losses) == 8

    # Interrupted: SIGTERM lands at iteration 2 of epoch 0.
    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="chaos", epochs=2,
        extra=[SigtermInjector(at_iter=2)],
    )
    launcher_b.launch()
    assert len(rec_b.losses) == 3  # iters 0..2, then the grace-window stop
    assert model_b.step == 3
    assert (tmp_path / "chaos" / "v0" / "weights" / "000002").is_dir()

    # Rerun-the-same-command recovery: resume('auto') finds the preemption
    # snapshot, re-enters epoch 0 at batch 3, finishes both epochs.
    launcher_c, model_c, rec_c = _tree(
        tmp_path, data, tag="chaos", epochs=2, resume="auto",
    )
    launcher_c.launch()
    stitched = rec_b.losses + rec_c.losses
    assert len(stitched) == 8
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-7)

    def flat(params):
        return np.concatenate([
            np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(params)
        ])

    np.testing.assert_allclose(
        flat(model_c.state.params), flat(model_a.state.params),
        rtol=1e-5, atol=1e-7,
    )


def test_auto_resume_fresh_start_when_no_snapshot(tmp_path, devices):
    """resume('auto') over an empty project dir starts fresh instead of
    crashing — the restart-the-same-command contract."""
    data = synthetic_classification(n=256)
    launcher, model, rec = _tree(tmp_path, data, tag="fresh", epochs=1,
                                 resume="auto")
    launcher.launch()
    assert model.step == 4
    assert len(rec.losses) == 4


def test_auto_resume_requires_tag(tmp_path, devices):
    launcher = rt.Launcher(capsules=[], tag=None, num_epochs=0).resume("auto")
    with pytest.raises(RuntimeError, match="auto"):
        launcher.launch()


def test_relaunch_in_one_process_after_preemption(tmp_path, devices):
    """Satellite: the SIGTERM handler chain and the preemption latch both
    reset across launches in one process — a preempted run followed by a
    fresh launch must run to completion, and the process handler must be
    restored after each."""
    before = signal.getsignal(signal.SIGTERM)
    data = synthetic_classification(n=256)
    launcher1, model1, _ = _tree(
        tmp_path, data, tag="re1", epochs=2,
        extra=[SigtermInjector(at_iter=1)],
    )
    launcher1.launch()
    assert model1.step == 2  # stopped inside epoch 0
    assert signal.getsignal(signal.SIGTERM) is before  # handler restored

    # Same process, new launch: must not inherit the stop vote or the
    # preemption latch.
    launcher2, model2, _ = _tree(tmp_path, data, tag="re2", epochs=2)
    launcher2.launch()
    assert model2.step == 8  # full 2 epochs
    assert signal.getsignal(signal.SIGTERM) is before


def test_stop_vote_honored_between_cycles(tmp_path, devices):
    """A stop vote cast where no attrs.looper exists (e.g. SIGTERM between
    cycles) must stop the run before the next epoch starts."""

    class StopVoter(rt.Capsule):
        def __init__(self):
            super().__init__(statefull=False, priority=50)
            self.cycles = 0

        def reset(self, attrs=None):  # fires AFTER the cycle, outside it
            self.cycles += 1
            self._runtime.request_stop("test vote between cycles")

    data = synthetic_classification(n=256)
    voter = StopVoter()
    launcher, model, _ = _tree(tmp_path, data, tag="vote", epochs=3,
                               extra=[voter])
    launcher.launch()
    assert voter.cycles == 1  # epochs 1 and 2 never started
    assert model.step == 4


# -- divergence: skip / rollback ---------------------------------------------


def _direct_module(skip, accum=1):
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
        skip_nonfinite=skip,
    )
    model.bind(rt.Runtime(gradient_accumulation_steps=accum))
    model.setup()
    return model


def _batches():
    import jax.numpy as jnp

    data = synthetic_classification(n=64)
    good = {"x": jnp.asarray(data["x"]), "label": jnp.asarray(data["label"])}
    bad = {"x": jnp.full_like(good["x"], jnp.nan), "label": good["label"]}
    return good, bad


def test_nan_batch_skipped_state_untouched(devices):
    """Acceptance: a NaN batch leaves params, optimizer state and the step
    counter untouched; the next good batch trains normally."""
    import jax

    good, bad = _batches()
    model = _direct_module(skip=True)
    attrs = rt.Attributes(
        batch=good,
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
    )
    model.launch(attrs)
    assert float(attrs.step_logs["skipped"]) == 0.0
    params1 = jax.tree_util.tree_map(np.asarray, model.state.params)
    opt1 = jax.tree_util.tree_map(np.asarray, model.state.opt_state)

    attrs.batch = bad
    model.launch(attrs)
    assert float(attrs.step_logs["skipped"]) == 1.0
    assert int(model.state.step) == 1  # update withheld
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, params1,
        jax.tree_util.tree_map(np.asarray, model.state.params),
    )
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, opt1,
        jax.tree_util.tree_map(np.asarray, model.state.opt_state),
    )

    attrs.batch = good
    model.launch(attrs)
    assert int(model.state.step) == 2
    assert np.isfinite(float(attrs.step_logs["loss"]))
    for leaf in jax.tree_util.tree_leaves(model.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_nan_microbatch_contributes_zero_gradient(devices):
    """With accumulation, a NaN micro-batch is dropped from the window sum;
    the boundary still applies a finite update from the good micro-batches."""
    import jax

    good, bad = _batches()
    model = _direct_module(skip=True, accum=2)
    attrs = rt.Attributes(
        batch=bad,
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
    )
    model.launch(attrs)  # micro #1: poisoned, accum stays zero
    assert float(attrs.step_logs["skipped"]) == 1.0
    attrs.batch = good
    model.launch(attrs)  # sync boundary: good grads only
    assert int(model.state.step) == 1
    for leaf in jax.tree_util.tree_leaves(model.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_sentinel_skip_policy_end_to_end(tmp_path, devices):
    """DivergenceSentinel(policy='skip') arms the in-graph guard through the
    runtime flag; a poisoned pipeline iteration is skipped and training
    finishes finite."""
    import jax

    data = synthetic_classification(n=256)
    sentinel = rt.DivergenceSentinel(policy="skip")
    launcher, model, rec = _tree(
        tmp_path, data, tag="skip", epochs=2,
        pre_model=[NaNInjector(at_iters=(2,))],
        extra=[sentinel],
    )
    launcher.launch()
    assert model.step == 7  # 8 iterations, one skipped
    assert sentinel.events >= 1  # host-side observation of the NaN loss
    assert np.isfinite(rec.losses[-1])
    for leaf in jax.tree_util.tree_leaves(model.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_sentinel_rollback_restores_last_good(tmp_path, devices):
    """policy='rollback': a NaN batch poisons the params (no skip guard);
    the sentinel restores the newest valid snapshot, applies the LR
    cooldown, and training continues finite."""
    import jax

    data = synthetic_classification(n=256)
    sentinel = rt.DivergenceSentinel(
        policy="rollback", spike_factor=None, cooldown_factor=0.1,
        cooldown_steps=100,
    )
    launcher, model, rec = _tree(
        tmp_path, data, tag="roll", epochs=2,
        pre_model=[NaNInjector(at_iters=(4,))],
        extra=[sentinel], save_every=2,
    )
    launcher.launch()
    assert sentinel.rollbacks == 1
    assert model._lr_scale == 0.1  # cooldown still armed at run end
    assert np.isfinite(rec.losses[-1])
    for leaf in jax.tree_util.tree_leaves(model.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_sentinel_rollback_without_snapshot_stops(tmp_path, devices):
    """Divergence with nothing to roll back to must stop the run, not spin."""
    data = synthetic_classification(n=256)
    sentinel = rt.DivergenceSentinel(policy="rollback", spike_factor=None)
    launcher, model, _ = _tree(
        tmp_path, data, tag="nosnap", epochs=2,
        pre_model=[NaNInjector(at_iters=(0,))],
        extra=[sentinel],
        save_every=100,  # no snapshot ever written
    )
    launcher.launch()
    assert model.step < 8  # stopped early instead of looping on NaN


# -- retry / faulty source ---------------------------------------------------


def test_transient_source_fault_absorbed(devices):
    data = synthetic_classification(n=128)
    source = FaultySource(rt.ArraySource(data), fail_on=(0, 5), times=1)
    loader = rt.DataLoader(source, batch_size=32, prefetch=0)
    batches = list(loader.iterate(epoch=0))
    assert len(batches) == 4
    assert source.faults == 2  # both scheduled faults fired and were retried


def test_persistent_source_fault_surfaces(devices):
    data = synthetic_classification(n=128)
    source = FaultySource(rt.ArraySource(data), fail_on=(0,), times=None)
    loader = rt.DataLoader(source, batch_size=32, prefetch=0)
    with pytest.raises(OSError, match="injected"):
        list(loader.iterate(epoch=0))
    assert source.faults == 3  # the full retry budget, then surfaced


def test_retry_call_contract():
    from rocket_tpu.utils.retry import retry_call

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("flap")
        return "ok"

    assert retry_call(flaky, tries=5, base_delay=0.001) == "ok"
    assert calls["n"] == 3

    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry_call(always, tries=3, base_delay=0.001)
    with pytest.raises(ValueError):
        retry_call(lambda: None, tries=0)
    # non-retryable exception types propagate immediately
    calls["n"] = 0

    def bug():
        calls["n"] += 1
        raise KeyError("bug")

    with pytest.raises(KeyError):
        retry_call(bug, tries=5, base_delay=0.001)
    assert calls["n"] == 1


# -- schema tolerance + prune barrier ----------------------------------------


def test_schema_tolerant_loads(devices):
    """Older checkpoints missing keys warn-and-default instead of raising."""
    looper = rt.Looper(capsules=[], progress=False)
    looper._iter_idx = 5
    looper.load_state_dict(rt.Attributes(unrelated=1))
    assert looper._iter_idx == 5

    ck = rt.Checkpointer(save_every=10)
    ck._iter_idx = 7
    ck.load_state_dict(rt.Attributes(unrelated=1))
    assert ck._iter_idx == 7

    launcher = rt.Launcher(capsules=[])
    launcher.load_state_dict(rt.Attributes(unrelated=1))
    assert launcher._epoch_idx == 0
    assert launcher._saved_num_procs is None  # topology guard skipped

    ds = rt.Dataset(source=rt.ArraySource({"x": np.zeros((4, 2))}))
    ds._batch_idx = 3
    ds.load_state_dict(rt.Attributes(unrelated=1))
    assert ds._batch_idx == 0  # restart the epoch, as the warning says


def test_prune_runs_behind_barriers(tmp_path, devices):
    """Satellite: retention deletes only between collective barriers, so a
    peer mid-restore can never see its snapshot vanish."""
    tags = []

    class Recording(rt.Runtime):
        def wait_for_everyone(self, tag="barrier"):
            tags.append(tag)
            super().wait_for_everyone(tag)

    data = synthetic_classification(n=256)
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
    )
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=7),
            model,
            rt.Checkpointer(save_every=2, keep_last=1),
        ],
        progress=False,
    )
    rt.Launcher(
        capsules=[looper], tag="prune", num_epochs=1,
        project_root=str(tmp_path), runtime=Recording(),
    ).launch()
    assert "ckpt-prune" in tags and "ckpt-pruned" in tags
    assert tags.index("ckpt-prune") < tags.index("ckpt-pruned")
    weights = tmp_path / "prune" / "v0" / "weights"
    assert len(list(weights.iterdir())) == 1  # retention applied


# -- bench guard: guard costs no traces --------------------------------------


def test_skip_guard_zero_extra_traces_happy_path(devices):
    """Bench-guard: with the skip guard compiled in, N good batches trace the
    objective exactly ONCE — identical to the unguarded baseline (no per-step
    retrace, no second step body).  The lr_scale operand costs exactly one
    extra trace on arming, none on value changes."""
    import jax.numpy as jnp

    data = synthetic_classification(n=256)
    batch = {"x": jnp.asarray(data["x"][:64]),
             "label": jnp.asarray(data["label"][:64])}

    def counting_module(skip):
        traces = {"n": 0}
        base = cross_entropy(labels_key="label")

        def objective(b):
            traces["n"] += 1  # Python body runs at trace time only
            return base(b)

        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(objective, name="ce"),
                rt.Optimizer(learning_rate=2e-2),
            ],
            skip_nonfinite=skip,
        )
        model.bind(rt.Runtime())
        model.setup()
        return model, traces

    def run(model, n):
        attrs = rt.Attributes(
            batch=batch,
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
        )
        for _ in range(n):
            attrs.batch = batch
            model.launch(attrs)

    baseline, base_traces = counting_module(skip=False)
    run(baseline, 4)
    guarded, guard_traces = counting_module(skip=True)
    run(guarded, 4)
    assert base_traces["n"] == guard_traces["n"] == 1

    # LR cooldown operand: None -> scalar retraces once; new VALUES don't.
    guarded.set_lr_scale(0.5)
    run(guarded, 1)
    assert guard_traces["n"] == 2
    guarded.set_lr_scale(0.25)
    run(guarded, 2)
    assert guard_traces["n"] == 2
    # and disarming returns to the cached no-operand signature
    guarded.set_lr_scale(None)
    run(guarded, 1)
    assert guard_traces["n"] == 2


# -- long chaos sweep (slow) -------------------------------------------------


@pytest.mark.slow
def test_repeated_preemption_cycles(tmp_path, devices):
    """Three consecutive preempt→auto-resume cycles still converge on the
    uninterrupted trajectory (run with: pytest -m 'slow and resilience')."""
    data = synthetic_classification(n=256)

    launcher_a, _, rec_a = _tree(tmp_path, data, tag="sweep-ref", epochs=3)
    launcher_a.launch()

    losses = []
    for round_idx, kill_at in enumerate((1, 2, 3)):
        launcher, _, rec = _tree(
            tmp_path, data, tag="sweep", epochs=3,
            extra=[SigtermInjector(at_iter=kill_at)],
            resume="auto" if round_idx else None,
        )
        launcher.launch()
        losses += rec.losses
    launcher_f, _, rec_f = _tree(tmp_path, data, tag="sweep", epochs=3,
                                 resume="auto")
    launcher_f.launch()
    losses += rec_f.losses
    assert len(losses) == len(rec_a.losses)
    np.testing.assert_allclose(losses, rec_a.losses, rtol=1e-5, atol=1e-7)
