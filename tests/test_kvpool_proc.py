"""Fleet KV page tier — cross-process proofs (spawn-heavy, heavy tail).

The unit zone (codec, pool protocol, in-process loop tier) lives in
``tests/test_kvpool.py``; this file proves the tier across REAL process
boundaries, which is the whole point of ISSUE 16:

- session migration (tier-1): kill a session's sticky worker after
  turn 1 — turn 2 lands on a replica that never saw the session and is
  served from POOL-TRANSFERRED pages, bit-equal to the cold oracle,
  with the transfer wall time visible in the worker's
  ``serve/kvstore/wire`` goodput bucket;
- disaggregated prefill (tier-1): a prefill replica pushes its
  handoff's pages to the pool and the router routes only a lightweight
  ``"pages"`` notice — the decode WORKER PROCESS imports the chain on
  admit, so prefilled KV never rides a pickled SUBMIT frame;
- fleet hit-rate parity (``slow``): an 87.5%-shared-prefix trace over
  two worker processes sharing one pool reuses exactly as many prompt
  tokens as the single-replica baseline;
- router-driven migration under heal, int8 layout (``slow``): the
  sticky replica dies mid-conversation, supervision respawns it, and
  turn 2 re-routes + serves from pooled int8 pages — exactly one typed
  result per request;
- TTFT bench guard (``slow``): on the CPU proxy, a prefix served from
  pool-transferred pages beats the cold prefill at p50 even after
  paying the wire cost.
"""

import time

import numpy as np
import pytest

from rocket_tpu.serve import (
    Completed,
    FleetRouter,
    KVPagePool,
    KVPoolClient,
    PrefillReplica,
    ProcReplica,
    Request,
    SharedPrefixIndex,
    WorkerSpec,
)
from rocket_tpu.testing import workers as tw

pytestmark = [pytest.mark.kvpool, pytest.mark.procfleet,
              pytest.mark.serving]

BUILDER = "rocket_tpu.testing.workers:build_tiny_loop"
SPAWN_S = 240.0     # worker spawn includes a jax import + model init
PAGE = 3            # pool/store page size for the tiny worker pair


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(17)
    return rng.integers(1, tw.VOCAB, size=(8, tw.P)).astype(np.int32)


def _await_corpse(rep, timeout=10.0):
    """SIGKILL delivery is asynchronous — wait for the pid to reap."""
    deadline = time.monotonic() + timeout
    while rep.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.proc.poll() is not None, "worker survived SIGKILL"


def _assert_exactly_once(results, rids):
    got = sorted(r.rid for r in results)
    assert got == sorted(rids), (got, sorted(rids))


def _pump_until_done(rep_or_router, want, max_rounds=400):
    out = []
    for _ in range(max_rounds):
        busy = rep_or_router.pump()
        out.extend(rep_or_router.drain_results())
        if len(out) >= want and not busy:
            return out
    raise AssertionError(f"only {len(out)}/{want} results after "
                         f"{max_rounds} rounds")


def _cold_serve(prompt_rows, int8=None):
    """rid-index -> tokens from a store-less, pool-less in-process loop
    over the SAME builder the workers run — the cold oracle (the
    local-hit oracle is bit-equal to it by the kvstore contract)."""
    loop = tw.build_tiny_loop(kv_cache_int8=int8)
    try:
        for i, p in enumerate(prompt_rows):
            assert loop.submit(Request(rid=i, prompt=p)) is None
        out = {}
        for res in loop.run_until_idle():
            assert isinstance(res, Completed), res
            out[res.rid] = np.asarray(res.tokens)
    finally:
        loop.close()
    return out


# -- session migration (tier-1 acceptance) -----------------------------------


def test_session_migration_transferred_pages_bit_equal(prompts):
    """Acceptance: the session's sticky worker is SIGKILLed after
    turn 1; turn 2 (a superset prompt) is served by a replica that never
    saw the session — its only warm path is the fleet pool — and the
    tokens are bit-equal to the cold oracle, with the transfer visible
    in both the pool counters and the worker's wire goodput bucket."""
    pool = KVPagePool(page_tokens=PAGE)
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": PAGE},
                      kvpool=pool.address)
    a = ProcReplica(spec, "mig-a", spawn_timeout_s=SPAWN_S,
                    rpc_timeout_s=SPAWN_S)
    b = ProcReplica(spec, "mig-b", spawn_timeout_s=SPAWN_S,
                    rpc_timeout_s=SPAWN_S)
    try:
        # turn 1 on the session's sticky replica
        assert a.submit(Request(rid="t1", prompt=prompts[0], session="s0"))
        (r1,) = _pump_until_done(a, 1)
        assert isinstance(r1, Completed)
        full = np.asarray(r1.tokens)          # the finished 24-token row
        # the worker exported the finished row's chain pool-ward
        assert pool.snapshot()["pages_pushed"] > 0

        # mid-session host loss — nothing supervisor-side is told
        a.kill()
        _await_corpse(a)
        assert not a.probe()

        # turn 2: the conversation continues with a superset prompt on
        # the OTHER replica, whose local store has never held a page
        p2 = full[:16].astype(np.int32)
        assert b.submit(Request(rid="t2", prompt=p2, session="s0"))
        (r2,) = _pump_until_done(b, 1)
        assert isinstance(r2, Completed)
        assert np.array_equal(np.asarray(r2.tokens),
                              _cold_serve([p2])[0])

        # served FROM TRANSFERRED PAGES, not cold: 5 full pages of the
        # 16-token prompt (limit = len - 1) came through the pool
        assert b.counters["pool_hits"] == 1.0
        assert b.counters["pool_hit_tokens"] == float((16 - 1) // PAGE
                                                      * PAGE)
        snap = pool.snapshot()
        assert snap["fetch_hits"] >= 1 and snap["bytes_out"] > 0
        # transfer wall time landed in the worker's wire goodput bucket
        stats = b.collect()
        assert stats is not None
        assert stats["goodput"].get("serve/kvstore/wire_s", 0.0) > 0.0
    finally:
        a.close()
        b.close()
        pool.close()


# -- disaggregated prefill (tier-1 acceptance) --------------------------------


def test_prefill_disaggregation_via_pool(prompts):
    """Acceptance: with a pool-armed prefill lane, the router never
    moves a pickled KVHandoff — each prefill pushes its pages to the
    pool and only a ``"pages"`` notice crosses; the decode WORKER
    PROCESS imports the chain on admit and serves bit-equal."""
    from rocket_tpu.models.generate import ContinuousBatcher

    pool = KVPagePool(page_tokens=PAGE)
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": PAGE},
                      kvpool=pool.address)
    decode = ProcReplica(spec, "dis-d0", spawn_timeout_s=SPAWN_S,
                         rpc_timeout_s=SPAWN_S)
    model, draft, params, dparams = tw.tiny_models()

    def bat_factory():
        return ContinuousBatcher(model, draft, params, dparams,
                                 total_len=tw.TOTAL, n_draft=tw.NDRAFT,
                                 eos_token=None)

    prefill = PrefillReplica(bat_factory, "dis-p0",
                             kvpool=KVPoolClient.connect(pool.address),
                             page_tokens=PAGE)
    router = FleetRouter([decode], prefill_replicas=[prefill],
                         prefill_threshold=None)
    rids = [f"d{i}" for i in range(3)]
    oracle = _cold_serve([prompts[i] for i in range(3)])
    try:
        for i, rid in enumerate(rids):
            assert router.submit(Request(rid=rid, prompt=prompts[i])) \
                is None
        results = router.run_until_idle()
        _assert_exactly_once(results, rids)
        assert router.counters.pool_handoffs == 3
        assert router.counters.handoffs == 0    # no pickled handoff moved
        for res in results:
            assert isinstance(res, Completed), res
            i = int(res.rid[1:])
            assert np.array_equal(np.asarray(res.tokens), oracle[i]), \
                res.rid
        # the decode worker imported every chain from the pool: 2 full
        # pages per 8-token prompt (the handoff covers prompt + 1 token)
        assert decode.counters["pool_hits"] == 3.0
        assert decode.counters["pool_hit_tokens"] == 3.0 * (tw.P // PAGE
                                                            * PAGE)
        snap = pool.snapshot()
        assert snap["pushes"] >= 3 and snap["fetch_hits"] >= 3
    finally:
        router.close()
        pool.close()


# -- fleet-wide hit-rate parity (slow acceptance) -----------------------------


@pytest.mark.slow
def test_fleet_hit_rate_matches_single_replica():
    """Acceptance: an 87.5%-shared-prefix trace (14 of 16 prompt tokens
    shared) across TWO worker processes sharing one pool reuses exactly
    as many prompt tokens as the single-replica baseline — local hits
    plus pool hits together close the cross-process gap — and the
    transfer cost shows up in the workers' wire goodput bucket."""
    PAGE2, PROMPT, SHARED, N = 2, 16, 14, 8
    rng = np.random.default_rng(23)
    header = rng.integers(1, tw.VOCAB, size=SHARED)

    def turn(i):
        tail = np.random.default_rng(100 + i).integers(
            1, tw.VOCAB, size=PROMPT - SHARED)
        return np.concatenate([header, tail]).astype(np.int32)

    trace = [turn(i) for i in range(N)]

    # single-replica baseline: one in-process loop, same builder
    base_loop = tw.build_tiny_loop(kvstore_page_tokens=PAGE2)
    base_tokens = {}
    try:
        assert base_loop.submit(Request(rid=0, prompt=trace[0])) is None
        for res in base_loop.run_until_idle():
            base_tokens[res.rid] = np.asarray(res.tokens)
        for i in range(1, N):
            assert base_loop.submit(Request(rid=i, prompt=trace[i])) \
                is None
        for res in base_loop.run_until_idle():
            base_tokens[res.rid] = np.asarray(res.tokens)
        base = base_loop.counters.snapshot()
    finally:
        base_loop.close()
    base_warm = base["kv_hit_tokens"]
    assert base_warm == (N - 1) * SHARED    # every follow-up fully warm

    pool = KVPagePool(page_tokens=PAGE2)
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": PAGE2},
                      kvpool=pool.address)
    # NO prefix index here, deliberately: the route-by-pages hint would
    # sticky every shared-prefix turn onto the one page-holder replica.
    # Pure least-loaded routing spreads the trace, so parity can only
    # hold if the pool closes the cross-process gap.
    reps = [ProcReplica(spec, f"hr-{i}", spawn_timeout_s=SPAWN_S,
                        rpc_timeout_s=SPAWN_S)
            for i in range(2)]
    router = FleetRouter(reps)
    try:
        assert router.submit(Request(rid=0, prompt=trace[0])) is None
        results = router.run_until_idle()
        for i in range(1, N):
            assert router.submit(Request(rid=i, prompt=trace[i])) is None
        results += router.run_until_idle()
        _assert_exactly_once(results, list(range(N)))
        for res in results:
            assert isinstance(res, Completed), res
            assert np.array_equal(np.asarray(res.tokens),
                                  base_tokens[res.rid]), res.rid
        # both processes served part of the trace
        assert all(rep.counters["completed"] >= 1 for rep in reps)
        # pool-fetched pages land in the local store and serve through
        # the normal kv-hit path, so pool_hit_tokens is an ATTRIBUTION
        # subset of kv_hit_tokens (how many warm tokens crossed the
        # wire), never an addition to it
        fleet_warm = sum(rep.counters["kv_hit_tokens"] for rep in reps)
        # parity: the pool closes the cross-process gap exactly — the
        # fleet reuses the same warm tokens the single replica did
        assert fleet_warm == base_warm, (fleet_warm, base_warm)
        # ...and at least one full shared header came cross-process
        assert sum(rep.counters["pool_hit_tokens"]
                   for rep in reps) >= SHARED
        # the transfer cost is visible, not hidden: some worker charged
        # wall time to the serve/kvstore/wire goodput bucket
        wire_s = []
        for rep in reps:
            stats = rep.collect()
            assert stats is not None
            wire_s.append(stats["goodput"].get("serve/kvstore/wire_s",
                                               0.0))
        assert max(wire_s) > 0.0, wire_s
        assert pool.snapshot()["bytes_moved"] > 0
    finally:
        router.close()
        pool.close()


# -- router-driven migration under heal, int8 (slow acceptance) ---------------


@pytest.mark.slow
@pytest.mark.resilience
def test_session_migration_router_heal_int8(prompts):
    """Acceptance: full fleet machinery, int8 KV layout.  The session's
    sticky replica is SIGKILLed mid-conversation; supervision heals it
    while turn 2 re-routes to the survivor, which imports the pooled
    int8 pages (payload + rank-4 f32 scales crossed the wire) and
    serves bit-equal to the int8 cold oracle — exactly one typed result
    per request."""
    pool = KVPagePool(page_tokens=PAGE)
    index = SharedPrefixIndex(page_tokens=PAGE)
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": PAGE,
                              "kv_cache_int8": True},
                      kvpool=pool.address)
    reps = [ProcReplica(spec, f"m8-{i}", spawn_timeout_s=SPAWN_S,
                        rpc_timeout_s=SPAWN_S, prefix_index=index)
            for i in range(2)]
    router = FleetRouter(reps, prefix_index=index)
    try:
        assert router.submit(Request(rid="i1", prompt=prompts[0],
                                     session="s8")) is None
        results = router.run_until_idle()
        (r1,) = results
        assert isinstance(r1, Completed)
        full = np.asarray(r1.tokens)
        sticky_id = router._affinity["s8"]
        (sticky,) = [r for r in reps if r.replica_id == sticky_id]

        sticky.kill()
        _await_corpse(sticky)

        p2 = full[:16].astype(np.int32)
        assert router.submit(Request(rid="i2", prompt=p2,
                                     session="s8")) is None
        results += router.run_until_idle()
        _assert_exactly_once(results, ["i1", "i2"])
        (r2,) = [r for r in results if r.rid == "i2"]
        assert isinstance(r2, Completed)
        assert np.array_equal(np.asarray(r2.tokens),
                              _cold_serve([p2], int8=True)[0])
        # supervision healed the killed sticky; the survivor served the
        # migrated turn from pooled int8 pages
        assert router.counters.heals == 1
        assert sticky.spawns == 2
        assert sum(rep.counters.get("pool_hits", 0.0)
                   for rep in reps) >= 1
        assert pool.snapshot()["fetch_hits"] >= 1
    finally:
        router.close()
        pool.close()


# -- TTFT bench guard (slow) --------------------------------------------------


def _proxy_models(hidden=128, max_seq=272, prompt=256):
    import jax

    from rocket_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = dict(vocab_size=64, hidden=hidden, n_layers=2, n_heads=4,
               max_seq=max_seq)
    out = []
    for seed in (1, 7):
        m = TransformerLM(TransformerConfig(**cfg))
        p = m.init(
            jax.random.PRNGKey(seed),
            {"tokens": np.zeros((1, prompt), np.int32),
             "positions": np.zeros((1, prompt), np.int32)},
        )["params"]
        out.append((m, p))
    (model, params), (_, dparams) = out
    return model, model, params, dparams


@pytest.mark.slow
def test_pool_transferred_ttft_p50_beats_cold():
    """Acceptance bench guard: on the CPU proxy (long prompts so
    prefill dominates dispatch), a prefix imported from POOL-TRANSFERRED
    pages beats the cold prefill at TTFT p50 — the wire cost of the
    fetch is smaller than the prefill it avoids.  Every turn runs on a
    FRESH loop with an empty local store, so the only warm path is the
    pool socket."""
    from rocket_tpu.models.generate import ContinuousBatcher
    from rocket_tpu.serve import ServingLoop
    from rocket_tpu.serve.kvstore import PrefixKVStore

    PROMPT, PAGE_B, SHARED, NEW, TURNS = 256, 32, 224, 8, 7
    frac = SHARED / PROMPT
    models = _proxy_models(prompt=PROMPT, max_seq=PROMPT + 16)
    model, draft, params, dparams = models
    rng = np.random.default_rng(5)
    header = rng.integers(1, 64, size=SHARED)

    def turn(t):
        tail = np.random.default_rng(100 + t).integers(
            1, 64, size=PROMPT - SHARED)
        return np.concatenate([header, tail]).astype(np.int32)

    def factory():
        return ContinuousBatcher(model, draft, params, dparams,
                                 total_len=PROMPT + NEW,
                                 n_draft=tw.NDRAFT, eos_token=None)

    def run(pool):
        """One pass over the trace; each turn gets a FRESH loop (empty
        local store) so warm pages can only arrive through the pool."""
        samples = []
        hits = 0
        for t in range(TURNS):
            t0 = time.perf_counter()
            kv = PrefixKVStore(page_tokens=PAGE_B,
                               capacity_bytes=1 << 30) \
                if pool is not None else None
            client = KVPoolClient.connect(pool.address) \
                if pool is not None else None
            loop = ServingLoop(
                factory, max_batch=1, queue_capacity=4,
                clock=lambda: time.perf_counter() - t0,
                kvstore=kv, kvpool=client)
            try:
                assert loop.submit(Request(rid=t, prompt=turn(t))) is None
                loop.run_until_idle(max_rounds=1_000_000)
                samples.append(loop.latency.summary()["ttft_ms/p50"])
                hits += int(loop.counters.pool_hits)
            finally:
                loop.close()
        return samples, hits

    pool = KVPagePool(page_tokens=PAGE_B)
    try:
        run(pool)                       # compile both paths + seed pool
        run(None)
        colds, warms = [], []
        warm_hits = 0
        for _ in range(3):
            colds.extend(run(None)[0])
            s, h = run(pool)
            warms.extend(s)
            warm_hits += h
        # the pool already holds the header after the seeding pass, so
        # every measured warm turn must have imported it
        assert warm_hits == 3 * TURNS
        cold = float(np.median(colds))
        warm = float(np.median(warms))
        drop = 1.0 - warm / cold
        assert drop >= 0.25 * frac, (
            f"pool-transferred TTFT p50 {warm:.1f}ms vs cold "
            f"{cold:.1f}ms — drop {drop:.0%} under the CPU proxy of the "
            f"{frac:.0%} shared prefill fraction "
            f"(expected >= {0.25 * frac:.0%} after wire cost)"
        )
    finally:
        pool.close()
