"""Serving-fleet tests — FleetRouter / Replica / PrefillReplica end to end.

Three layers:

- units: KV handoff export/import bit-equality (f32 and int8 layouts),
  fleet-level saturation shedding, routing metadata;
- the fault-free contract (acceptance 2): fleet output is bit-identical
  per request to the single-``ServingLoop`` oracle regardless of which
  replica served it, with every request answered exactly once;
- the chaos pair + lanes: a replica killed mid-stream (acceptance 1 —
  every request still typed, the sick replica rebuilt from its factory,
  post-recovery output bit-correct), a flaky health probe driving the
  graceful drain-and-rebuild path, and prefill/decode disaggregation
  (acceptance 3 — a burst of long prompts stalls the merged-lane
  control visibly while the disaggregated decode lane's round cadence
  stays within a guarded bound of the no-long-prompt baseline).

CPU-proxy sizes run under tier-1; the thousand-request trace is
``slow``.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rocket_tpu.models.generate import (
    ContinuousBatcher,
    speculative_generate_batched,
)
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.serve import (
    Completed,
    FleetRouter,
    HealthState,
    Overloaded,
    PrefillReplica,
    PrefixKVStore,
    Replica,
    Request,
    ServingLoop,
)
from rocket_tpu.testing.chaos import (
    FlakyReplicaProxy,
    ReplicaKillInjector,
    SlowPrefillInjector,
)

pytestmark = pytest.mark.fleet

B, P, TOTAL, NDRAFT = 3, 8, 24, 4
P_LONG = 16


def _lm(seed=1, **kw):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64, **kw
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


@pytest.fixture(scope="module")
def models():
    model, params = _lm(seed=1)
    draft, _ = _lm(seed=1)      # same structure...
    _, dparams = _lm(seed=7)    # ...different weights: low acceptance
    return model, draft, params, dparams


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(16, P)).astype(np.int32)


@pytest.fixture(scope="module")
def long_prompts():
    rng = np.random.default_rng(29)
    return rng.integers(1, 64, size=(4, P_LONG)).astype(np.int32)


def _bat_factory(models, **kw):
    model, draft, params, dparams = models

    def factory():
        return ContinuousBatcher(
            model, draft, params, dparams,
            total_len=TOTAL, n_draft=NDRAFT, eos_token=None, **kw,
        )

    return factory


def _loop_factory(models, **kw):
    bat = _bat_factory(models)
    kw.setdefault("max_batch", B)
    kw.setdefault("queue_capacity", 16)

    def factory():
        return ServingLoop(bat, **kw)

    return factory


def _oracle(models, prompt_row):
    model, draft, params, dparams = models
    toks = speculative_generate_batched(
        model, params, draft, dparams, prompt_row[None, :],
        max_new_tokens=TOTAL - prompt_row.shape[0], n_draft=NDRAFT,
    )
    return np.asarray(toks[0])


def _assert_exactly_once(results, rids):
    got = sorted(r.rid for r in results)
    assert got == sorted(rids), (got, sorted(rids))


@pytest.fixture(scope="module")
def warm_jit(models, prompts, long_prompts):
    """Compile every executable the timing-sensitive tests dispatch —
    short/long prefills, admits, the import scatter, and the round —
    so measured gaps are dispatch time, never compile time."""
    bat = _bat_factory(models)()
    bat.start(jnp.asarray(prompts[:B], jnp.int32))
    for r in range(B):
        bat.retire(r)
    bat.step()
    bat.admit(0, prompts[0][None, :])            # _spec_admit, P
    bat.retire(0)
    bat.admit(0, long_prompts[0][None, :])       # _spec_admit, P_LONG
    bat.retire(0)
    h = bat.prefill_handoff(prompts[1])          # _spec_prefill, B=1, P
    bat.admit_prefilled(0, h)                    # _spec_import_row
    bat.retire(0)
    bat.prefill_handoff(long_prompts[1])         # _spec_prefill, B=1, P_LONG
    # the loop's own warm group (P=1) + its step
    loop = _loop_factory(models)()
    loop.close()
    return True


# -- units: KV handoff ---------------------------------------------------


class TestKVHandoff:
    @pytest.mark.parametrize("int8", [False, True])
    def test_handoff_bit_equal_to_local_admit(self, models, prompts, int8):
        """A row prefilled on one batcher and imported into another is
        bit-identical to a local admit of the same prompt — including
        the int8 KV layout, whose pages travel with their scales."""
        kw = {"kv_cache_int8": True} if int8 else {}
        fac = _bat_factory(models, **kw)

        local = fac()
        local.start(jnp.asarray(prompts[:B], jnp.int32))
        for r in range(B):
            local.retire(r)
        local.admit(0, prompts[3][None, :])
        while not bool(np.asarray(local.state[2])[0]):
            local.step()
        tok_local, n_local = local.row_tokens(0)

        pre = fac()   # never start()-ed — the prefill-lane contract
        handoff = pre.prefill_handoff(prompts[3]).to_host()
        assert handoff.nbytes > 0
        assert handoff.total_len == TOTAL

        dec = fac()
        dec.start(jnp.asarray(prompts[:B], jnp.int32))
        for r in range(B):
            dec.retire(r)
        dec.admit_prefilled(0, handoff)
        while not bool(np.asarray(dec.state[2])[0]):
            dec.step()
        tok_dec, n_dec = dec.row_tokens(0)

        assert n_local == n_dec
        assert np.array_equal(tok_local, tok_dec)

    def test_int8_handoff_is_smaller(self, models, prompts):
        f32 = _bat_factory(models)().prefill_handoff(prompts[0]).to_host()
        i8 = _bat_factory(models, kv_cache_int8=True)() \
            .prefill_handoff(prompts[0]).to_host()
        assert i8.nbytes < f32.nbytes / 2

    def test_import_validates_layout(self, models, prompts):
        fac = _bat_factory(models)
        pre = fac()
        handoff = pre.prefill_handoff(prompts[0])
        dec = fac()
        with pytest.raises(ValueError, match="start"):
            dec.admit_prefilled(0, handoff)
        dec.start(jnp.asarray(prompts[:B], jnp.int32))
        with pytest.raises(ValueError, match="still decoding"):
            dec.admit_prefilled(0, handoff)
        dec.retire(0)
        with pytest.raises(ValueError, match="out of range"):
            dec.admit_prefilled(B, handoff)


# -- the fault-free contract (acceptance 2) ------------------------------


class TestFleetOracle:
    def test_fleet_matches_solo_oracle(self, models, prompts):
        """Fault-free fleet output is bit-identical per request to the
        single-loop oracle regardless of which replica served it, and
        the routing spreads across every replica."""
        reps = [Replica(_loop_factory(models), f"r{i}") for i in range(3)]
        router = FleetRouter(reps)
        n = 9
        for i in range(n):
            assert router.submit(Request(rid=i, prompt=prompts[i])) is None
        results = router.run_until_idle()
        _assert_exactly_once(results, range(n))
        served = set()
        for res in results:
            assert isinstance(res, Completed), res
            assert res.meta["replica"] in {"r0", "r1", "r2"}
            served.add(res.meta["replica"])
            assert np.array_equal(res.tokens,
                                  _oracle(models, prompts[res.rid]))
        # least-loaded routing must not pile everything on one replica
        assert len(served) >= 2, served
        assert router.counters.routed == n
        router.close()

    def test_fleet_saturation_shed(self, models, prompts):
        """When every replica refuses, the router sheds at fleet level
        with a typed Overloaded — and still exactly one result each."""
        reps = [
            Replica(_loop_factory(models, max_batch=1, queue_capacity=1),
                    f"s{i}")
            for i in range(2)
        ]
        router = FleetRouter(reps)
        n = 12
        rejected = 0
        for i in range(n):
            rej = router.submit(Request(rid=i, prompt=prompts[i % 8]))
            if rej is not None:
                assert isinstance(rej, Overloaded)
                assert rej.reason == "fleet saturated"
                assert rej.meta["replica"] is None
                rejected += 1
        assert rejected > 0
        assert router.counters.shed_saturated == rejected
        results = router.run_until_idle()
        _assert_exactly_once(results, range(n))
        completed = [r for r in results if isinstance(r, Completed)]
        assert len(completed) == n - rejected
        router.close()


# -- chaos: replica death and self-healing (acceptance 1) ----------------


class TestReplicaSelfHealing:
    def test_replica_kill_salvage_rebuild_bit_correct(self, models,
                                                      prompts):
        """Kill one of 3 replicas mid-stream: every in-flight and queued
        request still gets a typed result (here: all complete, served
        elsewhere or on the rebuilt replica), the sick replica rebuilds
        from its factory, and post-recovery output is bit-correct."""
        built = {"n": 0}
        base = _loop_factory(models)

        def killed_factory():
            built["n"] += 1
            loop = base()
            if built["n"] == 1:
                # die on the SECOND round: requests are in flight
                return ReplicaKillInjector(loop, kill_on=(1,))
            return loop

        reps = [Replica(killed_factory, "r0"),
                Replica(base, "r1"),
                Replica(base, "r2")]
        router = FleetRouter(reps)
        n = 9
        for i in range(n):
            assert router.submit(Request(rid=i, prompt=prompts[i])) is None
        results = router.run_until_idle()
        _assert_exactly_once(results, range(n))
        for res in results:
            assert isinstance(res, Completed), res
            assert np.array_equal(res.tokens,
                                  _oracle(models, prompts[res.rid]))
        assert router.counters.heals == 1
        assert router.counters.requeued > 0
        assert built["n"] == 2          # rebuilt from the factory

        # post-recovery: drain the survivors; the REBUILT replica must
        # serve — bit-correct — and routing must report it did
        reps[1].loop.drain()
        reps[2].loop.drain()
        assert reps[1].health is HealthState.DRAINING
        assert router.submit(Request(rid=100, prompt=prompts[10])) is None
        out = router.run_until_idle()
        assert len(out) == 1 and isinstance(out[0], Completed)
        assert out[0].meta["replica"] == "r0"
        assert np.array_equal(out[0].tokens,
                              _oracle(models, prompts[10]))
        router.close()

    def test_flaky_probe_drains_and_rebuilds(self, models, prompts):
        """A failed health probe (no exception anywhere) decommissions
        the replica gracefully: salvage, rebuild, keep serving."""
        built = {"n": 0}
        base = _loop_factory(models)

        def flaky_factory():
            built["n"] += 1
            loop = base()
            if built["n"] == 1:
                return FlakyReplicaProxy(loop, fail_on=(1,))
            return loop

        reps = [Replica(flaky_factory, "f0"), Replica(base, "f1")]
        router = FleetRouter(reps)
        n = 6
        for i in range(n):
            assert router.submit(Request(rid=i, prompt=prompts[i])) is None
        results = router.run_until_idle()
        _assert_exactly_once(results, range(n))
        for res in results:
            assert isinstance(res, Completed), res
            assert np.array_equal(res.tokens,
                                  _oracle(models, prompts[res.rid]))
        assert router.counters.heals == 1
        assert built["n"] == 2
        router.close()


# -- lanes: prefill/decode disaggregation (acceptance 3) -----------------


class TestDisaggregation:
    def test_handoff_lane_bit_equal(self, models, prompts):
        """With the prefill lane on, every request still matches the
        solo oracle bit for bit, and the handoffs actually happened."""
        dec = Replica(_loop_factory(models), "d0")
        pre = PrefillReplica(_bat_factory(models), "p0")
        router = FleetRouter([dec], prefill_replicas=[pre])
        n = 4
        for i in range(n):
            assert router.submit(Request(rid=i, prompt=prompts[i])) is None
        results = router.run_until_idle()
        _assert_exactly_once(results, range(n))
        for res in results:
            assert isinstance(res, Completed), res
            assert np.array_equal(res.tokens,
                                  _oracle(models, prompts[res.rid]))
        assert router.counters.handoffs == n
        assert router.counters.handoff_bytes > 0
        assert dec.loop.counters.prefilled_admits == n
        router.close()

    def _drive_decode(self, router, dec, n_expect, budget_s=60.0):
        """Pump the decode replica inline, recording the cadence of its
        working rounds.  Idle rounds reset the chain, so a gap measures
        'decode had work and could not advance', never 'decode waited
        for arrivals'."""
        gaps, last = [], None
        results = []
        t_end = time.monotonic() + budget_s
        while len(results) < n_expect:
            assert time.monotonic() < t_end, \
                f"decode drive timed out with {len(results)}/{n_expect}"
            router.supervise()
            did = dec.pump()
            now = time.perf_counter()
            if did:
                if last is not None:
                    gaps.append(now - last)
                last = now
            else:
                last = None
                time.sleep(0.0005)
            results.extend(dec.drain_results())
            results.extend(router.drain_results())
        return gaps, results

    def test_long_prompt_burst_tpot(self, models, prompts, long_prompts,
                                    warm_jit):
        """The disaggregation headline: a burst of long prompts must not
        stall decode-lane token cadence.  Merged-lane control: long
        prompts prefill on the decode replica (SlowPrefillInjector
        stretches exactly those prefills) — its round cadence visibly
        stalls.  Disaggregated: the same stretched prefills run on the
        prefill replica's own thread — the decode lane's worst gap stays
        under the stall, and its p95 within a guarded bound of the
        no-long-prompt baseline."""
        DELAY = 0.4
        n_short, n_long = 10, 3
        shorts = [Request(rid=i, prompt=prompts[i % 8])
                  for i in range(n_short)]
        longs = [Request(rid=100 + i, prompt=long_prompts[i % 4])
                 for i in range(n_long)]
        # interleave so longs admit while shorts still decode
        storm = shorts[:3] + [longs[0]] + shorts[3:6] + [longs[1]] \
            + shorts[6:8] + [longs[2]] + shorts[8:]

        def slow_bat_factory():
            # stretch only LONG prefills (min_len between P and P_LONG)
            return SlowPrefillInjector(
                _bat_factory(models)(), delay_s=DELAY, min_len=P + 2)

        def slow_loop_factory():
            return ServingLoop(slow_bat_factory, max_batch=B,
                               queue_capacity=32)

        # baseline: no long prompts at all
        dec = Replica(_loop_factory(models, queue_capacity=32), "b0")
        router = FleetRouter([dec])
        for req in shorts:
            assert router.submit(
                Request(rid=req.rid, prompt=req.prompt)) is None
        base_gaps, base_results = self._drive_decode(router, dec, n_short)
        assert all(isinstance(r, Completed) for r in base_results)
        router.close()

        # merged-lane control: longs prefill ON the decode replica
        dec = Replica(slow_loop_factory, "m0")
        router = FleetRouter([dec])
        for req in storm:
            assert router.submit(req) is None
        merged_gaps, merged_results = self._drive_decode(
            router, dec, n_short + n_long)
        assert all(isinstance(r, Completed) for r in merged_results)
        router.close()

        # disaggregated: longs prefill on the prefill replica's thread
        dec = Replica(_loop_factory(models, queue_capacity=32), "d0")
        pre = PrefillReplica(slow_bat_factory, "p0")
        router = FleetRouter([dec], prefill_replicas=[pre],
                             prefill_threshold=P + 2)
        pre.start()
        try:
            for req in storm:
                assert router.submit(
                    Request(rid=req.rid, prompt=req.prompt)) is None
            dis_gaps, dis_results = self._drive_decode(
                router, dec, n_short + n_long)
        finally:
            router.close()
        assert all(isinstance(r, Completed) for r in dis_results)
        assert router.counters.handoffs == n_long

        # the merged control VISIBLY stalls: some round gap carries the
        # injected prefill delay
        assert max(merged_gaps) >= 0.8 * DELAY, max(merged_gaps)
        # the disaggregated decode lane never does
        assert max(dis_gaps) < 0.8 * DELAY, max(dis_gaps)
        # and its cadence p95 stays within a guarded bound of the
        # no-long-prompt baseline (generous: CPU timing noise)
        p95 = lambda xs: float(np.percentile(np.asarray(xs), 95))  # noqa: E731
        assert p95(dis_gaps) <= p95(base_gaps) * 4.0 + 0.1 * DELAY, \
            (p95(dis_gaps), p95(base_gaps))


# -- scale ---------------------------------------------------------------


@pytest.mark.slow
def test_thousand_request_trace(models):
    """The seeded serve-demo arrival trace at fleet scale: a thousand
    requests across 3 replicas, every one answered exactly once, every
    completion bit-correct against the solo oracle (spot-checked)."""
    rng = np.random.default_rng(0)
    n = 1000
    all_prompts = rng.integers(1, 64, size=(n, P)).astype(np.int32)
    reps = [
        Replica(_loop_factory(models, queue_capacity=400), f"r{i}")
        for i in range(3)
    ]
    router = FleetRouter(reps)
    for i in range(n):
        router.submit(Request(rid=i, prompt=all_prompts[i]))
    results = router.run_until_idle(max_rounds=100_000)
    _assert_exactly_once(results, range(n))
    completed = [r for r in results if isinstance(r, Completed)]
    assert len(completed) == n
    for res in completed[::137]:      # spot-check bit-correctness
        assert np.array_equal(res.tokens,
                              _oracle(models, all_prompts[res.rid]))
    served = {r.meta["replica"] for r in completed}
    assert served == {"r0", "r1", "r2"}
    router.close()


# -- threaded-fleet race windows (deterministic probes) ------------------


class TestHealRaces:
    """A thread-backed replica can die BETWEEN a pump's supervise and
    its busy check, and submits can race a heal's rebuild.  Both
    windows are pinned deterministically here (no threads needed)."""

    def test_busy_sees_dead_replica_with_outstanding(self, models,
                                                     prompts):
        """A dead replica still owing results must keep the fleet busy:
        ``run_until_idle`` exiting before the next supervision beat
        would strand the shadowed request (exactly-once violation)."""
        rep = Replica(_loop_factory(models), "r0")
        router = FleetRouter([rep])
        assert router.submit(Request(rid=0, prompt=prompts[0])) is None
        # simulate the driver thread dying AFTER this beat's supervise
        rep._dead = "simulated mid-beat death"
        assert router.busy
        results = router.run_until_idle()
        _assert_exactly_once(results, [0])
        assert isinstance(results[0], Completed)
        assert np.array_equal(results[0].tokens,
                              _oracle(models, prompts[0]))
        assert router.counters.heals == 1
        assert router.counters.requeued == 1
        router.close()

    def test_heal_refuses_submits_until_rebuilt(self, models, prompts):
        """During heal's rebuild a concurrent submit must REFUSE: the
        death flag clears only after the fresh loop is in place, else
        the request lands in the old, already-salvaged loop."""
        built = {"n": 0}
        base = _loop_factory(models)
        box = {}

        def factory():
            built["n"] += 1
            if built["n"] == 2:   # i.e. called from inside heal()
                box["refused"] = not box["rep"].submit(
                    Request(rid=1, prompt=prompts[1]))
            return base()

        rep = Replica(factory, "r0")
        box["rep"] = rep
        router = FleetRouter([rep])
        assert router.submit(Request(rid=0, prompt=prompts[0])) is None
        rep._dead = "simulated"
        results = router.run_until_idle()
        _assert_exactly_once(results, [0])
        assert box["refused"] is True
        # healed: the replica accepts and serves again
        assert router.submit(Request(rid=2, prompt=prompts[2])) is None
        out = router.run_until_idle()
        _assert_exactly_once(out, [2])
        assert isinstance(out[0], Completed)
        router.close()


# -- session affinity over per-replica prefix stores (ISSUE 11) ----------


@pytest.mark.kvcache
class TestSessionAffinity:
    """Requests carrying a ``session`` key stick to the replica whose
    prefix store holds their pages; the cached turn decodes bit-equal
    to the oracle; a heal invalidates the stamp and the session
    re-routes cleanly with every request still typed exactly once."""

    PAGE = 4

    def _fleet(self, models, kill_r0_on=None, **bat_kw):
        stores = [PrefixKVStore(page_tokens=self.PAGE,
                                capacity_bytes=1 << 30) for _ in range(2)]
        base = _bat_factory(models, **bat_kw)
        built = {"r0": 0}

        def factory(i):
            def make():
                loop = ServingLoop(base, max_batch=B, queue_capacity=16,
                                   kvstore=stores[i])
                if i == 0 and kill_r0_on is not None:
                    built["r0"] += 1
                    if built["r0"] == 1:
                        return ReplicaKillInjector(loop,
                                                   kill_on=kill_r0_on)
                return loop
            return make

        reps = [Replica(factory(i), f"r{i}") for i in range(2)]
        return FleetRouter(reps), reps, stores

    def _turn(self, prompts, t):
        # turn t of the session: the first page is shared, the tail is
        # per-turn — the multi-turn shape at CPU-proxy size
        p = prompts[0].copy()
        p[self.PAGE:] = prompts[t][self.PAGE:]
        return p

    @pytest.mark.parametrize("int8", [False, True])
    def test_sticky_turn_hits_cache_bit_equal(self, models, prompts, int8):
        kw = {"kv_cache_int8": True} if int8 else {}
        router, reps, stores = self._fleet(models, **kw)
        p1, p2 = self._turn(prompts, 1), self._turn(prompts, 2)

        assert router.submit(Request(rid="t1", prompt=p1,
                                     session="s")) is None
        out1 = router.run_until_idle()
        _assert_exactly_once(out1, ["t1"])
        holder = out1[0].meta["replica"]
        assert router._affinity["s"] == holder

        # load the sticky replica so least-loaded WOULD pick the other:
        # affinity must override the load tiebreak, not ride it
        idx = int(holder[1])
        assert router.submit(Request(rid="fill", prompt=prompts[5])) is None
        if reps[idx].load == 0:
            reps[1 - idx].loop.submit(Request(rid="x", prompt=prompts[6]))
        assert router.submit(Request(rid="t2", prompt=p2,
                                     session="s")) is None
        assert router.counters.affinity_routed == 1
        out = router.run_until_idle()
        t2 = [r for r in out if r.rid == "t2"][0]
        assert isinstance(t2, Completed)
        assert t2.meta["replica"] == holder
        # the sticky replica really served turn 2 from its pages...
        snap = reps[idx].loop.counters.snapshot()
        assert snap["kv_hits"] >= 1
        assert stores[idx].snapshot()["hits"] >= 1
        assert stores[idx].snapshot()["pinned"] == 0
        # ...and the cached decode is bit-equal to the oracle
        assert np.array_equal(t2.tokens, _oracle(models, p2))
        router.close()

    def test_heal_invalidates_affinity_rerouted_exactly_once(
            self, models, prompts):
        router, reps, stores = self._fleet(models, kill_r0_on=(1,))
        p1, p2, p3 = (self._turn(prompts, t) for t in (1, 2, 3))

        assert router.submit(Request(rid="t1", prompt=p1,
                                     session="s")) is None
        out1 = router.run_until_idle()
        _assert_exactly_once(out1, ["t1"])
        assert out1[0].meta["replica"] == "r0"   # idle tie -> r0, stamped

        # turn 2 sticks to r0, which dies mid-round; the heal salvages
        # it, drops the stamp, and the re-route still types it once
        assert router.submit(Request(rid="t2", prompt=p2,
                                     session="s")) is None
        out2 = router.run_until_idle()
        _assert_exactly_once(out2, ["t2"])
        assert isinstance(out2[0], Completed)
        assert np.array_equal(out2[0].tokens, _oracle(models, p2))
        assert router.counters.heals == 1
        assert router.counters.affinity_invalidated >= 1
        # the rebuilt replica's store survived, with no leaked pins
        assert stores[0].snapshot()["pinned"] == 0

        # turn 3 routes cleanly on the fresh stamp (wherever the
        # salvaged turn 2 landed) and completes bit-correct
        assert router.submit(Request(rid="t3", prompt=p3,
                                     session="s")) is None
        out3 = router.run_until_idle()
        _assert_exactly_once(out3, ["t3"])
        assert isinstance(out3[0], Completed)
        assert np.array_equal(out3[0].tokens, _oracle(models, p3))
        assert router._affinity["s"] == out3[0].meta["replica"]
        router.close()
