"""Warm-start tier tests (ISSUE 15): persistent compile cache, AOT
executable reuse, WarmupPlan, emergency-tier restore in the worker, and
the autoscaler's pre-warmed standby pool.

Layered like the tier itself: pure-host units first (cache dir
resolution, AOT keys, plan wire format), then in-process compile-cache
behavior (CompileRecord.cache_hit across a ``jax.clear_caches()``,
AOT serialize/deserialize round-trip), then the batcher/loop warmup
path, the ``restore_params`` emergency election, and the standby-pool
control logic against fakes.  The real-subprocess promotion ride lives
at the bottom under the ``warmstart`` marker (heavy tail); the
cold-vs-warm spawn ratio itself is guarded in
``tests/test_bench_guard.py::TestWarmStartGuard``.
"""

import os
import sys
import time
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rocket_tpu.tune import compile_cache  # noqa: E402
from rocket_tpu.tune.warmup import (  # noqa: E402
    WarmupPlan,
    plan_for_batcher,
    warm_batcher,
)

import rocket_tpu.testing.workers as tw  # noqa: E402


# -- cache dir resolution ---------------------------------------------------


def test_cache_dir_env_override(monkeypatch, tmp_path):
    monkeypatch.setenv("ROCKET_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    assert compile_cache.cache_dir() == str(tmp_path / "cc")


@pytest.mark.parametrize("value", ["0", "off", "none", "disabled", " OFF "])
def test_cache_dir_disable_values(monkeypatch, value):
    monkeypatch.setenv("ROCKET_TPU_COMPILE_CACHE", value)
    assert compile_cache.cache_dir() is None
    # a disabled tier arms nothing and reports so
    assert compile_cache.enable_compile_cache() is None


def test_cache_dir_defaults_under_repo(monkeypatch):
    monkeypatch.delenv("ROCKET_TPU_COMPILE_CACHE", raising=False)
    d = compile_cache.cache_dir()
    assert d is not None
    assert d.endswith(os.path.join("experiments", "compile_cache"))


def test_aot_key_is_deterministic_and_filesystem_safe():
    a = compile_cache.aot_key("generate/spec_round", n_draft=4, batch=3,
                              backend="cpu")
    b = compile_cache.aot_key("generate/spec_round", backend="cpu", batch=3,
                              n_draft=4)
    assert a == b                       # kwarg order is canonicalized
    assert "/" not in a and " " not in a
    shaped = compile_cache.aot_key("engine/step", shapes="(3, 8)int32")
    assert all(c.isalnum() or c in "_.=-" for c in shaped)
    assert a != compile_cache.aot_key("generate/spec_round", n_draft=5,
                                      batch=3, backend="cpu")


# -- WarmupPlan -------------------------------------------------------------


def test_warmup_plan_wire_roundtrip():
    plan = WarmupPlan(max_batch=3, prompt_len=1, n_drafts=(4, 6), aot=False)
    assert WarmupPlan.from_wire(plan.to_wire()) == plan
    # wire dicts are plain data (WorkerSpec kwargs must pickle cleanly)
    wired = plan.to_wire()
    assert wired["n_drafts"] == [4, 6] and wired["aot"] is False
    # missing optional fields take the defaults
    assert WarmupPlan.from_wire({"max_batch": 2}) == WarmupPlan(max_batch=2)


def test_plan_for_batcher_dedupes_and_drops_nonpositive():
    bat = types.SimpleNamespace(n_draft=4)
    plan = plan_for_batcher(bat, 3, extra_drafts=(6, 4, 6, 0, -2))
    assert plan.max_batch == 3 and plan.prompt_len == 1
    assert plan.n_drafts[0] == 4        # the configured draft leads
    assert 6 in plan.n_drafts
    assert len(plan.n_drafts) == len(set(plan.n_drafts))
    assert all(n > 0 for n in plan.n_drafts)


# -- compile cache: arming, counters, per-edge cache_hit --------------------


@pytest.mark.goodput
class TestCompileCache:
    def test_enable_is_idempotent_and_registers_export(self, tmp_path):
        from rocket_tpu.observe import export

        d = str(tmp_path / "cc")
        assert compile_cache.enable_compile_cache(d) == d
        assert compile_cache.enable_compile_cache(d) == d
        assert compile_cache.enabled_dir() == d
        assert os.path.isdir(d)
        snap = export.collect()
        assert "compile_cache/hits" in snap
        assert "compile_cache/bytes" in snap

    def test_compile_record_cache_hit_after_cache_retrieval(
            self, tmp_path, devices):
        """The per-edge visibility promise: a compile served from the
        persistent disk cache stamps ``CompileRecord.cache_hit=True``
        (``jax.clear_caches()`` drops the dispatch cache, so the second
        dispatch re-lowers — but retrieves instead of compiling)."""
        import jax
        import jax.numpy as jnp

        from rocket_tpu.observe.ledger import (
            arm_ledgers,
            disarm_ledgers,
            get_retrace_ledger,
            ledger_call,
        )

        compile_cache.enable_compile_cache(str(tmp_path / "cc"))
        compile_cache.reset_stats()
        arm_ledgers()
        try:
            fn = jax.jit(lambda x: (x * 3.0 + 1.0).sum())
            x = jnp.arange(512.0)
            ledger_call(fn, "warmstart/probe", x)       # cold: real compile
            ledger = get_retrace_ledger()
            recs = [r for r in ledger.records()
                    if r.name == "warmstart/probe"]
            assert recs and recs[-1].cache_hit is False
            jax.clear_caches()
            with ledger.expect_compile("warmstart/probe"):
                ledger_call(fn, "warmstart/probe", x)   # warm: disk hit
            recs = [r for r in ledger.records()
                    if r.name == "warmstart/probe"]
            assert recs[-1].cache_hit is True
            assert ledger.snapshot()["cache_hits"] >= 1.0
            assert compile_cache.hit_count() >= 1
            snap = compile_cache.snapshot()
            assert snap["hits"] >= 1 and snap["entries"] >= 1
        finally:
            disarm_ledgers()
            get_retrace_ledger().reset()

    def test_aot_save_load_roundtrip_and_fallthrough(self, tmp_path,
                                                     devices):
        import jax
        import jax.numpy as jnp

        compile_cache.enable_compile_cache(str(tmp_path / "cc"))
        compile_cache.reset_stats()
        fn = jax.jit(lambda x: x * 2.0 + 1.0)
        x = jnp.arange(8.0)
        compiled = fn.lower(x).compile()
        key = compile_cache.aot_key("warmstart/aot_probe", n=8)
        assert compile_cache.save_aot(key, compiled)
        loaded = compile_cache.load_aot(key)
        assert loaded is not None
        np.testing.assert_array_equal(np.asarray(loaded(x)),
                                      np.asarray(compiled(x)))
        # a missing key is a silent fall-through, never an error
        assert compile_cache.load_aot("warmstart/no_such_key") is None
        # a corrupt payload falls through too (counted, not raised)
        path = os.path.join(str(tmp_path / "cc"), "aot", key + ".pkl")
        with open(path, "wb") as f:
            f.write(b"not a pickle")
        assert compile_cache.load_aot(key) is None
        snap = compile_cache.snapshot()
        assert snap["aot_saved"] >= 1 and snap["aot_hits"] >= 1
        assert snap["aot_fallthrough"] >= 1


# -- WarmupPlan execution against the tiny batcher --------------------------


@pytest.mark.warmstart
class TestWarmBatcher:
    def test_warm_batcher_compiles_edges_then_aot_hits(self, tmp_path,
                                                       devices):
        from rocket_tpu.models.generate import ContinuousBatcher

        compile_cache.enable_compile_cache(str(tmp_path / "cc"))
        compile_cache.reset_stats()
        model, draft, params, dparams = tw.tiny_models()
        bat = ContinuousBatcher(model, draft, params, dparams,
                                total_len=tw.TOTAL, n_draft=tw.NDRAFT,
                                eos_token=None)
        plan = plan_for_batcher(bat, tw.B)
        assert tw.NDRAFT in plan.n_drafts
        stats = warm_batcher(bat, plan)
        # prefill + at least one spec round compiled, timed, counted
        assert stats["edges"] >= 2
        assert stats["compile_ms"] > 0.0
        # the spec-round executable serialized (CPU supports it) —
        # a second pass loads it instead of compiling
        assert stats["aot_serialized"] >= 1
        stats2 = warm_batcher(bat, plan)
        assert stats2["aot_hits"] >= 1

    def test_serving_loop_consumes_auto_plan(self, devices):
        from rocket_tpu.serve import Completed, Request

        loop = tw.build_tiny_loop(warmup="auto")
        try:
            assert loop.warm_stats.get("edges", 0) >= 2
            # warm start is an accelerant, never a numerics change:
            # the warmed loop still serves bit-equal to a plain one
            prompt = np.random.default_rng(13).integers(
                1, tw.VOCAB, size=tw.P).astype(np.int32)
            loop.submit(Request(rid="r0", prompt=prompt))
            (out,) = loop.run_until_idle()
            assert isinstance(out, Completed)
        finally:
            loop.close()
        plain = tw.build_tiny_loop()
        try:
            plain.submit(Request(rid="r0", prompt=prompt))
            (ref,) = plain.run_until_idle()
        finally:
            plain.close()
        np.testing.assert_array_equal(np.asarray(out.tokens),
                                      np.asarray(ref.tokens))


# -- restore_params: emergency-tier election (satellite fix) ----------------


@pytest.mark.elastic
class TestEmergencyRestore:
    SEED = 5    # differs from the builder default, so a match PROVES restore

    def _assert_restored(self, restored):
        import jax

        _, _, want, _ = tw.tiny_models(seed_target=self.SEED)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            restored, want)

    def test_emergency_only_root_worker_layout(self, tmp_path, devices):
        from rocket_tpu.serve.worker import restore_params

        tw.save_tiny_emergency(str(tmp_path), seed_target=self.SEED)
        _, _, targets, _ = tw.tiny_models()     # default-seed template
        self._assert_restored(restore_params(str(tmp_path), targets))

    def test_emergency_only_root_trainer_layout(self, tmp_path, devices):
        """The flush a TRAINER leaves behind nests params inside the
        capsule state (``{"model": {"state": {"params": ...}}}``); the
        manifest's recorded leaf paths must locate the subtree."""
        from rocket_tpu.serve.worker import restore_params

        tw.save_tiny_emergency(str(tmp_path), seed_target=self.SEED,
                               trainer_layout=True)
        _, _, targets, _ = tw.tiny_models()
        self._assert_restored(restore_params(str(tmp_path), targets))

    def test_missing_root_still_raises(self, tmp_path):
        from rocket_tpu.serve.worker import restore_params

        with pytest.raises(FileNotFoundError):
            restore_params(str(tmp_path / "empty"), {})


# -- standby pool: control logic against fakes ------------------------------


class _FakeStandby:
    """Replica-shaped fake with the warm-start surface the pool touches
    (rename/close/compile_ms/standby_source)."""

    def __init__(self, rid):
        self.replica_id = rid
        self.load = 0
        self._dead = None
        self.threaded = False
        self.compile_ms = 123.0
        self.renames = []
        self.closed = False
        self.standby_source = None

    def rename(self, rid):
        self.renames.append(rid)
        self.replica_id = rid

    def start(self, idle_s=0.001):
        pass

    def drain(self):
        pass

    def close(self):
        self.closed = True


class _FakeRouter:
    def __init__(self, n=1):
        self.replicas = [_FakeStandby(f"r{i}") for i in range(n)]
        self._retiring = []
        self.added = []

    def add_replica(self, rep, *, start=None):
        self.replicas.append(rep)
        self.added.append(rep.replica_id)

    def remove_replica(self, rid):
        (rep,) = [r for r in self.replicas if r.replica_id == rid]
        self.replicas.remove(rep)
        return rep


def _standby_scaler(router, metrics, policy, spawned):
    from rocket_tpu.serve.autoscale import Autoscaler

    def spawn(rid):
        rep = _FakeStandby(rid)
        spawned.append(rep)
        return rep

    return Autoscaler(router, spawn, policy,
                      collect_fn=lambda: dict(metrics),
                      clock=time.monotonic)


@pytest.mark.procfleet
class TestStandbyPool:
    def _policy(self, **kw):
        from rocket_tpu.serve.autoscale import SLOPolicy

        base = dict(ttft_p95_ms=500.0, breach_rounds=1,
                    scale_up_cooldown_s=0.0, max_replicas=4, standby=1)
        base.update(kw)
        return SLOPolicy(**base)

    def test_pool_fills_synchronously_on_construction(self):
        spawned = []
        auto = _standby_scaler(_FakeRouter(1), {}, self._policy(), spawned)
        try:
            assert auto.counters.standby_ready == 1
            assert [r.replica_id for r in spawned] == ["standby-1"]
            # heal preference wired onto the existing router replicas
            (existing, ) = [r for r in auto.router.replicas
                            if not r.replica_id.startswith("standby")]
            assert existing.standby_source == auto._take_standby
        finally:
            auto.close()
        assert spawned[0].closed        # close tears the pool down

    def test_scale_up_promotes_standby_in_o_route(self):
        spawned = []
        router = _FakeRouter(1)
        metrics = {"serve_fleet/ttft_ms/p95": 900.0}
        auto = _standby_scaler(router, metrics, self._policy(), spawned)
        try:
            warm = spawned[0]
            assert auto.step() == 1
            # the promoted replica IS the pre-warmed one, renamed over
            # its live identity — no new spawn inside the breach
            assert router.added == ["scale-1"]
            assert router.replicas[-1] is warm
            assert warm.renames == ["scale-1"]
            assert auto.counters.standby_promotions == 1
            # the decision log surfaces the worker's READY compile_ms
            event = auto.events[-1]
            assert event["action"] == "scale_up"
            assert event["standby"] is True
            assert event["compile_ms"] == 123.0
            # the pool refills in the background toward standby=1
            assert auto.wait_standby() == 1
            assert auto.counters.standby_ready == 1
        finally:
            auto.close()

    def test_cold_spawn_fallback_when_pool_empty(self):
        spawned = []
        router = _FakeRouter(1)
        metrics = {"serve_fleet/ttft_ms/p95": 900.0}
        auto = _standby_scaler(router, metrics,
                               self._policy(standby=0), spawned)
        try:
            assert auto._take_standby() is None
            assert auto.step() == 1
            event = auto.events[-1]
            assert event["standby"] is False
            assert router.added == ["scale-1"]
        finally:
            auto.close()

    def test_failed_promotion_falls_back_to_cold_spawn(self):
        spawned = []
        router = _FakeRouter(1)
        metrics = {"serve_fleet/ttft_ms/p95": 900.0}
        auto = _standby_scaler(router, metrics, self._policy(), spawned)
        try:
            warm = spawned[0]
            warm.rename = lambda rid: (_ for _ in ()).throw(
                RuntimeError("standby died"))
            assert auto.step() == 1
            assert warm.closed          # the broken standby is reaped
            assert router.replicas[-1] is not warm
            assert router.added == ["scale-1"]
            assert auto.counters.standby_promotions == 0
            assert auto.events[-1]["standby"] is False
        finally:
            auto.close()

    def test_fleet_source_exports_spawn_and_heal_percentiles(self):
        from rocket_tpu.observe import export
        from rocket_tpu.observe.trace import Histogram
        from rocket_tpu.serve.autoscale import register_fleet_source
        from rocket_tpu.serve.metrics import ServeLatency

        class _Router:
            def __init__(self, reps):
                self.replicas = reps
                self._retiring = []

            def snapshot(self):
                return {"submitted": 0.0}

            def latency(self):
                return ServeLatency()

        rep = _FakeStandby("r0")
        rep.spawn_ms = Histogram()
        rep.heal_ms = Histogram()
        rep.first_token_ms = Histogram()
        for v in (1000.0, 2000.0, 3000.0):
            rep.spawn_ms.record(v)
        rep.heal_ms.record(500.0)
        name = "serve_fleet_ws_test"
        register_fleet_source(_Router([rep]), name)
        try:
            snap = export.collect()
            assert snap[f"{name}/spawn_ms/count"] == 3.0
            assert snap[f"{name}/spawn_ms/p50"] == 2000.0
            assert snap[f"{name}/heal_ms/p99"] == 500.0
            # an empty histogram exports no keys (thread-backed fleets)
            assert f"{name}/first_token_ms/count" not in snap
        finally:
            export.unregister_source(name)


# -- the real thing: a promoted standby serves without compiling ------------


@pytest.mark.warmstart
@pytest.mark.procfleet
def test_standby_promotion_real_worker_serves_without_compile(tmp_path):
    """ISSUE 15 acceptance: with ``standby=1`` the scale-up promotes an
    already-READY worker — the first routed request completes without
    ever touching the backend compiler (the plan pre-paid every edge
    including the per-prompt-length admit; serving dispatches are
    dispatch-cache hits or disk retrievals), under its new fleet
    identity, with zero unexpected retraces cross-process."""
    from rocket_tpu.serve.autoscale import Autoscaler, SLOPolicy
    from rocket_tpu.serve.procfleet import ProcReplica
    from rocket_tpu.serve.types import Completed, Request
    from rocket_tpu.serve.wire import WorkerSpec

    plan = WarmupPlan(max_batch=tw.B, n_drafts=(tw.NDRAFT,),
                      prompt_lens=(tw.P,))
    spec = WorkerSpec(builder="rocket_tpu.testing.workers:build_tiny_loop",
                      kwargs={"warmup": plan.to_wire()})
    env = {"ROCKET_TPU_COMPILE_CACHE": str(tmp_path / "cc"),
           "JAX_PLATFORMS": "cpu"}

    def spawn(rid):
        return ProcReplica(spec, rid, spawn_timeout_s=600.0,
                           rpc_timeout_s=600.0, env=env)

    router = _FakeRouter(0)
    metrics = {"serve_fleet/ttft_ms/p95": 900.0}
    auto = Autoscaler(router, spawn,
                      SLOPolicy(ttft_p95_ms=500.0, breach_rounds=1,
                                scale_up_cooldown_s=0.0, max_replicas=2,
                                standby=1),
                      collect_fn=lambda: dict(metrics))
    rep = None
    try:
        assert auto.counters.standby_ready == 1
        assert auto.step() == 1
        assert auto.counters.standby_promotions == 1
        rep = router.replicas[-1]
        assert rep.replica_id == "scale-1"
        # the worker ran its WarmupPlan (prefill + round + admit) pre-READY
        assert rep.ready_info.get("warm_stats", {}).get("edges", 0) >= 3
        pre = rep.collect()
        assert pre["goodput"].get("compile_s", 0.0) > 0.0  # real work
        backend_before = pre["compile_cache"]["backend_compile_s"]
        prompt = np.random.default_rng(13).integers(
            1, tw.VOCAB, size=tw.P).astype(np.int32)
        assert rep.submit(Request(rid="r0", prompt=prompt))
        out = []
        for _ in range(400):
            rep.pump()
            out = rep.drain_results()
            if out:
                break
        (res,) = out
        assert isinstance(res, Completed)
        # stamped with the promoted identity, not the standby's
        assert res.meta.get("replica") == "scale-1"
        post = rep.collect()
        # the admit edge — the only named compile serving could trigger
        # — was served from the persistent cache (the plan pre-paid it),
        # visible per-edge through CompileRecord.cache_hit
        assert post["ledger"]["cache_hits"] > pre["ledger"]["cache_hits"]
        assert post["compile_cache"]["hits"] > pre["compile_cache"]["hits"]
        # backend-compiler residue is op-by-op noise (host-side fold_in
        # and friends, ~0.1s), nowhere near an un-warmed admit's ~2.4s
        assert post["compile_cache"]["backend_compile_s"] \
            - backend_before < 1.0
        assert post["ledger"]["sentinel_dumps"] == 0.0
    finally:
        auto.close()
        if rep is not None:
            rep.close()
