"""Muon optimizer (engine/muon.py): Newton-Schulz orthogonalization and
end-to-end training through the capsule API's param groups."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.engine.muon import muon, orthogonalize


def test_orthogonalize_near_orthogonal(devices):
    rng = np.random.default_rng(0)
    for shape in [(64, 64), (32, 128), (128, 32)]:
        g = jnp.asarray(rng.normal(size=shape), jnp.float32)
        o = orthogonalize(g, steps=8)
        assert o.shape == g.shape
        sv = np.linalg.svd(np.asarray(o, np.float64), compute_uv=False)
        # NS converges singular values into ~[0.7, 1.25] — approximate
        # orthogonality is the contract, not exact
        assert sv.max() < 1.6 and sv.min() > 0.4, (shape, sv.min(), sv.max())
        # sign structure follows UV^T of the input: positive alignment
        u, _, vt = np.linalg.svd(np.asarray(g, np.float64))
        uvt = u[:, : min(shape)] @ vt[: min(shape)]
        align = float(np.sum(uvt * np.asarray(o, np.float64)))
        assert align > 0.5 * min(shape)


def test_orthogonalize_rejects_non_matrix(devices):
    with pytest.raises(ValueError, match="matrix"):
        orthogonalize(jnp.zeros((4,)))


def test_muon_trains_mlp(devices):
    import flax.linen as nn
    from rocket_tpu.models.objectives import cross_entropy

    class Net(nn.Module):
        @nn.compact
        def __call__(self, batch, train: bool = False):
            x = nn.relu(nn.Dense(32, use_bias=False)(batch["x"]))
            out = rt.Attributes(batch)
            out["logits"] = nn.Dense(4, use_bias=False)(x)
            return out

    rng = np.random.default_rng(0)
    batch = {
        "x": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 4, size=(32,)), jnp.int32),
    }
    mod = rt.Module(
        Net(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(tx=muon(learning_rate=0.05)),
        ],
    )
    mod.bind(rt.Runtime())
    mod.setup()
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    losses = []
    for _ in range(20):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["ce"]))
    assert losses[-1] < 0.5 * losses[0], losses[:3] + losses[-3:]
    mod.destroy()


def test_muon_param_groups_with_adamw(devices):
    """The paper's recommended split through the capsule API: Muon on
    hidden 2D matrices, adamw on embeddings/the rest."""
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    from rocket_tpu.engine.muon import hidden_matrices as is_hidden_matrix

    def is_rest(path, leaf):
        return not is_hidden_matrix(path, leaf)

    cfg = TransformerConfig.tiny(attention="dot")
    mod = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(tx=muon(learning_rate=0.02),
                         params_filter=is_hidden_matrix, tag="lr_muon"),
            rt.Optimizer(learning_rate=1e-2, params_filter=is_rest,
                         tag="lr_adam"),
        ],
    )
    mod.bind(rt.Runtime())
    mod.setup()
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(8, 64)), jnp.int32)}
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    losses = []
    for _ in range(8):
        attrs.batch = batch
        mod.launch(attrs)
        losses.append(float(attrs.step_logs["lm"]))
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]
    mod.destroy()


def test_muon_non_2d_leaves_fall_back_to_momentum(devices):
    tx = muon(learning_rate=1.0, momentum=0.0)
    params = {"w": jnp.eye(4), "b": jnp.ones((4,))}
    state = tx.init(params)
    grads = {"w": jnp.eye(4) * 3.0, "b": jnp.full((4,), 2.0)}
    updates, _ = tx.update(grads, state, params)
    # bias: plain momentum direction scaled by -lr
    np.testing.assert_allclose(np.asarray(updates["b"]), -2.0 * np.ones(4))
    # matrix: orthogonalized — identity direction has unit singular values
    sv = np.linalg.svd(np.asarray(updates["w"]), compute_uv=False)
    assert sv.max() < 1.6 and sv.min() > 0.4
