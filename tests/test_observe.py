"""Observability tests: tracker flush protocol, throughput meter, backends."""

import json
import os

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.observe import JsonlBackend, MemoryBackend, Throughput
from rocket_tpu.observe.backends import resolve_backend


class TestTracker:
    def _tracked(self, flush_every=2):
        backend = MemoryBackend()
        tracker = rt.Tracker(backend, flush_every=flush_every)
        runtime = rt.Runtime()
        tracker.bind(runtime)
        tracker.setup()
        return tracker, backend

    def test_buffered_flush_cadence(self):
        tracker, backend = self._tracked(flush_every=3)
        attrs = rt.Attributes()
        tracker.set(attrs)
        for step in range(5):
            attrs.tracker.scalars.append(
                rt.Attributes(step=step, data={"loss": float(step)})
            )
            tracker.launch(attrs)
        # flushed once at step 3; 2 records still buffered
        assert len(backend.scalars) == 3
        tracker.reset(attrs)  # final flush + drop buffers
        assert len(backend.scalars) == 5
        assert attrs.tracker is None

    def test_backend_shared_via_runtime_registry(self):
        backend = MemoryBackend()
        runtime = rt.Runtime()
        t1 = rt.Tracker(backend)
        t2 = rt.Tracker(backend)
        for t in (t1, t2):
            t.bind(runtime)
            t.setup()
        assert t1._backend is t2._backend

    def test_jsonl_backend(self, tmp_path):
        backend = JsonlBackend(str(tmp_path))
        backend.log_scalars({"a": 1.5}, step=7)
        backend.close()
        line = json.loads(open(tmp_path / "metrics.jsonl").read().strip())
        assert line["a"] == 1.5 and line["step"] == 7

    def test_resolve_backend_needs_project_dir(self):
        with pytest.raises(RuntimeError, match="project dir"):
            resolve_backend("tensorboard", None)
        with pytest.raises(ValueError, match="unknown tracker backend"):
            resolve_backend("wandb-nope", "/tmp")


class TestThroughput:
    def test_rate_published_to_loop_state(self):
        tp = Throughput(ema=0.0, log_every=2)
        attrs = rt.Attributes(
            batch={"x": np.zeros((16, 2))},
            looper=rt.Attributes(state=rt.Attributes()),
            tracker=rt.Attributes(scalars=[], images=[]),
        )
        tp.set(attrs)
        for _ in range(4):
            tp.launch(attrs)
        assert "throughput" in attrs.looper.state
        tags = [t for rec in attrs.tracker.scalars for t in rec.data]
        assert "throughput/samples_per_sec" in tags
