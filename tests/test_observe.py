"""Observability tests: tracker flush protocol, throughput meter, backends."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.observe import (
    JsonlBackend,
    MemoryBackend,
    Profiler,
    Throughput,
    scalar_sink,
)
from rocket_tpu.observe.backends import resolve_backend


class TestTracker:
    def _tracked(self, flush_every=2):
        backend = MemoryBackend()
        tracker = rt.Tracker(backend, flush_every=flush_every)
        runtime = rt.Runtime()
        tracker.bind(runtime)
        tracker.setup()
        return tracker, backend

    def test_buffered_flush_cadence(self):
        tracker, backend = self._tracked(flush_every=3)
        attrs = rt.Attributes()
        tracker.set(attrs)
        for step in range(5):
            attrs.tracker.scalars.append(
                rt.Attributes(step=step, data={"loss": float(step)})
            )
            tracker.launch(attrs)
        # flushed once at step 3; 2 records still buffered
        assert len(backend.scalars) == 3
        tracker.reset(attrs)  # final flush + drop buffers
        assert len(backend.scalars) == 5
        assert attrs.tracker is None

    def test_backend_shared_via_runtime_registry(self):
        backend = MemoryBackend()
        runtime = rt.Runtime()
        t1 = rt.Tracker(backend)
        t2 = rt.Tracker(backend)
        for t in (t1, t2):
            t.bind(runtime)
            t.setup()
        assert t1._backend is t2._backend

    def test_composite_shares_components_with_plain_tracker(self):
        """Tracker('memory') and Tracker(['memory', ...]) must share ONE
        component instance — duplicate writers on the same sink would
        interleave/duplicate records."""
        runtime = rt.Runtime()
        plain = rt.Tracker("memory")
        composite = rt.Tracker(["memory"])
        for t in (plain, composite):
            t.bind(runtime)
            t.setup()
        assert composite._backend.backends[0] is plain._backend
        # two composites (any order) also share
        composite2 = rt.Tracker(["memory"])
        composite2.bind(runtime)
        composite2.setup()
        assert composite2._backend.backends[0] is plain._backend

    def test_jsonl_backend(self, tmp_path):
        backend = JsonlBackend(str(tmp_path))
        backend.log_scalars({"a": 1.5}, step=7)
        backend.close()
        line = json.loads(open(tmp_path / "metrics.jsonl").read().strip())
        assert line["a"] == 1.5 and line["step"] == 7

    def test_resolve_backend_needs_project_dir(self):
        with pytest.raises(RuntimeError, match="project dir"):
            resolve_backend("tensorboard", None)
        with pytest.raises(ValueError, match="unknown tracker backend"):
            resolve_backend("wandb-nope", "/tmp")

    def test_wandb_backend(self, tmp_path, monkeypatch):
        """Tracker('wandb') logs through the wandb run API (offline mode);
        skipped when wandb is not installed (it is not a framework dep)."""
        wandb = pytest.importorskip("wandb")

        monkeypatch.setenv("WANDB_MODE", "offline")
        backend = resolve_backend("wandb", str(tmp_path))
        backend.log_scalars({"loss": 0.5}, step=3)
        backend.log_images(
            {"img": np.zeros((4, 4, 3), np.float32)}, step=3
        )
        backend.close()


class TestImageLogging:
    """Image records flow producer -> tracker buffer -> backend end-to-end
    (VERDICT r1 missing #4: log_images previously had no producer)."""

    def test_image_logger_through_pipeline(self, tmp_path, devices):
        import jax.numpy as jnp

        from test_pipeline import MLP, synthetic_classification

        rng = np.random.default_rng(0)
        data = {
            "image": rng.normal(size=(64, 8, 8, 3)).astype(np.float32),
            "x": rng.normal(size=(64, 16)).astype(np.float32),
            "label": rng.integers(0, 4, size=64).astype(np.int32),
        }
        backend = MemoryBackend()
        from rocket_tpu.models.objectives import cross_entropy

        looper = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=16),
                rt.Module(
                    MLP(),
                    capsules=[
                        rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                        rt.Optimizer(learning_rate=1e-2),
                    ],
                ),
                rt.ImageLogger(key="image", max_images=2, log_every=2),
                rt.Tracker(backend),
            ],
            progress=False,
        )
        launcher = rt.Launcher(
            capsules=[looper], tag="img", num_epochs=1,
            project_root=str(tmp_path),
        )
        launcher.launch()
        # 4 iterations, log_every=2 -> records at iters 0 and 2
        assert len(backend.images) == 2
        step, record = backend.images[0]
        assert len(record) == 2  # max_images
        img = next(iter(record.values()))
        assert np.asarray(img).shape == (8, 8, 3)

    def test_tensorboard_backend_writes_images(self, tmp_path):
        from rocket_tpu.observe.backends import TensorBoardBackend

        backend = TensorBoardBackend(str(tmp_path))
        backend.log_images(
            {"sample": np.random.default_rng(0).random((8, 8, 3))}, step=1
        )
        backend.close()
        event_files = [f for f in os.listdir(tmp_path) if "tfevents" in f]
        assert event_files
        assert os.path.getsize(tmp_path / event_files[0]) > 100


class TestInStepMeter:
    """In-step metric reduction (SURVEY §5.5 / VERDICT r1 weakness #8):
    device-side accumulation, one host transfer per cycle, numerically
    identical to the host-gather path."""

    def _eval_batches(self, devices, n_batches=3):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        batches = []
        for i in range(n_batches):
            logits = rng.normal(size=(16, 4)).astype(np.float32)
            label = rng.integers(0, 4, size=16).astype(np.int32)
            # final batch is partial: half the rows are padding
            valid = np.ones(16, np.float32)
            if i == n_batches - 1:
                valid[8:] = 0.0
            batches.append(
                rt.Attributes(
                    logits=jnp.asarray(logits),
                    label=jnp.asarray(label),
                    _valid=jnp.asarray(valid),
                )
            )
        return batches

    def _run(self, meter, metric, batches):
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
        )
        meter.set(attrs)
        for batch in batches:
            attrs.batch = batch
            meter.launch(attrs)
        meter.reset(attrs)
        return metric.last

    def test_matches_host_gather_accuracy(self, devices):
        from test_pipeline import Accuracy as HostAccuracy

        batches = self._eval_batches(devices)

        in_step = rt.Accuracy()
        meter = rt.Meter(capsules=[in_step], mode="in_step")
        got = self._run(meter, in_step, batches)["accuracy"]

        host_metric = HostAccuracy()
        host_meter = rt.Meter(keys=["logits", "label"], capsules=[host_metric])
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
        )
        for batch in batches:
            attrs.batch = batch
            host_meter.launch(attrs)
        host_metric.reset(attrs)
        assert got == pytest.approx(host_metric.last, abs=1e-9)

    def test_accumulator_stays_on_device(self, devices):
        import jax

        batches = self._eval_batches(devices)
        metric = rt.Accuracy()
        meter = rt.Meter(capsules=[metric], mode="in_step")
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
        )
        for batch in batches:
            attrs.batch = batch
            meter.launch(attrs)
        # between iterations the stats live as device arrays, not numpy
        leaves = jax.tree_util.tree_leaves(meter._acc)
        assert leaves and all(isinstance(x, jax.Array) for x in leaves)
        meter.reset(attrs)
        assert meter._acc is None and metric.last is not None

    def test_publishes_to_tracker_and_loop_state(self, devices):
        batches = self._eval_batches(devices)
        metric = rt.Accuracy()
        meter = rt.Meter(capsules=[metric], mode="in_step")
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes()),
            tracker=rt.Attributes(scalars=[], images=[]),
        )
        meter.set(attrs)
        for batch in batches:
            attrs.batch = batch
            meter.launch(attrs)
        meter.reset(attrs)
        assert "accuracy" in attrs.looper.state
        tags = [t for rec in attrs.tracker.scalars for t in rec.data]
        assert "accuracy" in tags

    def test_mode_guards_children(self, devices):
        from test_pipeline import Accuracy as HostAccuracy

        with pytest.raises(TypeError, match="StatMetric"):
            rt.Meter(capsules=[HostAccuracy()], mode="in_step").guard()


class TestThroughput:
    def _attrs(self):
        return rt.Attributes(
            batch={"x": np.zeros((16, 2))},
            looper=rt.Attributes(state=rt.Attributes()),
            tracker=rt.Attributes(scalars=[], images=[]),
        )

    def test_rate_published_to_loop_state(self):
        tp = Throughput(ema=0.0, log_every=2)
        attrs = self._attrs()
        tp.set(attrs)
        for _ in range(4):
            tp.launch(attrs)
        assert "throughput" in attrs.looper.state
        tags = [t for rec in attrs.tracker.scalars for t in rec.data]
        assert "throughput/samples_per_sec" in tags

    def test_set_realigns_log_every_cadence(self):
        """ISSUE 4 satellite: ``set`` must reset the within-cycle counter
        — a leftover ``_iter`` skewed every later cycle's record cadence
        (the first launch after ``set`` only primes the clock)."""
        tp = Throughput(ema=0.0, log_every=3)
        attrs = self._attrs()
        tp.set(attrs)
        for _ in range(4):  # prime + 3 counted -> one record
            tp.launch(attrs)
        assert len(attrs.tracker.scalars) == 1
        tp.set(attrs)       # new cycle: cadence restarts from zero
        for _ in range(3):  # prime + 2 counted -> nothing yet
            tp.launch(attrs)
        assert len(attrs.tracker.scalars) == 1
        tp.launch(attrs)    # third counted iteration of THIS cycle
        assert len(attrs.tracker.scalars) == 2

    def test_reset_flushes_final_subwindow_reading(self):
        """ISSUE 4 satellite: a cycle shorter than ``log_every`` still
        produces one throughput scalar at cycle end — and re-resetting
        must not double-flush it."""
        tp = Throughput(ema=0.0, log_every=50)
        attrs = self._attrs()
        tp.set(attrs)
        for _ in range(3):
            tp.launch(attrs)
        assert attrs.tracker.scalars == []
        tp.reset(attrs)
        assert len(attrs.tracker.scalars) == 1
        assert "throughput/samples_per_sec" in attrs.tracker.scalars[0].data
        tp.reset(attrs)  # nothing pending -> no duplicate record
        assert len(attrs.tracker.scalars) == 1

    def test_record_steps_monotonic_across_cycles(self):
        """Records carry the never-resetting global iteration as their
        step, so a later cycle's scalars never overwrite an earlier
        cycle's in last-write-wins backends."""
        tp = Throughput(ema=0.0, log_every=2)
        attrs = self._attrs()
        for _ in range(2):
            tp.set(attrs)
            for _ in range(5):  # prime + 4 counted -> records at 2 and 4
                tp.launch(attrs)
            tp.reset(attrs)
        steps = [int(rec.step) for rec in attrs.tracker.scalars]
        assert steps == sorted(set(steps)), steps  # strictly increasing


class TestProfiler:
    def _calls(self, monkeypatch):
        calls = []
        import jax

        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: calls.append("start")
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append("stop")
        )
        return calls

    def test_window_captured_once_then_done(self, tmp_path, monkeypatch):
        calls = self._calls(monkeypatch)
        prof = Profiler(start=2, count=2, log_dir=str(tmp_path))
        prof.bind(rt.Runtime())
        for _ in range(8):
            prof.launch()
        assert calls == ["start", "stop"]
        prof.destroy()  # _done: no double-stop
        assert calls == ["start", "stop"]

    def test_start_trace_failure_disables(self, tmp_path, monkeypatch):
        """ISSUE 4 satellite: a failed ``start_trace`` (e.g. another
        trace already active in the process) disables this Profiler
        instead of re-raising every remaining iteration."""
        import jax

        calls = []

        def boom(d):
            calls.append("start")
            raise RuntimeError("already tracing")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        prof = Profiler(start=0, count=2, log_dir=str(tmp_path))
        prof.bind(rt.Runtime())
        prof.launch()           # fails, disables
        prof.launch()           # must not retry
        assert calls == ["start"]
        assert prof._done and not prof._active

    def test_non_main_process_skips_capture(self, tmp_path, monkeypatch):
        """ISSUE 4 satellite: non-main processes never call start_trace —
        they log the skip once and mark themselves done."""
        calls = self._calls(monkeypatch)

        class NotMain:
            is_main_process = False
            process_index = 3

        prof = Profiler(start=0, count=2, log_dir=str(tmp_path))
        prof.bind(NotMain())
        for _ in range(3):
            prof.launch()
        assert calls == []
        assert prof._done

    def test_stop_trace_exception_leaves_clean_flags(
        self, tmp_path, monkeypatch
    ):
        """ISSUE 4 satellite: a raising ``stop_trace`` must not leave
        ``_active`` set — teardown would double-stop and mask the
        original error."""
        import jax

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

        def boom():
            raise RuntimeError("xplane writer died")

        monkeypatch.setattr(jax.profiler, "stop_trace", boom)
        prof = Profiler(start=0, count=1, log_dir=str(tmp_path))
        prof.bind(rt.Runtime())
        prof.launch()  # starts
        with pytest.raises(RuntimeError, match="xplane"):
            prof.launch()  # window over -> stop raises
        assert prof._done and not prof._active
        prof.destroy()  # early-returns; the error above stays the story


class TestScalarSink:
    def test_context_manager_closes_backend(self, tmp_path):
        """ISSUE 4 satellite: ``scalar_sink`` handles work as context
        managers, so serve loops / scripts can't leak a writer."""
        with scalar_sink("jsonl", str(tmp_path)) as sink:
            assert isinstance(sink, JsonlBackend)
            sink.log_scalars({"serve/rounds": 1.0}, step=0)
        assert sink._file.closed
        line = json.loads(open(tmp_path / "metrics.jsonl").read().strip())
        assert line["serve/rounds"] == 1.0

    def test_exception_still_closes(self, tmp_path):
        with pytest.raises(ValueError, match="boom"):
            with scalar_sink("jsonl", str(tmp_path)) as sink:
                raise ValueError("boom")
        assert sink._file.closed

    def test_memory_sink_roundtrip(self):
        with scalar_sink("memory") as sink:
            sink.log_scalars({"a": 2.0}, step=1)
        assert sink.scalars == [(1, {"a": 2.0})]


class TestPerplexity:
    """LM perplexity StatMetric: logits path vs token_nll (fused_ce) path."""

    def _batches(self, with_nll):
        import jax.numpy as jnp
        import optax

        rng = np.random.default_rng(7)
        batches = []
        for _ in range(3):
            tokens = jnp.asarray(rng.integers(0, 32, size=(4, 16)), jnp.int32)
            logits = jnp.asarray(rng.normal(size=(4, 16, 32)), jnp.float32)
            b = rt.Attributes(tokens=tokens, logits=logits)
            if with_nll:
                b = rt.Attributes(
                    tokens=tokens,
                    token_nll=optax.softmax_cross_entropy_with_integer_labels(
                        logits[:, :-1], tokens[:, 1:]
                    ),
                )
            batches.append(b)
        return batches

    def _run(self, batches):
        metric = rt.Perplexity()
        meter = rt.Meter(capsules=[metric], mode="in_step")
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
        )
        meter.set(attrs)
        for batch in batches:
            attrs.batch = batch
            meter.launch(attrs)
        meter.reset(attrs)
        return metric.last["perplexity"]

    def test_matches_direct_computation(self, devices):
        import optax

        batches = self._batches(with_nll=False)
        got = self._run(batches)
        nlls = [
            optax.softmax_cross_entropy_with_integer_labels(
                b["logits"][:, :-1], b["tokens"][:, 1:]
            )
            for b in batches
        ]
        want = float(np.exp(np.concatenate([np.asarray(x).ravel() for x in nlls]).mean()))
        assert got == pytest.approx(want, rel=1e-5)

    def test_nll_path_matches_logits_path(self, devices):
        a = self._run(self._batches(with_nll=False))
        b = self._run(self._batches(with_nll=True))
        assert a == pytest.approx(b, rel=1e-5)


class TestClassStats:
    def _eval(self, logits, labels, valid=None, **kw):
        import jax

        m = rt.ClassStats(num_classes=3, **kw)
        batch = rt.Attributes(
            logits=jnp.asarray(logits, jnp.float32),
            label=jnp.asarray(labels, jnp.int32),
        )
        if valid is not None:
            batch["_valid"] = jnp.asarray(valid)
        stats = m.stats(batch)
        return m.finalize(jax.tree_util.tree_map(np.asarray, stats))

    def test_macro_matches_sklearn_style_hand_calc(self, devices):
        # preds: [0, 1, 1, 2]; labels: [0, 1, 2, 2]
        logits = np.eye(3)[[0, 1, 1, 2]] * 5
        labels = [0, 1, 2, 2]
        out = self._eval(logits, labels, average="macro")
        # per class: c0 p=1 r=1 f1=1; c1 p=.5 r=1 f1=2/3; c2 p=1 r=.5
        # f1=2/3.  sklearn macro-F1 = mean of per-class F1 (NOT the
        # harmonic mean of macro-P and macro-R).
        prec, rec = (1 + 0.5 + 1) / 3, (1 + 1 + 0.5) / 3
        np.testing.assert_allclose(out["f1/precision"], prec, rtol=1e-6)
        np.testing.assert_allclose(out["f1/recall"], rec, rtol=1e-6)
        np.testing.assert_allclose(
            out["f1"], (1.0 + 2 / 3 + 2 / 3) / 3, rtol=1e-6
        )

    def test_micro_equals_accuracy(self, devices):
        rng = np.random.default_rng(0)
        labels = rng.integers(0, 3, 64)
        logits = rng.normal(size=(64, 3))
        out = self._eval(logits, labels, average="micro")
        acc = float((logits.argmax(-1) == labels).mean())
        np.testing.assert_allclose(out["f1"], acc, rtol=1e-6)

    def test_valid_mask_drops_padded_rows(self, devices):
        logits = np.eye(3)[[0, 1, 2, 0]] * 5
        labels = [0, 1, 2, 2]  # row 3 wrong — but masked out
        out = self._eval(logits, labels, valid=[True, True, True, False])
        np.testing.assert_allclose(out["f1"], 1.0, rtol=1e-6)

    def test_through_meter_in_step(self, devices):
        """Summed across batches through the in-step Meter path."""
        meter = rt.Meter(
            mode="in_step",
            capsules=[rt.ClassStats(num_classes=3, average="micro")],
        )
        meter.bind(rt.Runtime())
        meter.setup()
        rng = np.random.default_rng(1)
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
        )
        all_logits, all_labels = [], []
        for _ in range(3):
            logits = rng.normal(size=(16, 3))
            labels = rng.integers(0, 3, 16)
            all_logits.append(logits)
            all_labels.append(labels)
            attrs.batch = rt.Attributes(
                logits=jnp.asarray(logits, jnp.float32),
                label=jnp.asarray(labels, jnp.int32),
            )
            meter.launch(attrs)
        meter.reset(attrs)
        want = float(
            (np.concatenate(all_logits).argmax(-1)
             == np.concatenate(all_labels)).mean()
        )
        np.testing.assert_allclose(
            float(attrs.looper.state["f1"]), want, rtol=1e-6
        )
        assert "f1" in next(iter(meter._capsules)).last
        meter.destroy()

    def test_rejects_bad_average(self, devices):
        with pytest.raises(ValueError, match="average"):
            rt.ClassStats(num_classes=3, average="weighted")
