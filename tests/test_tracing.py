"""Structured-tracing / flight-recorder tests — rocket_tpu.observe end to end.

Four layers, mirroring the ISSUE 4 tentpole:

- units: the Tracer ring (wraparound, span nesting, cross-thread appends,
  error capture), the latency Histogram, Chrome-trace export schema;
- the flight recorder: dump artifacts (trace.json + tail.txt), the
  process-global install/uninstall protocol, SIGTERM chaining;
- automatic instrumentation: Dispatcher capsule spans, Looper iteration
  spans, the DivergenceSentinel's dump hook;
- the serve acceptance path: a StuckStepInjector watchdog trip produces
  a valid Chrome-trace dump whose LAST event is the stuck round's
  ``serve/round`` span (``tripped=True``), and every ``Failed`` result
  carries the dump path.
"""

import json
import os
import signal
import threading

import numpy as np
import pytest

import jax

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule
from rocket_tpu.core.dispatcher import Dispatcher
from rocket_tpu.engine.sentinel import DivergenceSentinel
from rocket_tpu.launch.loop import Looper
from rocket_tpu.models.generate import ContinuousBatcher, _spec_round
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.observe import recorder as flightrec
from rocket_tpu.observe.backends import MemoryBackend
from rocket_tpu.observe.recorder import FlightRecorder, active_recorder
from rocket_tpu.observe.trace import (
    Histogram,
    Tracer,
    _main,
    arm,
    disarm,
    get_tracer,
    merge_traces,
)
from rocket_tpu.runtime import Runtime
from rocket_tpu.serve import Completed, Failed, Request, ServingLoop
from rocket_tpu.testing.chaos import StuckStepInjector

pytestmark = pytest.mark.tracing

B, P, TOTAL, NDRAFT = 3, 8, 24, 4


@pytest.fixture()
def armed_global():
    """Arm the process-global tracer for one test, then fully restore it
    (disarmed + empty) so no other test sees leaked events."""
    tracer = arm()
    tracer.clear()
    yield tracer
    disarm()
    tracer.clear()


# -- units: the ring --------------------------------------------------------


class TestTracerRing:
    def test_wraparound_keeps_last_capacity(self):
        t = Tracer(capacity=8, enabled=True)
        for i in range(20):
            t.instant(f"ev{i}")
        events = t.events()
        assert len(events) == 8
        assert [e[1] for e in events] == [f"ev{i}" for i in range(12, 20)]

    def test_span_records_duration_fields_and_kind(self):
        t = Tracer(capacity=16, enabled=True)
        with t.span("work", rid=7) as sp:
            sp.add(extra="mid-span")
        (ev,) = t.events()
        kind, name, ts_ns, dur_ns, tid, fields = ev
        assert kind == "X" and name == "work"
        assert dur_ns >= 0 and tid == threading.get_ident()
        assert fields == {"rid": 7, "extra": "mid-span"}

    def test_nested_spans_close_inner_first(self):
        t = Tracer(capacity=16, enabled=True)
        with t.span("outer"):
            with t.span("inner"):
                pass
        names = [e[1] for e in t.events()]
        assert names == ["inner", "outer"]
        inner, outer = t.events()
        # the outer span brackets the inner one on the timeline
        assert outer[2] <= inner[2]
        assert outer[2] + outer[3] >= inner[2] + inner[3]

    def test_span_captures_escaping_exception(self):
        t = Tracer(capacity=16, enabled=True)
        with pytest.raises(ValueError):
            with t.span("doomed"):
                raise ValueError("boom")
        (ev,) = t.events()
        assert "boom" in ev[5]["error"]

    def test_disabled_tracer_is_shared_noop(self):
        t = Tracer(capacity=16, enabled=False)
        a, b = t.span("x"), t.span("y", k=1)
        assert a is b  # one shared null span — no per-call allocation
        with a:
            a.add(ignored=True)
        t.counter("c", 1.0)
        t.instant("i")
        t.health("h", "SERVING")
        assert t.events() == []

    def test_spans_across_threads_carry_distinct_tids(self):
        t = Tracer(capacity=64, enabled=True)

        def worker():
            with t.span("worker-side"):
                pass

        with t.span("caller-side"):
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        by_name = {e[1]: e for e in t.events()}
        assert set(by_name) == {"worker-side", "caller-side"}
        assert by_name["worker-side"][4] != by_name["caller-side"][4]
        assert by_name["caller-side"][4] == threading.get_ident()

    def test_counter_health_instant_kinds(self):
        t = Tracer(capacity=16, enabled=True)
        t.counter("serve/queue_depth", 3)
        t.instant("serve/submit", rid=1)
        t.health("serve/health", "DEGRADED", prev="SERVING")
        kinds = [e[0] for e in t.events()]
        assert kinds == ["C", "I", "H"]
        counter = t.events()[0]
        assert counter[5]["queue_depth"] == 3.0
        health = t.events()[2]
        assert health[5] == {"prev": "SERVING", "state": "DEGRADED"}

    def test_resize_preserves_recent_events(self):
        t = Tracer(capacity=8, enabled=True)
        for i in range(8):
            t.instant(f"ev{i}")
        t.resize(4)
        assert [e[1] for e in t.events()] == ["ev4", "ev5", "ev6", "ev7"]
        with pytest.raises(ValueError):
            t.resize(0)

    def test_arm_disarm_global(self, armed_global):
        assert get_tracer() is armed_global and armed_global.enabled
        armed_global.instant("armed")
        assert len(armed_global.events()) == 1
        disarm()
        armed_global.instant("dropped")
        assert len(armed_global.events()) == 1


# -- units: chrome export ---------------------------------------------------


class TestChromeExport:
    def test_dump_json_is_valid_catapult(self, tmp_path):
        t = Tracer(capacity=32, enabled=True)
        with t.span("phase", rid=1):
            pass
        t.counter("depth", 2)
        t.instant("mark", note=object())  # unserializable -> default=str
        t.health("health", "SERVING")
        t.set_anchor()
        path = t.dump_json(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        assert set(doc) == {"traceEvents", "displayTimeUnit", "metadata"}
        assert doc["displayTimeUnit"] == "ms"
        meta = doc["metadata"]
        assert meta["process_index"] == jax.process_index()
        assert "anchor_wall_s" in meta and "anchor_perf_us" in meta
        events = doc["traceEvents"]
        assert [e["ph"] for e in events] == ["X", "C", "i", "i"]
        for ev in events:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        assert events[0]["dur"] >= 0  # complete spans carry a duration
        assert events[3]["s"] == "p" and events[3]["cat"] == "health"

    def test_tail_text_is_human_readable(self):
        t = Tracer(capacity=32, enabled=True)
        with t.span("serve/round", round=3):
            pass
        t.health("serve/health", "DEGRADED")
        txt = t.tail_text()
        assert "span  serve/round" in txt
        assert "health serve/health -> DEGRADED" in txt
        assert Tracer(capacity=4).tail_text() == ""


# -- units: histogram -------------------------------------------------------


class TestHistogram:
    def test_nearest_rank_percentiles(self):
        h = Histogram()
        for v in (10.0, 20.0, 30.0, 40.0):
            h.record(v)
        assert h.percentile(0) == 10.0
        assert h.percentile(50) == 30.0  # nearest rank of 4 samples
        assert h.percentile(95) == 40.0
        assert h.percentile(100) == 40.0

    def test_empty_emits_nothing(self):
        h = Histogram()
        assert h.percentile(50) is None
        assert h.summary("ttft_ms") == {}

    def test_window_bounded_count_lifetime(self):
        h = Histogram(capacity=4)
        for v in range(10):
            h.record(float(v))
        assert len(h) == 4 and h.count == 10
        # window holds the most recent samples only
        assert h.percentile(0) == 6.0
        s = h.summary("lat")
        assert set(s) == {"lat/p50", "lat/p95", "lat/p99", "lat/count"}
        assert s["lat/count"] == 10.0


# -- units: multi-host merge ------------------------------------------------


def _host_doc(pid, wall_s, perf_us, events):
    return {
        "traceEvents": [
            {"name": n, "ph": "i", "s": "t", "ts": ts, "pid": pid,
             "tid": 1, "args": {}}
            for n, ts in events
        ],
        "displayTimeUnit": "ms",
        "metadata": {
            "process_index": pid,
            "anchor_wall_s": wall_s,
            "anchor_perf_us": perf_us,
        },
    }


class TestMergeTraces:
    def test_aligns_on_barrier_anchor(self, tmp_path):
        # host 0 anchored at wall=100.0s with perf=1000us; host 1 at
        # wall=100.5s with perf=5000us — its events land 0.5s later on
        # the merged timeline regardless of its raw clock origin.
        d0 = tmp_path / "a-p0"
        d1 = tmp_path / "b-p1"
        d0.mkdir(), d1.mkdir()
        with open(d0 / "trace.json", "w") as f:
            json.dump(_host_doc(0, 100.0, 1000.0, [("h0", 1000.0)]), f)
        with open(d1 / "trace.json", "w") as f:
            json.dump(_host_doc(1, 100.5, 5000.0, [("h1", 5000.0)]), f)
        doc = merge_traces(str(tmp_path))
        assert doc["metadata"]["merged_from"] == 2
        assert doc["metadata"]["hosts"] == [0, 1]
        assert doc["metadata"]["unanchored_files"] == []
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        assert by_name["h0"]["ts"] == 0.0
        assert by_name["h1"]["ts"] == pytest.approx(0.5e6)
        assert by_name["h0"]["pid"] == 0 and by_name["h1"]["pid"] == 1
        # merged stream is time-sorted
        ts = [e["ts"] for e in doc["traceEvents"]]
        assert ts == sorted(ts)

    def test_unanchored_dump_kept_and_flagged(self, tmp_path):
        doc0 = _host_doc(0, 50.0, 0.0, [("anchored", 10.0)])
        doc1 = _host_doc(1, None, None, [("raw", 77.0)])
        del doc1["metadata"]["anchor_wall_s"], doc1["metadata"]["anchor_perf_us"]
        with open(tmp_path / "p0.json", "w") as f:
            json.dump(doc0, f)
        with open(tmp_path / "p1.json", "w") as f:
            json.dump(doc1, f)
        doc = merge_traces(str(tmp_path))
        assert doc["metadata"]["unanchored_files"] == ["p1.json"]
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        assert by_name["raw"]["ts"] == 77.0  # raw clock, unshifted

    def test_empty_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            merge_traces(str(tmp_path))

    def test_cli_writes_merged_json(self, tmp_path, capsys):
        with open(tmp_path / "p0.json", "w") as f:
            json.dump(_host_doc(0, 1.0, 0.0, [("ev", 5.0)]), f)
        assert _main([str(tmp_path)]) == 0
        out_path = tmp_path / "merged.json"
        assert out_path.is_file()
        with open(out_path) as f:
            merged = json.load(f)
        assert merged["metadata"]["merged_from"] == 1
        assert "merged 1 dump(s)" in capsys.readouterr().out


# -- flight recorder --------------------------------------------------------


class TestFlightRecorder:
    def test_dump_writes_trace_and_tail(self, tmp_path):
        tracer = Tracer(capacity=64, enabled=True)
        with tracer.span("serve/round", round=1):
            pass
        rec = FlightRecorder(tracer, out_dir=str(tmp_path / "fr"), tail=8)
        path = rec.dump("watchdog trip!")
        assert rec.last_dump == path and os.path.isdir(path)
        base = os.path.basename(path)
        assert "watchdog-trip" in base  # reason slugified into the name
        assert base.endswith(f"-p{jax.process_index()}")
        with open(os.path.join(path, "trace.json")) as f:
            doc = json.load(f)
        assert doc["metadata"]["dump_reason"] == "watchdog trip!"
        assert doc["traceEvents"][0]["name"] == "serve/round"
        with open(os.path.join(path, "tail.txt")) as f:
            txt = f.read()
        assert "reason: watchdog trip!" in txt and "serve/round" in txt
        # successive dumps never collide, even within one second
        assert rec.dump("again") != path

    def test_disabled_tracer_still_dumps_empty_ring(self, tmp_path):
        rec = FlightRecorder(Tracer(capacity=8), out_dir=str(tmp_path))
        path = rec.dump()
        with open(os.path.join(path, "trace.json")) as f:
            assert json.load(f)["traceEvents"] == []

    def test_install_uninstall_global(self, tmp_path):
        rec = FlightRecorder(Tracer(capacity=8), out_dir=str(tmp_path))
        try:
            assert flightrec.install(rec, sigterm=False) is rec
            assert active_recorder() is rec
        finally:
            flightrec.uninstall()
        assert active_recorder() is None

    def test_sigterm_dumps_then_chains_previous_handler(self, tmp_path):
        calls = []
        orig = signal.getsignal(signal.SIGTERM)
        tracer = Tracer(capacity=8, enabled=True)
        tracer.instant("pre-sigterm")
        rec = FlightRecorder(tracer, out_dir=str(tmp_path))
        try:
            signal.signal(signal.SIGTERM, lambda s, f: calls.append(s))
            flightrec.install(rec, sigterm=True)
            handler = signal.getsignal(signal.SIGTERM)
            assert handler is flightrec._on_sigterm
            handler(signal.SIGTERM, None)
            assert rec.last_dump is not None
            assert calls == [signal.SIGTERM]  # previous handler still fired
            # re-install must not re-chain onto our own hook
            flightrec.install(rec, sigterm=True)
            assert flightrec._PREV_SIGTERM["handler"] is not handler
        finally:
            flightrec.uninstall()
            signal.signal(signal.SIGTERM, orig)
            flightrec._PREV_SIGTERM["handler"] = None


# -- automatic instrumentation ---------------------------------------------


class _Probe(Capsule):
    """Capsule whose launch records nothing — the spans under test come
    from the Dispatcher/Looper wrapping, not from the capsule itself."""

    def launch(self, attrs=None):
        pass


class TestAutomaticInstrumentation:
    def test_dispatcher_wraps_lifecycle_in_spans(self, devices,
                                                 armed_global):
        runtime = Runtime(tracing=True)
        disp = Dispatcher(capsules=[_Probe()])
        disp.bind(runtime)
        disp.setup(None)
        disp.set(None)
        disp.launch(None)
        disp.reset(None)
        disp.destroy(None)
        names = [e[1] for e in armed_global.events()]
        assert names == [
            "_Probe.setup", "_Probe.set", "_Probe.launch",
            "_Probe.reset", "_Probe.destroy",
        ]
        assert all(e[5] == {"cat": "capsule"} for e in armed_global.events())

    def test_dispatcher_untraced_without_runtime_flag(self, devices,
                                                      armed_global):
        runtime = Runtime(tracing=False)
        disp = Dispatcher(capsules=[_Probe()])
        disp.bind(runtime)
        disp.setup(None)
        disp.launch(None)
        disp.destroy(None)
        assert armed_global.events() == []

    def test_looper_iteration_spans(self, devices, armed_global):
        runtime = Runtime(tracing=True)
        looper = Looper(capsules=[_Probe()], repeats=3, progress=False)
        looper.bind(runtime)
        attrs = Attributes()
        looper.setup(attrs)
        looper.launch(attrs)
        names = [e[1] for e in armed_global.events()]
        assert names.count("looper/TRAIN/iter") == 3
        assert names.count("_Probe.launch") >= 3
        # the capsule span closes before its enclosing iteration span
        first_iter = names.index("looper/TRAIN/iter")
        assert names[first_iter - 1] == "_Probe.launch"

    def test_sentinel_divergence_marks_and_dumps(self, tmp_path,
                                                 armed_global):
        rec = FlightRecorder(armed_global, out_dir=str(tmp_path))
        sent = DivergenceSentinel(policy="warn")
        try:
            flightrec.install(rec, sigterm=False)
            sent._act(float("nan"))
        finally:
            flightrec.uninstall()
        assert sent.events == 1
        instants = [e for e in armed_global.events()
                    if e[1] == "sentinel/divergence"]
        assert len(instants) == 1
        assert instants[0][5]["policy"] == "warn"
        assert rec.last_dump is not None
        assert "sentinel-warn" in os.path.basename(rec.last_dump)


# -- the serve acceptance path ----------------------------------------------


def _lm(seed=1, **kw):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64, **kw
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


@pytest.fixture(scope="module")
def models():
    model, params = _lm(seed=1)
    draft, _ = _lm(seed=1)
    _, dparams = _lm(seed=7)
    return model, draft, params, dparams


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(8, P)).astype(np.int32)


def _factory(models, **kw):
    model, draft, params, dparams = models

    def factory():
        return ContinuousBatcher(
            model, draft, params, dparams,
            total_len=TOTAL, n_draft=NDRAFT, eos_token=None, **kw,
        )

    return factory


class TestServeTracing:
    def test_request_spans_and_latency_percentiles(self, models, prompts):
        tracer = Tracer(capacity=512, enabled=True)
        sink = MemoryBackend()
        loop = ServingLoop(_factory(models), max_batch=B, queue_capacity=8,
                           tracer=tracer, sink=sink, flush_every=1)
        for i in range(3):
            assert loop.submit(Request(rid=i, prompt=prompts[i])) is None
        results = loop.run_until_idle()
        loop.close()
        assert all(isinstance(r, Completed) for r in results)

        names = [e[1] for e in tracer.events()]
        assert names.count("serve/submit") == 3
        assert names.count("serve/admit") == 3
        assert names.count("serve/round") >= 1
        assert names.count("serve/complete") == 3
        admit = next(e for e in tracer.events() if e[1] == "serve/admit")
        assert admit[5]["prompt_len"] == P

        # TTFT/TPOT/e2e percentiles computed and flushed as trace/* scalars
        summary = loop.latency.summary()
        for key in ("queue_wait_ms/p50", "ttft_ms/p50", "ttft_ms/p99",
                    "tpot_ms/p50", "e2e_ms/p95"):
            assert key in summary
        assert summary["ttft_ms/count"] == 3.0
        _step, last = sink.scalars[-1]
        assert "trace/ttft_ms/p50" in last and "serve/completed" in last
        assert last["trace/e2e_ms/p50"] >= last["trace/ttft_ms/p50"] >= 0.0

    def test_tracing_adds_no_step_traces(self, models, prompts):
        bare = _factory(models)()
        bare.start(prompts[:B])
        while not bare.all_done:
            bare.step()
        traces_before = _spec_round._cache_size()
        tracer = Tracer(capacity=512, enabled=True)
        loop = ServingLoop(_factory(models), max_batch=B, queue_capacity=8,
                           tracer=tracer)
        for i in range(3):
            loop.submit(Request(rid=i, prompt=prompts[i]))
        results = loop.run_until_idle()
        loop.close()
        assert len(results) == 3
        # armed tracing recorded spans but traced ZERO new step bodies
        assert _spec_round._cache_size() == traces_before
        assert any(e[1] == "serve/round" for e in tracer.events())

    def test_watchdog_trip_dumps_flight_recorder(self, models, prompts,
                                                 tmp_path):
        """ISSUE 4 acceptance: a StuckStepInjector trip produces a valid
        Chrome-trace dump whose last event is the stuck round's span, and
        the Failed results carry the dump path."""
        tracer = Tracer(capacity=512, enabled=True)
        rec = FlightRecorder(tracer, out_dir=str(tmp_path / "flightrec"))
        instances = {"n": 0}
        base_factory = _factory(models)

        def factory():
            bat = base_factory()
            instances["n"] += 1
            if instances["n"] == 1:
                return StuckStepInjector(bat, hang_on=(2,), hang_s=8.0)
            return bat

        loop = ServingLoop(factory, max_batch=B, queue_capacity=4,
                           watchdog_timeout=0.4, recover_rounds=2,
                           tracer=tracer, recorder=rec)
        for i in range(2):
            loop.submit(Request(rid=i, prompt=prompts[i]))
        loop.run_round()                   # proxy step #1: fine
        loop.run_round()                   # proxy step #2: wedged
        results = loop.drain_results()
        loop.close()

        assert loop.watchdog.trips == 1
        failed = [r for r in results if isinstance(r, Failed)]
        assert sorted(r.rid for r in failed) == [0, 1]
        dump = failed[0].dump_path
        assert dump is not None and os.path.isdir(dump)
        assert all(r.dump_path == dump for r in failed)
        assert rec.last_dump == dump

        with open(os.path.join(dump, "trace.json")) as f:
            doc = json.load(f)
        assert doc["metadata"]["dump_reason"] == "watchdog-trip"
        events = doc["traceEvents"]
        # the stuck round's span closed BEFORE the dump, so it is the
        # ring's final event — exactly what the operator reads first
        assert events[-1]["name"] == "serve/round"
        assert events[-1]["ph"] == "X"
        assert events[-1]["args"].get("tripped") is True
        with open(os.path.join(dump, "tail.txt")) as f:
            txt = f.read()
        assert "watchdog-trip" in txt and "serve/round" in txt
        # the failure instants landed AFTER the dump: in the ring but not
        # in the dumped artifact
        assert not any(e["name"] == "serve/failed" for e in events)
        assert any(e[1] == "serve/failed" for e in tracer.events())
