"""Fresh parent for the notebook_launch fork-N test (not a pytest file).

Run as: python notebook_parent.py <workdir>.  Must NOT initialize any JAX
backend before notebook_launch — that is the constraint under test.
"""

import os
import sys


def worker(workdir: str) -> None:
    import jax
    import numpy as np

    from rocket_tpu.parallel import multihost

    pid = jax.process_index()
    assert jax.process_count() == 2
    # real host collectives inside the forked workers
    got = multihost.broadcast_object(
        {"token": 99} if pid == 0 else None
    )
    assert got == {"token": 99}, got
    gathered = multihost.process_allgather(np.asarray([pid], np.int32))
    np.testing.assert_array_equal(np.sort(np.ravel(gathered)), [0, 1])
    with open(os.path.join(workdir, f"nb{pid}.ok"), "w") as f:
        f.write("ok")


def main() -> None:
    workdir = sys.argv[1]
    from rocket_tpu import notebook_launch

    # 1-process mode: runs inline, returns the value
    assert notebook_launch(lambda: 41 + 1) == 42

    # fork-N mode (closure over workdir — the reason forking, not
    # pickling, is the mechanism)
    notebook_launch(worker, args=(workdir,), num_processes=2)
    assert os.path.exists(os.path.join(workdir, "nb0.ok"))
    assert os.path.exists(os.path.join(workdir, "nb1.ok"))
    print("NOTEBOOK-PARENT-OK", flush=True)


if __name__ == "__main__":
    main()
