"""Multi-optimizer / param-group composition (VERDICT r3 missing #1).

The reference Module hosts N Optimizer capsules, each stepping its own
torch param group (``rocket/core/module.py:50-60``).  Here N Optimizer
capsules compose into ONE jitted step via ``optax.multi_transform`` over
path-labelled groups; params matched by no group freeze.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy


class TwoPart(nn.Module):
    """backbone -> head, with path-addressable param groups."""

    @nn.compact
    def __call__(self, batch, train: bool = False):
        x = nn.relu(nn.Dense(16, name="backbone")(batch["x"]))
        logits = nn.Dense(4, name="head")(x)
        out = rt.Attributes(batch)
        out["logits"] = logits
        return out


def _path_has(name):
    def f(path, leaf):
        return any(
            str(getattr(p, "key", getattr(p, "name", ""))) == name
            for p in path
        )

    return f


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 4, size=(8,)), jnp.int32),
    }


def _module(optimizers, **kw):
    mod = rt.Module(
        TwoPart(),
        capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                  *optimizers],
        **kw,
    )
    mod.bind(rt.Runtime())
    mod.setup()
    return mod


def _run(mod, n=3):
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    for _ in range(n):
        attrs.batch = _batch()
        mod.launch(attrs)
    return attrs


def _flat(params):
    return {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_leaves_with_path(params)
    }


def test_zero_lr_backbone_trains_only_head(devices):
    """The VERDICT contract: backbone LR 0 + head LR>0 trains only the
    head — two Optimizer capsules, one step."""
    mod = _module([
        rt.Optimizer(learning_rate=0.0, params_filter=_path_has("backbone"),
                     tag="lr_backbone"),
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                     tag="lr_head"),
    ])
    mod.materialize(_batch())
    before = _flat(mod.state.params)
    _run(mod)
    after = _flat(mod.state.params)
    for key in before:
        if "backbone" in key:
            np.testing.assert_array_equal(before[key], after[key])
        else:
            assert not np.allclose(before[key], after[key]), key
    mod.destroy()


def test_both_groups_train_with_distinct_lrs(devices):
    mod = _module([
        rt.Optimizer(learning_rate=0.05, params_filter=_path_has("backbone"),
                     tag="lr_backbone"),
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                     tag="lr_head"),
    ])
    mod.materialize(_batch())
    before = _flat(mod.state.params)
    attrs = _run(mod)
    after = _flat(mod.state.params)
    for key in before:
        assert not np.allclose(before[key], after[key]), key
    # per-group LR logging landed in the looper state under distinct tags
    assert float(attrs.looper.state["lr_backbone"]) == 0.05
    assert float(attrs.looper.state["lr_head"]) == 0.1
    mod.destroy()


def test_single_filter_freezes_unmatched(devices):
    """One Optimizer with params_filter: its group trains, the rest
    freezes — the one-capsule spelling of a head-only fine-tune."""
    mod = _module([
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head")),
    ])
    mod.materialize(_batch())
    before = _flat(mod.state.params)
    _run(mod)
    after = _flat(mod.state.params)
    for key in before:
        if "backbone" in key:
            np.testing.assert_array_equal(before[key], after[key])
        else:
            assert not np.allclose(before[key], after[key]), key
    mod.destroy()


def test_per_optimizer_schedule_overrides_sibling_scheduler(devices):
    """Sibling Scheduler = default schedule; Optimizer(schedule=...) wins
    for its own group."""
    own = optax.constant_schedule(0.07)
    mod = _module([
        rt.Optimizer(params_filter=_path_has("backbone"),
                     tag="lr_backbone"),
        rt.Optimizer(params_filter=_path_has("head"), schedule=own,
                     tag="lr_head"),
        rt.Scheduler(optax.constant_schedule(0.02)),
    ])
    mod.materialize(_batch())
    attrs = _run(mod, n=1)
    assert float(attrs.looper.state["lr_backbone"]) == pytest.approx(0.02)
    assert float(attrs.looper.state["lr_head"]) == pytest.approx(0.07)
    mod.destroy()


def test_missing_filter_rejected(devices):
    with pytest.raises(RuntimeError, match="params_filter"):
        _module([
            rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                         tag="a"),
            rt.Optimizer(learning_rate=0.1, tag="b"),
        ])


def test_duplicate_tags_rejected(devices):
    with pytest.raises(RuntimeError, match="distinct tag"):
        _module([
            rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head")),
            rt.Optimizer(learning_rate=0.1,
                         params_filter=_path_has("backbone")),
        ])


def test_overlapping_groups_rejected(devices):
    mod = _module([
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                     tag="a"),
        rt.Optimizer(learning_rate=0.1, params_filter=lambda p, x: True,
                     tag="b"),
    ])
    with pytest.raises(ValueError, match="multiple Optimizers"):
        mod.materialize(_batch())
    mod.destroy()


def test_empty_group_rejected(devices):
    mod = _module([
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                     tag="a"),
        rt.Optimizer(learning_rate=0.1, params_filter=_path_has("no_such"),
                     tag="b"),
    ])
    with pytest.raises(RuntimeError, match="matched no"):
        mod.materialize(_batch())
    mod.destroy()


def test_ema_with_multiple_optimizers_rejected(devices):
    with pytest.raises(RuntimeError, match="ema_decay"):
        _module([
            rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                         tag="a", ema_decay=0.99),
            rt.Optimizer(learning_rate=0.1,
                         params_filter=_path_has("backbone"), tag="b"),
        ])


def test_lora_params_filter_matches_wrap_freeze(devices):
    """Optimizer(params_filter=is_lora) must train identically to the
    wrap=freeze_non_lora spelling (same seed, same steps)."""
    from rocket_tpu.models.lora import freeze_non_lora, is_lora
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    def lm_batch():
        rng = np.random.default_rng(0)
        return {"tokens": jnp.asarray(
            rng.integers(0, 256, size=(4, 32)), jnp.int32)}

    results = []
    for opt in (
        rt.Optimizer(learning_rate=1e-2, wrap=freeze_non_lora),
        rt.Optimizer(learning_rate=1e-2, params_filter=is_lora),
    ):
        cfg = TransformerConfig.tiny(lora_rank=4)
        mod = rt.Module(
            TransformerLM(cfg),
            capsules=[rt.Loss(lm_cross_entropy(), name="lm"), opt],
        )
        mod.bind(rt.Runtime())
        mod.setup()
        mod.materialize(lm_batch())
        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
        )
        for _ in range(3):
            attrs.batch = lm_batch()
            mod.launch(attrs)
        results.append(_flat(mod.state.params))
        mod.destroy()
    assert results[0].keys() == results[1].keys()
    for key in results[0]:
        np.testing.assert_allclose(
            results[0][key], results[1][key], atol=1e-6, rtol=1e-5,
            err_msg=key,
        )


def test_ready_tx_group_skips_scheduler_default(devices):
    """A ready tx= owns its LR: the sibling Scheduler default must not be
    force-injected into (and break) that group, and no fabricated LR is
    logged for it."""
    mod = _module([
        rt.Optimizer(tx=optax.sgd(0.1), params_filter=_path_has("head"),
                     tag="lr_head"),
        rt.Optimizer(params_filter=_path_has("backbone"),
                     tag="lr_backbone"),
        rt.Scheduler(optax.constant_schedule(0.02)),
    ])
    mod.materialize(_batch())
    before = _flat(mod.state.params)
    attrs = _run(mod, n=2)
    after = _flat(mod.state.params)
    for key in before:  # both groups actually train
        assert not np.allclose(before[key], after[key]), key
    assert float(attrs.looper.state["lr_backbone"]) == pytest.approx(0.02)
    assert "lr_head" not in attrs.looper.state  # opaque tx: no LR log
    mod.destroy()


def test_single_filter_with_ema_rejected_clearly(devices):
    """One filtered Optimizer + ema_decay: the masked EMA would cover the
    group only — the error must describe THIS situation, not 'multiple
    Optimizer capsules'."""
    with pytest.raises(RuntimeError, match="params_filter"):
        _module([
            rt.Optimizer(learning_rate=0.1,
                         params_filter=_path_has("head"), ema_decay=0.99),
        ])


def test_frozen_tag_reserved(devices):
    with pytest.raises(RuntimeError, match="reserved"):
        _module([
            rt.Optimizer(learning_rate=0.1, params_filter=_path_has("head"),
                         tag="frozen"),
            rt.Optimizer(learning_rate=0.1,
                         params_filter=_path_has("backbone"), tag="b"),
        ])
