"""Serving-robustness tests — rocket_tpu.serve end to end.

Three layers, mirroring the package:

- units: AdmissionQueue, DegradationPolicy, DispatchWatchdog, the typed
  Request/Result vocabulary, the new chaos injectors, retry deadlines,
  and the ContinuousBatcher admit/start validation;
- the fault-free contract: a ServingLoop with no faults, no deadlines,
  and an uncontended queue produces tokens BIT-IDENTICAL to the solo
  one-dispatch oracle for every request, adds no traced step bodies
  (``_spec_round`` jit cache is unchanged), and costs <5% per-round
  host overhead over the bare batcher;
- the chaos trio: bursty overload (every request typed, bounded
  deadline overrun), a wedged device step (watchdog trips, in-flight
  rows fail cleanly with partials, the rebuilt batcher serves the next
  request correctly), and the degradation ladder (engages under queue
  pressure, restores full quality once the queue drains).
"""

import time

import numpy as np
import pytest

import jax

from rocket_tpu.models.generate import (
    ContinuousBatcher,
    _spec_round,
    speculative_generate_batched,
)
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.serve import (
    AdmissionQueue,
    Completed,
    DeadlineExceeded,
    DegradationLevel,
    DegradationPolicy,
    DispatchWatchdog,
    Failed,
    HealthState,
    Overloaded,
    Request,
    ServingLoop,
)
from rocket_tpu.testing.chaos import (
    FaultySource,
    SlowSource,
    StuckStepInjector,
    bursty_arrivals,
)
from rocket_tpu.utils.retry import retry_call

pytestmark = pytest.mark.serving

B, P, TOTAL, NDRAFT = 3, 8, 24, 4


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt


def _lm(seed=1, **kw):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64, **kw
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


@pytest.fixture(scope="module")
def models():
    model, params = _lm(seed=1)
    draft, _ = _lm(seed=1)      # same structure...
    _, dparams = _lm(seed=7)    # ...different weights: low acceptance
    return model, draft, params, dparams


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(8, P)).astype(np.int32)


def _factory(models, **kw):
    model, draft, params, dparams = models

    def factory():
        return ContinuousBatcher(
            model, draft, params, dparams,
            total_len=TOTAL, n_draft=NDRAFT, eos_token=None, **kw,
        )

    return factory


def _oracle(models, prompt_row):
    model, draft, params, dparams = models
    toks = speculative_generate_batched(
        model, params, draft, dparams, prompt_row[None, :],
        max_new_tokens=TOTAL - P, n_draft=NDRAFT,
    )
    return np.asarray(toks[0])


# -- units: queue --------------------------------------------------------


class TestAdmissionQueue:
    def test_capacity_validated(self):
        with pytest.raises(ValueError, match="capacity"):
            AdmissionQueue(0)

    def test_offer_pop_fifo_and_full(self):
        q = AdmissionQueue(2)
        r1 = Request(rid=1, prompt=np.ones(4, np.int32))
        r2 = Request(rid=2, prompt=np.ones(4, np.int32))
        r3 = Request(rid=3, prompt=np.ones(4, np.int32))
        assert q.offer(r1) and q.offer(r2)
        assert not q.offer(r3)          # full: typed shed, not growth
        assert q.depth_frac == 1.0
        assert q.pop() is r1 and q.pop() is r2 and q.pop() is None

    def test_shed_hopeless_keeps_order_and_deadlineless(self):
        q = AdmissionQueue(4)
        doomed = Request(rid=1, prompt=np.ones(4, np.int32), deadline=5.0)
        fine = Request(rid=2, prompt=np.ones(4, np.int32), deadline=100.0)
        forever = Request(rid=3, prompt=np.ones(4, np.int32))
        for r in (doomed, fine, forever):
            q.offer(r)
        shed = q.shed_hopeless(now=4.5, floor_s=1.0)
        assert [r.rid for r in shed] == [1]
        assert [q.pop().rid for _ in range(2)] == [2, 3]


# -- units: degradation policy -------------------------------------------


class TestDegradationPolicy:
    def test_validation(self):
        with pytest.raises(ValueError, match="at least one level"):
            DegradationPolicy(ladder=())
        with pytest.raises(ValueError, match="one threshold per level"):
            DegradationPolicy(engage_depth=(0.5,))
        with pytest.raises(ValueError, match="ascending"):
            DegradationPolicy(engage_depth=(0.9, 0.5))
        with pytest.raises(ValueError, match="recover_rounds"):
            DegradationPolicy(recover_rounds=0)

    def test_depth_escalation_immediate(self):
        p = DegradationPolicy(engage_depth=(0.5, 0.875))
        assert p.update(0.2) == 0
        assert p.update(0.6) == 1          # one signal: instant
        assert p.update(0.9) == 2
        assert p.current.name == "survival"

    def test_latency_escalation(self):
        p = DegradationPolicy(round_ms_budget=100.0)
        assert p.update(0.0, round_ms=50.0) == 0
        assert p.update(0.0, round_ms=150.0) == 1
        assert p.update(0.0, round_ms=900.0) == 2  # clamped to top rung

    def test_hysteresis_recovery_one_level_at_a_time(self):
        p = DegradationPolicy(recover_rounds=3)
        p.update(0.95)
        assert p.level == 2
        for _ in range(2):
            assert p.update(0.0) == 2      # calm, but not calm enough
        assert p.update(0.0) == 1          # 3rd calm round: ONE level down
        assert p.update(0.6) == 1          # target==level resets the streak
        for _ in range(3):
            p.update(0.0)
        assert p.level == 0

    def test_n_draft_floor(self):
        p = DegradationPolicy()
        p.update(0.95)
        assert p.n_draft(4) == 1           # 4 * 0.25, floored at >= 1
        assert p.n_draft(2) == 1


# -- units: watchdog ------------------------------------------------------


class TestDispatchWatchdog:
    def test_timeout_validated(self):
        with pytest.raises(ValueError, match="timeout"):
            DispatchWatchdog(0.0)

    def test_none_runs_inline(self):
        wd = DispatchWatchdog(None)
        assert wd.run(lambda: 7) == (True, 7)
        assert wd._worker is None          # no thread was ever spawned

    def test_success_and_exception_reraise(self):
        wd = DispatchWatchdog(5.0)
        try:
            assert wd.run(lambda: "ok") == (True, "ok")
            with pytest.raises(RuntimeError, match="boom"):
                wd.run(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        finally:
            wd.close()

    def test_trip_then_recover_on_fresh_worker(self):
        wd = DispatchWatchdog(0.15)
        try:
            ok, value = wd.run(lambda: time.sleep(2.0))
            assert (ok, value) == (False, None)
            assert wd.trips == 1
            # the zombie still holds the old worker; a new one serves this
            assert wd.run(lambda: 42) == (True, 42)
        finally:
            wd.close()


# -- units: typed requests ------------------------------------------------


class TestRequestValidation:
    def test_prompt_normalized_to_1d(self):
        r = Request(rid=0, prompt=np.ones((1, 4), np.int32))
        assert r.prompt.shape == (4,) and r.prompt.dtype == np.int32

    def test_bad_prompts_rejected(self):
        with pytest.raises(ValueError, match="non-empty 1-D"):
            Request(rid=0, prompt=np.ones((2, 4), np.int32))
        with pytest.raises(ValueError, match="non-empty 1-D"):
            Request(rid=0, prompt=np.zeros((0,), np.int32))

    def test_bad_max_new_rejected(self):
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(rid=0, prompt=np.ones(4, np.int32), max_new_tokens=0)


# -- units: chaos injectors ----------------------------------------------


class TestChaosInjectors:
    def test_slow_source_delays_without_failing(self):
        naps = []
        src = SlowSource(
            list(range(5)), slow_on=(1, 3), delay_s=0.25, sleep=naps.append
        )
        assert [src[i] for i in range(5)] == list(range(5))
        assert src.stalls == 2 and naps == [0.25, 0.25]

    def test_bursty_arrivals_shape(self):
        arr = bursty_arrivals(7, burst=3, gap_s=2.0, spread_s=0.3,
                              start_s=1.0)
        assert len(arr) == 7 and arr == sorted(arr)
        assert arr[0] == 1.0 and arr[3] == 3.0 and arr[6] == 5.0
        with pytest.raises(ValueError):
            bursty_arrivals(0, 1, 1.0)

    def test_stuck_injector_delegates_and_wedges(self):
        class Inner:
            def __init__(self):
                self.n_draft = 4
                self.stepped = 0

            def step(self):
                self.stepped += 1
                return self.stepped

        naps = []
        inner = Inner()
        proxy = StuckStepInjector(inner, hang_on=(1,), hang_s=3.0,
                                  sleep=naps.append)
        assert proxy.n_draft == 4          # attribute reads delegate
        proxy.n_draft = 2                  # ...and writes land on the inner
        assert inner.n_draft == 2
        assert proxy.step() == 1 and naps == []
        assert proxy.step() == 2 and naps == [3.0]   # scheduled wedge
        assert proxy.steps == 2 and proxy.hangs == 1


# -- units: retry deadlines ----------------------------------------------


class TestRetryDeadline:
    def test_deadline_exhausted_raises_without_sleeping(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            raise OSError("transient")

        clock = FakeClock(10.0)
        t0 = time.monotonic()
        # deadline == now: every backoff would finish at/past it, so the
        # FIRST failure surfaces — tries and budget still had room
        with pytest.raises(OSError, match="transient"):
            retry_call(flaky, tries=10, base_delay=0.2, budget=30.0,
                       deadline=10.0, clock=clock)
        assert calls["n"] == 1
        assert time.monotonic() - t0 < 0.15   # no backoff was slept

    def test_generous_deadline_still_retries(self):
        calls = {"n": 0}

        def flaky_then_ok():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "done"

        assert retry_call(flaky_then_ok, tries=5, base_delay=0.001,
                          deadline=time.monotonic() + 60.0) == "done"
        assert calls["n"] == 3

    def test_no_deadline_unchanged(self):
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                       tries=2, base_delay=0.001)


# -- units: batcher admit/start validation --------------------------------


class TestBatcherValidation:
    def test_paths(self, models, prompts):
        factory = _factory(models)
        bat = factory()
        with pytest.raises(ValueError, match="non-empty \\[B, P\\]"):
            bat.start(np.ones(P, np.int32))            # 1-D
        with pytest.raises(ValueError, match="integer token ids"):
            bat.start(np.ones((2, P), np.float32))     # float ids
        with pytest.raises(ValueError, match="exceeds total_len"):
            bat.start(np.ones((2, TOTAL), np.int32))   # no room to generate
        with pytest.raises(ValueError, match="call start"):
            bat.admit(0, prompts[0])

        bat.start(prompts[:B])
        bat.step()
        with pytest.raises(ValueError, match="out of range"):
            bat.admit(B + 2, prompts[0])   # silent .at[row] drop otherwise
        with pytest.raises(ValueError, match="still decoding"):
            bat.admit(0, prompts[3])       # live row needs explicit preempt
        with pytest.raises(ValueError, match="out of range"):
            bat.retire(B + 2)
        with pytest.raises(ValueError, match="single non-empty prompt row"):
            bat.admit(0, prompts[:2], preempt=True)    # [2, P] is 2 rows

        bat.admit(0, prompts[3], preempt=True)         # explicit: allowed
        bat.retire(1)
        bat.admit(1, prompts[4])                       # done row: allowed


# -- sentinel scalar emission ---------------------------------------------


class TestSentinelScalars:
    def test_skip_and_event_counters_emitted_on_change(self):
        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.engine.sentinel import DivergenceSentinel

        s = DivergenceSentinel(policy="warn", spike_factor=None)
        s._runtime = object()
        tracker = Attributes(scalars=[], images=[])
        losses = [1.0, float("nan"), 1.0, 1.0, 1.0]
        skips = [0.0, 1.0, 0.0, 0.0, 0.0]
        for loss, sk in zip(losses, skips):
            s.launch(Attributes(
                step_logs={"loss": loss, "skipped": sk},
                looper=Attributes(grad_enabled=True),
                tracker=tracker,
            ))
        assert s.events == 1 and s.skips == 1 and s.rollbacks == 0
        # emit-on-change: ONE record despite five launches
        assert len(tracker.scalars) == 1
        rec = tracker.scalars[0]
        assert rec.data["sentinel/skips"] == 1.0
        assert rec.data["sentinel/events"] == 1.0
        assert rec.data["sentinel/rollbacks"] == 0.0

    def test_no_tracker_no_crash(self):
        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.engine.sentinel import DivergenceSentinel

        s = DivergenceSentinel(policy="warn", spike_factor=None)
        s._runtime = object()
        s.launch(Attributes(step_logs={"loss": float("nan")},
                            looper=Attributes(grad_enabled=True)))
        s.launch(Attributes(step_logs={"loss": float("nan")},
                            looper=Attributes(grad_enabled=True)))
        assert s.events >= 1


# -- fault-free contract --------------------------------------------------


class TestFaultFree:
    def test_bit_equality_and_no_new_traces(self, models, prompts):
        # bare run first: compiles (and pins) every executable the
        # wrapped loop should reuse
        bare = _factory(models)()
        bare.start(prompts[:B])
        while not bare.all_done:
            bare.step()
        bare_rows = [bare.row_tokens(r)[0] for r in range(B)]
        for r in range(B):
            assert np.array_equal(bare_rows[r], _oracle(models, prompts[r]))

        traces_before = _spec_round._cache_size()
        loop = ServingLoop(_factory(models), max_batch=B, queue_capacity=8)
        for i in range(5):
            assert loop.submit(Request(rid=i, prompt=prompts[i])) is None
        results = loop.run_until_idle()
        loop.close()

        assert len(results) == 5
        assert all(isinstance(r, Completed) for r in results)
        for r in results:
            assert np.array_equal(r.tokens, _oracle(models, prompts[r.rid]))
        # the robustness wrapper added ZERO traced step bodies
        assert _spec_round._cache_size() == traces_before
        assert loop.health is HealthState.SERVING
        snap = loop.counters.snapshot()
        assert snap["completed"] == 5 and snap["failed"] == 0
        assert snap["watchdog_trips"] == 0 and snap["degrade_peak"] == 0

    def test_host_overhead_under_5pct(self, models, prompts):
        rounds = 8

        def bare_round_times():
            bat = _factory(models)()
            bat.start(prompts[:B])
            bat.step()  # settle
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                bat.step()
                np.asarray(bat.state[0])   # same host fetch the loop does
                out.append(time.perf_counter() - t0)
            return out

        def wrapped_round_times():
            # watchdog ARMED (generous timeout): the honest steady-state
            # config, thread-hop included
            loop = ServingLoop(_factory(models), max_batch=B,
                               queue_capacity=8, watchdog_timeout=30.0)
            for i in range(B):
                loop.submit(Request(rid=i, prompt=prompts[i]))
            loop.run_round()  # admits + settles
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                loop.run_round()
                out.append(time.perf_counter() - t0)
            loop.close()
            return out

        bare = float(np.median(bare_round_times()))
        wrapped = float(np.median(wrapped_round_times()))
        # 5% relative plus an absolute floor for scheduler noise on tiny
        # CPU rounds
        assert wrapped <= bare * 1.05 + 5e-4, (
            f"wrapped round {wrapped * 1e3:.3f}ms vs bare "
            f"{bare * 1e3:.3f}ms"
        )

    def test_results_are_typed_exactly_once(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=B, queue_capacity=2)
        outcomes = [loop.submit(Request(rid=i, prompt=prompts[i % 8]))
                    for i in range(6)]
        rejected = [o for o in outcomes if o is not None]
        assert rejected and all(isinstance(o, Overloaded) for o in rejected)
        results = loop.run_until_idle()
        loop.close()
        assert sorted(r.rid for r in results) == list(range(6))


# -- chaos trio -----------------------------------------------------------


class TestChaosTrio:
    def test_bursty_overload_every_request_typed(self, models, prompts):
        """(a) burst past capacity: every submitted request resolves to
        exactly one typed result, and nothing overruns its deadline by
        more than one decode round (here: one fake-clock tick)."""
        clock = FakeClock()
        tick = 1.0
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=4, clock=clock)
        offsets = bursty_arrivals(12, burst=6, gap_s=4 * tick)
        deadlines = {i: (clock.t + offsets[i] + 3 * tick
                         if i % 3 == 0 else None)
                     for i in range(12)}
        submitted = 0
        results = []
        for _ in range(400):
            while submitted < 12 and offsets[submitted] <= clock.t:
                loop.submit(Request(
                    rid=submitted,
                    prompt=prompts[submitted % 8],
                    deadline=deadlines[submitted],
                ))
                submitted += 1
            loop.run_round()
            results.extend(loop.drain_results())
            clock.tick(tick)
            if submitted == 12 and len(results) == 12:
                break
        loop.close()

        assert sorted(r.rid for r in results) == list(range(12))
        by_type = {}
        for r in results:
            by_type.setdefault(type(r).__name__, []).append(r)
        # the burst of 6 into 3 rows + 4 queue slots must shed typed
        assert by_type.get("Overloaded"), by_type.keys()
        for r in results:
            if isinstance(r, DeadlineExceeded):
                dl = deadlines[r.rid]
                assert dl is not None
                assert r.finished_at - dl <= tick + 1e-9, (
                    f"rid {r.rid} overran its deadline by "
                    f"{r.finished_at - dl:.3f}s (> one round tick)"
                )
                if r.stage == "decode":
                    assert r.n_tok > P   # eviction kept the partials
        completed = by_type.get("Completed", [])
        for r in completed:
            assert np.array_equal(
                r.tokens, _oracle(models, prompts[r.rid % 8])
            )

    def test_stuck_step_trips_watchdog_and_recovers(self, models, prompts):
        """(b) a wedged device dispatch: the watchdog trips, in-flight
        rows fail cleanly with last-good partials, the batcher is
        rebuilt, and the NEXT batch completes bit-correct."""
        instances = {"n": 0}
        base_factory = _factory(models)

        def factory():
            bat = base_factory()
            instances["n"] += 1
            if instances["n"] == 1:
                # proxy step #0 is the loop's inline warm step; #1 the
                # first served round; #2 wedges
                return StuckStepInjector(bat, hang_on=(2,), hang_s=8.0)
            return bat

        loop = ServingLoop(factory, max_batch=B, queue_capacity=4,
                           watchdog_timeout=0.4, recover_rounds=2)
        for i in range(2):
            loop.submit(Request(rid=i, prompt=prompts[i]))
        loop.run_round()                     # proxy step #1: fine
        assert not loop.drain_results()
        loop.run_round()                     # proxy step #2: wedged
        results = loop.drain_results()

        assert loop.watchdog.trips == 1
        assert instances["n"] == 2           # rebuilt from the factory
        assert loop.health is HealthState.DEGRADED
        assert sorted(r.rid for r in results) == [0, 1]
        for r in results:
            assert isinstance(r, Failed)
            assert "watchdog" in r.reason
            # one clean round ran first, so partials exist and start
            # with the request's own prompt
            assert r.n_tok > P
            assert np.array_equal(r.tokens[:P], prompts[r.rid])

        # the rebuilt batcher serves the next request bit-correct
        loop.submit(Request(rid=7, prompt=prompts[7]))
        results = loop.run_until_idle()
        loop.close()
        (done,) = results
        assert isinstance(done, Completed) and done.rid == 7
        assert np.array_equal(done.tokens, _oracle(models, prompts[7]))
        assert loop.health is HealthState.SERVING  # recover window elapsed

    def test_degradation_ladder_engages_and_restores(self, models, prompts):
        """(c) queue pressure engages the ladder (n_draft shrinks, beam
        demotes); draining restores full quality (base n_draft, beam
        honored) — and every greedy result stays bit-equal to the
        oracle, degraded or not."""
        beam_calls = []

        def beam_fn(prompt_2d, max_new):
            beam_calls.append(int(max_new))
            row = np.asarray(prompt_2d[0])
            return np.concatenate(
                [row, np.zeros(max_new, np.int32)]
            )[None, :]

        ladder = (
            DegradationLevel("full"),
            DegradationLevel("lean", draft_frac=0.5, beam=False),
        )
        policy = DegradationPolicy(ladder=ladder, engage_depth=(0.5,),
                                   recover_rounds=2)
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=4, policy=policy,
                           beam_fn=beam_fn)
        base = loop.base_n_draft

        # fill the rows first, then pile the queue past the 0.5 threshold;
        # the beam request heads the FIFO so it is guaranteed to pop
        # while the ladder is still engaged
        for i in range(3):
            assert loop.submit(Request(rid=i, prompt=prompts[i % 8])) is None
        loop.run_round()                      # admits 3, queue empty
        loop.submit(Request(rid=90, prompt=prompts[5], beam=True))
        for i in range(3, 5):
            assert loop.submit(Request(rid=i, prompt=prompts[i % 8])) is None
        loop.run_round()                      # queue 3/4 = 0.75 -> engage
        assert loop.policy.level == 1
        assert loop.health is HealthState.DEGRADED
        assert loop._bat.n_draft == max(1, base // 2)
        peak_ndraft = loop._bat.n_draft

        results = loop.run_until_idle()
        assert loop.counters.degrade_peak == 1
        demoted = next(r for r in results if r.rid == 90)
        assert isinstance(demoted, Completed) and demoted.beam_demoted
        assert not beam_calls                 # the beam lane never ran
        assert sorted(r.rid for r in results) == [0, 1, 2, 3, 4, 90]
        for r in results:
            if r.rid != 90:
                assert np.array_equal(
                    r.tokens, _oracle(models, prompts[r.rid % 8])
                ), f"rid {r.rid} diverged while degraded"
        # greedy speculative decoding is n_draft-invariant: the demoted
        # request's tokens ALSO match its oracle
        assert np.array_equal(demoted.tokens, _oracle(models, prompts[5]))

        # drained queue -> calm rounds -> full quality restored
        assert loop.policy.level == 0
        assert loop._bat.n_draft == base > peak_ndraft
        assert loop.health is HealthState.SERVING

        # ...and the beam lane is honored again at level 0
        loop.submit(Request(rid=91, prompt=prompts[6], beam=True))
        (res,) = loop.run_until_idle()
        loop.close()
        assert isinstance(res, Completed) and res.via_beam
        assert beam_calls == [TOTAL - P]


# -- fleet satellites: queue counters, result meta, clock jumps ----------


class TestQueueTraceCounters:
    def test_depth_and_oldest_age_emitted_on_change(self):
        from rocket_tpu.observe.trace import Tracer

        tracer = Tracer(capacity=64, enabled=True)
        clk = FakeClock()
        q = AdmissionQueue(4, name="r0", tracer=tracer, clock=clk)
        q.offer(Request(rid=0, prompt=np.ones(4, np.int32)))
        clk.tick(2.0)
        q.offer(Request(rid=1, prompt=np.ones(4, np.int32)))
        q.pop()

        def series(name):
            key = name.rsplit("/", 1)[-1]
            return [e[5][key] for e in tracer.events() if e[1] == name]

        assert series("serve/queue/r0/depth") == [1.0, 2.0, 1.0]
        ages = series("serve/queue/r0/oldest_age_s")
        # offer@t0, offer@t2 (head aged 2s), pop@t2 (new head age 0)
        assert ages == [0.0, 2.0, 0.0]

    def test_shed_observes_once(self):
        from rocket_tpu.observe.trace import Tracer

        tracer = Tracer(capacity=64, enabled=True)
        clk = FakeClock()
        q = AdmissionQueue(4, name="q1", tracer=tracer, clock=clk)
        for i in range(3):
            q.offer(Request(rid=i, prompt=np.ones(4, np.int32),
                            deadline=1.0))
        before = len([e for e in tracer.events()
                      if e[1] == "serve/queue/q1/depth"])
        clk.tick(5.0)
        shed = q.shed_hopeless(clk(), 0.0)
        assert len(shed) == 3
        depth = [e[5]["depth"] for e in tracer.events()
                 if e[1] == "serve/queue/q1/depth"]
        assert len(depth) == before + 1 and depth[-1] == 0.0


class TestResultMeta:
    def test_completed_meta_carries_replica_and_level(self, models,
                                                      prompts):
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=8, replica_id="r7")
        assert loop.submit(Request(rid=0, prompt=prompts[0])) is None
        (res,) = loop.run_until_idle()
        loop.close()
        assert isinstance(res, Completed)
        assert res.meta == {"replica": "r7", "level": 0}

    def test_rejection_meta(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=8, replica_id="r8")
        loop.drain()
        rej = loop.submit(Request(rid=0, prompt=prompts[0]))
        loop.close()
        assert isinstance(rej, Overloaded)
        assert rej.meta["replica"] == "r8"


class TestClockJumpShedding:
    def test_queued_deadlines_shed_after_wedge(self, models, prompts):
        """A clock jump while the loop was wedged: queued entries whose
        deadline passed meanwhile are shed as DeadlineExceeded
        (stage='queue', never prefilled) on the FIRST round after
        recovery; the in-flight deadline-free row still completes."""
        clk = FakeClock()
        loop = ServingLoop(_factory(models), max_batch=1,
                           queue_capacity=8, clock=clk)
        assert loop.submit(Request(rid=0, prompt=prompts[0])) is None
        loop.run_round()                     # rid 0 is in flight
        admitted_before = loop.counters.admitted
        for i in (1, 2):
            assert loop.submit(
                Request(rid=i, prompt=prompts[i], deadline=clk() + 5.0)
            ) is None

        clk.tick(100.0)                      # the wedge: deadlines passed
        loop.run_round()                     # first round after recovery

        shed = [r for r in loop.drain_results()
                if isinstance(r, DeadlineExceeded)]
        assert sorted(r.rid for r in shed) == [1, 2]
        assert all(r.stage == "queue" for r in shed)
        assert all(r.tokens is None for r in shed)
        # neither shed entry ever reached the batcher
        assert loop.counters.admitted == admitted_before
        assert loop.counters.shed_deadline == 2

        results = loop.run_until_idle()
        loop.close()
        assert [r.rid for r in results] == [0]
        assert isinstance(results[0], Completed)
        assert np.array_equal(results[0].tokens,
                              _oracle(models, prompts[0]))


class TestRetryObservability:
    def test_on_retry_hook_and_trace_counter(self):
        from rocket_tpu.observe import trace

        src = FaultySource([10, 20, 30], fail_on=(0,), times=2)
        seen = []
        trace.arm(128)
        try:
            value = retry_call(
                src.__getitem__, 0, tries=5, base_delay=0.0,
                name="fetch", on_retry=lambda a, e, d: seen.append(a),
            )
            events = [e for e in trace.get_tracer().events()
                      if e[1] == "retry/fetch/attempts"]
        finally:
            trace.disarm()
        assert value == 10
        assert seen == [1, 2]
        assert [e[5]["attempts"] for e in events] == [1.0, 2.0]
