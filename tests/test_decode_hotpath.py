"""Decode hot path: KV-cached beam search + round-granular continuous
batching (PR 6).

The serving-side contracts under test:

- :func:`beam_search_cached` is bit-equal (tokens) to the O(T)
  re-decode oracle :func:`beam_search`, from ONE prompt prefill plus
  O(T) single-token cached forwards — proven by an instrumented proxy
  model that records every forward's token shape;
- :class:`ContinuousBatcher` (one speculative round per dispatch,
  state on device) reproduces the one-dispatch
  :func:`speculative_generate_batched` bit for bit, and a request
  admitted into a half-finished batch decodes exactly as a solo run
  without disturbing the live rows;
- the host speculative loops prefill through ``_chunked_prefill``
  (rolling-cache prompts longer than the slack no longer die) and
  their ``accepted`` stat never counts drafts an eos truncated away.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.models.generate import (
    ContinuousBatcher,
    beam_search,
    beam_search_cached,
    generate,
    speculative_generate,
    speculative_generate_batched,
)
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM


def _lm(seed=1, **kw):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot", **kw,
    )
    model = TransformerLM(cfg)
    init = jnp.zeros((1, 8), jnp.int32)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(seed), {"tokens": init})["params"]
    )
    return model, params


def _prompt(B=3, P=8, seed=13, vocab=64):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, vocab, size=(B, P)), jnp.int32)


class TestBeamSearchCached:
    def test_matches_redecode_oracle(self, devices):
        """Tokens bit-equal to :func:`beam_search` on the same inputs;
        scores agree to float tolerance (the cached path's softmax
        reduces over the cache allocation, a different — equally
        correct — reduction shape than the full forward)."""
        model, params = _lm()
        prompt = _prompt()
        oracle_t, oracle_s = beam_search(
            model, params, prompt, 12, eos_id=63, beam_size=4)
        cached_t, cached_s = beam_search_cached(
            model, params, prompt, 12, eos_id=63, beam_size=4)
        np.testing.assert_array_equal(
            np.asarray(oracle_t), np.asarray(cached_t))
        np.testing.assert_allclose(
            np.asarray(oracle_s), np.asarray(cached_s), atol=2e-5)

    def test_matches_oracle_with_live_eos(self, devices):
        """Same equality when eos actually fires: freeze + pad behavior
        must agree, because frozen beams keep writing pad continuations
        into the cache exactly as the oracle's buffer holds them."""
        model, params = _lm()
        prompt = _prompt()
        probe, _ = beam_search(model, params, prompt, 12, eos_id=63,
                               beam_size=2)
        eos = int(np.asarray(probe)[0, 8 + 2])  # fires mid-stream
        for K in (1, 2):
            ot, os_ = beam_search(model, params, prompt, 12, eos_id=eos,
                                  beam_size=K)
            ct, cs = beam_search_cached(model, params, prompt, 12,
                                        eos_id=eos, beam_size=K)
            np.testing.assert_array_equal(np.asarray(ot), np.asarray(ct))
            np.testing.assert_allclose(np.asarray(os_), np.asarray(cs),
                                       atol=2e-5)

    def test_single_new_token_edge(self, devices):
        model, params = _lm()
        prompt = _prompt()
        ot, _ = beam_search(model, params, prompt, 1, eos_id=63, beam_size=4)
        ct, _ = beam_search_cached(model, params, prompt, 1, eos_id=63,
                                   beam_size=4)
        np.testing.assert_array_equal(np.asarray(ot), np.asarray(ct))

    def test_cached_forwards_are_single_token(self, devices):
        """Instrumented O(T) proof: a recording proxy sees NO
        full-buffer-length forward from the cached path — only the
        prompt prefill plus a single-token decode trace whose count
        does not grow with T — while the oracle's step body runs the
        full ``[B*K, P+T]`` forward."""
        model, params = _lm()
        prompt = _prompt(B=2)
        P, K = 8, 4

        class Recorder:
            # identity hash/eq: each instance is a fresh static-arg
            # cache key, so every jitted caller re-traces and the
            # trace-time apply shapes land in `calls`
            def __init__(self, inner):
                self._inner = inner
                self.calls = []

            @property
            def config(self):
                return self._inner.config

            def apply(self, variables, batch, *args, **kw):
                self.calls.append(tuple(batch["tokens"].shape))
                return self._inner.apply(variables, batch, *args, **kw)

        def decode_widths(rec):
            return [s for s in rec.calls if s[0] == 2 * K]  # B*K rows

        counts = {}
        for T in (6, 12):
            rec = Recorder(model)
            beam_search_cached(rec, params, prompt, T, eos_id=63,
                               beam_size=K)
            widths = decode_widths(rec)
            assert widths, rec.calls
            # every beam-frontier forward feeds exactly ONE token; the
            # prompt is never replayed per beam or per step
            assert all(s[1] == 1 for s in widths), rec.calls
            assert all(s[1] <= P for s in rec.calls), rec.calls
            counts[T] = len(widths)
        # the decode step is traced a constant number of times (a
        # lax.scan body), independent of T: O(T) comes from scan
        # iterations of that single-token executable
        assert counts[6] == counts[12], counts

        rec = Recorder(model)
        beam_search(rec, params, prompt, 12, eos_id=63, beam_size=K)
        assert any(s == (2 * K, P + 12) for s in rec.calls), rec.calls

    def test_requires_causal_model(self, devices):
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
            norm="layernorm", mlp="gelu", positions="learned",
            tie_embeddings=True, use_bias=True, attention="dot",
            causal=False,
        )
        model = TransformerLM(cfg)
        prompt = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="causal"):
            beam_search(model, {}, prompt, 4, eos_id=1)
        with pytest.raises(ValueError, match="causal"):
            beam_search_cached(model, {}, prompt, 4, eos_id=1)


class TestContinuousBatcher:
    def _models(self):
        model, params = _lm(seed=1)
        draft, _ = _lm(seed=1)  # same structure...
        _, draft_params = _lm(seed=7)  # ...different weights
        return model, params, draft, draft_params

    def test_step_loop_matches_one_dispatch(self, devices):
        """Driving the round-granular step API to completion reproduces
        the one-dispatch while_loop bit for bit — same prefill, same
        round body, same key threading."""
        model, params, draft, draft_params = self._models()
        prompt = _prompt(B=3)
        NEW = 16
        toks, stats = speculative_generate_batched(
            model, params, draft, draft_params, prompt, NEW,
            n_draft=4, return_stats=True,
        )
        bat = ContinuousBatcher(model, draft, params, draft_params,
                                total_len=8 + NEW, n_draft=4)
        bat.start(prompt)
        steps = 0
        while not bat.all_done:
            bat.step()
            steps += 1
            assert steps < 100
        for r in range(3):
            row, n = bat.row_tokens(r)
            np.testing.assert_array_equal(row, np.asarray(toks)[r])
            assert n == 8 + NEW  # no eos: every row fills its buffer
        st = bat.stats()
        assert st["rounds"] == int(stats["rounds"]) == steps
        np.testing.assert_array_equal(st["drafted"],
                                      np.asarray(stats["drafted"]))
        np.testing.assert_array_equal(st["accepted"],
                                      np.asarray(stats["accepted"]))

    def test_admit_mid_batch_matches_solo_run(self, devices):
        """A request admitted into a half-finished batch decodes to
        completion exactly as a solo one-dispatch run — and the rows it
        joined are not disturbed."""
        model, params, draft, draft_params = self._models()
        prompt = _prompt(B=2)
        NEW = 16
        newcomer = _prompt(B=1, seed=99)[0]

        baseline = np.asarray(speculative_generate_batched(
            model, params, draft, draft_params, prompt, NEW, n_draft=4))
        solo = np.asarray(speculative_generate_batched(
            model, params, draft, draft_params, newcomer[None, :], NEW,
            n_draft=4))[0]

        bat = ContinuousBatcher(model, draft, params, draft_params,
                                total_len=8 + NEW, n_draft=4)
        bat.start(prompt)
        for _ in range(2):
            bat.step()  # both rows now mid-decode
        assert not bat.all_done
        bat.retire(0)  # preempt row 0...
        bat.admit(0, newcomer)  # ...and admit the newcomer mid-batch
        steps = 0
        while not bat.all_done:
            bat.step()
            steps += 1
            assert steps < 100
        row0, _ = bat.row_tokens(0)
        row1, _ = bat.row_tokens(1)
        np.testing.assert_array_equal(row0, solo)
        np.testing.assert_array_equal(row1, baseline[1])

    def test_validation(self, devices):
        model, params, draft, draft_params = self._models()
        with pytest.raises(ValueError, match="max_seq"):
            ContinuousBatcher(model, draft, params, draft_params,
                              total_len=64, n_draft=4)  # 64 + 4 > 64
        with pytest.raises(ValueError, match="n_draft"):
            ContinuousBatcher(model, draft, params, draft_params,
                              total_len=32, n_draft=0)
        with pytest.raises(ValueError, match="temperature"):
            ContinuousBatcher(model, draft, params, draft_params,
                              total_len=32, sampled=True, temperature=0.0)
        bat = ContinuousBatcher(model, draft, params, draft_params,
                                total_len=16)
        with pytest.raises(ValueError, match="start"):
            bat.step()
        with pytest.raises(ValueError, match="prompt length"):
            bat.start(jnp.zeros((2, 16), jnp.int32))


class TestHostLoopSatellites:
    def test_rolling_cache_prompt_longer_than_slack(self, devices):
        """The host speculative loop prefills through
        ``_chunked_prefill`` now: a rolling-cache model with a prompt
        longer than its decode slack must decode (and still match
        greedy generate) instead of dying in the chunk-size check."""
        model, params = _lm(
            attention_window=8, decode_rolling_cache=True,
            decode_rolling_slack=8,
        )
        P, T = 24, 8  # P >> slack: the old single-shot prefill raised
        prompt = _prompt(B=1, P=P)
        ref = np.asarray(generate(model, params, prompt, T,
                                  temperature=0.0))
        out = np.asarray(speculative_generate(
            model, params, model, params, prompt, T, n_draft=4))
        np.testing.assert_array_equal(out, ref)

    def test_accepted_stat_clamped_by_eos_truncation(self, devices):
        """Self-draft accepts every draft; an eos landing mid-block
        truncates what is EMITTED, and the accepted stat must count the
        emitted drafts, not the pre-truncation acceptance length."""
        model, params = _lm()
        prompt = _prompt(B=1, seed=0)
        ref = np.asarray(generate(model, params, prompt, 12,
                                  temperature=0.0))[0]
        g, second = int(ref[8]), int(ref[9])
        if g == second:
            pytest.skip("degenerate greedy chain: g == second token")
        out, stats = speculative_generate(
            model, params, model, params, prompt, 12, n_draft=4,
            return_stats=True, eos_token=second,
        )
        # round 1: drafts [d1..d4] all accepted, but eos == d1 cuts the
        # emission to one token — accepted must clamp to 1
        assert stats["rounds"] == 1
        assert stats["drafted"] == 4
        assert stats["accepted"] == 1
        row = np.asarray(out)[0]
        assert int(row[9]) == second
        assert np.all(row[10:] == second)  # fixed-length eos fill
