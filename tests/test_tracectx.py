"""Distributed request tracing — fast units (see docs/observability.md
"Distributed request tracing").

Covers the pieces the proc acceptance test (test_tracing_proc.py)
composes: TraceContext determinism + head-sampling, wire v2<->v3
tolerance and the typed ProtocolMismatch both directions, clock-offset
estimation edges (asymmetric RTT, drift between pings, negative
offset), flow-event Chrome validity, critical-path decomposition on
synthetic rings, timeline stitching with known offsets, tail-sampled
flight-dump metadata, and the admission queue's non-popping inventory.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rocket_tpu.observe.trace import (  # noqa: E402
    OffsetEstimator,
    TraceContext,
    Tracer,
    get_sampling,
    set_sampling,
)

pytestmark = pytest.mark.tracing


# -- TraceContext -------------------------------------------------------------


class TestTraceContext:
    def teardown_method(self):
        set_sampling(1.0, 0)

    def test_make_is_deterministic_across_processes(self):
        # same rid + same sampling config -> identical trace_id and
        # keep/drop decision, with no shared state (the cross-process
        # agreement the wire protocol leans on)
        a = TraceContext.make("req-7")
        b = TraceContext.make("req-7")
        assert a == b
        assert a.trace_id.endswith("-req-7")

    def test_sampling_rate_holds(self):
        set_sampling(0.25, seed=3)
        assert get_sampling() == (0.25, 3)
        kept = sum(TraceContext.make(f"r{i}").sampled for i in range(2000))
        assert 0.18 < kept / 2000 < 0.32
        # rate 0 and 1 are exact
        set_sampling(0.0)
        assert not any(
            TraceContext.make(f"r{i}").sampled for i in range(50))
        set_sampling(1.0)
        assert all(TraceContext.make(f"r{i}").sampled for i in range(50))

    def test_seed_varies_the_subset(self):
        picks = []
        for seed in (0, 1):
            set_sampling(0.5, seed=seed)
            picks.append({i for i in range(200)
                          if TraceContext.make(f"r{i}").sampled})
        assert picks[0] != picks[1]

    def test_wire_roundtrip_and_child(self):
        ctx = TraceContext.make("abc")
        rt = TraceContext.from_wire(ctx.to_wire())
        assert rt == ctx
        kid = ctx.child("wire")
        assert kid.trace_id == ctx.trace_id
        assert kid.parent == "wire"
        assert kid.flow_id == ctx.flow_id  # the chain key never changes

    def test_from_wire_tolerates_garbage(self):
        for junk in (None, 7, "x", (1, 2), ("a", "b", True, "extra"),
                     (7, "p", True), [None, "", False]):
            assert TraceContext.from_wire(junk) is None


# -- wire v2 <-> v3 matrix ----------------------------------------------------


class TestWireV3:
    def test_v3_request_roundtrips_ctx(self):
        from rocket_tpu.serve.types import Request
        from rocket_tpu.serve.wire import pack_request, unpack_request

        req = Request(rid="r1", prompt=np.arange(4, dtype=np.int32))
        req._ctx = TraceContext.make("r1")
        out = unpack_request(pack_request(req))
        assert out._ctx.trace_id == req._ctx.trace_id
        assert out._ctx.sampled == req._ctx.sampled
        # crossing the wire marks the hop: the worker-side submit must
        # emit a flow step ("t"), never a second start
        assert out._ctx.parent == "wire"

    def test_v2_frame_unpacks_without_ctx(self):
        from rocket_tpu.serve.types import Request
        from rocket_tpu.serve.wire import pack_request, unpack_request

        req = Request(rid="r2", prompt=np.arange(4, dtype=np.int32))
        wire = pack_request(req)        # no _ctx stamped -> no "ctx" key
        assert "ctx" not in wire
        out = unpack_request(wire)
        assert getattr(out, "_ctx", None) is None
        # a v2 peer that pickled extra garbage into ctx degrades the
        # same way — None, never an exception
        wire["ctx"] = {"not": "a tuple"}
        assert getattr(unpack_request(wire), "_ctx", None) is None

    def test_protocol_mismatch_both_directions(self):
        from rocket_tpu.serve.wire import (
            PROTOCOL_VERSION,
            ProtocolMismatch,
            WorkerSpec,
            check_hello,
            check_ready,
            hello_payload,
        )

        assert PROTOCOL_VERSION == 3
        spec = WorkerSpec(builder="m:f")
        # matched versions pass both ways
        assert check_hello(hello_payload(spec)) is spec
        assert check_ready({"proto": PROTOCOL_VERSION})["proto"] == 3
        # worker side rejects a v2 supervisor
        with pytest.raises(ProtocolMismatch) as ei:
            check_hello({"proto": 2, "spec": spec})
        assert ei.value.theirs == 2 and ei.value.side == "worker"
        # supervisor side rejects a v2 worker
        with pytest.raises(ProtocolMismatch) as ei:
            check_ready({"proto": 2})
        assert ei.value.side == "supervisor"
        # pre-versioning peers count as version 0
        with pytest.raises(ProtocolMismatch):
            check_hello(spec)
        with pytest.raises(ProtocolMismatch):
            check_ready({})


# -- clock-offset estimation --------------------------------------------------


class TestOffsetEstimator:
    def test_symmetric_exchange_recovers_offset(self):
        est = OffsetEstimator()
        true_offset = 5_000_000          # worker 5ms ahead
        t0 = 1_000_000
        tw = (t0 + 500_000) + true_offset   # reply stamped mid-flight
        est.add(t0, tw, t0 + 1_000_000)
        assert est.offset_ns == true_offset
        assert est.rtt_ns == 1_000_000

    def test_asymmetric_rtt_error_bounded_by_half_rtt(self):
        # transit 100us out, 900us back: the midpoint assumption is
        # maximally wrong, but the error stays within rtt/2
        est = OffsetEstimator()
        true_offset = 2_000_000
        t0 = 10_000_000
        tw = t0 + 100_000 + true_offset
        t1 = t0 + 1_000_000
        est.add(t0, tw, t1)
        assert abs(est.offset_ns - true_offset) <= est.rtt_ns // 2

    def test_min_rtt_sample_wins_over_congested_ones(self):
        est = OffsetEstimator()
        true_offset = 3_000_000
        # congested exchanges with asymmetric queueing (bad estimates)
        for i in range(5):
            t0 = i * 100_000_000
            est.add(t0, t0 + 8_000_000 + true_offset, t0 + 9_000_000)
        # one tight exchange
        t0 = 900_000_000
        est.add(t0, t0 + 50_000 + true_offset, t0 + 100_000)
        assert est.rtt_ns == 100_000
        assert abs(est.offset_ns - true_offset) <= 50_000

    def test_drift_between_pings_tracked_by_window(self):
        # offset drifts 1us per exchange; the bounded window forgets
        # old samples so the estimate follows, within rtt/2 of current
        est = OffsetEstimator(window=4)
        for i in range(20):
            off = 1_000_000 + i * 1_000
            t0 = i * 10_000_000
            est.add(t0, t0 + 50_000 + off, t0 + 100_000)
        current = 1_000_000 + 19 * 1_000
        assert abs(est.offset_ns - current) <= 4_000 + 50_000

    def test_negative_offset_and_backwards_clock(self):
        est = OffsetEstimator()
        t0 = 50_000_000
        est.add(t0, t0 + 100_000 - 7_000_000, t0 + 200_000)  # worker behind
        assert est.offset_ns < 0
        assert abs(est.offset_ns - (-7_000_000)) <= est.rtt_ns // 2
        # a sample where the supervisor clock ran backwards is rejected
        n = len(est)
        est.add(t0, t0, t0 - 1)
        assert len(est) == n

    def test_empty_estimator_answers_none(self):
        est = OffsetEstimator()
        assert est.offset_ns is None and est.rtt_ns is None
        assert est.snapshot() == {"samples": 0.0}

    def test_stitched_ordering_stays_monotone_per_request(self):
        # supervisor events at t=10,40; worker events (clock +off) that
        # REALLY happened at t=20,30.  After stitching (ts_w - offset)
        # the per-request order is monotone for positive AND negative
        # offsets.
        for true_offset_us in (7_000.0, -7_000.0):
            est = OffsetEstimator()
            t0 = 1_000_000
            off_ns = int(true_offset_us * 1e3)
            est.add(t0, t0 + 50_000 + off_ns, t0 + 100_000)
            est_off_us = est.offset_ns / 1e3
            sup = [("fleet/route", 10.0), ("fleet/delivered", 40.0)]
            wrk = [("serve/admit", 20.0 + true_offset_us),
                   ("serve/complete", 30.0 + true_offset_us)]
            stitched = sup + [(n, ts - est_off_us) for n, ts in wrk]
            stitched.sort(key=lambda e: e[1])
            assert [n for n, _ in stitched] == [
                "fleet/route", "serve/admit", "serve/complete",
                "fleet/delivered"]


# -- flow events / Chrome export ---------------------------------------------


class TestFlowEvents:
    def test_flow_chain_exports_valid_chrome_phases(self):
        tr = Tracer(enabled=True)
        ctx = TraceContext.make("r9")
        tr.flow("serve/request", "s", ctx.flow_id, rid="r9")
        tr.flow("serve/request", "t", ctx.flow_id, rid="r9", hop="admit")
        tr.flow("serve/request", "f", ctx.flow_id, rid="r9",
                outcome="complete")
        evs = [e for e in tr.to_chrome()["traceEvents"]
               if e["name"] == "serve/request"]
        assert [e["ph"] for e in evs] == ["s", "t", "f"]
        assert len({e["id"] for e in evs}) == 1
        assert all(e["cat"] == "request" for e in evs)
        # chrome schema: ph/id/cat are event fields, not args; the
        # finish binds to its enclosing slice
        assert all("ph" not in e["args"] and "id" not in e["args"]
                   for e in evs)
        assert evs[-1]["bp"] == "e"
        assert evs[1]["args"]["hop"] == "admit"
        json.dumps(tr.to_chrome())  # serializable end to end

    def test_tracer_meta_rides_dump_metadata(self, tmp_path):
        tr = Tracer(enabled=True)
        tr.meta.update({"role": "worker", "replica": "w0", "pid": 123})
        tr.instant("serve/submit", rid="r")
        path = tr.dump_json(str(tmp_path / "worker-w0-123.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["metadata"]["role"] == "worker"
        assert doc["metadata"]["replica"] == "w0"

    def test_disarmed_flow_is_noop(self):
        tr = Tracer(enabled=False)
        tr.flow("serve/request", "s", 1)
        assert tr.events() == []


# -- critical-path decomposition ---------------------------------------------


def _ring(events_ms):
    """Synthetic ring tuples from (name, t_ms, dur_ms, fields)."""
    out = []
    for name, t_ms, dur_ms, fields in events_ms:
        kind = "X" if dur_ms else "I"
        out.append((kind, name, int(t_ms * 1e6), int(dur_ms * 1e6),
                    1, dict(fields)))
    return out


class TestCritPath:
    def test_segments_sum_to_e2e_on_synthetic_request(self):
        from rocket_tpu.observe.critpath import analyze_events

        ring = _ring([
            ("serve/submit", 0, 0, {"rid": "r1", "cls": "interactive",
                                    "trace_id": "ab-r1"}),
            ("fleet/route", 1, 0, {"rid": "r1", "route_ms": 1.0}),
            ("fleet/prefill", 1, 4, {"rid": "r1", "replica": "p0"}),
            ("fleet/handoff", 6, 0, {"rid": "r1", "wire_ms": 1.0}),
            ("serve/pool_fetch", 7, 2, {"rid": "r1"}),
            ("serve/admit", 9, 3, {"rid": "r1", "queue_wait_ms": 2.0}),
            ("serve/preempt", 14, 0, {"rid": "r1"}),
            ("serve/resume", 18, 0, {"rid": "r1"}),
            ("serve/first_token", 13, 0, {"rid": "r1", "ttft_ms": 13.0}),
            ("serve/complete", 22, 0, {"rid": "r1", "cls": "interactive",
                                       "e2e_ms": 22.0}),
            ("fleet/delivered", 23, 0, {"rid": "r1"}),
        ])
        (p,) = analyze_events(ring)
        assert p.cls == "interactive" and p.trace_id == "ab-r1"
        s = p.segments
        assert s["route"] == pytest.approx(1.0)
        # prefill-lane span (4) + the admit span (3): the admit IS the
        # row's prefill work (KV import here, full prefill when local)
        assert s["prefill"] == pytest.approx(7.0)
        assert s["handoff_wire"] == pytest.approx(1.0)
        assert s["pool_fetch"] == pytest.approx(2.0)
        assert s["queue_wait"] == pytest.approx(2.0)
        assert s["preempt_parked"] == pytest.approx(4.0)
        # decode = terminal(22) - admit_end(12) - parked(4) = 6
        assert s["decode_rounds"] == pytest.approx(6.0)
        assert s["delivery"] == pytest.approx(1.0)
        assert p.ttft_ms == pytest.approx(13.0)
        assert p.e2e_ms == pytest.approx(22.0)
        assert p.dominant == "prefill"

    def test_heal_segment_lands_on_critical_path(self):
        from rocket_tpu.observe.critpath import analyze_events

        ring = _ring([
            ("serve/submit", 0, 0, {"rid": "r2", "cls": "standard"}),
            ("fleet/requeued", 5, 0, {"rid": "r2", "heal_ms": 50.0}),
            ("serve/admit", 60, 1, {"rid": "r2", "queue_wait_ms": 3.0}),
            ("serve/complete", 70, 0, {"rid": "r2", "cls": "standard",
                                       "e2e_ms": 70.0}),
        ])
        (p,) = analyze_events(ring)
        assert p.segments["heal"] == pytest.approx(50.0)
        assert p.dominant == "heal"

    def test_only_terminated_requests_emerge(self):
        from rocket_tpu.observe.critpath import analyze_events

        ring = _ring([
            ("serve/submit", 0, 0, {"rid": "done"}),
            ("serve/complete", 5, 0, {"rid": "done", "e2e_ms": 5.0}),
            ("serve/submit", 1, 0, {"rid": "inflight"}),
        ])
        assert [p.rid for p in analyze_events(ring)] == ["done"]

    def test_stats_snapshot_is_sum_mergeable(self):
        from rocket_tpu.observe.critpath import (
            aggregate,
            analyze_events,
            format_table,
        )
        from rocket_tpu.observe.export import merge_counters

        ring = _ring([
            ("serve/submit", 0, 0, {"rid": "a", "cls": "interactive"}),
            ("serve/admit", 1, 2, {"rid": "a", "queue_wait_ms": 1.0}),
            ("serve/complete", 9, 0, {"rid": "a", "cls": "interactive",
                                      "e2e_ms": 9.0}),
            ("serve/submit", 0, 0, {"rid": "b", "cls": "batch"}),
            ("serve/admit", 2, 1, {"rid": "b", "queue_wait_ms": 2.0}),
            ("serve/evict", 30, 0, {"rid": "b", "cls": "batch",
                                    "e2e_ms": 30.0}),
        ])
        stats = aggregate(analyze_events(ring))
        snap = stats.snapshot()
        assert snap["interactive/count"] == 1.0
        assert snap["batch/count"] == 1.0
        assert snap["batch/dominant_decode_rounds"] == 1.0
        # no /pNN keys — merge_counters SUMs everything here
        assert not any(k.rsplit("/", 1)[-1].startswith("p")
                       and k.rsplit("/", 1)[-1][1:].isdigit()
                       for k in snap)
        merged = merge_counters([snap, snap])
        assert merged["interactive/count"] == 2.0
        assert merged["interactive/e2e_ms_total"] == \
            pytest.approx(2 * snap["interactive/e2e_ms_total"])
        table = format_table(stats)
        assert "interactive" in table and "queue_wait" in table

    def test_critpath_source_exports_prometheus(self):
        from rocket_tpu.observe import export
        from rocket_tpu.observe.critpath import (
            CritPathStats,
            RequestPath,
            register_critpath_source,
        )

        stats = CritPathStats()
        stats.record(RequestPath(
            "r", cls="interactive", e2e_ms=4.0,
            segments={"decode_rounds": 4.0}))
        name = register_critpath_source(stats)
        try:
            text = export.prometheus_text(export.collect())
            assert "rocket_tpu_serve_critpath_interactive_count 1" \
                in text.replace(".0", "")
        finally:
            export.unregister_source(name)


# -- timeline stitching -------------------------------------------------------


class TestTimelineStitch:
    def _write(self, path, events, meta):
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "metadata": meta}
        with open(path, "w") as f:
            json.dump(doc, f)

    def test_offset_stitch_aligns_and_orders(self, tmp_path):
        from rocket_tpu.observe.timeline import (
            request_timelines,
            stitch_timeline,
        )

        # worker clock runs 500000us AHEAD of the supervisor's
        off_us = 500_000.0
        self._write(tmp_path / "supervisor.json", [
            {"name": "fleet/route", "ph": "i", "ts": 100.0, "pid": 0,
             "tid": 1, "args": {"rid": "r1"}},
            {"name": "fleet/delivered", "ph": "i", "ts": 900.0, "pid": 0,
             "tid": 1, "args": {"rid": "r1"}},
        ], {"process_index": 0})
        self._write(tmp_path / "worker-w0-42.json", [
            {"name": "serve/admit", "ph": "X", "ts": 200.0 + off_us,
             "dur": 50.0, "pid": 0, "tid": 2, "args": {"rid": "r1"}},
            {"name": "serve/complete", "ph": "i", "ts": 800.0 + off_us,
             "pid": 0, "tid": 2, "args": {"rid": "r1"}},
        ], {"process_index": 0, "role": "worker", "replica": "w0",
            "pid": 42})
        with open(tmp_path / "clock_offsets.json", "w") as f:
            json.dump({"w0": {"offset_us": off_us, "rtt_us": 80.0,
                              "samples": 4, "pid": 42}}, f)
        out_path = str(tmp_path / "timeline.json")
        doc = stitch_timeline(str(tmp_path), out_path=out_path)
        assert os.path.exists(out_path)
        lanes = {l["label"]: l for l in doc["metadata"]["lanes"]}
        assert lanes["w0"]["aligned"] == "offset"
        assert lanes["w0"]["shift_us"] == pytest.approx(-off_us)
        (rid_events,) = request_timelines(doc).values()
        assert [e["name"] for e in rid_events] == [
            "fleet/route", "serve/admit", "serve/complete",
            "fleet/delivered"]
        # distinct perfetto lanes, named
        assert len({e["pid"] for e in rid_events}) == 2
        names = [e for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert len(names) == 2

    def test_unmatched_worker_falls_back_to_wall_anchor(self, tmp_path):
        from rocket_tpu.observe.timeline import stitch_timeline

        self._write(tmp_path / "supervisor.json", [], {
            "process_index": 0, "anchor_wall_s": 100.0,
            "anchor_perf_us": 1_000.0})
        self._write(tmp_path / "worker-w9-7.json", [
            {"name": "serve/submit", "ph": "i", "ts": 2_000.0, "pid": 0,
             "tid": 1, "args": {"rid": "r"}}], {
            "process_index": 0, "role": "worker", "replica": "w9",
            "pid": 7, "anchor_wall_s": 100.5, "anchor_perf_us": 500.0})
        doc = stitch_timeline(str(tmp_path))  # no offsets file at all
        lane = [l for l in doc["metadata"]["lanes"]
                if l["label"] == "w9"][0]
        assert lane["aligned"] == "wall_anchor"
        # (100.5-100)*1e6 - 500 + 1000
        assert lane["shift_us"] == pytest.approx(500_500.0)
        assert not doc["metadata"]["unaligned_files"]


# -- tail sampling / flight dumps --------------------------------------------


class TestTailSampling:
    def test_dump_metadata_carries_extra_meta(self, tmp_path):
        from rocket_tpu.observe.recorder import FlightRecorder

        tr = Tracer(enabled=True)
        tr.instant("serve/submit", rid="r1")
        rec = FlightRecorder(tr, out_dir=str(tmp_path))
        path = rec.dump("test", extra_meta={"inflight": [
            {"rid": "r1", "cls": "interactive", "trace_id": "ab-r1"}]})
        with open(os.path.join(path, "trace.json")) as f:
            doc = json.load(f)
        assert doc["metadata"]["dump_reason"] == "test"
        assert doc["metadata"]["inflight"][0]["trace_id"] == "ab-r1"

    def test_queue_pending_inventory_does_not_pop(self):
        from rocket_tpu.serve.queue import AdmissionQueue
        from rocket_tpu.serve.types import Request

        q = AdmissionQueue(capacity=8)
        for i, cls in enumerate(("batch", "interactive", "standard")):
            assert q.offer(Request(rid=f"r{i}",
                                   prompt=np.arange(4, dtype=np.int32),
                                   slo_class=cls))
        inv = q.pending()
        # priority-class order, nothing consumed
        assert [r.slo_class for r in inv] == [
            "interactive", "standard", "batch"]
        assert len(q) == 3
        assert [r.rid for r in q.pending()] == [r.rid for r in inv]
