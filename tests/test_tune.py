"""rocket_tpu.tune — the search-driven autotuner (cost model, tune
space, persistent store, successive halving) and its reach into bench.py
(`_resolve_gpt2_tune` precedence) and the runtime donate default.

The CPU-proxy smoke at the bottom runs the REAL subprocess probe path
(`bench_probe` → fresh `python -c` → `bench.bench_gpt2(tune=...)`) over
the tiny 2-point space — the zero-re-search contract (second `autotune`
call returns the stored record with ``probes == 0``) is the acceptance
bar from the ISSUE.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rocket_tpu.tune import (  # noqa: E402
    TuneParam,
    TuneSpace,
    autotune,
    best_tune,
    canonical_tune_key,
    device_peak_flops,
    device_peak_hbm_bytes,
    gpt2_space,
    gpt2_step_flops,
    predict_point,
    runtime_default,
    save_tune,
    successive_halving,
)


@pytest.fixture()
def tune_dir(tmp_path, monkeypatch):
    d = tmp_path / "tunes"
    monkeypatch.setenv("ROCKET_TPU_TUNE_DIR", str(d))
    return d


# -- cost model ---------------------------------------------------------


def test_peaks_positive(devices):
    assert device_peak_flops() > 0
    assert device_peak_hbm_bytes() > 0
    # known silicon resolves to its table entry, not the default
    assert device_peak_flops("TPU v4") != device_peak_flops("unknown-chip")


def test_cost_model_orderings(devices):
    """The roofline must rank knobs the way the measured ladder does:
    remat taxes FLOPs, fused_ce deletes the logits round-trip bytes,
    donate=False pays a params copy."""
    base = {"batch": 8, "seq": 1024}
    p = predict_point(base)
    assert p["flops"] > 0 and p["bytes"] > 0 and p["seconds"] > 0
    assert predict_point({**base, "remat": True})["flops"] > p["flops"]
    assert predict_point({**base, "fused_ce": True})["bytes"] < p["bytes"]
    assert predict_point({**base, "donate": False})["bytes"] > p["bytes"]
    assert (predict_point({**base, "mu_dtype": "bf16"})["bytes"]
            < p["bytes"])


def test_gpt2_step_flops_is_benchs(devices):
    """bench.py re-exports the tune package's FLOPs accounting — one
    definition, two consumers (ladder MFU and search seeding)."""
    import bench

    assert bench.gpt2_step_flops is gpt2_step_flops


# -- space --------------------------------------------------------------


def test_space_candidates_merge_fragments(devices):
    sp = TuneSpace((
        TuneParam("a", ({"x": 1}, {"x": 2})),
        TuneParam("b", ({}, {"y": True})),
    ))
    cands = list(sp.candidates())
    assert sp.size == 4 and len(cands) == 4
    assert {"x": 2, "y": True} in cands


def test_space_advisory_keys_stripped_from_bench_tune(devices):
    sp = gpt2_space()
    advisory = sp.advisory_keys()
    assert "prefetch" in advisory and "mesh" in advisory
    point = {"batch": 8, "prefetch": 2, "mesh": "fsdp"}
    bench_point = sp.bench_tune(point)
    assert bench_point == {"batch": 8}


def test_canonical_key_resolves_default_blocks(devices):
    """An explicit block pair equal to auto_blocks(seq) must collide
    with the library-default point — the sweep dedupe contract."""
    from rocket_tpu.ops.flash import auto_blocks

    bq, bk = auto_blocks(1024)
    defaults = {"seq": 1024, "block_q": None, "block_k": None}
    explicit = canonical_tune_key(
        {"block_q": bq, "block_k": bk}, defaults=defaults
    )
    implied = canonical_tune_key({}, defaults=defaults)
    assert explicit == implied
    other = canonical_tune_key({"block_q": bq // 2}, defaults=defaults)
    assert other != implied


# -- store --------------------------------------------------------------


def _record(**kw):
    import jax

    rec = {
        "model": "gpt2",
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "batch": 8,
        "tune": {"batch": 8},
        "value": 100.0,
    }
    rec.update(kw)
    return rec


def test_store_round_trip_and_matching(devices, tune_dir):
    save_tune(_record(value=100.0))
    hit = best_tune(model="gpt2")
    assert hit is not None and hit["value"] == 100.0
    assert hit["schema"] == 1 and "created" in hit
    # wrong silicon / backend must NOT match
    assert best_tune(model="gpt2", device="TPU v999") is None
    assert best_tune(model="gpt2", backend="not-a-backend") is None
    # newer record for the same key wins
    save_tune(_record(value=200.0, tune={"batch": 8, "donate": False}))
    assert best_tune(model="gpt2")["value"] == 200.0


def test_store_batch_specific_beats_wildcard(devices, tune_dir):
    save_tune(_record(batch=8, value=1.0))
    save_tune(_record(batch=16, value=2.0, tune={"batch": 16}))
    assert best_tune(model="gpt2", batch=16)["value"] == 2.0
    assert best_tune(model="gpt2", batch=8)["value"] == 1.0


def test_runtime_default_consults_store(devices, tune_dir):
    # no record: caller default
    assert runtime_default("donate", default=True) is True
    save_tune(_record(tune={"batch": 8, "donate": False}))
    assert runtime_default("donate", default=True) is False
    # knob absent from the record: caller default again
    assert runtime_default("prefetch", default=3) == 3


def test_save_tune_rejects_incomplete(devices, tune_dir):
    with pytest.raises(ValueError):
        save_tune({"model": "gpt2"})


def test_engine_donate_none_consults_store(devices, tune_dir):
    from rocket_tpu.engine.step import _resolve_donate

    assert _resolve_donate(None) is True       # no record -> historical
    assert _resolve_donate(False) is False     # explicit wins, no lookup
    save_tune(_record(tune={"batch": 8, "donate": False}))
    assert _resolve_donate(None) is False


# -- bench precedence ---------------------------------------------------


def test_resolve_gpt2_tune_precedence(devices, tune_dir, monkeypatch):
    """defaults < store < BENCH_GPT2_TUNE < explicit tune=."""
    import bench

    monkeypatch.delenv("BENCH_GPT2_TUNE", raising=False)
    monkeypatch.delenv("BENCH_NO_TUNE_STORE", raising=False)
    save_tune(_record(tune={"batch": 8, "hidden": 64}))

    merged, survived = bench._resolve_gpt2_tune(None)
    assert merged["hidden"] == 64 and "hidden" in survived

    monkeypatch.setenv("BENCH_GPT2_TUNE", json.dumps({"hidden": 32}))
    merged, survived = bench._resolve_gpt2_tune(None)
    assert merged["hidden"] == 32 and "hidden" not in survived

    merged, _ = bench._resolve_gpt2_tune({"hidden": 16})
    assert merged["hidden"] == 16

    monkeypatch.setenv("BENCH_NO_TUNE_STORE", "1")
    monkeypatch.delenv("BENCH_GPT2_TUNE")
    merged, survived = bench._resolve_gpt2_tune(None)
    assert merged["hidden"] == 768 and not survived


def test_headline_match_is_canonical(devices, tune_dir, monkeypatch):
    """A tune spelling out the library-default blocks still counts as
    the headline config (canonical comparison, not literal)."""
    import bench
    from rocket_tpu.ops.flash import auto_blocks

    monkeypatch.setenv("BENCH_NO_TUNE_STORE", "1")
    bq, bk = auto_blocks(bench.GPT2_TUNE["seq"])
    assert bench._tune_matches_headline({"block_q": bq, "block_k": bk})
    assert not bench._tune_matches_headline({"batch": 999})
    assert not bench._tune_matches_headline({"unknown_knob": 1})


# -- successive halving (fake probe: deterministic, no subprocesses) ----


def test_successive_halving_seeds_and_halves(devices, tune_dir):
    space = TuneSpace((
        TuneParam("p", tuple({"batch": b} for b in (1, 2, 3, 4))),
    ))
    calls = []

    def fake_probe(tune, steps, warmup, timeout_s):
        calls.append((dict(tune), steps))
        return {"value": 1000.0 * tune["batch"], "mfu": 0.1}

    rec = successive_halving(
        space, base={"seq": 64}, seed_k=4, eta=2, rung_steps=(2, 5),
        probe=fake_probe, save=True, log=lambda s: None,
    )
    # rung 0 probes all 4 seeds at 2 steps, keeps ceil(4/2)=2;
    # rung 1 (last) probes 2 at 5 steps, keeps 1
    assert [s for _, s in calls] == [2, 2, 2, 2, 5, 5]
    assert rec["probes"] == 6
    assert rec["tune"]["batch"] == 4 and rec["value"] == 4000.0
    assert rec["tune"]["seq"] == 64  # base pinned through
    assert len(rec["rungs"]) == 2
    # persisted: best_tune round-trips it
    assert best_tune(model="gpt2")["value"] == 4000.0


def test_successive_halving_drops_dead_points(devices, tune_dir):
    space = TuneSpace((
        TuneParam("p", tuple({"batch": b} for b in (1, 2, 3))),
    ))

    def fake_probe(tune, steps, warmup, timeout_s):
        if tune["batch"] == 3:  # the best-predicted point dies
            return {"value": None, "error": "boom"}
        return {"value": 1000.0 * tune["batch"]}

    rec = successive_halving(
        space, seed_k=3, eta=3, rung_steps=(2,), probe=fake_probe,
        save=False, log=lambda s: None,
    )
    assert rec["tune"]["batch"] == 2


def test_successive_halving_all_dead_raises(devices, tune_dir):
    space = TuneSpace((TuneParam("p", ({"batch": 1},)),))
    with pytest.raises(RuntimeError, match="every probe"):
        successive_halving(
            space, seed_k=1, rung_steps=(2,),
            probe=lambda *a: {"value": None, "error": "x"},
            save=False, log=lambda s: None,
        )


# -- the CPU-proxy acceptance smoke (real subprocess probes) ------------


def test_autotune_cpu_proxy_smoke(devices, tune_dir):
    """Tiny 2-point space through the REAL probe path: fresh
    subprocesses run bench.bench_gpt2 with each point, a record lands in
    the store, and a second autotune() call re-searches NOTHING."""
    space = gpt2_space(tiny=True)
    assert space.size == 2
    rec = autotune(
        model="gpt2", space=space, seed_k=2, rung_steps=(2,),
        warmup=1, probe_timeout_s=240.0, log=lambda s: None,
    )
    assert rec["probes"] == 2
    assert rec["value"] and rec["value"] > 0
    assert rec["tune"]["hidden"] == 64  # the tiny proxy dims
    files = list(tune_dir.glob("*.json"))
    assert len(files) == 1
    on_disk = json.loads(files[0].read_text())
    assert on_disk["value"] == rec["value"]

    again = autotune(model="gpt2", space=space)
    assert again["probes"] == 0 and again.get("reused") is True
    assert again["tune"] == rec["tune"]
