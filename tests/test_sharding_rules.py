"""Rule-based sharding engine tests (rocket_tpu.parallel.sharding).

Covers the PartitionRules regex engine (first-match precedence, anchoring,
scalar replication, unmatched-leaf errors), the manifest round-trip through
persist.integrity, the retired suffix-match heuristic's ambiguity (as a
regression against the structural-mirror engine), model-zoo rule coverage
(regex-derived specs must equal annotation-derived specs leaf-for-leaf),
zero_compose unit semantics, and bit-equality of ``zero_stage=1`` training
against the unsharded optimizer path for Adam and Muon (± EMA).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rocket_tpu.engine import Objective, TrainState, build_train_step
from rocket_tpu.engine.ema import params_ema
from rocket_tpu.engine.muon import muon
from rocket_tpu.parallel.mesh import MeshSpec
from rocket_tpu.parallel.sharding import (
    DEFAULT_PARTITION_RULES,
    DEFAULT_RULES,
    PartitionRules,
    ShardingRules,
    UnmatchedLeafError,
    canonical_path,
    specs_for_state,
    zero_compose,
)
from rocket_tpu.persist import integrity


def _mesh(**axes):
    spec = MeshSpec(**axes)
    n = 1
    for v in axes.values():
        n *= v
    return spec.build(jax.devices()[:n])


# -- rule semantics -----------------------------------------------------------


class TestRuleSemantics:
    def test_first_match_wins(self):
        """An earlier, more specific rule beats a later catch-all."""
        rules = PartitionRules(rules=(
            (r"attn/q/kernel$", ("embed", "heads")),
            (r"kernel$", (None, None)),
        ))
        assert rules.spec_for("block_0/attn/q/kernel", (16, 16)) == \
            P("fsdp", "tensor")
        # the catch-all still handles everything else
        assert rules.spec_for("block_0/mlp/up/kernel", (16, 32)) == P(None, None)

    def test_order_flip_changes_outcome(self):
        """Same rules, reversed order: the catch-all now shadows."""
        rules = PartitionRules(rules=(
            (r"kernel$", (None, None)),
            (r"attn/q/kernel$", ("embed", "heads")),
        ))
        assert rules.spec_for("block_0/attn/q/kernel", (16, 16)) == P(None, None)

    def test_anchoring_head_does_not_match_overhead(self):
        """`(^|/)head/` must not fire inside a longer name."""
        hit = DEFAULT_PARTITION_RULES.match("model/overhead/kernel")
        assert hit is None or "head/" not in hit[0] or "(^|/)head" not in hit[0]
        # the real head still matches at both root and nested positions
        assert DEFAULT_PARTITION_RULES.match("head/kernel") is not None
        assert DEFAULT_PARTITION_RULES.match("decoder/head/kernel") is not None

    def test_scalar_leaf_forced_replicated(self):
        """Scalars and size-1 leaves bypass matching entirely."""
        rules = PartitionRules(rules=((r"scale$", ("embed",)),))
        assert rules.spec_for("temp/scale", ()) == P()
        assert rules.spec_for("temp/scale", (1,)) == P()
        assert rules.spec_for("temp/scale", (8,)) == P("fsdp")

    def test_unmatched_leaf_error_names_exact_path(self):
        tree = {"block_3": {"weird": {"thing": jnp.zeros((4, 4))}}}
        with pytest.raises(UnmatchedLeafError, match=r"block_3/weird/thing"):
            PartitionRules(rules=()).specs_for_tree(tree)

    def test_partitioned_value_suffix_stripped(self):
        """flax nn.Partitioned boxes add a trailing /value path component."""
        assert DEFAULT_PARTITION_RULES.match("b0/attn/q/kernel/value") == \
            DEFAULT_PARTITION_RULES.match("b0/attn/q/kernel")

    def test_trailing_dims_right_aligned(self):
        """A rule names TRAILING dims; leading dims pad None — one rule
        covers the scan-stacked (layers-first) variant of a kernel."""
        rules = PartitionRules(rules=((r"kernel$", ("embed", "mlp")),))
        assert rules.spec_for("mlp/up/kernel", (16, 32)) == P("fsdp", "tensor")
        assert rules.spec_for("blocks/mlp/up/kernel", (4, 16, 32)) == \
            P(None, "fsdp", "tensor")

    def test_rule_longer_than_leaf_rank_raises(self):
        rules = PartitionRules(rules=((r"kernel$", ("embed", "mlp")),))
        with pytest.raises(ValueError):
            rules.spec_for("mlp/up/kernel", (16,))

    def test_none_logical_spec_replicates(self):
        rules = PartitionRules(rules=((r"Conv_0/kernel$", None),))
        assert rules.spec_for("Conv_0/kernel", (3, 3, 8, 16)) == P()

    def test_with_axes_remaps_logical_names(self):
        rules = PartitionRules(rules=((r"kernel$", ("embed", "heads")),))
        remapped = rules.with_axes(DEFAULT_RULES.replace(embed="tensor"))
        assert remapped.spec_for("q/kernel", (8, 8)) == P("tensor", "tensor")
        # original is unchanged (frozen dataclass)
        assert rules.spec_for("q/kernel", (8, 8)) == P("fsdp", "tensor")


# -- manifest round-trip ------------------------------------------------------


class TestManifestRoundTrip:
    def test_partition_rules_survive_manifest_json(self):
        mesh = _mesh(data=2, fsdp=2, tensor=2)
        manifest = integrity.build_manifest(
            {"module_0": {"state": {"w": np.zeros((8, 4), np.float32)}}},
            mesh=mesh, rules=DEFAULT_PARTITION_RULES,
        )
        section = json.loads(json.dumps(manifest))["mesh"]
        # legacy logical-axis table is still stamped in the old format
        legacy = dict((name, axes) for name, axes in section["rules"])
        assert legacy["embed"] == "fsdp"
        # the regex table rides alongside
        rebuilt = PartitionRules.from_manifest(section)
        assert rebuilt.to_table() == DEFAULT_PARTITION_RULES.to_table()
        assert rebuilt.table() == DEFAULT_PARTITION_RULES.table()

    def test_rebuilt_rules_produce_identical_specs(self):
        mesh = _mesh(data=2, fsdp=2, tensor=2)
        manifest = integrity.build_manifest(
            {}, mesh=mesh, rules=DEFAULT_PARTITION_RULES,
        )
        rebuilt = PartitionRules.from_manifest(
            json.loads(json.dumps(manifest))["mesh"]
        )
        tree = {
            "embed": {"embedding": jnp.zeros((64, 16))},
            "block_0": {"attn": {"q": {"kernel": jnp.zeros((16, 16))}}},
            "head": {"kernel": jnp.zeros((16, 64))},
        }
        assert rebuilt.specs_for_tree(tree) == \
            DEFAULT_PARTITION_RULES.specs_for_tree(tree)

    def test_zero_stage_stamp_round_trips(self):
        """Manifests stamp the ZeRO stage the run was sharded at; legacy
        manifests (no kwarg) omit the key entirely so old snapshots keep
        the strict stage-less restore path."""
        mesh = _mesh(data=2, fsdp=2, tensor=2)
        stamped = integrity.build_manifest(
            {}, mesh=mesh, rules=DEFAULT_PARTITION_RULES, zero_stage=3)
        assert json.loads(json.dumps(stamped))["mesh"]["zero_stage"] == 3
        legacy = integrity.build_manifest(
            {}, mesh=mesh, rules=DEFAULT_PARTITION_RULES)
        assert "zero_stage" not in json.loads(json.dumps(legacy))["mesh"]

    def test_check_reshard_accepts_rule_derived_targets(self):
        """check_reshard and the trainer resolve from the same table: a
        target tree shardend via PartitionRules passes the restore gate."""
        mesh = _mesh(data=2, fsdp=2, tensor=2)
        arrays = {"head": {"kernel": np.zeros((16, 64), np.float32)}}
        manifest = integrity.build_manifest(
            {"module_0": {"state": arrays}},
            mesh=mesh, rules=DEFAULT_PARTITION_RULES,
        )
        rebuilt = PartitionRules.from_manifest(manifest["mesh"])
        specs = rebuilt.specs_for_tree(arrays)
        targets = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=NamedSharding(mesh, s)
            ),
            arrays, specs,
        )
        integrity.check_reshard(manifest, {"module_0": {"state": targets}})


# -- suffix-match heuristic regression ----------------------------------------


class TestSuffixRegression:
    def test_container_named_mu_does_not_confuse_mirrors(self):
        """The retired engine/adapter suffix heuristic matched optimizer
        leaves to params by longest path suffix.  A param container
        literally named ``mu`` made Adam's mu-moment of ``proj/kernel``
        (state path ``...mu/proj/kernel``) collide with the *param*
        ``mu/proj/kernel``.  The structural-mirror engine maps positionally
        and must give each moment its own param's spec."""
        mesh = _mesh(data=2, fsdp=2, tensor=2)
        params = {
            "mu": {"proj": {"kernel": jnp.zeros((8, 16))}},
            "proj": {"kernel": jnp.zeros((8, 16))},
        }
        rules = PartitionRules(rules=(
            (r"^mu/proj/kernel$", ("embed", None)),
            (r"^proj/kernel$", (None, "heads")),
        ))
        tx = optax.adam(1e-2)
        abstract = jax.eval_shape(lambda: TrainState.create(params, tx))
        plan = specs_for_state(mesh, abstract, rules=rules)
        mu = plan.state_specs.opt_state[0].mu
        nu = plan.state_specs.opt_state[0].nu
        assert mu == plan.state_specs.params
        assert nu == plan.state_specs.params
        assert mu["mu"]["proj"]["kernel"] == P("fsdp", None)
        assert mu["proj"]["kernel"] == P(None, "tensor")


# -- model-zoo coverage lint --------------------------------------------------


def _zoo_configs():
    from rocket_tpu.models.lenet import LeNet
    from rocket_tpu.models.resnet import resnet18
    from rocket_tpu.models.seq2seq import EncoderDecoder, Seq2SeqConfig
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.models.vit import ViT, ViTConfig

    B = 2
    tok = {"tokens": jnp.zeros((B, 8), jnp.int32)}
    img32 = {"image": jnp.zeros((B, 32, 32, 3), jnp.float32)}
    return {
        "transformer": (TransformerLM(TransformerConfig(
            vocab_size=64, hidden=16, n_layers=2, n_heads=2, ffn_dim=32,
            max_seq=8, use_bias=True, lora_rank=4, tie_embeddings=False,
            positions="learned")), tok),
        "transformer-scan": (TransformerLM(TransformerConfig(
            vocab_size=64, hidden=16, n_layers=2, n_heads=2, ffn_dim=32,
            max_seq=8, scan_layers=True, fused_qkv=True,
            tie_embeddings=True)), tok),
        "transformer-int8": (TransformerLM(TransformerConfig(
            vocab_size=64, hidden=16, n_layers=2, n_heads=2, ffn_dim=32,
            max_seq=8, weights_int8=True, tie_embeddings=True)), tok),
        "moe": (TransformerLM(TransformerConfig(
            vocab_size=64, hidden=16, n_layers=2, n_heads=2, ffn_dim=32,
            max_seq=8, n_experts=4, moe_top_k=2, use_bias=True)), tok),
        "transformer-pipelined": (TransformerLM(TransformerConfig(
            vocab_size=64, hidden=16, n_layers=4, n_heads=2, ffn_dim=32,
            max_seq=8, use_bias=True, tie_embeddings=True,
            pipeline_microbatches=2, pipeline_schedule="interleaved",
            pipeline_chunks=2)), tok),
        "vit": (ViT(ViTConfig.tiny()), img32),
        "resnet": (resnet18(num_classes=10), img32),
        "seq2seq": (EncoderDecoder(Seq2SeqConfig(
            vocab_size=64, hidden=16, n_encoder_layers=1, n_decoder_layers=1,
            n_heads=2, ffn_dim=32, max_seq=8)), {
                "inputs": jnp.zeros((B, 8), jnp.int32),
                "targets": jnp.zeros((B, 8), jnp.int32)}),
        "lenet": (LeNet(), {"image": jnp.zeros((B, 28, 28, 1), jnp.float32)}),
    }


@pytest.mark.parametrize("name", [
    "transformer", "transformer-scan", "transformer-int8", "moe",
    "transformer-pipelined", "vit", "resnet", "seq2seq", "lenet",
])
def test_zoo_default_rules_match_annotations(name):
    """CI lint: every model-zoo config gets a fully-matched spec tree from
    DEFAULT_PARTITION_RULES, identical leaf-for-leaf to the specs derived
    from the model's own nn.with_partitioning annotations."""
    from rocket_tpu.engine.adapter import FlaxModel

    model, batch = _zoo_configs()[name]
    adapter = FlaxModel(model)
    params, mutable = jax.eval_shape(
        lambda: adapter.init_variables(jax.random.PRNGKey(0), batch)
    )
    ann = adapter.partition_specs(params, DEFAULT_RULES)
    reg = DEFAULT_PARTITION_RULES.specs_for_tree(params)  # must not raise

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    is_spec = lambda x: isinstance(x, P)
    ann_leaves = jax.tree_util.tree_leaves(ann, is_leaf=is_spec)
    reg_leaves = jax.tree_util.tree_leaves(reg, is_leaf=is_spec)
    assert len(flat) == len(ann_leaves) == len(reg_leaves)
    mismatches = [
        f"{canonical_path(path)} shape={tuple(leaf.shape)}: "
        f"annotation={sa} rules={sr}"
        for (path, leaf), sa, sr in zip(flat, ann_leaves, reg_leaves)
        # size-1 leaves are forced replicated by the engine; the
        # annotation value is irrelevant for them
        if int(np.prod(leaf.shape)) > 1 and sa != sr
    ]
    assert not mismatches, "\n".join(mismatches)
    # mutable collections (e.g. BatchNorm stats) must also be coverable
    for path, leaf in jax.tree_util.tree_flatten_with_path(mutable)[0]:
        p = canonical_path(path)
        if int(np.prod(leaf.shape)) > 1:
            assert DEFAULT_PARTITION_RULES.match(p) is not None, (
                f"mutable leaf {p} (shape {tuple(leaf.shape)}) unmatched"
            )


def _data_eligible(spec, shape, mesh):
    """Independent recomputation of zero_compose's fold condition: True iff
    the data axis can divide some dim of the leaf given its base spec."""
    shape = tuple(shape)
    if int(np.prod(shape)) <= 1:
        return False
    axes = dict(mesh.shape)
    if axes.get("data", 1) <= 1:
        return False
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for dim, entry in zip(shape, entries):
        names = (
            () if entry is None
            else (entry,) if isinstance(entry, str) else tuple(entry)
        )
        if "data" in names:
            return True
        factor = axes["data"] * int(np.prod([axes[n] for n in names] or [1]))
        if dim % factor == 0:
            return True
    return False


@pytest.mark.parametrize("name", [
    "transformer", "transformer-scan", "transformer-int8", "moe",
    "transformer-pipelined", "vit", "resnet", "seq2seq", "lenet",
])
def test_zoo_rules_resolve_zero_stage_2_and_3_leaves(name):
    """CI lint for ZeRO stages 2/3: every zoo config's rule-derived table
    must produce a plan whose grad-accum (stage 2) and param-storage
    (stage 3) trees equal the leafwise zero_compose of the base specs —
    and every leaf the data axis *can* divide must actually carry it.  No
    silent fall-through to replicated.  (AdamW has no matrix-update
    exemptions, so nothing is legitimately left at base here except
    genuinely indivisible leaves.)"""
    model, batch = _zoo_configs()[name]
    mesh = _mesh(data=2, fsdp=2, tensor=2)
    from rocket_tpu.engine.adapter import FlaxModel

    adapter = FlaxModel(model)
    params, _ = jax.eval_shape(
        lambda: adapter.init_variables(jax.random.PRNGKey(0), batch)
    )
    pspecs = DEFAULT_PARTITION_RULES.specs_for_tree(params)
    abstract = jax.eval_shape(lambda: TrainState.create(
        params, optax.adamw(1e-3), gradient_accumulation_steps=2))

    is_spec = lambda x: isinstance(x, P)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    base_leaves = [
        P() if s is None else s
        for s in jax.tree_util.tree_leaves(pspecs, is_leaf=is_spec)
    ]
    expected = [
        zero_compose(s, tuple(leaf.shape), mesh)
        for (_, leaf), s in zip(flat, base_leaves)
    ]

    plan2 = specs_for_state(
        mesh, abstract, param_specs=pspecs, zero_stage=2,
        make_shardings=False)
    plan3 = specs_for_state(
        mesh, abstract, param_specs=pspecs, zero_stage=3,
        make_shardings=False)

    got_accum = jax.tree_util.tree_leaves(
        plan2.state_specs.grad_accum, is_leaf=is_spec)
    got_params = jax.tree_util.tree_leaves(
        plan3.state_specs.params, is_leaf=is_spec)
    assert len(got_accum) == len(got_params) == len(expected)

    mismatches = []
    for (path, leaf), base, want, ga, p3 in zip(
            flat, base_leaves, expected, got_accum, got_params):
        where = f"{canonical_path(path)} shape={tuple(leaf.shape)}"
        if ga != want:
            mismatches.append(f"{where}: stage-2 grad_accum {ga} != {want}")
        if p3 != want:
            mismatches.append(f"{where}: stage-3 params {p3} != {want}")
        # eligibility cross-check: a divisible leaf must gain the axis
        eligible = _data_eligible(base, leaf.shape, mesh)
        gained = any(
            "data" in ((e,) if isinstance(e, str) else tuple(e or ()))
            for e in want
        )
        if eligible != gained:
            mismatches.append(
                f"{where}: base={base} composed={want} "
                f"eligible={eligible} but gained={gained}"
            )
    assert not mismatches, "\n".join(mismatches)
    # stage 2 leaves the forward/backward param domain untouched
    assert jax.tree_util.tree_leaves(
        plan2.state_specs.params, is_leaf=is_spec) == base_leaves


# -- zero_compose -------------------------------------------------------------


class TestZeroCompose:
    def test_folds_data_into_first_divisible_dim(self):
        mesh = _mesh(data=4, tensor=2)
        assert zero_compose(P(None, "tensor"), (64, 128), mesh) == \
            P(("data",), "tensor")

    def test_composes_with_existing_axis_on_same_dim(self):
        mesh = _mesh(data=4, tensor=2)
        # dim 0 carries tensor(2); folding data(4) needs 8 | 64 — ok
        assert zero_compose(P("tensor", None), (64, 128), mesh) == \
            P(("tensor", "data"), None)

    def test_skips_to_next_dim_when_first_indivisible(self):
        mesh = _mesh(data=4, tensor=2)
        assert zero_compose(P(), (6, 64), mesh) == P(None, ("data",))

    def test_scalar_and_size1_pass_through(self):
        mesh = _mesh(data=4)
        assert zero_compose(P(), (), mesh) == P()
        assert zero_compose(P(), (1,), mesh) == P()

    def test_already_data_sharded_unchanged(self):
        mesh = _mesh(data=4)
        assert zero_compose(P("data"), (64,), mesh) == P("data")

    def test_no_divisible_dim_stays_base(self):
        mesh = _mesh(data=4)
        assert zero_compose(P(), (6, 10), mesh) == P(None, None)

    def test_data_axis_size_one_is_noop(self):
        mesh = _mesh(data=1, tensor=2)
        assert zero_compose(P(None, "tensor"), (64, 128), mesh) == \
            P(None, "tensor")


# -- specs_for_state plan shape -----------------------------------------------


class TestSpecsForState:
    def _state(self, tx, accum=1):
        params = {
            "w1": jnp.zeros((64, 128)),
            "w2": jnp.zeros((128, 64)),
            "b": jnp.zeros((64,)),
        }
        return jax.eval_shape(lambda: TrainState.create(
            params, tx, gradient_accumulation_steps=accum))

    _pspecs = {"w1": P(None, "tensor"), "w2": P("tensor", None), "b": P()}

    def test_zero_stage0_mirrors_param_specs(self):
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(optax.adam(1e-2)), param_specs=self._pspecs)
        assert plan.state_specs.opt_state[0].mu == plan.state_specs.params
        assert plan.state_specs.step == P()
        assert plan.zero_param_shardings == plan.param_shardings

    def test_zero_stage1_repartitions_adam_moments(self):
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(optax.adam(1e-2)),
            param_specs=self._pspecs, zero_stage=1)
        mu = plan.state_specs.opt_state[0].mu
        assert mu["w1"] == P(("data",), "tensor")
        assert mu["w2"] == P(("tensor", "data"), None)
        assert mu["b"] == P(("data",))
        # params themselves stay at base for forward/backward
        assert plan.state_specs.params == self._pspecs

    def test_zero_stage1_grad_accum_stays_base(self):
        """Accumulation buffers add elementwise-exactly at base sharding;
        they are NOT zero-composed (only optimizer mirrors are)."""
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(optax.adam(1e-2), accum=2),
            param_specs=self._pspecs, zero_stage=1)
        assert plan.state_specs.grad_accum == self._pspecs
        assert plan.state_specs.micro == P()

    def test_zero_stage2_grad_accum_zero_composed(self):
        """Stage 2 moves the accumulation buffers into the zero domain —
        gradients reduce-scatter straight into the shard owner."""
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(optax.adam(1e-2), accum=2),
            param_specs=self._pspecs, zero_stage=2)
        ga = plan.state_specs.grad_accum
        assert ga["w1"] == P(("data",), "tensor")
        assert ga["w2"] == P(("tensor", "data"), None)
        assert ga["b"] == P(("data",))
        # forward/backward domain is untouched at stage 2
        assert plan.state_specs.params == self._pspecs

    def test_zero_stage3_params_storage_zero_composed(self):
        """Stage 3 stores the params themselves on the zero shard; the
        compute specs keep the base layout (the step gathers on demand)."""
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(optax.adam(1e-2)),
            param_specs=self._pspecs, zero_stage=3)
        stored = plan.state_specs.params
        assert stored["w1"] == P(("data",), "tensor")
        assert stored["w2"] == P(("tensor", "data"), None)
        assert stored["b"] == P(("data",))
        assert plan.param_specs == self._pspecs
        # optimizer mirrors live in the same domain as the storage
        assert plan.state_specs.opt_state[0].mu == stored

    def test_zero_stage3_muon_rank2_params_stay_base(self):
        """Muon's matrix-update exemption extends to the storage domain:
        rank-2 params are never data-sliced, only the rank-1 bias is."""
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(muon(1e-2)),
            param_specs=self._pspecs, zero_stage=3)
        stored = plan.state_specs.params
        assert stored["w1"] == P(None, "tensor")
        assert stored["w2"] == P("tensor", None)
        assert stored["b"] == P(("data",))

    def test_invalid_zero_stage_rejected(self):
        mesh = _mesh(data=4, tensor=2)
        with pytest.raises(ValueError, match="zero_stage"):
            specs_for_state(
                mesh, self._state(optax.adam(1e-2)),
                param_specs=self._pspecs, zero_stage=4)

    def test_make_shardings_false_prices_hypothetical_mesh(self):
        """Spec arithmetic must run against a mesh this host doesn't have
        (bench.py's 30B memory-plan rows): any object with a ``.shape``
        mapping works when NamedSharding construction is skipped."""
        class PodMesh:
            shape = {"data": 64, "tensor": 1}

        plan = specs_for_state(
            PodMesh(), self._state(optax.adam(1e-2)),
            param_specs=self._pspecs, zero_stage=3, make_shardings=False)
        assert plan.param_shardings is None
        assert plan.zero_param_shardings is None
        assert plan.state_shardings is None
        assert plan.state_specs.params["w1"] == P(("data",), "tensor")
        assert plan.state_specs.opt_state[0].mu["b"] == P(("data",))

    def test_muon_rank2_exempt_from_zero(self):
        """Newton-Schulz orthogonalization reduces over the full matrix:
        rank-2 params (and their momenta) must keep base sharding."""
        mesh = _mesh(data=4, tensor=2)
        plan = specs_for_state(
            mesh, self._state(muon(1e-2)),
            param_specs=self._pspecs, zero_stage=1)
        leaves = {
            canonical_path(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(
                plan.state_specs.opt_state,
                is_leaf=lambda x: isinstance(x, P))[0]
        }
        momenta = {k: v for k, v in leaves.items() if "momentum" in k}
        assert any(v == P(None, "tensor") for v in momenta.values())
        assert any(v == P("tensor", None) for v in momenta.values())
        # the rank-1 bias momentum is still zero-composed
        assert any(v == P(("data",)) for v in momenta.values())


# -- zero_stage=1 bit-equality ------------------------------------------------


def _bit_eq_setup():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    params = {
        "w1": jax.random.normal(k1, (64, 128), jnp.float32),
        "w2": jax.random.normal(k2, (128, 64), jnp.float32) * 0.1,
        "b": jnp.zeros((64,), jnp.float32),
    }
    pspecs = {"w1": P(None, "tensor"), "w2": P("tensor", None), "b": P()}

    def apply_fn(p, mutable, rng, batch, train):
        out = dict(batch)
        h = jnp.tanh(batch["x"] @ p["w1"])
        out["pred"] = h @ p["w2"] + p["b"]
        return out, mutable

    def loss(batch):
        return jnp.mean((batch["pred"] - batch["y"]) ** 2)

    return params, pspecs, apply_fn, loss


def _run_zero(tx, zero_stage, steps_n=6, accum=1):
    """Train `steps_n` steps on a data=4 × tensor=2 mesh through the repo's
    own machinery (specs_for_state + build_train_step).  ``accum > 1``
    drives the micro/sync cadence (``steps_n`` counts micro batches)."""
    mesh = _mesh(data=4, tensor=2)
    params, pspecs, apply_fn, loss = _bit_eq_setup()
    abstract = jax.eval_shape(lambda: TrainState.create(
        params, tx, gradient_accumulation_steps=accum))
    plan = specs_for_state(
        mesh, abstract, param_specs=pspecs, zero_stage=zero_stage)
    state = TrainState.create(params, tx, gradient_accumulation_steps=accum)
    state = jax.device_put(state, plan.state_shardings)
    step_fns = build_train_step(
        apply_fn, [Objective("mse", loss)], tx,
        gradient_accumulation_steps=accum,
        shard_plan=plan if zero_stage else None,
    )
    batch_sh = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(0)
    losses = []
    for i in range(steps_n):
        batch = {
            "x": jax.device_put(
                jnp.asarray(rng.normal(size=(8, 64)), jnp.float32), batch_sh),
            "y": jax.device_put(
                jnp.asarray(rng.normal(size=(8, 64)), jnp.float32), batch_sh),
        }
        fn = step_fns["sync"] if (i + 1) % accum == 0 else step_fns["micro"]
        state, logs = fn(state, batch)
        losses.append(float(logs["loss"]))
    return losses, jax.device_get(state.params), jax.device_get(state.opt_state)


def _tx_variants():
    return {
        "adam": optax.adamw(1e-2),
        "muon": muon(1e-2),
        "adam+ema": optax.chain(optax.adamw(1e-2), params_ema(0.99)),
        "muon+ema": optax.chain(muon(1e-2), params_ema(0.99)),
    }


_ORACLES = {}


def _oracle(variant, accum=1):
    """Memoized unsharded (zero_stage=0) trajectory per optimizer variant —
    the oracle every sharded stage is compared against bitwise."""
    key = (variant, accum)
    if key not in _ORACLES:
        _ORACLES[key] = _run_zero(
            _tx_variants()[variant], zero_stage=0, accum=accum)
    return _ORACLES[key]


def _assert_bit_equal(ref, got):
    l0, p0, o0 = ref
    l1, p1, o1 = got
    assert l0 == l1
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree_util.tree_leaves(o0),
                    jax.tree_util.tree_leaves(o1)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("variant", ["adam", "muon", "adam+ema", "muon+ema"])
def test_zero_stage1_bitwise_equals_unsharded(variant):
    """ZeRO-1 must not change the training trajectory AT ALL: per-step
    losses, final params, and final optimizer state are compared bitwise
    against the unsharded optimizer path on the same mesh."""
    _assert_bit_equal(
        _oracle(variant),
        _run_zero(_tx_variants()[variant], zero_stage=1),
    )


@pytest.mark.parametrize("stage", [2, 3])
@pytest.mark.parametrize("variant", ["adam", "muon", "adam+ema", "muon+ema"])
def test_zero_stage23_bitwise_equals_unsharded(stage, variant):
    """Stages 2 (grads reduce-scattered into the shard owner) and 3
    (params stored sharded, gathered on demand) are pure layout moves:
    the trajectory must stay bitwise identical to the unsharded path."""
    _assert_bit_equal(
        _oracle(variant),
        _run_zero(_tx_variants()[variant], zero_stage=stage),
    )


@pytest.mark.parametrize("stage", [2, 3])
@pytest.mark.parametrize("variant", ["adam", "muon"])
def test_zero_stage23_bitwise_with_grad_accum(stage, variant):
    """Gradient accumulation under stages 2/3: micro-sums happen on the
    zero shard (elementwise, exact) — still bitwise vs the unsharded
    accumulating oracle."""
    _assert_bit_equal(
        _oracle(variant, accum=2),
        _run_zero(_tx_variants()[variant], zero_stage=stage, accum=2),
    )
