"""ZeRO stages 2/3 + host-offloaded optimizer state (ISSUE 20).

Covers the Module-level integration of the extended sharding engine:
the typed ``ZeroIncompatibleError`` matrix (each genuinely incompatible
combination names its remedy), ``memory_plan()``'s host-tier accounting
under ``zero_offload``, the :class:`~rocket_tpu.engine.offload
.ZeroOffloader` round trip (bitwise exact, overlap-armed vs serialized,
``offload_wait`` goodput booking), bit-equality of an offloaded run
against the same run without offload, and the zero-new-jit-traces
contract of the offload path (``jax.device_get``/``device_put`` are not
jit sites).

Spec-level stage-2/3 coverage (zero_compose trees, zoo lint, oracle
bit-equality) lives in tests/test_sharding_rules.py; elastic restore
across stage transitions in tests/test_elastic.py.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import rocket_tpu as rt
from rocket_tpu.engine.offload import ZeroOffloader
from rocket_tpu.engine.state import TrainState, memory_plan
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.observe.ledger import GoodputLedger, get_goodput
from rocket_tpu.parallel.mesh import MeshSpec
from rocket_tpu.parallel.sharding import (
    ZERO_STAGES,
    ZeroIncompatibleError,
    specs_for_state,
)

from test_pipeline import MLP, synthetic_classification


def _module(runtime, fuse=False):
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
        fuse_accumulation=fuse,
    )
    model.bind(runtime)
    model.setup()
    return model


def _run_steps(runtime, steps_n=6, batch_size=64):
    """Drive a Module directly for ``steps_n`` sync steps; returns the
    model and the per-step loss list."""
    data = synthetic_classification(n=256)
    model = _module(runtime)
    losses = []
    for i in range(steps_n):
        lo = (i * batch_size) % 256
        batch = {
            "x": jnp.asarray(data["x"][lo:lo + batch_size]),
            "label": jnp.asarray(data["label"][lo:lo + batch_size]),
        }
        attrs = rt.Attributes(
            batch=batch,
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
        )
        model.launch(attrs)
        losses.append(float(attrs.step_logs["loss"]))
    return model, losses


# -- typed incompatibility matrix --------------------------------------------


class TestIncompatibilityMatrix:
    """Satellite 1: every refused combination raises ONE typed error
    carrying the feature, the stage, and the remedy — asserted on the
    error's fields, not a bare message match."""

    def test_runtime_accepts_all_stages(self, devices):
        for stage in ZERO_STAGES:
            runtime = rt.Runtime(
                mesh=MeshSpec(data=8).build(devices), zero_stage=stage
            )
            assert runtime.zero_stage == stage

    def test_runtime_rejects_unknown_stage(self, devices):
        with pytest.raises(ValueError, match="zero_stage"):
            rt.Runtime(mesh=MeshSpec(data=8).build(devices), zero_stage=4)

    def test_offload_requires_sharded_opt_state(self, devices):
        with pytest.raises(ZeroIncompatibleError) as exc_info:
            rt.Runtime(
                mesh=MeshSpec(data=8).build(devices), zero_offload=True
            )
        err = exc_info.value
        assert err.feature == "zero_offload"
        assert err.zero_stage == 0
        assert "zero_stage >= 1" in err.remedy
        assert "Remedy" in str(err)

    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_fuse_accumulation_refused_per_stage(self, devices, stage):
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8).build(devices),
            gradient_accumulation_steps=2,
            zero_stage=stage,
        )
        model = _module(runtime, fuse=True)
        data = synthetic_classification(n=64)
        batch = {
            "x": jnp.asarray(data["x"]),
            "label": jnp.asarray(data["label"]),
        }
        with pytest.raises(ZeroIncompatibleError) as exc_info:
            model.materialize(batch)
        err = exc_info.value
        assert err.feature == "fuse_accumulation"
        assert err.zero_stage == stage
        assert "micro/sync" in err.remedy

    def test_fuse_accumulation_fine_at_stage0(self, devices):
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8).build(devices),
            gradient_accumulation_steps=2,
        )
        model = _module(runtime, fuse=True)
        data = synthetic_classification(n=64)
        model.materialize({
            "x": jnp.asarray(data["x"]),
            "label": jnp.asarray(data["label"]),
        })
        assert "window" in model._steps

    def test_error_is_a_value_error(self):
        # callers that guarded the old bare ValueError keep working
        assert issubclass(ZeroIncompatibleError, ValueError)


# -- memory accounting --------------------------------------------------------


class TestOffloadMemoryPlan:
    def _plan(self, devices, zero_stage):
        mesh = MeshSpec(data=8).build(devices)
        params = {"w": jnp.zeros((64, 128)), "b": jnp.zeros((64,))}
        tx = optax.adamw(1e-2)
        abstract = jax.eval_shape(lambda: TrainState.create(params, tx))
        pspecs = {"w": P(), "b": P()}
        plan = specs_for_state(
            mesh, abstract, param_specs=pspecs, zero_stage=zero_stage)
        return abstract, plan, mesh

    def test_offload_moves_opt_bytes_to_host_tier(self, devices):
        abstract, plan, mesh = self._plan(devices, zero_stage=1)
        on_dev = memory_plan(abstract, plan.state_specs, mesh)
        off = memory_plan(
            abstract, plan.state_specs, mesh, zero_offload=True)
        assert on_dev["opt_bytes"] > 0
        assert on_dev["host_opt_bytes"] == 0
        assert off["opt_bytes"] == 0
        assert off["host_opt_bytes"] == on_dev["opt_bytes"]
        assert off["total_bytes"] == (
            on_dev["total_bytes"] - on_dev["opt_bytes"]
        )
        assert off["param_bytes"] == on_dev["param_bytes"]

    def test_module_memory_plan_reflects_runtime_offload(self, devices):
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8).build(devices),
            zero_stage=1, zero_offload=True,
        )
        model, _ = _run_steps(runtime, steps_n=1)
        mem = model.memory_plan()
        assert mem["opt_bytes"] == 0
        assert mem["host_opt_bytes"] > 0
        model.destroy()


# -- the offloader ------------------------------------------------------------


class TestZeroOffloader:
    def _tree(self, devices, n=1024):
        mesh = MeshSpec(data=8).build(devices)
        sh = NamedSharding(mesh, P())
        key = jax.random.PRNGKey(3)
        tree = {
            "mu": jax.device_put(
                jax.random.normal(key, (n,), jnp.float32), sh),
            "nu": jax.device_put(
                jax.random.uniform(key, (n,), jnp.float32), sh),
        }
        shardings = {"mu": sh, "nu": sh}
        return tree, shardings

    def test_goodput_ledger_has_offload_wait_bucket(self):
        assert "offload_wait" in GoodputLedger.BUCKETS
        assert "offload_wait" in GoodputLedger.NESTED

    def test_fetch_without_stash_returns_fallback(self, devices):
        tree, shardings = self._tree(devices)
        off = ZeroOffloader(shardings)
        try:
            assert off.fetch(tree) is tree
            assert off.rounds == 0
        finally:
            off.close()

    @pytest.mark.parametrize("synchronous", [False, True])
    def test_round_trip_is_bitwise_exact(self, devices, synchronous):
        tree, shardings = self._tree(devices)
        off = ZeroOffloader(shardings, synchronous=synchronous)
        try:
            off.stash(tree)
            out = off.fetch(None)
            assert out is not None and out is not tree
            for a, b in zip(jax.tree_util.tree_leaves(tree),
                            jax.tree_util.tree_leaves(out)):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b))
            assert out["mu"].sharding == shardings["mu"]
            assert off.rounds == 1
        finally:
            off.close()

    def test_double_stash_refused(self, devices):
        tree, shardings = self._tree(devices)
        off = ZeroOffloader(shardings)
        try:
            off.stash(tree)
            with pytest.raises(RuntimeError, match="in flight"):
                off.stash(tree)
        finally:
            off.close()

    def test_wait_booked_into_offload_wait_bucket(self, devices):
        tree, shardings = self._tree(devices)
        goodput = get_goodput()
        goodput.start_run()
        try:
            off = ZeroOffloader(shardings, synchronous=True)
            off.stash(tree)
            off.fetch(None)
            off.close()
            assert goodput._buckets["offload_wait"] > 0.0
        finally:
            goodput.end_run()
            goodput.armed = False

    def test_armed_prefetch_overlaps_compute(self, devices):
        """THE overlap acceptance: with compute (here a sleep — the
        worker thread needs no GIL cooperation from jitted code) between
        stash and fetch, the armed fetch's wait is a fraction of the
        serialized round trip, and the armed 'step wall' beats the
        synchronous-offload one."""
        tree, shardings = self._tree(devices, n=4 << 20)  # 2 x 16 MB
        sync = ZeroOffloader(shardings, synchronous=True)
        compute_s = 0.25
        t0 = time.perf_counter()
        sync.stash(tree)
        time.sleep(compute_s)
        sync.fetch(None)
        sync_wall = time.perf_counter() - t0
        sync_wait = sync.total_wait
        sync.close()
        assert sync_wait > 0.0

        armed = ZeroOffloader(shardings)
        try:
            t0 = time.perf_counter()
            armed.stash(tree)
            time.sleep(compute_s)
            armed.fetch(None)
            armed_wall = time.perf_counter() - t0
            assert armed_wall < sync_wall, (
                f"armed step wall {armed_wall:.3f}s should beat the "
                f"serialized offload wall {sync_wall:.3f}s"
            )
            assert armed.total_wait < max(sync_wait / 2, 0.01), (
                f"armed wait {armed.total_wait:.4f}s vs serialized round "
                f"trip {sync_wait:.4f}s — prefetch failed to hide"
            )
        finally:
            armed.close()


# -- module integration -------------------------------------------------------


class TestModuleOffload:
    @pytest.mark.parametrize("stage", [1, 3])
    def test_offload_bitwise_equals_no_offload(self, devices, stage):
        """The host round trip is a pure memcpy pair: training with
        zero_offload must match the same sharded run without it bit for
        bit (losses, params, opt state)."""
        runtime = rt.Runtime(
            mesh=MeshSpec(data=8).build(devices), zero_stage=stage)
        model_a, losses_a = _run_steps(runtime, steps_n=6)
        runtime_b = rt.Runtime(
            mesh=MeshSpec(data=8).build(devices),
            zero_stage=stage, zero_offload=True,
        )
        model_b, losses_b = _run_steps(runtime_b, steps_n=6)
        assert losses_a == losses_b
        for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(model_a.state.params)),
            jax.tree_util.tree_leaves(jax.device_get(model_b.state.params)),
        ):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(
                jax.device_get(model_a.state.opt_state)),
            jax.tree_util.tree_leaves(
                jax.device_get(model_b.state.opt_state)),
        ):
            np.testing.assert_array_equal(a, b)
        # the offloader actually ran round trips (one per joined boundary)
        assert model_b._offloader is not None
        assert model_b._offloader.rounds >= 4
        model_a.destroy()
        model_b.destroy()
        assert model_b._offloader is None

    def test_offload_zero_new_traces_per_step(self, devices):
        """device_get/device_put are not jit sites: after the 2-step
        warmup (first output normalizes shardings) the sync step's trace
        count must not grow, offload armed or not."""
        def trace_counts(zero_offload):
            runtime = rt.Runtime(
                mesh=MeshSpec(data=8).build(devices),
                zero_stage=2, zero_offload=zero_offload,
            )
            model, _ = _run_steps(runtime, steps_n=2)
            warm = model._steps["sync"]._cache_size()
            model_steps = model
            data = synthetic_classification(n=256)
            for i in range(5):
                lo = (i * 64) % 256
                attrs = rt.Attributes(
                    batch={
                        "x": jnp.asarray(data["x"][lo:lo + 64]),
                        "label": jnp.asarray(data["label"][lo:lo + 64]),
                    },
                    looper=rt.Attributes(
                        grad_enabled=True, state=rt.Attributes()),
                )
                model_steps.launch(attrs)
            final = model._steps["sync"]._cache_size()
            model.destroy()
            return warm, final

        base_warm, base_final = trace_counts(zero_offload=False)
        off_warm, off_final = trace_counts(zero_offload=True)
        assert off_final == off_warm, "offload retraces per step"
        # The prefetch's H2D re-pin lands opt state back on the PLAN's
        # shardings every step, so the offloaded loop can only ever see
        # fewer signatures than the baseline (whose first output pays
        # one XLA sharding-normalization retrace) — never more.
        assert off_final <= base_final, (
            f"offload traced {off_final}x vs baseline {base_final}x"
        )
