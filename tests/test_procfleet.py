"""Process-backed fleet tests — wire protocol, worker subprocess,
kill -9 salvage, and goodput-driven autoscaling.

Three layers:

- units (no subprocess): the shared framing transport, request packing
  with deadline re-anchoring, the shared prefix-hash index's
  route-by-pages walk, and the autoscaler's decision logic against a
  fake metrics feed (breach streaks, cooldowns, floors/ceilings);
- one-worker smoke (tier-1, heavy tail): a real ``python -m
  rocket_tpu.serve.worker`` subprocess serving bit-identical to the
  in-process oracle — the exactly-once + bit-equality contract crossing
  the process boundary;
- chaos + elasticity (``slow``): SIGKILL mid-burst through the router
  (exactly one typed result per request, salvaged included, respawned
  worker serves bit-correct), autoscaler spawning/draining real worker
  processes with decisions visible on the export surface, and a
  respawn that elastic-restores from a snapshot root.
"""

import time

import numpy as np
import pytest

from rocket_tpu.observe import export
from rocket_tpu.serve import (
    Autoscaler,
    Completed,
    FleetRouter,
    ProcReplica,
    Request,
    SharedPrefixIndex,
    SLOPolicy,
    WorkerSpec,
    page_hashes,
    register_fleet_source,
    successive_halving_capacity,
)
from rocket_tpu.serve import wire
from rocket_tpu.testing import workers as tw
from rocket_tpu.testing.chaos import ProcessKillInjector
from rocket_tpu.utils.framing import (
    FramedSocket,
    FrameListener,
    parse_address,
)

pytestmark = pytest.mark.procfleet

BUILDER = "rocket_tpu.testing.workers:build_tiny_loop"
SPAWN_S = 240.0     # worker spawn includes a jax import + model init


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, tw.VOCAB, size=(16, tw.P)).astype(np.int32)


@pytest.fixture(scope="module")
def oracle(prompts):
    """rid-index -> expected tokens, from the single-loop oracle."""
    from rocket_tpu.models.generate import speculative_generate_batched

    model, draft, params, dparams = tw.tiny_models()

    def _expect(i):
        toks = speculative_generate_batched(
            model, params, draft, dparams, prompts[i][None, :],
            max_new_tokens=tw.TOTAL - tw.P, n_draft=tw.NDRAFT,
        )
        return np.asarray(toks[0])

    return _expect


@pytest.fixture(autouse=True)
def _clean_export_sources():
    yield
    export.unregister_source("autoscaler")
    export.unregister_source("serve_fleet")


def _await_corpse(rep, timeout=10.0):
    """SIGKILL delivery is asynchronous — wait for the pid to reap."""
    deadline = time.monotonic() + timeout
    while rep.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.proc.poll() is not None, "worker survived SIGKILL"


def _assert_exactly_once(results, rids):
    got = sorted(r.rid for r in results)
    assert got == sorted(rids), (got, sorted(rids))


def _pump_until_done(rep_or_router, want, max_rounds=400):
    out = []
    for _ in range(max_rounds):
        busy = rep_or_router.pump()
        out.extend(rep_or_router.drain_results())
        if len(out) >= want and not busy:
            return out
    raise AssertionError(f"only {len(out)}/{want} results after "
                         f"{max_rounds} rounds")


# -- units: framing ----------------------------------------------------------


def test_framing_roundtrip_and_peer_close():
    listener = FrameListener(0)
    client = FramedSocket.connect("127.0.0.1", listener.port)
    server = listener.accept(timeout=10.0)
    listener.close()
    try:
        client.send_obj({"a": np.arange(5), "b": "x"})
        msg = server.recv_obj(10.0)
        assert msg["b"] == "x" and np.array_equal(msg["a"], np.arange(5))
        # a frame bigger than one recv() chunk crosses intact
        blob = np.random.default_rng(0).bytes(1 << 20)
        server.send_bytes(blob)
        assert client.recv_bytes(10.0) == blob
        server.close()
        with pytest.raises(ConnectionError):
            client.recv_bytes(5.0)
    finally:
        client.close()
        server.close()


def test_parse_address():
    assert parse_address("127.0.0.1:8432") == ("127.0.0.1", 8432)
    with pytest.raises(ValueError):
        parse_address("no-port")


# -- units: wire protocol ----------------------------------------------------


def test_request_packing_reanchors_deadline():
    req = Request(rid="w0", prompt=np.arange(8, dtype=np.int32),
                  deadline=105.0, max_new_tokens=4, session="s1")
    packed = wire.pack_request(req, clock=lambda: 100.0)
    assert packed["remaining"] == pytest.approx(5.0)
    # the receiving process has a completely different clock origin
    got = wire.unpack_request(packed, clock=lambda: 7000.0)
    assert got.rid == "w0" and got.session == "s1"
    assert got.deadline == pytest.approx(7005.0)
    assert got.max_new_tokens == 4
    assert np.array_equal(got.prompt, req.prompt)
    assert getattr(got, "_handoff", None) is None


def test_request_packing_carries_handoff_host_form():
    class FakeHandoff:
        def to_host(self):
            return {"pages": np.ones((2, 4), np.float32)}

    req = Request(rid="w1", prompt=np.arange(8, dtype=np.int32))
    req._handoff = FakeHandoff()
    packed = wire.pack_request(req, clock=lambda: 0.0)
    assert packed["remaining"] is None
    assert isinstance(packed["handoff"], dict)
    got = wire.unpack_request(packed, clock=lambda: 0.0)
    assert np.array_equal(got._handoff["pages"],
                          np.ones((2, 4), np.float32))


def test_workerspec_resolve_rejects_bad_refs():
    with pytest.raises(ValueError):
        WorkerSpec(builder="not.a.module.function").resolve()
    with pytest.raises(ValueError):
        WorkerSpec(builder="os:no_such_function").resolve()
    fn = WorkerSpec(builder=BUILDER).resolve()
    assert callable(fn)


# -- units: shared prefix-hash index -----------------------------------------


def test_shared_prefix_index_routes_longest_chain():
    idx = SharedPrefixIndex(page_tokens=4)
    toks = np.arange(17, dtype=np.int32)
    chain = page_hashes(toks, 4, limit=toks.shape[0] - 1)
    assert len(chain) == 4
    idx.note("a", chain[:2])        # holds pages 0-1
    idx.note("b", chain)            # holds the whole chain
    assert idx.best_replica(toks) == "b"
    # a replica with a HOLE in the chain is unreachable past it
    idx2 = SharedPrefixIndex(page_tokens=4)
    idx2.note("c", [chain[0], chain[2]])
    idx2.note("d", chain[:1])
    assert idx2.best_replica(toks) in ("c", "d")  # both hold page 0 only
    # total miss
    assert SharedPrefixIndex(page_tokens=4).best_replica(toks) is None
    # invalidation drops every claim at once
    dropped = idx.invalidate("b")
    assert dropped == 4
    assert idx.best_replica(toks) == "a"
    snap = idx.snapshot()
    assert snap["invalidations"] == 1.0 and snap["queries"] >= 2.0


def test_shared_prefix_index_tiebreak_deterministic():
    idx = SharedPrefixIndex(page_tokens=4)
    toks = np.arange(9, dtype=np.int32)
    chain = page_hashes(toks, 4, limit=8)
    idx.note("z", chain)
    idx.note("a", chain)
    assert idx.best_replica(toks) == "a"   # sorted-id tie-break


# -- units: autoscaler decision logic ----------------------------------------


class _FakeReplica:
    def __init__(self, rid, load=0):
        self.replica_id = rid
        self.load = load
        self._dead = None
        self.threaded = False

    def start(self, idle_s=0.001):
        pass

    def drain(self):
        pass


class _FakeRouter:
    def __init__(self, n=1):
        self.replicas = [_FakeReplica(f"r{i}") for i in range(n)]
        self._retiring = []
        self.added = []
        self.removed = []

    def add_replica(self, rep, *, start=None):
        self.replicas.append(rep)
        self.added.append(rep.replica_id)

    def remove_replica(self, rid):
        (rep,) = [r for r in self.replicas if r.replica_id == rid]
        self.replicas.remove(rep)
        self.removed.append(rid)
        return rep


def _scaler(router, metrics, policy, t):
    return Autoscaler(
        router, lambda rid: _FakeReplica(rid), policy,
        collect_fn=lambda: dict(metrics), clock=lambda: t[0])


def test_autoscaler_scales_up_on_ttft_breach_after_streak():
    router = _FakeRouter(1)
    metrics = {"serve_fleet/ttft_ms/p95": 900.0, "serve_fleet/load": 10.0,
               "serve_fleet/submitted": 0.0,
               "serve_fleet/shed_saturated": 0.0}
    t = [0.0]
    auto = _scaler(router, metrics, SLOPolicy(
        ttft_p95_ms=500.0, breach_rounds=2, max_replicas=3,
        scale_up_cooldown_s=0.0), t)
    assert auto.step() == 0          # first breach: streak building
    assert auto.step() == 1          # second consecutive breach: spawn
    assert router.added == ["scale-1"]
    assert auto.counters.scale_ups == 1
    assert auto.counters.breach_ttft == 2
    # ceiling holds even under a continuing breach
    auto.policy.max_replicas = 2
    assert auto.step() == 0 and auto.step() == 0
    assert auto.counters.held_ceiling >= 1


def test_autoscaler_shed_rate_is_windowed_not_cumulative():
    router = _FakeRouter(1)
    metrics = {"serve_fleet/ttft_ms/p95": 0.0, "serve_fleet/load": 10.0,
               "serve_fleet/submitted": 1000.0,
               "serve_fleet/shed_saturated": 100.0}
    t = [0.0]
    auto = _scaler(router, metrics, SLOPolicy(
        ttft_p95_ms=1e9, max_shed_rate=0.05, breach_rounds=1,
        scale_up_cooldown_s=0.0), t)
    # first poll only seeds the window — a big CUMULATIVE shed count
    # from history must not read as a live breach
    assert auto.step() == 0
    # no new sheds between polls: rate 0, still no breach
    metrics["serve_fleet/submitted"] = 1100.0
    assert auto.step() == 0
    # 50 sheds out of 100 new submissions: a live 50% shed rate
    metrics["serve_fleet/submitted"] = 1200.0
    metrics["serve_fleet/shed_saturated"] = 150.0
    assert auto.step() == 1
    assert auto.counters.breach_shed == 1


def test_autoscaler_cooldown_and_scale_down():
    router = _FakeRouter(3)
    router.replicas[0].load = 4     # the busy one
    metrics = {"serve_fleet/ttft_ms/p95": 0.0, "serve_fleet/load": 0.2,
               "serve_fleet/submitted": 0.0,
               "serve_fleet/shed_saturated": 0.0}
    t = [0.0]
    auto = _scaler(router, metrics, SLOPolicy(
        ttft_p95_ms=1e9, breach_rounds=1, min_replicas=1,
        drain_below_load=0.5, scale_down_cooldown_s=100.0), t)
    assert auto.step() == -1        # cold fleet: drain one
    assert router.removed == ["r1"]  # least-loaded live replica, r0 busy
    assert auto.step() == 0         # cooldown holds
    assert auto.counters.held_cooldown == 1
    t[0] = 200.0
    assert auto.step() == -1        # cooldown elapsed
    t[0] = 400.0
    assert auto.step() == 0         # floor: never below min_replicas
    assert auto.counters.held_floor == 1
    assert len(router.replicas) == 1


def test_autoscaler_registers_decisions_as_export_source():
    router = _FakeRouter(1)
    metrics = {"serve_fleet/ttft_ms/p95": 900.0, "serve_fleet/load": 1.0,
               "serve_fleet/submitted": 0.0,
               "serve_fleet/shed_saturated": 0.0}
    t = [0.0]
    auto = _scaler(router, metrics, SLOPolicy(
        ttft_p95_ms=500.0, breach_rounds=1, scale_up_cooldown_s=0.0), t)
    try:
        auto.step()
        snap = export.collect()
        assert snap["autoscaler/scale_ups"] == 1.0
        assert snap["autoscaler/polls"] == 1.0
        assert "rocket_tpu_autoscaler_scale_ups" in export.prometheus_text()
    finally:
        export.unregister_source("autoscaler")


def test_successive_halving_capacity_converges_cheaply():
    calls = []

    def measure(cap, budget):
        calls.append((cap, budget))
        return abs(cap - 4) + 1.0 / budget   # true optimum: 4 replicas

    best = successive_halving_capacity(
        [1, 2, 4, 8, 16, 32, 64, 128], measure, budget0=1, eta=2)
    assert best == 4
    # geometric rungs: 8 + 4 + 2 measurements, budgets doubling
    assert len(calls) == 14
    assert max(b for _, b in calls) == 4


# -- one-worker smoke (tier-1 heavy tail) ------------------------------------


def test_proc_worker_bit_equal_and_salvage(prompts, oracle):
    """One real worker subprocess: results bit-identical to the
    in-process oracle; kill -9 leaves every accepted request salvageable
    from the supervisor shadow; a respawn serves again."""
    spec = WorkerSpec(builder=BUILDER)
    rep = ProcReplica(spec, "smoke-0", spawn_timeout_s=SPAWN_S,
                      rpc_timeout_s=SPAWN_S)
    try:
        for i in range(2):
            assert rep.submit(Request(rid=f"s{i}", prompt=prompts[i]))
        assert rep.load == 2
        results = _pump_until_done(rep, 2)
        _assert_exactly_once(results, ["s0", "s1"])
        for res in results:
            assert isinstance(res, Completed)
            i = int(res.rid[1:])
            assert np.array_equal(np.asarray(res.tokens), oracle(i)), res.rid
        assert not rep._outstanding

        # kill -9: the corpse is discovered, nothing is lost
        assert rep.submit(Request(rid="s2", prompt=prompts[2]))
        rep.kill()
        _await_corpse(rep)
        assert not rep.probe()
        assert rep.load == 1 << 30          # dead replicas repel routing
        final, salvaged = rep.heal()        # respawns a fresh worker
        assert [q.rid for q in salvaged] == ["s2"] and not final
        assert rep.spawns == 2
        assert rep.probe()
        # the respawned worker serves bit-correct
        assert rep.submit(salvaged[0])
        (res,) = _pump_until_done(rep, 1)
        assert isinstance(res, Completed)
        assert np.array_equal(np.asarray(res.tokens), oracle(2))
    finally:
        rep.close()
    assert rep._dead == "closed"


# -- chaos + elasticity (slow) -----------------------------------------------


@pytest.mark.slow
def test_proc_fleet_kill9_mid_burst_exactly_once(prompts, oracle):
    """Acceptance: SIGKILL one worker mid-burst through the router — the
    fleet keeps serving, every request resolves to exactly one typed
    result (salvaged included), the respawned replica serves
    bit-correct.  Fault-free requests stay bit-equal to the oracle."""
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": 4})
    index = SharedPrefixIndex(page_tokens=4)
    reps = [ProcReplica(spec, f"pf{i}", spawn_timeout_s=SPAWN_S,
                        rpc_timeout_s=SPAWN_S, prefix_index=index)
            for i in range(2)]
    router = FleetRouter(reps, prefix_index=index)
    injector = ProcessKillInjector(reps[0], kill_on=(2,))
    rids = []
    try:
        for i in range(10):
            req = Request(rid=f"k{i}", prompt=prompts[i % len(prompts)])
            rids.append(req.rid)
            router.submit(req)
            injector.tick()      # tick #2 SIGKILLs pf0 mid-burst
            router.pump()        # supervision discovers + heals inline
        results = router.run_until_idle()
        _assert_exactly_once(results, rids)
        assert injector.kills == 1
        assert reps[0].spawns == 2          # healed once
        assert router.counters.heals == 1
        assert router.counters.requeued >= 1
        # worker stores shipped their page-hash deltas cross-process
        assert index.snapshot()["notes"] > 0
        for res in results:
            assert isinstance(res, Completed), res
            i = int(res.rid[1:]) % len(prompts)
            assert np.array_equal(np.asarray(res.tokens), oracle(i)), res.rid
    finally:
        router.close()


@pytest.mark.slow
def test_autoscaler_spawns_and_drains_real_workers(prompts):
    """Acceptance: the autoscaler spawns >= 1 worker process on an SLO
    breach, drains one after load drops (retired replica closed once
    idle), and its decisions are visible on the export surface."""
    spec = WorkerSpec(builder=BUILDER)
    rep0 = ProcReplica(spec, "auto-0", spawn_timeout_s=SPAWN_S,
                       rpc_timeout_s=SPAWN_S)
    router = FleetRouter([rep0])
    register_fleet_source(router)
    spawned = []

    def spawn(rid):
        rep = ProcReplica(spec, rid, spawn_timeout_s=SPAWN_S,
                          rpc_timeout_s=SPAWN_S)
        spawned.append(rep)
        return rep

    auto = Autoscaler(router, spawn, SLOPolicy(
        ttft_p95_ms=1e-6, breach_rounds=1, max_replicas=2,
        scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.0,
        drain_below_load=0.5))
    try:
        rids = []
        for i in range(4):
            rid = f"a{i}"
            rids.append(rid)
            router.submit(Request(rid=rid, prompt=prompts[i]))
        results = router.run_until_idle()
        # any served request breaches the absurd TTFT SLO -> scale up
        while auto.counters.scale_ups == 0 and auto.counters.polls < 5:
            auto.step()
        assert auto.counters.scale_ups >= 1
        assert len(router.replicas) == 2
        assert router.counters.replicas_added == 1
        # the grown fleet serves through both replicas
        for i in range(4, 8):
            rid = f"a{i}"
            rids.append(rid)
            router.submit(Request(rid=rid, prompt=prompts[i]))
        results += router.run_until_idle()
        _assert_exactly_once(results, rids)
        assert all(isinstance(r, Completed) for r in results)
        # load drops; relax the latency SLO (cumulative percentiles
        # never decay) so the cold-fleet down-trigger can fire
        auto.policy.ttft_p95_ms = 1e9
        while auto.counters.scale_downs == 0 and auto.counters.polls < 20:
            auto.step()
        assert auto.counters.scale_downs == 1
        assert len(router.replicas) == 1
        for _ in range(50):                 # sweep closes the idle one
            router.pump()
            if not router._retiring:
                break
        assert not router._retiring
        assert router.counters.replicas_retired == 1
        snap = export.collect()
        assert snap["autoscaler/scale_ups"] >= 1.0
        assert snap["autoscaler/scale_downs"] == 1.0
        assert snap["serve_fleet/replicas"] == 1.0
        assert "rocket_tpu_autoscaler_scale_ups" in export.prometheus_text()
    finally:
        export.unregister_source("autoscaler")
        export.unregister_source("serve_fleet")
        router.close()
        for rep in spawned:
            rep.close()


@pytest.mark.slow
@pytest.mark.elastic
def test_respawn_elastic_restores_from_snapshot(tmp_path, prompts):
    """A worker spawned with ``restore_dir`` serves the SNAPSHOT weights
    (not its seed default) — and still does after a kill -9 respawn."""
    from rocket_tpu.models.generate import speculative_generate_batched

    tw.save_tiny_snapshot(str(tmp_path), seed_target=11)
    model, draft, p11, _ = tw.tiny_models(seed_target=11)
    _, _, _, dparams = tw.tiny_models()

    def expect(i):
        toks = speculative_generate_batched(
            model, p11, draft, dparams, prompts[i][None, :],
            max_new_tokens=tw.TOTAL - tw.P, n_draft=tw.NDRAFT)
        return np.asarray(toks[0])

    spec = WorkerSpec(builder=BUILDER, restore_dir=str(tmp_path))
    rep = ProcReplica(spec, "el-0", spawn_timeout_s=SPAWN_S,
                      rpc_timeout_s=SPAWN_S)
    try:
        assert rep.submit(Request(rid="e0", prompt=prompts[0]))
        (res,) = _pump_until_done(rep, 1)
        assert np.array_equal(np.asarray(res.tokens), expect(0))
        rep.kill()
        _await_corpse(rep)
        assert not rep.probe()
        final, salvaged = rep.heal()
        assert not final and not salvaged
        assert rep.spawns == 2
        assert rep.submit(Request(rid="e1", prompt=prompts[1]))
        (res,) = _pump_until_done(rep, 1)
        assert np.array_equal(np.asarray(res.tokens), expect(1))
    finally:
        rep.close()
