"""Checkpoint / resume tests (SURVEY §3.4 semantics + §4 round-trip
requirement)."""

import os

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.observe.backends import MemoryBackend

from test_pipeline import MLP, synthetic_classification


def _strip_mesh(ckpt):
    """Rewrite a snapshot's manifest without its 'mesh' section — the
    schema-1 (pre-elastic) shape, which keeps the strict topology guard."""
    import json

    mf = os.path.join(str(ckpt), "manifest.json")
    with open(mf) as fh:
        manifest = json.load(fh)
    manifest.pop("mesh", None)
    with open(mf, "w") as fh:
        json.dump(manifest, fh)


def _tree(tmp_path, data, *, epochs, save_every=4, resume=None, load_capsules=True,
          project_root=None, seed=0, input_spec=None):
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
        input_spec=input_spec,
    )
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True, seed=7),
            model,
            rt.Checkpointer(save_every=save_every),
        ],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper],
        tag="ckpt",
        num_epochs=epochs,
        project_root=str(project_root or tmp_path),
        seed=seed,
    )
    if resume:
        launcher.resume(resume, load_capsules=load_capsules)
    return launcher, model


def test_checkpoint_write_and_full_resume(tmp_path, devices):
    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=2)
    launcher.launch()
    # 256/64 = 4 iters/epoch, 2 epochs; (idx+1) % 4 cadence -> saves at
    # iters 3 and 7 (no useless step-0 snapshot)
    v0 = tmp_path / "ckpt" / "v0"
    ckpts = sorted((v0 / "weights").iterdir())
    assert [c.name for c in ckpts] == ["000003", "000007"]
    trained_step = model.step
    assert trained_step == 8

    # Full resume from the last snapshot: step counter restores to 8 (saved
    # post-step at the final iteration), then continues.
    launcher2, model2 = _tree(
        tmp_path, data, epochs=3, resume=str(ckpts[-1]), load_capsules=True
    )
    launcher2.launch()
    # resumed at epoch 1 (epoch_idx stored = 1), runs epochs 1 and 2 from
    # the restored state
    assert model2.step > 8


def test_full_resume_restores_exact_state(tmp_path, devices):
    """Save -> restore -> params bitwise equal (SURVEY §4: checkpoint
    round-trip)."""
    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=1, save_every=100)
    launcher.launch()
    # manual snapshot of the trained state via the public Checkpointer path
    from rocket_tpu.persist import default_io

    state = model.state
    path = str(tmp_path / "manual")
    default_io().save(path, {"module_x": {"state": state}}, wait=True)

    import jax

    restored = default_io().restore_item(
        path,
        "module_x",
        target={
            "state": jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
                state,
            )
        },
    )["state"]
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state.step)


def test_weights_only_resume(tmp_path, devices):
    import jax

    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=2)
    launcher.launch()
    # last snapshot (iter 7) is saved post-final-step == the trained params
    ckpt = str(tmp_path / "ckpt" / "v0" / "weights" / "000007")
    trained_params = jax.device_get(model.state.params)

    # Materialize a fresh module eagerly (input_spec) under a weights-only
    # resume, without training — restored params must EQUAL the trained
    # checkpoint's params, while optimizer state / step start fresh.
    import jax.numpy as jnp

    spec = {
        "x": jax.ShapeDtypeStruct((64, 16), jnp.float32),
        "label": jax.ShapeDtypeStruct((64,), jnp.int32),
    }
    launcher2, model2 = _tree(
        tmp_path, data, epochs=0, resume=ckpt, load_capsules=False,
        input_spec=spec,
    )
    launcher2.launch()
    assert model2.step == 0  # optimizer state fresh, not resumed
    restored_params = jax.device_get(model2.state.params)
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, trained_params, restored_params
    )

    # And training from those weights for one epoch counts only this run's
    # iterations (reference launcher.py:349-359 fine-tune contract).
    launcher3, model3 = _tree(
        tmp_path, data, epochs=1, resume=ckpt, load_capsules=False
    )
    launcher3.launch()
    assert model3.step == 4  # 1 epoch x 4 iters, NOT resumed 8 + 4


def test_mid_epoch_data_resume_determinism(devices):
    """skip_batches replays the permutation: batches [k:] of a resumed epoch
    equal batches [k:] of an uninterrupted one (reference
    skip_first_batches, dataset.py:205-210)."""
    from rocket_tpu.data import ArraySource, DataLoader

    data = synthetic_classification(n=128)
    loader = DataLoader(ArraySource(data), batch_size=32, shuffle=True, seed=5)
    full = [b for b in loader.iterate(epoch=3)]
    resumed = [b for b in loader.iterate(epoch=3, skip_batches=2)]
    assert len(resumed) == len(full) - 2
    for a, b in zip(full[2:], resumed):
        np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))


def test_resume_mid_accumulation_window(devices):
    """A resume landing inside a gradient-accumulation window re-enters the
    window at the saved position: the host-side micro counter re-derives
    from the restored TrainState's ``micro`` so every later sync boundary
    stays aligned (VERDICT r1 weakness #7)."""
    import jax.numpy as jnp

    def build(runtime):
        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=2e-2),
            ],
        )
        model.bind(runtime)
        model.setup()
        return model

    data = synthetic_classification(n=64)
    batch = {
        "x": jnp.asarray(data["x"]),
        "label": jnp.asarray(data["label"]),
    }
    attrs = rt.Attributes(
        batch=batch, looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )

    model = build(rt.Runtime(gradient_accumulation_steps=4))
    for _ in range(6):  # 4 = one sync step, then 2 micro steps into window 2
        attrs.batch = batch
        model.launch(attrs)
    assert int(model.state.step) == 1
    assert int(model.state.micro) == 2 and model._micro_idx == 2

    model2 = build(rt.Runtime(gradient_accumulation_steps=4))
    model2.load_state_dict(model.state_dict())
    assert model2._micro_idx == 2  # re-derived from state.micro
    for _ in range(2):  # micro #3, then the window-4 sync boundary
        attrs.batch = batch
        model2.launch(attrs)
    assert int(model2.state.step) == 2
    assert int(model2.state.micro) == 0 and model2._micro_idx == 0


def test_retention_keep_last_survives_restart(tmp_path, devices):
    """keep_last keeps bounding disk across a full-resume restart: the prior
    run's snapshots join the retention window (VERDICT r1 weakness #9)."""
    data = synthetic_classification(n=256)

    def tree(epochs, resume=None):
        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=2e-2),
            ],
        )
        looper = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True, seed=7),
                model,
                rt.Checkpointer(save_every=2, keep_last=2),
            ],
            progress=False,
        )
        launcher = rt.Launcher(
            capsules=[looper],
            tag="keep",
            num_epochs=epochs,
            project_root=str(tmp_path),
        )
        if resume:
            launcher.resume(resume, load_capsules=True)
        return launcher

    tree(epochs=2).launch()  # 8 iters, saves at 1,3,5,7 -> keeps 5,7
    v0_weights = tmp_path / "keep" / "v0" / "weights"
    assert sorted(p.name for p in v0_weights.iterdir()) == ["000005", "000007"]

    # Resume and run 2 more epochs: new saves land in v1; retention spans
    # BOTH runs, so v0's pre-restart snapshots get pruned away.
    tree(epochs=4, resume=str(v0_weights / "000007")).launch()
    v1_weights = tmp_path / "keep" / "v1" / "weights"
    remaining = sorted(p.name for p in v0_weights.iterdir()) + sorted(
        p.name for p in v1_weights.iterdir()
    )
    assert len(remaining) == 2, remaining


def test_retention_prunes_only_issued_saves(tmp_path, devices):
    """Crash-safety ordering: when a save is issued, the previous snapshot
    must still be on disk — with ``keep_last=1`` it is the ONLY durable
    state if the in-flight async write never completes.  (Append-then-prune
    used to rmtree it before the new save was even enqueued.)  ``destroy``
    then prunes the surplus once the final save is durable."""
    data = synthetic_classification(n=256)
    weights = tmp_path / "pred" / "v0" / "weights"

    class Probe(rt.Capsule):
        """Priority 50 < Checkpointer's 100: runs right after each save."""

        def __init__(self):
            super().__init__(statefull=False, priority=50)
            self.iter = 0
            self.missing = []

        def launch(self, attrs=None):
            self.iter += 1
            # saves issue at iters 2,4,6,8 (save_every=2); from the second
            # save on, the predecessor snapshot must have survived the
            # prune that ran as this iteration's save was issued
            expect = {4: "000001", 6: "000003", 8: "000005"}.get(self.iter)
            if expect and not (weights / expect).is_dir():
                self.missing.append(expect)

    probe = Probe()
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=7),
            rt.Module(
                MLP(),
                capsules=[
                    rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                    rt.Optimizer(learning_rate=2e-2),
                ],
            ),
            rt.Checkpointer(save_every=2, keep_last=1),
            probe,
        ],
        progress=False,
    )
    rt.Launcher(
        capsules=[looper], tag="pred", num_epochs=2,
        project_root=str(tmp_path),
    ).launch()
    assert probe.missing == []
    # destroy() pruned the in-flight surplus down to keep_last
    assert sorted(p.name for p in weights.iterdir()) == ["000007"]


def test_preemption_sigterm_saves_and_resumes(tmp_path, devices):
    """SIGTERM mid-epoch: the Checkpointer writes a durable snapshot at the
    next iteration boundary, terminates the loop inside the grace window,
    and a resume from that snapshot restores bitwise-identical params
    (SURVEY §5.3; VERDICT r1 item 8)."""
    import signal

    import jax
    import jax.numpy as jnp

    class Preemptor(rt.Capsule):
        """Delivers SIGTERM to our own process on iteration 2 (between the
        train step and the Checkpointer, like a real preemption notice)."""

        def __init__(self):
            super().__init__(statefull=False, priority=500)
            self._iters = 0

        def launch(self, attrs=None):
            if self._iters == 2:
                signal.raise_signal(signal.SIGTERM)
            self._iters += 1

    data = synthetic_classification(n=512)  # 8 iters/epoch at bs 64
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
    )
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True, seed=7),
            model,
            Preemptor(),
            rt.Checkpointer(save_every=100),  # periodic cadence never fires
        ],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag="pre", num_epochs=1, project_root=str(tmp_path)
    )
    launcher.launch()

    # loop stopped early: only 3 of 8 iterations ran, snapshot at iter 2
    assert model.step == 3
    ckpts = sorted((tmp_path / "pre" / "v0" / "weights").iterdir())
    assert [c.name for c in ckpts] == ["000002"]
    final_params = jax.device_get(model.state.params)

    launcher2, model2 = _tree(
        tmp_path, data, epochs=0, resume=str(ckpts[0]), load_capsules=True,
        input_spec={
            "x": jax.ShapeDtypeStruct((64, 16), jnp.float32),
            "label": jax.ShapeDtypeStruct((64,), jnp.int32),
        },
    )
    launcher2.launch()
    assert model2.step == 3  # restored post-save step counter
    jax.tree_util.tree_map(
        np.testing.assert_array_equal,
        final_params,
        jax.device_get(model2.state.params),
    )
    # handler restored after destroy (ours is gone)
    from rocket_tpu.persist.checkpoint import _on_sigterm

    assert signal.getsignal(signal.SIGTERM) is not _on_sigterm


def test_sigterm_handler_single_install(tmp_path, devices):
    """Two Checkpointers (train + eval looper) share ONE handler install —
    a second install must not make the handler its own 'previous' (which
    would recurse on a real SIGTERM)."""
    import signal

    from rocket_tpu.persist import checkpoint as cp

    runtime = rt.Runtime()
    runtime.project_dir = str(tmp_path / "dup")
    c1 = rt.Checkpointer(save_every=10)
    c2 = rt.Checkpointer(save_every=10)
    before = signal.getsignal(signal.SIGTERM)
    try:
        for c in (c1, c2):
            c.bind(runtime)
            c.setup()
        assert c1._installed_handler and not c2._installed_handler
        assert cp._PREV_HANDLER["handler"] is not cp._on_sigterm
        cp._preempted.clear()
        signal.raise_signal(signal.SIGTERM)  # must not recurse
        assert cp._preempted.is_set()
    finally:
        cp._preempted.clear()
        signal.signal(signal.SIGTERM, before)


def test_topology_guard(tmp_path, devices):
    """Resume refuses a different process count for LEGACY (pre-elastic,
    no manifest mesh section) snapshots (reference launcher.py:370-375).
    Mesh-stamped snapshots relax this to a logged elastic resume — see
    test_elastic.py.  Single-process env: simulate by editing the saved
    launcher state."""
    data = synthetic_classification(n=128)
    launcher, _ = _tree(tmp_path, data, epochs=1, save_every=2)
    launcher.launch()
    ckpt = tmp_path / "ckpt" / "v0" / "weights" / "000001"
    _strip_mesh(ckpt)

    launcher2, _ = _tree(tmp_path, data, epochs=1, resume=str(ckpt))
    launcher2._saved_num_procs = None  # reset
    # monkey-wrench: pretend the checkpoint was written by 4 processes
    orig = rt.Launcher.load_state_dict

    def fake_load(self, state):
        orig(self, state)
        self._saved_num_procs = 4

    rt.Launcher.load_state_dict = fake_load
    try:
        with pytest.raises(RuntimeError, match="topology"):
            launcher2.launch()
    finally:
        rt.Launcher.load_state_dict = orig


def test_seq2seq_checkpoint_resume(tmp_path, devices):
    """The generic persistence machinery round-trips the encoder-decoder
    family: save mid-run, full resume, bitwise-equal params."""
    import rocket_tpu as rt
    from rocket_tpu.models import EncoderDecoder, Seq2SeqConfig
    from rocket_tpu.models.objectives import lm_cross_entropy

    cfg = Seq2SeqConfig.tiny(attention="dot")
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, cfg.vocab_size, size=(64, 16)).astype(np.int32)
    data = {"inputs": inputs, "targets": inputs[:, :12].copy()}

    def tree(resume=None, epochs=1):
        model = rt.Module(
            EncoderDecoder(cfg),
            capsules=[
                rt.Loss(lm_cross_entropy(tokens_key="targets"), name="s2s"),
                rt.Optimizer(learning_rate=1e-2),
            ],
        )
        launcher = rt.Launcher(
            capsules=[
                rt.Looper(capsules=[
                    rt.Dataset(rt.ArraySource(data), batch_size=16,
                               shuffle=True),
                    model,
                    rt.Checkpointer(save_every=2),
                ], progress=False)
            ],
            tag="s2s", num_epochs=epochs, project_root=str(tmp_path),
        )
        if resume:
            launcher.resume(resume)
        return launcher, model

    launcher, model = tree()
    launcher.launch()
    assert model.step == 4
    ckpts = sorted((tmp_path / "s2s" / "v0" / "weights").iterdir())
    assert len(ckpts) == 2  # saves at iters 2 and 4

    import jax

    # Bitwise round-trip: the post-final-step snapshot must restore the
    # exact in-memory state (incl. the cross-attention tree).
    from rocket_tpu.persist import default_io

    state = model.state
    restored = default_io().restore_item(
        str(ckpts[-1]),
        model._ckpt_key,
        target={
            "state": jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=x.sharding
                ),
                state,
            )
        },
    )["state"]
    flat = jax.tree_util.tree_leaves_with_path(restored.params)
    assert any("cross_attn" in jax.tree_util.keystr(p) for p, _ in flat)
    for (pa, a), b in zip(flat, jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(pa),
        )

    # MID-RUN resume: restart from the iter-2 snapshot, finish the epoch.
    launcher2, model2 = tree(resume=str(ckpts[0]))
    launcher2.launch()
    assert int(model2.step) == 4


def test_weights_only_resume_reseeds_ema(tmp_path, devices):
    """After a weights-only restore the parameter EMA must snapshot the
    RESTORED weights (not the fresh random init), so eval_with_ema evaluates
    the restored model immediately."""
    import rocket_tpu as rt
    from rocket_tpu.models.lenet import LeNet
    from rocket_tpu.models.objectives import cross_entropy

    rng = np.random.default_rng(0)
    data = {
        "image": rng.normal(size=(64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, size=(64,)).astype(np.int32),
    }

    def tree(resume=None, load_capsules=True):
        model = rt.Module(
            LeNet(num_classes=10),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=1e-2, ema_decay=0.5),
            ],
            eval_with_ema=True,
        )
        launcher = rt.Launcher(
            capsules=[
                rt.Looper(capsules=[
                    rt.Dataset(rt.ArraySource(data), batch_size=16,
                               shuffle=True),
                    model,
                    rt.Checkpointer(save_every=2),
                ], progress=False)
            ],
            tag="ema", num_epochs=1, project_root=str(tmp_path),
        )
        if resume:
            launcher.resume(resume, load_capsules=load_capsules)
        return launcher, model

    launcher, model = tree()
    launcher.launch()
    ckpts = sorted((tmp_path / "ema" / "v0" / "weights").iterdir())

    import jax
    import jax.numpy as jnp

    launcher2, model2 = tree(resume=str(ckpts[-1]), load_capsules=False)
    launcher2.launch()
    # With load_capsules=False the optimizer state is fresh, so the EMA
    # must have been re-seeded from the restored params at materialization
    # (it then moved with decay=0.5 during the resumed epoch — but it must
    # NOT be anywhere near a random init; check it tracks params closely).
    ema = model2.ema_params
    params = model2.state.params
    assert ema is not None
    for e, p in zip(
        jax.tree_util.tree_leaves(ema), jax.tree_util.tree_leaves(params)
    ):
        # decay 0.5 over >=4 steps: EMA within a small neighborhood of the
        # live params; a random-init seed would differ at O(1).
        assert float(jnp.abs(e - p).max()) < 0.05


def test_weights_only_topology_guard(tmp_path, devices):
    """The topology guard applies to BOTH resume paths (reference
    launcher.py:370-375): a weights-only restore of arrays saved by a
    different process count is still an elastic resume — and for a LEGACY
    (no mesh section) snapshot it must stay fatal.  Single-process env:
    pretend the current run has 2 processes."""
    from rocket_tpu.runtime import Runtime

    data = synthetic_classification(n=128)
    launcher, _ = _tree(tmp_path, data, epochs=1, save_every=2)
    launcher.launch()
    ckpt = str(tmp_path / "ckpt" / "v0" / "weights" / "000001")
    _strip_mesh(ckpt)

    launcher2, _ = _tree(
        tmp_path, data, epochs=1, resume=ckpt, load_capsules=False
    )
    orig = Runtime.process_count
    Runtime.process_count = property(lambda self: 2)
    try:
        with pytest.raises(RuntimeError, match="weights-only included"):
            launcher2.launch()
    finally:
        Runtime.process_count = orig


def test_best_k_checkpoint_by_metric(tmp_path, devices):
    """Checkpointer(track_metric=...) in the eval looper keeps the
    keep_best highest-accuracy snapshots with durable metadata, prunes
    the rest, and reloads the ranking after a restart."""
    import json

    data = synthetic_classification(n=256)

    def tree(epochs):
        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=5e-2),
            ],
        )
        train = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                           seed=7),
                model,
            ],
            progress=False,
        )
        evaluate = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=64),
                model,
                rt.Meter(mode="in_step", capsules=[rt.Accuracy()]),
                rt.Tracker(backend),
                rt.Checkpointer(save_every=None, track_metric="accuracy",
                                keep_best=2),
            ],
            grad_enabled=False,
            progress=False,
        )
        return rt.Launcher(
            capsules=[train, evaluate], tag="best", num_epochs=epochs,
            project_root=str(tmp_path),
        )

    backend = MemoryBackend()
    tree(epochs=4).launch()
    root = tmp_path / "best" / "v0"
    best_dirs = sorted(root.glob("best/*"))
    assert 1 <= len(best_dirs) <= 2, best_dirs
    metas = []
    for d in best_dirs:
        with open(d / "best_metric.json") as fh:
            metas.append(json.load(fh))
    assert all(m["metric"] == "accuracy" for m in metas)
    values = sorted((m["value"] for m in metas), reverse=True)
    # the kept snapshots are exactly the top-k of EVERY observed cycle
    observed = sorted(
        (rec["accuracy"] for _, rec in backend.scalars if "accuracy" in rec),
        reverse=True,
    )
    assert len(observed) == 4  # one eval cycle per epoch
    np.testing.assert_allclose(values, observed[: len(values)])
    # no periodic weights/ dirs (save_every=None)
    assert not (root / "weights").exists()

    # a fresh capsule over the same project dir reloads the ranking
    ck = rt.Checkpointer(save_every=None, track_metric="accuracy",
                         keep_best=2)
    best = ck._scan_best(str(root))
    assert len(best) == len(best_dirs)
    assert best[0][0] == values[0]


def test_best_checkpoint_resumable(tmp_path, devices):
    """A best snapshot is a full checkpoint: resume from it."""
    import jax
    import jax.numpy as jnp

    data = synthetic_classification(n=128)
    model = rt.Module(
        MLP(),
        capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                  rt.Optimizer(learning_rate=5e-2)],
    )
    evaluate = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64),
            model,
            rt.Meter(mode="in_step", capsules=[rt.Accuracy()]),
            rt.Checkpointer(save_every=None, track_metric="accuracy",
                            keep_best=1),
        ],
        grad_enabled=False,
        progress=False,
    )
    train = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=3),
            model,
        ],
        progress=False,
    )
    # ONE epoch: the single eval cycle's best snapshot IS the final state
    # (a second epoch could skip its save when the metric saturates).
    rt.Launcher(capsules=[train, evaluate], tag="bestres", num_epochs=1,
                project_root=str(tmp_path)).launch()
    best = sorted((tmp_path / "bestres" / "v0" / "best").iterdir())[-1]
    trained = jax.tree_util.tree_map(np.asarray, model.state.params)

    launcher2, model2 = _tree(
        tmp_path, data, epochs=0, resume=str(best), load_capsules=False,
        input_spec={
            "x": jax.ShapeDtypeStruct((64, 16), jnp.float32),
            "label": jax.ShapeDtypeStruct((64,), jnp.int32),
        },
    )
    launcher2.launch()
    restored = jax.tree_util.tree_map(np.asarray, model2.state.params)
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, trained, restored
    )


def test_best_ranking_survives_versioned_restart(tmp_path, devices):
    """The Launcher gives a resumed run a fresh v{N} dir; the best-k
    ranking must seed from the PRIOR version's best dirs (resume path
    itself a best/ snapshot) or a worse post-resume value would win."""
    import json

    data = synthetic_classification(n=128)

    def tree(epochs, resume=None):
        model = rt.Module(
            MLP(),
            capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                      rt.Optimizer(learning_rate=5e-2)],
        )
        ck = rt.Checkpointer(save_every=None, track_metric="accuracy",
                             keep_best=2)
        evaluate = rt.Looper(
            capsules=[rt.Dataset(rt.ArraySource(data), batch_size=64),
                      model,
                      rt.Meter(mode="in_step", capsules=[rt.Accuracy()]),
                      ck],
            grad_enabled=False, progress=False,
        )
        train = rt.Looper(
            capsules=[rt.Dataset(rt.ArraySource(data), batch_size=64,
                                 shuffle=True, seed=3), model],
            progress=False,
        )
        launcher = rt.Launcher(capsules=[train, evaluate], tag="bestv",
                               num_epochs=epochs,
                               project_root=str(tmp_path))
        if resume:
            launcher.resume(resume)
        return launcher, ck

    launcher, _ = tree(epochs=1)
    launcher.launch()
    best = sorted((tmp_path / "bestv" / "v0" / "best").iterdir())[-1]
    with open(best / "best_metric.json") as fh:
        v0_value = json.load(fh)["value"]

    launcher2, ck2 = tree(epochs=0, resume=str(best))
    launcher2.launch()  # v1 project dir; no epochs run
    assert ck2._best, "ranking not seeded from the prior version"
    assert ck2._best[0][0] == v0_value
    assert "v0" in ck2._best[0][1]
