"""Checkpoint / resume tests (SURVEY §3.4 semantics + §4 round-trip
requirement)."""

import os

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.observe.backends import MemoryBackend

from test_pipeline import MLP, synthetic_classification


def _tree(tmp_path, data, *, epochs, save_every=4, resume=None, load_capsules=True,
          project_root=None, seed=0):
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
    )
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True, seed=7),
            model,
            rt.Checkpointer(save_every=save_every),
        ],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper],
        tag="ckpt",
        num_epochs=epochs,
        project_root=str(project_root or tmp_path),
        seed=seed,
    )
    if resume:
        launcher.resume(resume, load_capsules=load_capsules)
    return launcher, model


def test_checkpoint_write_and_full_resume(tmp_path, devices):
    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=2)
    launcher.launch()
    # 256/64 = 4 iters/epoch, 2 epochs -> saves at iter 0 and 4
    v0 = tmp_path / "ckpt" / "v0"
    ckpts = sorted((v0 / "weights").iterdir())
    assert [c.name for c in ckpts] == ["000000", "000004"]
    trained_step = model.step
    assert trained_step == 8

    # Full resume from the last snapshot: step counter restores to 4 (saved
    # post-step at iteration boundary), then continues to 8 + 4 more.
    launcher2, model2 = _tree(
        tmp_path, data, epochs=3, resume=str(ckpts[-1]), load_capsules=True
    )
    launcher2.launch()
    # resumed at epoch 1 (saved during epoch 1... epoch_idx stored = 1),
    # runs epochs 1 and 2 from the restored state
    assert model2.step > 4


def test_full_resume_restores_exact_state(tmp_path, devices):
    """Save -> restore -> params bitwise equal (SURVEY §4: checkpoint
    round-trip)."""
    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=1, save_every=100)
    launcher.launch()
    # manual snapshot of the trained state via the public Checkpointer path
    from rocket_tpu.persist import default_io

    state = model.state
    path = str(tmp_path / "manual")
    default_io().save(path, {"module_x": {"state": state}}, wait=True)

    import jax

    restored = default_io().restore_item(
        path,
        "module_x",
        target={
            "state": jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
                state,
            )
        },
    )["state"]
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state.step)


def test_weights_only_resume(tmp_path, devices):
    data = synthetic_classification(n=256)
    launcher, model = _tree(tmp_path, data, epochs=2)
    launcher.launch()
    ckpt = str(tmp_path / "ckpt" / "v0" / "weights" / "000004")
    trained_params = model.state.params

    launcher2, model2 = _tree(
        tmp_path, data, epochs=1, resume=ckpt, load_capsules=False
    )
    # trigger materialization via one launch
    launcher2.launch()
    # optimizer state started fresh: step counts only this run's iterations
    assert model2.step == 4  # 1 epoch x 4 iters, NOT resumed 4 + 4
    # but weights started from the checkpoint, not from init: the loss of the
    # first step should already be low
    import jax

    leaves_restored = jax.tree_util.tree_leaves(trained_params)
    assert leaves_restored  # sanity


def test_mid_epoch_data_resume_determinism(devices):
    """skip_batches replays the permutation: batches [k:] of a resumed epoch
    equal batches [k:] of an uninterrupted one (reference
    skip_first_batches, dataset.py:205-210)."""
    from rocket_tpu.data import ArraySource, DataLoader

    data = synthetic_classification(n=128)
    loader = DataLoader(ArraySource(data), batch_size=32, shuffle=True, seed=5)
    full = [b for b in loader.iterate(epoch=3)]
    resumed = [b for b in loader.iterate(epoch=3, skip_batches=2)]
    assert len(resumed) == len(full) - 2
    for a, b in zip(full[2:], resumed):
        np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))


def test_topology_guard(tmp_path, devices):
    """Resume refuses a different process count (reference
    launcher.py:370-375). Single-process env: simulate by editing the saved
    launcher state."""
    data = synthetic_classification(n=128)
    launcher, _ = _tree(tmp_path, data, epochs=1)
    launcher.launch()
    ckpt = tmp_path / "ckpt" / "v0" / "weights" / "000000"

    launcher2, _ = _tree(tmp_path, data, epochs=1, resume=str(ckpt))
    launcher2._saved_num_procs = None  # reset
    # monkey-wrench: pretend the checkpoint was written by 4 processes
    orig = rt.Launcher.load_state_dict

    def fake_load(self, state):
        orig(self, state)
        self._saved_num_procs = 4

    rt.Launcher.load_state_dict = fake_load
    try:
        with pytest.raises(RuntimeError, match="topology"):
            launcher2.launch()
    finally:
        rt.Launcher.load_state_dict = orig
