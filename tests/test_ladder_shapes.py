"""Ladder-config structural smoke tests (VERDICT r1 item 9).

The BASELINE.json ladder's big configs (Llama-2 7B, ViT-B/16, ResNet-50)
can't run for real on CI hardware, but their shapes and sharding plans can:
``jax.eval_shape`` traces the full init at zero memory cost, and the
adapter's partition-spec resolution is exactly what materialization uses —
so wrong param counts or accidentally-replicated 7B weight matrices fail
here, long before a pod run.
"""

import jax
import jax.numpy as jnp
import pytest

import rocket_tpu as rt
from rocket_tpu.engine.adapter import FlaxModel
from rocket_tpu.models.resnet import resnet50
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.models.vit import ViT, ViTConfig
from rocket_tpu.parallel.mesh import MeshSpec


def _abstract_plan(model, batch_spec, mesh_spec, devices):
    """(abstract_params, resolved PartitionSpecs, param_count) without
    allocating anything."""
    runtime = rt.Runtime(mesh=mesh_spec.build(devices))
    adapter = FlaxModel(model)
    adapter.configure(runtime.mesh, runtime.rules)

    def init_fn():
        batch = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_spec
        )
        params, _ = adapter.init_variables(jax.random.PRNGKey(0), batch)
        return params

    abstract = jax.eval_shape(init_fn)
    specs = adapter.partition_specs(abstract, runtime.rules)
    count = sum(
        int(leaf.size) for leaf in jax.tree_util.tree_leaves(abstract)
    )
    return abstract, specs, count


def _spec_axes(specs):
    axes = set()
    for spec in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    ):
        for part in spec:
            if part is None:
                continue
            parts = part if isinstance(part, tuple) else (part,)
            axes.update(parts)
    return axes


def test_llama2_7b_shape_and_sharding_plan(devices):
    """7B config: correct param count and fsdp x tensor sharded big matrices
    on an 8-device mesh (the BASELINE 'Llama-2 7B LoRA (GSPMD, v4-32)'
    config, structurally)."""
    cfg = TransformerConfig.llama2_7b(scan_layers=True)
    batch_spec = {"tokens": jax.ShapeDtypeStruct((8, 4096), jnp.int32)}
    abstract, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(fsdp=4, tensor=2), devices
    )
    assert 6.5e9 < count < 7.0e9, f"param count {count:,}"
    axes = _spec_axes(specs)
    assert "fsdp" in axes and "tensor" in axes, axes
    # every big (>= hidden^2) matrix must be sharded, not replicated
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    flat_shapes = jax.tree_util.tree_leaves(abstract)
    for leaf, spec in zip(flat_shapes, flat_specs):
        if leaf.size >= cfg.hidden * cfg.hidden:
            assert any(axis is not None for axis in spec), (
                f"{leaf.shape} is replicated"
            )


def test_llama2_7b_lora_plan(devices):
    """LoRA variant: adapters exist, base count grows only by the low-rank
    terms (the 'Llama-2 7B LoRA' ladder config)."""
    cfg = TransformerConfig.llama2_7b(scan_layers=True, lora_rank=8)
    batch_spec = {"tokens": jax.ShapeDtypeStruct((4, 512), jnp.int32)}
    _, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(fsdp=4, tensor=2), devices
    )
    assert 6.5e9 < count < 7.1e9
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(specs)
    ]
    assert any("lora_a" in p for p in paths) and any(
        "lora_b" in p for p in paths
    )


def test_vit_b16_shape_plan(devices):
    """ViT-B/16: ~86M params; encoder matrices carry the transformer
    sharding axes (the 'ViT-B/16 ImageNet bf16' ladder config)."""
    cfg = ViTConfig.b16()
    batch_spec = {"image": jax.ShapeDtypeStruct((8, 224, 224, 3), jnp.float32)}
    _, specs, count = _abstract_plan(
        ViT(cfg), batch_spec, MeshSpec(data=2, fsdp=2, tensor=2), devices
    )
    assert 85e6 < count < 88e6, f"param count {count:,}"
    axes = _spec_axes(specs)
    assert "tensor" in axes or "fsdp" in axes, axes


def test_resnet50_shape_plan(devices):
    """ResNet-50: ~25.6M params; CNNs are data-parallel by design (SURVEY
    §2.2 DDP contract) — params replicated, batch sharded."""
    batch_spec = {"image": jax.ShapeDtypeStruct((8, 224, 224, 3), jnp.float32)}
    _, specs, count = _abstract_plan(
        resnet50(), batch_spec, MeshSpec(data=8), devices
    )
    assert 25.0e6 < count < 26.5e6, f"param count {count:,}"
    assert _spec_axes(specs) == set()  # replicated = the documented contract


def test_gpt2_124m_fused_bench_layout_plan(devices):
    """The tuned single-chip bench layout (bench.py GPT2_TUNE with
    fused_qkv + fused_ce, padded vocab) at REAL scale: correct param count
    and a clean sharding plan, traced at zero memory cost."""
    cfg = TransformerConfig.gpt2_124m(
        vocab_size=50304, fused_qkv=True, fused_ce=True,
        attention_block_q=512, attention_block_k=1024,
    )
    batch_spec = {"tokens": jax.ShapeDtypeStruct((16, 1024), jnp.int32)}
    abstract, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(data=4, tensor=2), devices
    )
    # 124M-class: tied embed (50304*768) + pos + 12 blocks
    assert 1.2e8 < count < 1.3e8, f"param count {count:,}"
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(specs)
    ]
    assert any("qkv" in p for p in paths), paths[:8]  # fused projection
    assert not any("'head'" in p for p in paths)      # tied — no extra head


def test_llama2_7b_full_finetune_zero1_fits_v4_hbm(devices):
    """7B FULL finetune (non-LoRA) Adam on a pure data(8) mesh: replicated
    optimizer state provably does NOT fit a v4 chip (bf16 params 13.4GB +
    bf16 Adam mu/nu 26.9GB ≈ 40GB of arguments > 32GB HBM), while
    ``zero_stage=1`` re-partitions the moments over the data axis and the
    AOT-compiled step fits.  Both plans come from the same
    :func:`specs_for_state` call — this is the ladder config ZeRO exists
    for (arXiv 2004.13336 §4: ZeRO-1 fits 7.5B on 32GB where DDP cannot).
    """
    import optax

    from rocket_tpu.engine.precision import Policy
    from rocket_tpu.engine.state import TrainState, memory_plan
    from rocket_tpu.engine.step import Objective, build_train_step
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.parallel.sharding import batch_sharding, specs_for_state

    B, S = 8, 1024
    cfg = TransformerConfig.llama2_7b(
        scan_layers=True, remat=True, attention="flash"
    )
    runtime = rt.Runtime(mesh=MeshSpec(data=8).build(devices))
    mesh = runtime.mesh
    policy = Policy.from_string("bf16_full")
    adapter = FlaxModel(TransformerLM(cfg))
    adapter.configure(mesh, runtime.rules)
    adapter.apply_policy(policy)
    batch_struct = {"tokens": jax.ShapeDtypeStruct((B, S), jnp.int32)}
    tx = optax.adamw(1e-5)

    def init_fn():
        batch = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_struct
        )
        params, mutable = adapter.init_variables(jax.random.PRNGKey(0), batch)
        params = policy.cast_to_param(params)
        return TrainState.create(
            params, tx, rng=jax.random.PRNGKey(0), mutable=mutable
        )

    abstract_state = jax.eval_shape(init_fn)
    param_specs = adapter.partition_specs(abstract_state.params, runtime.rules)
    GB = 1 << 30

    # The replicated plan: assert analytically (via the memory plan — no
    # point compiling a program we know cannot fit) that per-device
    # ARGUMENTS alone exceed the 32GB v4 envelope.
    repl = specs_for_state(
        mesh, abstract_state, param_specs=param_specs, zero_stage=0
    )
    repl_mem = memory_plan(abstract_state, repl.state_specs, mesh)
    assert repl_mem["param_bytes"] / GB > 12.0   # bf16 7B ≈ 13.4GB
    assert repl_mem["opt_bytes"] / GB > 24.0     # mu + nu ≈ 2x params
    assert repl_mem["total_bytes"] / GB > 32.0, (
        f"replicated plan only needs "
        f"{repl_mem['total_bytes'] / GB:.1f} GB/device — the ZeRO test "
        f"config no longer demonstrates anything"
    )

    # The ZeRO-1 plan from the SAME rule table: optimizer mirrors fold
    # the 8-way data axis; compile for real and check the envelope.
    plan = specs_for_state(
        mesh, abstract_state, param_specs=param_specs, zero_stage=1
    )
    zero_mem = memory_plan(abstract_state, plan.state_specs, mesh)
    assert zero_mem["opt_bytes"] <= repl_mem["opt_bytes"] / 8 + 1024
    assert zero_mem["total_bytes"] / GB < 18.0

    state_structs = jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract_state,
        plan.state_shardings,
    )
    batch_structs = {
        "tokens": jax.ShapeDtypeStruct(
            (B, S), jnp.int32, sharding=batch_sharding(mesh, 2)
        )
    }
    steps = build_train_step(
        adapter.apply_fn,
        [Objective("lm", lm_cross_entropy())],
        tx,
        policy=policy,
        donate=True,
        shard_plan=plan,
    )
    compiled = steps["sync"].lower(state_structs, batch_structs).compile()
    ma = compiled.memory_analysis()
    args_gb = ma.argument_size_in_bytes / GB
    temp_gb = ma.temp_size_in_bytes / GB
    assert ma.alias_size_in_bytes > 0.9 * ma.output_size_in_bytes
    # arguments: 12.6GB bf16 params + 25.1/8 ≈ 3.1GB moments ≈ 15.7GB —
    # the number the sharding plan commands, asserted un-fudged.
    assert 14.0 < args_gb < 19.0, f"arguments {args_gb:.2f} GB/device"
    # Steady state: the CPU SPMD partitioner materializes two param-sized
    # STAGING buffers that TPU GSPMD does not pay for — the identity
    # grads→base-sharding pin becomes a full reshard copy (ablating that
    # one constraint drops temps by exactly params−shard bytes), and the
    # updated-params all-gather stages into a temp instead of writing the
    # donation-aliased output buffer.  Discount both; what remains is the
    # real ZeRO-1 footprint (params + opt shard args, one grads temp,
    # activations) that the v4 envelope must cover.
    # params are data-replicated, so per-device param bytes = full params
    param_gb = zero_mem["param_bytes"] / GB
    steady_gb = args_gb + temp_gb - 2 * param_gb
    assert steady_gb < 32.0, (
        f"per-device steady state {steady_gb:.2f} GB (after discounting "
        f"2x{param_gb:.1f} GB CPU-partitioner staging copies) exceeds the "
        f"v4 HBM envelope — ZeRO-1 is supposed to make this config fit"
    )
    # and the temps themselves must stay param-scale (grads + 2 staging
    # copies + activations) — catches an accidental extra full-size copy
    assert temp_gb < 3 * param_gb + 4.0, f"temps {temp_gb:.2f} GB/device"


@pytest.mark.slow
def test_llama2_7b_lora_aot_memory_fits_v4_hbm(devices):
    """AOT-compile (not just eval_shape) the REAL 7B LoRA train step —
    flash attention, remat, scanned layers, bf16 compute — on an
    fsdp(4) x tensor(2) mesh and check the compiled per-device memory
    against a v4 chip's 32GB HBM (VERDICT r3 next #3).

    XLA's memory analysis is per-device under SPMD; with the donated
    state aliasing outputs onto arguments, steady-state per-device use is
    arguments + temps.  The CPU backend models neither TPU tile padding
    nor Mosaic scratch, so this is an ESTIMATE of the TPU footprint, not
    a bound in either direction — the assertion leaves 9GB of headroom
    against the v4 envelope for exactly that reason (ladder config
    'Llama-2 7B LoRA (GSPMD, v4-32)').
    """
    import optax

    from rocket_tpu.engine.precision import Policy
    from rocket_tpu.engine.state import TrainState
    from rocket_tpu.engine.step import Objective, build_train_step
    from rocket_tpu.engine.adapter import state_shardings
    from rocket_tpu.models.lora import freeze_non_lora
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.parallel.sharding import batch_sharding

    B, S = 8, 4096
    cfg = TransformerConfig.llama2_7b(
        lora_rank=8, scan_layers=True, remat=True, attention="flash"
    )
    runtime = rt.Runtime(mesh=MeshSpec(fsdp=4, tensor=2).build(devices))
    mesh = runtime.mesh
    policy = Policy.from_string("bf16")
    adapter = FlaxModel(TransformerLM(cfg))
    adapter.configure(mesh, runtime.rules)
    adapter.apply_policy(policy)
    batch_struct = {"tokens": jax.ShapeDtypeStruct((B, S), jnp.int32)}
    tx = freeze_non_lora(optax.adamw(1e-4))

    def init_fn():
        batch = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_struct
        )
        params, mutable = adapter.init_variables(jax.random.PRNGKey(0), batch)
        params = policy.cast_to_param(params)
        return TrainState.create(
            params, tx, rng=jax.random.PRNGKey(0), mutable=mutable
        )

    abstract_state = jax.eval_shape(init_fn)
    param_specs = adapter.partition_specs(abstract_state.params, runtime.rules)
    shardings = state_shardings(mesh, abstract_state, param_specs)
    state_structs = jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract_state,
        shardings,
    )
    batch_structs = {
        "tokens": jax.ShapeDtypeStruct(
            (B, S), jnp.int32, sharding=batch_sharding(mesh, 2)
        )
    }
    steps = build_train_step(
        adapter.apply_fn,
        [Objective("lm", lm_cross_entropy())],
        tx,
        policy=policy,
        donate=True,
    )
    compiled = steps["sync"].lower(state_structs, batch_structs).compile()
    ma = compiled.memory_analysis()
    GB = 1 << 30
    args_gb = ma.argument_size_in_bytes / GB
    temp_gb = ma.temp_size_in_bytes / GB
    # donation: outputs alias arguments, so they don't add
    assert ma.alias_size_in_bytes > 0.9 * ma.output_size_in_bytes
    steady_gb = args_gb + temp_gb
    # fp32 master params ~27GB sharded 8 ways -> ~3.4GB/device; LoRA-only
    # adamw moments add noise-level bytes.  Catch accidental replication.
    assert 2.5 < args_gb < 5.0, f"arguments {args_gb:.2f} GB/device"
    assert steady_gb < 30.0, (
        f"per-device steady state {steady_gb:.2f} GB exceeds the v4 HBM "
        f"envelope (32GB - headroom)"
    )


def test_mistral_7b_swa_aot_memory_fits_v4_hbm(devices):
    """AOT-compile the REAL Mistral-7B LoRA train step — GQA(8),
    sliding-window 4096 at seq 8192 (the flash kernel skips
    out-of-window blocks), scanned layers, remat, bf16 — on an
    fsdp(4) x tensor(2) mesh and check per-device memory against the
    v4 envelope, same method and caveats as the Llama-2 test above.
    This is the new-family counterpart: the window path must survive
    scan + remat + GSPMD at 7B scale, not just the unit tests."""
    import optax

    from rocket_tpu.engine.precision import Policy
    from rocket_tpu.engine.state import TrainState
    from rocket_tpu.engine.step import Objective, build_train_step
    from rocket_tpu.engine.adapter import state_shardings
    from rocket_tpu.models.lora import freeze_non_lora
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.parallel.sharding import batch_sharding

    B, S = 4, 8192
    cfg = TransformerConfig.mistral_7b(
        lora_rank=8, scan_layers=True, remat=True, attention="flash"
    )
    assert cfg.attention_window == 4096  # the windowed path is the point
    runtime = rt.Runtime(mesh=MeshSpec(fsdp=4, tensor=2).build(devices))
    mesh = runtime.mesh
    policy = Policy.from_string("bf16")
    adapter = FlaxModel(TransformerLM(cfg))
    adapter.configure(mesh, runtime.rules)
    adapter.apply_policy(policy)
    batch_struct = {"tokens": jax.ShapeDtypeStruct((B, S), jnp.int32)}
    tx = freeze_non_lora(optax.adamw(1e-4))

    def init_fn():
        batch = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_struct
        )
        params, mutable = adapter.init_variables(jax.random.PRNGKey(0), batch)
        params = policy.cast_to_param(params)
        return TrainState.create(
            params, tx, rng=jax.random.PRNGKey(0), mutable=mutable
        )

    abstract_state = jax.eval_shape(init_fn)
    param_specs = adapter.partition_specs(abstract_state.params, runtime.rules)
    shardings = state_shardings(mesh, abstract_state, param_specs)
    state_structs = jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract_state,
        shardings,
    )
    batch_structs = {
        "tokens": jax.ShapeDtypeStruct(
            (B, S), jnp.int32, sharding=batch_sharding(mesh, 2)
        )
    }
    steps = build_train_step(
        adapter.apply_fn,
        [Objective("lm", lm_cross_entropy())],
        tx,
        policy=policy,
        donate=True,
    )
    compiled = steps["sync"].lower(state_structs, batch_structs).compile()
    ma = compiled.memory_analysis()
    GB = 1 << 30
    args_gb = ma.argument_size_in_bytes / GB
    temp_gb = ma.temp_size_in_bytes / GB
    assert ma.alias_size_in_bytes > 0.9 * ma.output_size_in_bytes
    steady_gb = args_gb + temp_gb
    assert 2.5 < args_gb < 5.0, f"arguments {args_gb:.2f} GB/device"
    assert steady_gb < 30.0, (
        f"per-device steady state {steady_gb:.2f} GB exceeds the v4 HBM "
        f"envelope (32GB - headroom)"
    )
