"""Ladder-config structural smoke tests (VERDICT r1 item 9).

The BASELINE.json ladder's big configs (Llama-2 7B, ViT-B/16, ResNet-50)
can't run for real on CI hardware, but their shapes and sharding plans can:
``jax.eval_shape`` traces the full init at zero memory cost, and the
adapter's partition-spec resolution is exactly what materialization uses —
so wrong param counts or accidentally-replicated 7B weight matrices fail
here, long before a pod run.
"""

import jax
import jax.numpy as jnp
import pytest

import rocket_tpu as rt
from rocket_tpu.engine.adapter import FlaxModel
from rocket_tpu.models.resnet import resnet50
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.models.vit import ViT, ViTConfig
from rocket_tpu.parallel.mesh import MeshSpec


def _abstract_plan(model, batch_spec, mesh_spec, devices):
    """(abstract_params, resolved PartitionSpecs, param_count) without
    allocating anything."""
    runtime = rt.Runtime(mesh=mesh_spec.build(devices))
    adapter = FlaxModel(model)
    adapter.configure(runtime.mesh, runtime.rules)

    def init_fn():
        batch = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_spec
        )
        params, _ = adapter.init_variables(jax.random.PRNGKey(0), batch)
        return params

    abstract = jax.eval_shape(init_fn)
    specs = adapter.partition_specs(abstract, runtime.rules)
    count = sum(
        int(leaf.size) for leaf in jax.tree_util.tree_leaves(abstract)
    )
    return abstract, specs, count


def _spec_axes(specs):
    axes = set()
    for spec in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    ):
        for part in spec:
            if part is None:
                continue
            parts = part if isinstance(part, tuple) else (part,)
            axes.update(parts)
    return axes


def test_llama2_7b_shape_and_sharding_plan(devices):
    """7B config: correct param count and fsdp x tensor sharded big matrices
    on an 8-device mesh (the BASELINE 'Llama-2 7B LoRA (GSPMD, v4-32)'
    config, structurally)."""
    cfg = TransformerConfig.llama2_7b(scan_layers=True)
    batch_spec = {"tokens": jax.ShapeDtypeStruct((8, 4096), jnp.int32)}
    abstract, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(fsdp=4, tensor=2), devices
    )
    assert 6.5e9 < count < 7.0e9, f"param count {count:,}"
    axes = _spec_axes(specs)
    assert "fsdp" in axes and "tensor" in axes, axes
    # every big (>= hidden^2) matrix must be sharded, not replicated
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    flat_shapes = jax.tree_util.tree_leaves(abstract)
    for leaf, spec in zip(flat_shapes, flat_specs):
        if leaf.size >= cfg.hidden * cfg.hidden:
            assert any(axis is not None for axis in spec), (
                f"{leaf.shape} is replicated"
            )


def test_llama2_7b_lora_plan(devices):
    """LoRA variant: adapters exist, base count grows only by the low-rank
    terms (the 'Llama-2 7B LoRA' ladder config)."""
    cfg = TransformerConfig.llama2_7b(scan_layers=True, lora_rank=8)
    batch_spec = {"tokens": jax.ShapeDtypeStruct((4, 512), jnp.int32)}
    _, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(fsdp=4, tensor=2), devices
    )
    assert 6.5e9 < count < 7.1e9
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(specs)
    ]
    assert any("lora_a" in p for p in paths) and any(
        "lora_b" in p for p in paths
    )


def test_vit_b16_shape_plan(devices):
    """ViT-B/16: ~86M params; encoder matrices carry the transformer
    sharding axes (the 'ViT-B/16 ImageNet bf16' ladder config)."""
    cfg = ViTConfig.b16()
    batch_spec = {"image": jax.ShapeDtypeStruct((8, 224, 224, 3), jnp.float32)}
    _, specs, count = _abstract_plan(
        ViT(cfg), batch_spec, MeshSpec(data=2, fsdp=2, tensor=2), devices
    )
    assert 85e6 < count < 88e6, f"param count {count:,}"
    axes = _spec_axes(specs)
    assert "tensor" in axes or "fsdp" in axes, axes


def test_resnet50_shape_plan(devices):
    """ResNet-50: ~25.6M params; CNNs are data-parallel by design (SURVEY
    §2.2 DDP contract) — params replicated, batch sharded."""
    batch_spec = {"image": jax.ShapeDtypeStruct((8, 224, 224, 3), jnp.float32)}
    _, specs, count = _abstract_plan(
        resnet50(), batch_spec, MeshSpec(data=8), devices
    )
    assert 25.0e6 < count < 26.5e6, f"param count {count:,}"
    assert _spec_axes(specs) == set()  # replicated = the documented contract


def test_gpt2_124m_fused_bench_layout_plan(devices):
    """The tuned single-chip bench layout (bench.py GPT2_TUNE with
    fused_qkv + fused_ce, padded vocab) at REAL scale: correct param count
    and a clean sharding plan, traced at zero memory cost."""
    cfg = TransformerConfig.gpt2_124m(
        vocab_size=50304, fused_qkv=True, fused_ce=True,
        attention_block_q=512, attention_block_k=1024,
    )
    batch_spec = {"tokens": jax.ShapeDtypeStruct((16, 1024), jnp.int32)}
    abstract, specs, count = _abstract_plan(
        TransformerLM(cfg), batch_spec, MeshSpec(data=4, tensor=2), devices
    )
    # 124M-class: tied embed (50304*768) + pos + 12 blocks
    assert 1.2e8 < count < 1.3e8, f"param count {count:,}"
    paths = [
        jax.tree_util.keystr(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(specs)
    ]
    assert any("qkv" in p for p in paths), paths[:8]  # fused projection
    assert not any("'head'" in p for p in paths)      # tied — no extra head
