"""Int8 weight-only quantization (ops.quant): kernel correctness on the
interpret backend, params-tree rewriting, and end-to-end decode parity.

The reference has no quantization or generation path at all; this is a
TPU-native serving addition (W8A16: int8 HBM reads for decode-shaped
matmuls, dequant in VMEM)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.ops.quant import (
    dequantize_int8,
    dequantize_kv_page,
    int8_matmul,
    quantize_int8,
    quantize_kv_page,
    quantize_params,
)


def test_quantize_roundtrip_error_bound(devices):
    w = jax.random.normal(jax.random.PRNGKey(0), (128, 256)) * 3.0
    q, s = quantize_int8(w, axis=0)
    assert q.dtype == jnp.int8 and s.shape == (256,)
    back = dequantize_int8(q, s, axis=0, dtype=jnp.float32)
    # symmetric rounding: per-element error <= half a quantization step
    err = np.abs(np.asarray(w - back))
    bound = np.broadcast_to(np.asarray(s)[None, :] * 0.5 + 1e-7, err.shape)
    np.testing.assert_array_less(err, bound)


def test_quantize_zero_channel(devices):
    w = jnp.zeros((64, 128))
    q, s = quantize_int8(w, axis=0)
    assert np.all(np.asarray(q) == 0)
    back = dequantize_int8(q, s, axis=0, dtype=jnp.float32)
    assert np.all(np.asarray(back) == 0)


@pytest.mark.parametrize("m", [1, 8])
@pytest.mark.parametrize("nk_layout", [False, True])
def test_int8_matmul_kernel_matches_dequant(devices, m, nk_layout):
    """The pallas kernel path (decode-shaped M) must equal the dequant
    einsum bit-for-bit-ish; N=320 is deliberately not a multiple of the
    256 block to exercise the padding."""
    K, N = 128, 320
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (m, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(2), (K, N))
    q, s = quantize_int8(w, axis=0)
    if nk_layout:
        q_in = q.T  # [N, K] — the tied-embedding layout
    else:
        q_in = q
    got = int8_matmul(x, q_in, s, nk_layout=nk_layout, block_n=256)
    # f32 oracle: the kernel accumulates f32 over exact int8 weights and
    # applies the scale AFTER the dot, so it sits closer to this than a
    # bf16-dequantized-weights matmul does
    want = x.astype(jnp.float32) @ (
        q.astype(jnp.float32) * s[None, :]
    )
    assert got.shape == (m, N)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=1.5e-2, atol=1.5e-2,
    )


def test_int8_matmul_large_m_falls_back(devices):
    """Prefill/training shapes (M > KERNEL_MAX_ROWS) take the einsum path
    and still match."""
    K, N = 128, 256
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 128, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(4), (K, N))
    q, s = quantize_int8(w, axis=0)
    got = int8_matmul(x, q, s)
    want = jnp.einsum(
        "bsk,kn->bsn", x, dequantize_int8(q, s, axis=0, dtype=jnp.bfloat16)
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def _tiny_cfg(**kw):
    from rocket_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=48,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot", **kw,
    )


def test_quantize_params_matches_int8_model_structure(devices):
    """quantize_params must produce exactly the tree the weights_int8
    model expects — same paths, shapes, and dtypes as its own init."""
    import flax.linen as nn

    from rocket_tpu.models.transformer import TransformerLM

    prompt = jnp.zeros((1, 4), jnp.int32)
    f32 = TransformerLM(_tiny_cfg())
    params = nn.meta.unbox(
        f32.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    qmodel = TransformerLM(_tiny_cfg(weights_int8=True))
    target = nn.meta.unbox(
        qmodel.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    got = quantize_params(params)
    tgt_shapes = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), target)
    got_shapes = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), got)
    assert tgt_shapes == got_shapes


def test_int8_forward_close_to_f32(devices):
    """Quantized forward logits stay close in relative terms — W8A16 is a
    bandwidth layout, not a different model."""
    import flax.linen as nn

    from rocket_tpu.models.transformer import TransformerLM

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 16)), jnp.int32)
    f32 = TransformerLM(_tiny_cfg())
    params = nn.meta.unbox(
        f32.init(jax.random.PRNGKey(0), {"tokens": tokens})["params"]
    )
    ref = f32.apply({"params": params}, {"tokens": tokens})["logits"]
    qmodel = TransformerLM(_tiny_cfg(weights_int8=True))
    got = qmodel.apply(
        {"params": quantize_params(params)}, {"tokens": tokens}
    )["logits"]
    ref = np.asarray(ref, np.float32)
    got = np.asarray(got, np.float32)
    denom = np.maximum(np.abs(ref).max(), 1e-6)
    assert np.abs(got - ref).max() / denom < 0.12, (
        np.abs(got - ref).max() / denom
    )


def test_int8_generate_end_to_end(devices):
    """KV-cache decode runs with the quantized layout and emits tokens in
    vocab range."""
    import flax.linen as nn

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerLM

    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 8)), jnp.int32
    )
    f32 = TransformerLM(_tiny_cfg())
    params = nn.meta.unbox(
        f32.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    qmodel = TransformerLM(_tiny_cfg(weights_int8=True))
    got = generate(
        qmodel, quantize_params(params), prompt, max_new_tokens=6,
        temperature=0.0,
    )
    assert got.shape == (2, 14)
    assert np.all((np.asarray(got) >= 0) & (np.asarray(got) < 64))


def test_int8_model_hits_kernel_path_at_aligned_hidden(devices):
    """hidden=128 makes K % 128 == 0, so decode-shaped calls inside the
    model take the PALLAS kernel (interpret mode on CPU), not the
    dequant-einsum fallback the other model tests exercise — this is the
    in-model integration coverage for the kernel (dtype, layout, real
    PDense/attend call sites)."""
    import flax.linen as nn

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=256, hidden=128, n_layers=1, n_heads=2, max_seq=32,
        norm="layernorm", mlp="gelu", positions="learned",
        tie_embeddings=True, use_bias=True, attention="dot",
    )
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 256, size=(1, 8)), jnp.int32
    )
    f32 = TransformerLM(cfg)
    params = nn.meta.unbox(
        f32.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    from dataclasses import replace

    qmodel = TransformerLM(replace(cfg, weights_int8=True))
    qparams = quantize_params(params)
    # decode step x is [1, 1, 128]: M=1 <= KERNEL_MAX_ROWS and K=128
    got = generate(qmodel, qparams, prompt, max_new_tokens=4,
                   temperature=0.0)
    want = generate(f32, params, prompt, max_new_tokens=4, temperature=0.0)
    assert got.shape == want.shape == (1, 12)
    # int8 rounding can flip argmax, but on a RANDOM-init model the two
    # paths' logits are near-identical in scale; require the decode to
    # at least run the kernel and emit in-vocab tokens
    assert np.all((np.asarray(got) >= 0) & (np.asarray(got) < 256))


def test_int8_embed_vocab_sharded_one_hot_path(devices):
    """Under a mesh whose rules shard 'vocab' (default: tensor), the int8
    Embed must route through the one-hot matmul like the f32 branch — a
    gather from a vocab-sharded table forces a full rematerialization —
    and still produce the same values as the unsharded gather path."""
    import flax.linen as nn

    from rocket_tpu.models.layers import Embed
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.mesh import MeshSpec

    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 32, size=(2, 8)), jnp.int32
    )
    embed = Embed(32, 16, weights_int8=True)
    params = nn.meta.unbox(
        embed.init(jax.random.PRNGKey(0), tokens)["params"]
    )
    # real (non-zero) quantized values: fill from a dense table
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    q, s = quantize_int8(w, axis=1)
    params = {"embedding_q": q, "embedding_scale": s}
    plain = embed.apply({"params": params}, tokens)
    mesh = MeshSpec(tensor=2, data=4).build(jax.devices())
    with mesh_context(mesh):
        sharded = embed.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(plain, np.float32), np.asarray(sharded, np.float32),
        rtol=1e-2, atol=1e-2,
    )


def test_int8_params_orbax_round_trip(devices, tmp_path):
    """A quantized tree checkpoints and restores bit-exactly through the
    same Orbax path training checkpoints use — int8 leaves and f32
    scales included — and the restored tree still decodes."""
    import flax.linen as nn

    from rocket_tpu.models.generate import generate
    from rocket_tpu.models.transformer import TransformerLM
    from rocket_tpu.persist.orbax_io import CheckpointIO

    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, size=(1, 8)), jnp.int32
    )
    f32 = TransformerLM(_tiny_cfg())
    params = nn.meta.unbox(
        f32.init(jax.random.PRNGKey(0), {"tokens": prompt})["params"]
    )
    qparams = quantize_params(params)

    io = CheckpointIO(use_async=False)
    path = str(tmp_path / "qckpt")
    io.save(path, {"params": qparams})
    io.wait()
    restored = io.restore(path)["params"]
    io.close()

    flat_a = jax.tree_util.tree_leaves_with_path(qparams)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(restored))
    for key, a in flat_a:
        b = flat_b[key]
        assert a.dtype == b.dtype, key
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    qmodel = TransformerLM(_tiny_cfg(weights_int8=True))
    toks = generate(qmodel, restored, prompt, max_new_tokens=4,
                    temperature=0.0)
    assert toks.shape == (1, 12)


def test_weights_int8_rejects_fused_ce(devices):
    with pytest.raises(ValueError, match="inference-only"):
        _tiny_cfg(weights_int8=True, fused_ce=True)


def test_weights_int8_rejects_scan_layers(devices):
    with pytest.raises(ValueError, match="unrolled"):
        _tiny_cfg(weights_int8=True, scan_layers=True)


def test_quantize_params_handles_frozen_dict(devices):
    """FrozenDict checkpoints (flax serialization) must quantize, not
    pass through silently unquantized."""
    import flax.core

    w = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
    got = quantize_params(flax.core.freeze({"dense": {"kernel": w}}))
    assert "kernel_q" in got["dense"] and "kernel_scale" in got["dense"]


def test_quantize_params_rejects_stacked_kernels(devices):
    """nn.scan stacks kernels to [L, K, N]; quantizing that layout would
    silently skip it — it must fail loudly instead."""
    w = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32))
    with pytest.raises(ValueError, match="scan_layers"):
        quantize_params({"blocks": {"mlp": {"kernel": w}}})


def test_quantize_params_unboxes_partitioned_leaves(devices):
    """A sharding-annotated checkpoint carries nn.Partitioned boxes;
    quantize_params must unbox and QUANTIZE those kernels, not let the
    box shield them into a silent f32 passthrough."""
    import flax.linen as nn

    w = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
    boxed = {"dense": {"kernel": nn.Partitioned(w, names=("embed", "mlp"))}}
    got = quantize_params(boxed)
    assert "kernel_q" in got["dense"] and "kernel_scale" in got["dense"]
    assert got["dense"]["kernel_q"].dtype == jnp.int8
    back = dequantize_int8(
        got["dense"]["kernel_q"], got["dense"]["kernel_scale"],
        axis=0, dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        np.asarray(back), np.asarray(w), rtol=2e-2, atol=2e-2
    )


def test_quantize_params_lora_adapters_pass_through(devices):
    """LoRA adapter trees (lora_a/lora_b rank-2 leaves NOT named
    'kernel') must pass through untouched — they are precision-critical
    deltas, and quantize_params documents it leaves them alone."""
    a = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    b = jax.random.normal(jax.random.PRNGKey(2), (4, 32))
    k = jax.random.normal(jax.random.PRNGKey(3), (16, 32))
    tree = {"dense": {"kernel": k, "lora_a": a, "lora_b": b}}
    got = quantize_params(tree)
    assert "kernel_q" in got["dense"]
    np.testing.assert_array_equal(
        np.asarray(got["dense"]["lora_a"]), np.asarray(a)
    )
    np.testing.assert_array_equal(
        np.asarray(got["dense"]["lora_b"]), np.asarray(b)
    )
    assert got["dense"]["lora_a"].dtype == a.dtype


def test_quantize_params_stacked_error_names_the_remedy(devices):
    """The stacked-kernel rejection must tell the user WHAT to do —
    re-export with scan_layers=False — not just that rank 3 is bad."""
    w = jnp.zeros((2, 16, 32))
    with pytest.raises(ValueError) as exc:
        quantize_params({"blocks": {"mlp": {"kernel": w}}})
    msg = str(exc.value)
    assert "scan_layers=False" in msg and "rank 3" in msg


def test_kv_page_quantize_roundtrip_and_shapes(devices):
    """Per-page KV quantization: int8 payload + rank-preserving
    [..., KV, 1] f32 scale, error within half a quantization step, and
    all-zero pages dequantize to exact zeros."""
    kv = jax.random.normal(jax.random.PRNGKey(4), (2, 5, 3, 16)) * 2.0
    q, s = quantize_kv_page(kv)
    assert q.dtype == jnp.int8 and q.shape == kv.shape
    assert s.dtype == jnp.float32 and s.shape == (2, 5, 3, 1)
    back = dequantize_kv_page(q, s, jnp.float32)
    err = np.abs(np.asarray(kv, np.float32) - np.asarray(back))
    bound = np.broadcast_to(np.asarray(s) * 0.5 + 1e-7, err.shape)
    np.testing.assert_array_less(err, bound)
    qz, sz = quantize_kv_page(jnp.zeros((1, 2, 2, 8)))
    assert np.all(np.asarray(qz) == 0)
    assert np.all(np.asarray(dequantize_kv_page(qz, sz)) == 0)


def test_int8_matmul_fallback_warns_once_and_counts(devices):
    """Satellite: a misaligned-K fallback warns ONCE per process (with
    the padding remedy) and increments the tracing counter per trace;
    the by-design large-M fallback is counted but never warns."""
    import warnings

    import rocket_tpu.ops.quant as quant_mod
    from rocket_tpu.observe import trace

    tracer = trace.arm(512)
    try:
        w = jax.random.normal(jax.random.PRNGKey(5), (100, 60))
        q, s = quantize_int8(w, axis=0)
        x = jnp.ones((2, 100), jnp.bfloat16)
        quant_mod._warned_fallback = False
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            int8_matmul(x, q, s)
            int8_matmul(x, q, s)  # second call: counter yes, warning no
        msgs = [str(c.message) for c in caught
                if "int8_matmul" in str(c.message)]
        assert len(msgs) == 1, msgs
        assert "multiple of 128" in msgs[0]  # the remedy
        events = [e for e in tracer.events()
                  if e[1] == "quant/int8_matmul/fallback"]
        assert len(events) >= 2
        assert events[0][5]["reason"].startswith("K % 128")

        # large M: counted with its own reason, no warning even unwarned
        w2 = jax.random.normal(jax.random.PRNGKey(6), (128, 60))
        q2, s2 = quantize_int8(w2, axis=0)
        quant_mod._warned_fallback = False
        before = len([e for e in tracer.events()
                      if e[1] == "quant/int8_matmul/fallback"])
        with warnings.catch_warnings(record=True) as caught2:
            warnings.simplefilter("always")
            int8_matmul(jnp.ones((200, 128), jnp.bfloat16), q2, s2)
        assert not [c for c in caught2 if "int8_matmul" in str(c.message)]
        after = [e for e in tracer.events()
                 if e[1] == "quant/int8_matmul/fallback"]
        assert len(after) == before + 1
        assert "KERNEL_MAX_ROWS" in after[-1][5]["reason"]
    finally:
        trace.disarm()
        quant_mod._warned_fallback = True  # leave quiet for other tests


def test_int8_embed_attend_vocab_sharded_dequant_path(devices):
    """Embed.attend under a vocab-sharding mesh must mirror __call__'s
    _vocab_sharded() routing (ADVICE r4): dequant + einsum (GSPMD can
    shard the LM-head matmul) instead of the pallas int8 kernel, and the
    values must match the unsharded kernel path."""
    import flax.linen as nn

    from rocket_tpu.models.layers import Embed
    from rocket_tpu.parallel.context import mesh_context
    from rocket_tpu.parallel.mesh import MeshSpec

    embed = Embed(32, 16, weights_int8=True)
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    q, s = quantize_int8(w, axis=1)
    params = {"embedding_q": q, "embedding_scale": s}
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(2, 4, 16)), jnp.bfloat16
    )
    plain = embed.apply({"params": params}, x, method="attend")
    mesh = MeshSpec(tensor=2, data=4).build(jax.devices())
    with mesh_context(mesh):
        sharded = embed.apply({"params": params}, x, method="attend")
    assert sharded.shape == (2, 4, 32)
    np.testing.assert_allclose(
        np.asarray(plain, np.float32), np.asarray(sharded, np.float32),
        rtol=1e-2, atol=1e-2,
    )
