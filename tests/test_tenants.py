"""Multi-tenant serving tests — SLO classes end to end (ISSUE 18).

Four layers, mirroring the tentpole:

- units: the weighted-fair admission queue (stride shares, EDF within a
  class, per-class slot/byte budgets, per-class depth counters), the
  Request class vocabulary, and the v2 wire handshake matrix;
- preemption: a batch-class in-flight row evicted at a round boundary
  resumes from its parked ticket and yields EXACTLY ONE typed result,
  bit-identical to the uninterrupted oracle — with and without the
  prefix-cache tier armed;
- per-class observability: ServeCounters / FleetCounters class splits,
  ClassLatency's merge-then-recompute attainment rule, and the
  ``serve_slo/*`` export source;
- the harness: seeded trace synthesis (determinism, diurnal shape,
  shared-prefix sessions, tenant mix), replay against a real loop with
  exactly-once asserted, and the chaos additions (BatchFloodInjector,
  the bursty_arrivals tenant-skew knob).

Spawn-heavy cases (process fleet, kill-between-preempt-and-resume, the
1.25x interactive-TTFT acceptance) live in tests/test_tenants_proc.py
on the heavy tail.
"""

import numpy as np
import pytest

import jax

from rocket_tpu.models.generate import (
    ContinuousBatcher,
    speculative_generate_batched,
)
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.serve import (
    SLO_CLASSES,
    AdmissionQueue,
    ClassLatency,
    Completed,
    DEFAULT_CLASS_WEIGHTS,
    Overloaded,
    PrefixKVStore,
    Request,
    SLOPolicy,
    ServeCounters,
    ServingLoop,
    TenantSpec,
    TraceConfig,
    replay_trace,
    synth_trace,
    wire,
)
from rocket_tpu.serve.autoscale import Autoscaler
from rocket_tpu.testing.chaos import BatchFloodInjector, bursty_arrivals

pytestmark = [pytest.mark.serving, pytest.mark.tenants]

B, P, TOTAL, NDRAFT = 3, 8, 24, 4


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt


def _lm(seed=1):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


@pytest.fixture(scope="module")
def models():
    model, params = _lm(seed=1)
    draft, _ = _lm(seed=1)
    _, dparams = _lm(seed=7)
    return model, draft, params, dparams


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(8, P)).astype(np.int32)


def _factory(models, **kw):
    model, draft, params, dparams = models

    def factory():
        return ContinuousBatcher(
            model, draft, params, dparams,
            total_len=TOTAL, n_draft=NDRAFT, eos_token=None, **kw,
        )

    return factory


def _oracle(models, prompt_row, max_new=TOTAL - P):
    model, draft, params, dparams = models
    toks = speculative_generate_batched(
        model, params, draft, dparams, prompt_row[None, :],
        max_new_tokens=max_new, n_draft=NDRAFT,
    )
    return np.asarray(toks[0])


def _req(rid, prompt, **kw):
    return Request(rid=rid, prompt=prompt, **kw)


# -- units: Request class vocabulary --------------------------------------


class TestRequestClasses:
    def test_default_is_standard_no_tenant(self):
        r = _req(0, np.ones(4, np.int32))
        assert r.slo_class == "standard" and r.tenant is None

    def test_unknown_class_refused(self):
        with pytest.raises(ValueError, match="slo_class"):
            _req(0, np.ones(4, np.int32), slo_class="platinum")

    def test_tenant_and_class_ride(self):
        r = _req(0, np.ones(4, np.int32), tenant="acme",
                 slo_class="interactive")
        assert r.tenant == "acme" and r.slo_class == "interactive"

    def test_class_order_is_priority_order(self):
        assert SLO_CLASSES == ("interactive", "standard", "batch")


# -- units: weighted-fair queue --------------------------------------------


class TestWeightedFairQueue:
    def test_stride_shares_deterministic(self):
        """interactive weight 2, batch weight 1 -> the pop sequence is
        exactly I B I I B I (stride scheduling, ties to the
        higher-priority class)."""
        q = AdmissionQueue(16, weights={"interactive": 2.0,
                                        "standard": 4.0, "batch": 1.0})
        for i in range(4):
            q.offer(_req(f"i{i}", np.ones(4, np.int32),
                         slo_class="interactive"))
        for i in range(2):
            q.offer(_req(f"b{i}", np.ones(4, np.int32), slo_class="batch"))
        order = [q.pop().slo_class[0] for _ in range(6)]
        assert order == ["i", "b", "i", "i", "b", "i"]

    def test_default_weights_favor_interactive_8x(self):
        q = AdmissionQueue(64)
        for i in range(18):
            q.offer(_req(f"i{i}", np.ones(4, np.int32),
                         slo_class="interactive"))
            q.offer(_req(f"b{i}", np.ones(4, np.int32), slo_class="batch"))
        first9 = [q.pop().slo_class for _ in range(9)]
        # 8 interactive pops before batch's first trough
        assert first9.count("interactive") == 8
        assert DEFAULT_CLASS_WEIGHTS["interactive"] \
            / DEFAULT_CLASS_WEIGHTS["batch"] == 8.0

    def test_single_class_stays_fifo(self):
        q = AdmissionQueue(8)
        for i in range(4):
            q.offer(_req(i, np.ones(4, np.int32)))
        assert [q.pop().rid for _ in range(4)] == [0, 1, 2, 3]

    def test_edf_within_class_deadlineless_behind(self):
        q = AdmissionQueue(8)
        q.offer(_req("late", np.ones(4, np.int32), deadline=90.0))
        q.offer(_req("none1", np.ones(4, np.int32)))
        q.offer(_req("soon", np.ones(4, np.int32), deadline=10.0))
        q.offer(_req("none2", np.ones(4, np.int32)))
        order = [q.pop().rid for _ in range(4)]
        assert order == ["soon", "late", "none1", "none2"]

    def test_slot_budget_refuses_only_that_class(self):
        q = AdmissionQueue(8, slot_budget={"batch": 2})
        assert q.offer(_req(0, np.ones(4, np.int32), slo_class="batch"))
        assert q.offer(_req(1, np.ones(4, np.int32), slo_class="batch"))
        assert not q.offer(_req(2, np.ones(4, np.int32),
                                slo_class="batch"))
        # other classes still welcome past batch's budget
        assert q.offer(_req(3, np.ones(4, np.int32),
                            slo_class="interactive"))

    def test_byte_budget_tracks_pop_and_shed(self):
        q = AdmissionQueue(8, byte_budget={"batch": 40})
        big = _req(0, np.ones(8, np.int32), slo_class="batch")    # 32 B
        assert q.offer(big)
        assert q.bytes_queued("batch") == 32
        assert not q.offer(_req(1, np.ones(4, np.int32),          # 16 B
                                slo_class="batch"))
        q.pop()
        assert q.bytes_queued("batch") == 0
        assert q.offer(_req(2, np.ones(4, np.int32), slo_class="batch"))

    def test_urgent_depth_excludes_batch(self):
        q = AdmissionQueue(10)
        for i in range(4):
            q.offer(_req(f"b{i}", np.ones(4, np.int32), slo_class="batch"))
        q.offer(_req("s", np.ones(4, np.int32)))
        assert q.depth() == 5 and q.depth("batch") == 4
        assert q.urgent_waiting() == 1
        assert q.depth_frac == 0.5
        assert q.depth_frac_urgent == 0.1

    def test_bad_knobs_refused(self):
        with pytest.raises(ValueError, match="unknown SLO class"):
            AdmissionQueue(4, weights={"gold": 2.0})
        with pytest.raises(ValueError, match="must be > 0"):
            AdmissionQueue(4, weights={"batch": 0.0})

    def test_per_class_depth_counters_emitted(self):
        from rocket_tpu.observe.trace import Tracer

        tracer = Tracer(capacity=64, enabled=True)
        q = AdmissionQueue(4, name="r0", tracer=tracer, clock=FakeClock())
        q.offer(_req(0, np.ones(4, np.int32), slo_class="batch"))
        q.offer(_req(1, np.ones(4, np.int32), slo_class="interactive"))
        q.pop()   # interactive pops first (smaller stride state tie)

        def series(name):
            key = name.rsplit("/", 1)[-1]
            return [e[5][key] for e in tracer.events() if e[1] == name]

        assert series("serve/queue/r0/batch/depth") == [1.0, 1.0, 1.0]
        assert series("serve/queue/r0/interactive/depth") == [0.0, 1.0,
                                                              0.0]
        assert series("serve/queue/r0/depth") == [1.0, 2.0, 1.0]

    def test_shed_hopeless_is_per_class_order_preserving(self):
        q = AdmissionQueue(8)
        q.offer(_req("b-doomed", np.ones(4, np.int32), slo_class="batch",
                     deadline=1.0))
        q.offer(_req("i-doomed", np.ones(4, np.int32),
                     slo_class="interactive", deadline=1.0))
        q.offer(_req("i-fine", np.ones(4, np.int32),
                     slo_class="interactive", deadline=100.0))
        shed = q.shed_hopeless(now=50.0, floor_s=0.0)
        # SLO_CLASSES scan order: interactive shed reported before batch
        assert [r.rid for r in shed] == ["i-doomed", "b-doomed"]
        assert {r.slo_class for r in shed} == {"interactive", "batch"}
        assert q.pop().rid == "i-fine"


# -- units: wire v2 handshake matrix ---------------------------------------


BUILDER = "rocket_tpu.testing.workers.build_tiny_loop"


class TestWireV2:
    def test_protocol_version_bumped(self):
        # at least the v2 tenant-fields bump; later protocol revisions
        # (v3 trace contexts) only raise it further
        assert wire.PROTOCOL_VERSION >= 2

    def test_old_supervisor_new_worker_refused(self):
        # a v1 supervisor's HELLO against this build's worker-side check
        with pytest.raises(wire.ProtocolMismatch) as ei:
            wire.check_hello({"proto": 1,
                              "spec": wire.WorkerSpec(builder=BUILDER)})
        assert ei.value.theirs == 1 and ei.value.side == "worker"
        assert "Remedy" in str(ei.value)

    def test_old_worker_new_supervisor_refused(self):
        # a v1 worker's READY against this build's supervisor-side check
        with pytest.raises(wire.ProtocolMismatch) as ei:
            wire.check_ready({"proto": 1, "pid": 1})
        assert ei.value.theirs == 1 and ei.value.side == "supervisor"

    def test_matched_versions_pass_both_directions(self):
        spec = wire.WorkerSpec(builder=BUILDER)
        assert wire.check_hello(wire.hello_payload(spec)) is spec
        info = wire.check_ready({"proto": wire.PROTOCOL_VERSION, "pid": 7})
        assert info["pid"] == 7

    def test_submit_frame_carries_tenant_and_class(self):
        clk = FakeClock(100.0)
        req = _req("r1", np.arange(1, 5, dtype=np.int32), tenant="acme",
                   slo_class="interactive", deadline=106.0)
        frame = wire.pack_request(req, clock=clk)
        assert frame["tenant"] == "acme"
        assert frame["slo_class"] == "interactive"
        clk.tick(2.0)
        back = wire.unpack_request(frame, clock=clk)
        assert back.tenant == "acme" and back.slo_class == "interactive"
        assert back.deadline == pytest.approx(108.0)  # remaining held

    def test_v1_frame_unpacks_to_standard(self):
        # a frame missing the v2 keys (what a v1 peer would send) must
        # not crash the unpack — it lands in the standard class
        clk = FakeClock()
        frame = wire.pack_request(_req("r1", np.ones(4, np.int32)),
                                  clock=clk)
        frame.pop("tenant")
        frame.pop("slo_class")
        back = wire.unpack_request(frame, clock=clk)
        assert back.tenant is None and back.slo_class == "standard"


# -- preemption: exactly-once, bit-equal -----------------------------------


class TestBatchPreemption:
    def _flood_then_urgent(self, models, prompts, *, kvstore=None):
        """One batch request decoding in a full loop, then interactive
        arrivals force its preemption; returns (loop, results)."""
        loop = ServingLoop(_factory(models), max_batch=2,
                           queue_capacity=8, kvstore=kvstore)
        batch_req = _req("bat", prompts[0], slo_class="batch",
                         tenant="bulk")
        std_req = _req("std", prompts[1])
        assert loop.submit(batch_req) is None
        assert loop.submit(std_req) is None
        loop.run_round()            # both admitted, one decode round
        assert loop.counters.preempted == 0
        for i in (2, 3):
            assert loop.submit(_req(f"int{i}", prompts[i],
                                    slo_class="interactive")) is None
        loop.run_round()            # urgent 2 > free 0: batch evicted
        assert loop.counters.preempted == 1
        assert len(loop.parked) == 1
        assert loop.parked[0].req.rid == "bat"
        assert loop.parked[0].produced >= 1   # it really decoded first
        results = loop.run_until_idle()
        loop.close()
        return loop, results

    def test_preempted_resumes_exactly_once_bit_equal(self, models,
                                                      prompts):
        loop, results = self._flood_then_urgent(models, prompts)
        assert sorted(r.rid for r in results) == ["bat", "int2", "int3",
                                                  "std"]
        assert all(isinstance(r, Completed) for r in results)
        (bat,) = [r for r in results if r.rid == "bat"]
        assert np.array_equal(bat.tokens, _oracle(models, prompts[0]))
        assert loop.counters.preempted == 1
        assert loop.counters.resumed == 1
        assert loop.counters.class_counts["batch"]["preempted"] == 1
        assert loop.counters.class_counts["batch"]["resumed"] == 1
        # the others were never preempted, and are bit-equal too
        for r in results:
            if r.rid != "bat":
                i = {"std": 1, "int2": 2, "int3": 3}[r.rid]
                assert np.array_equal(r.tokens, _oracle(models, prompts[i]))

    def test_preemption_with_prefix_cache_bit_equal(self, models,
                                                    prompts):
        store = PrefixKVStore(page_tokens=4)
        loop, results = self._flood_then_urgent(models, prompts,
                                                kvstore=store)
        (bat,) = [r for r in results if r.rid == "bat"]
        assert np.array_equal(bat.tokens, _oracle(models, prompts[0]))
        # the preempt exported pages; the resume imported a cached prefix
        assert loop.counters.kv_hits >= 1

    def test_no_preemption_without_urgent_pressure(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=2,
                           queue_capacity=8)
        for i, rid in enumerate(("b0", "b1")):
            assert loop.submit(_req(rid, prompts[i],
                                    slo_class="batch")) is None
        loop.run_round()
        # more batch queued is NOT urgency — batch never preempts batch
        assert loop.submit(_req("b2", prompts[2],
                                slo_class="batch")) is None
        loop.run_round()
        assert loop.counters.preempted == 0
        results = loop.run_until_idle()
        loop.close()
        assert sorted(r.rid for r in results) == ["b0", "b1", "b2"]

    def test_resumed_respects_max_new_tokens(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=2,
                           queue_capacity=8)
        assert loop.submit(_req("bat", prompts[0], slo_class="batch",
                                max_new_tokens=9)) is None
        assert loop.submit(_req("std", prompts[1])) is None
        loop.run_round()
        for i in (2, 3):
            assert loop.submit(_req(f"i{i}", prompts[i],
                                    slo_class="interactive")) is None
        loop.run_round()
        assert loop.counters.preempted == 1
        results = loop.run_until_idle()
        loop.close()
        (bat,) = [r for r in results if r.rid == "bat"]
        assert isinstance(bat, Completed)
        # preempted + resumed stops at the SAME count as uninterrupted
        # (tokens is the fixed-length buffer row; n_tok marks the end)
        oracle = _oracle(models, prompts[0], max_new=9)
        assert bat.n_tok == oracle.shape[0] == P + 9
        assert np.array_equal(bat.tokens[:bat.n_tok], oracle)

    def test_parked_deadline_expiry_ships_partial_once(self, models,
                                                       prompts):
        from rocket_tpu.serve import DeadlineExceeded

        clk = FakeClock()
        loop = ServingLoop(_factory(models), max_batch=2,
                           queue_capacity=8, clock=clk)
        assert loop.submit(_req("bat", prompts[0], slo_class="batch",
                                deadline=1e4)) is None
        assert loop.submit(_req("std", prompts[1])) is None
        loop.run_round()
        for i in (2, 3):
            assert loop.submit(_req(f"i{i}", prompts[i],
                                    slo_class="interactive")) is None
        loop.run_round()
        assert len(loop.parked) == 1
        clk.tick(2e4)               # the parked ticket's deadline passes
        results = loop.run_until_idle()
        loop.close()
        (bat,) = [r for r in results if r.rid == "bat"]
        assert isinstance(bat, DeadlineExceeded)
        assert bat.stage == "decode"
        assert bat.tokens is not None and bat.n_tok > P  # partial rides
        assert sum(1 for r in results if r.rid == "bat") == 1

    def test_salvage_returns_parked_original(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=2,
                           queue_capacity=8)
        req = _req("bat", prompts[0], slo_class="batch")
        assert loop.submit(req) is None
        assert loop.submit(_req("std", prompts[1])) is None
        loop.run_round()
        for i in (2, 3):
            assert loop.submit(_req(f"i{i}", prompts[i],
                                    slo_class="interactive")) is None
        loop.run_round()
        assert len(loop.parked) == 1
        salvaged = loop.salvage()
        loop.close()
        # the ORIGINAL request object comes back — a healthy replica
        # re-serves it from scratch, bit-equal by determinism
        assert req in salvaged
        assert loop.parked == []


# -- per-class policy feeds -------------------------------------------------


class TestUrgentPolicyFeed:
    def test_batch_backlog_never_degrades(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=1,
                           queue_capacity=8)
        for i in range(7):
            assert loop.submit(_req(f"b{i}", prompts[i % 8],
                                    slo_class="batch")) is None
        loop.run_round()
        # deep batch backlog, zero urgent depth: full quality holds
        assert loop.queue.depth_frac >= 0.5
        assert loop.policy.level == 0
        loop.run_until_idle()
        loop.close()

    def test_standard_backlog_still_degrades(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=1,
                           queue_capacity=8)
        for i in range(7):
            assert loop.submit(_req(f"s{i}", prompts[i % 8])) is None
        loop.run_round()
        assert loop.policy.level >= 1
        loop.run_until_idle()
        loop.close()


class TestAutoscalerClassPolicies:
    def _auto(self, **kw):
        return Autoscaler(router=None, spawn_fn=lambda rid: None,
                          policy=SLOPolicy(ttft_p95_ms=1e9),
                          collect_fn=dict, **kw)

    def test_interactive_breach_trips(self):
        auto = self._auto(class_policies={
            "interactive": SLOPolicy(ttft_p95_ms=500.0)})
        assert auto._breached({"serve_slo/interactive/ttft_ms/p95": 900.0})
        assert auto.counters.breach_class_ttft == 1
        assert "breach_class_ttft" in auto.counters.snapshot()

    def test_batch_breach_never_scales_up(self):
        auto = self._auto(class_policies={
            "batch": SLOPolicy(ttft_p95_ms=1.0)})
        assert not auto._breached({"serve_slo/batch/ttft_ms/p95": 1e6})
        assert auto.counters.breach_class_ttft == 0


# -- per-class observability ------------------------------------------------


class TestClassCounters:
    def test_snapshot_flattens_class_events(self):
        c = ServeCounters()
        c.observe_class("interactive", "submitted")
        c.observe_class("batch", "preempted")
        c.observe_class("batch", "resumed", 2)
        snap = c.snapshot()
        assert snap["class/interactive/submitted"] == 1.0
        assert snap["class/batch/preempted"] == 1.0
        assert snap["class/batch/resumed"] == 2.0

    def test_unknown_class_lands_in_standard(self):
        c = ServeCounters()
        c.observe_class("mystery", "shed")
        assert c.class_counts["standard"]["shed"] == 1

    def test_loop_records_per_class(self, models, prompts):
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=8)
        assert loop.submit(_req("i0", prompts[0],
                                slo_class="interactive")) is None
        loop.run_until_idle()
        loop.close()
        assert loop.counters.class_counts["interactive"]["submitted"] == 1
        assert loop.counters.class_counts["interactive"]["completed"] == 1
        assert loop.slo_latency.ttft_ms["interactive"].count == 1
        assert loop.slo_latency.e2e_ms["interactive"].count == 1


class TestClassLatencyMerge:
    def test_attainment_recomputed_over_merged_window(self):
        # replica A: 2 good interactive samples; replica B: 8 bad ones.
        # Merge rule: recompute over the union -> 0.2, NEVER the 0.5 an
        # average of per-replica fractions would report.
        a, b = ClassLatency(), ClassLatency()
        for _ in range(2):
            a.record_ttft("interactive", 100.0)
        for _ in range(8):
            b.record_ttft("interactive", 5000.0)
        assert a.attainment()["interactive"] == 1.0
        assert b.attainment()["interactive"] == 0.0
        a.merge(b)
        assert a.attainment()["interactive"] == pytest.approx(0.2)

    def test_empty_class_exports_nothing(self):
        lat = ClassLatency()
        lat.record_ttft("interactive", 10.0)
        att = lat.attainment()
        assert "batch" not in att and "standard" not in att

    def test_summary_keys_per_class(self):
        lat = ClassLatency()
        lat.record_ttft("batch", 50.0)
        lat.record_e2e("batch", 80.0)
        s = lat.summary()
        assert s["batch/ttft_ms/p50"] == 50.0
        assert s["batch/e2e_ms/p95"] == 80.0


class TestSLOExportSource:
    def test_register_and_collect(self):
        from rocket_tpu.observe import export

        class Provider:
            def __init__(self):
                self.slo_latency = ClassLatency()
                self.counters = ServeCounters()

        prov = Provider()
        prov.slo_latency.record_ttft("interactive", 100.0)
        prov.counters.observe_class("interactive", "completed")
        try:
            from rocket_tpu.serve import register_slo_source

            register_slo_source(prov, name="serve_slo_test")
            out = export.collect()
            assert out["serve_slo_test/interactive/ttft_attainment"] == 1.0
            assert out["serve_slo_test/interactive/ttft_ms/p95"] == 100.0
            assert out["serve_slo_test/interactive/completed"] == 1.0
        finally:
            export.unregister_source("serve_slo_test")


# -- the harness: trace synthesis + replay ----------------------------------


_MIX = (TenantSpec("acme", "interactive", share=3.0, sessions=2,
                   deadline_s=30.0),
        TenantSpec("corp", "standard", share=2.0),
        TenantSpec("bulk", "batch", share=1.0))


class TestSynthTrace:
    def test_seeded_determinism(self):
        cfg = TraceConfig(duration_s=30.0, base_rate=3.0, burst_rate=4.0)
        t1 = synth_trace(_MIX, cfg, seed=11)
        t2 = synth_trace(_MIX, cfg, seed=11)
        assert len(t1) == len(t2) > 0
        for a, b in zip(t1, t2):
            assert a.t == b.t and a.rid == b.rid
            assert np.array_equal(a.prompt, b.prompt)
        t3 = synth_trace(_MIX, cfg, seed=12)
        assert [e.rid for e in t3] != [e.rid for e in t1]

    def test_arrivals_sorted_and_bounded(self):
        cfg = TraceConfig(duration_s=20.0, base_rate=5.0)
        tr = synth_trace(_MIX, cfg, seed=0)
        ts = [e.t for e in tr]
        assert ts == sorted(ts)
        assert all(0.0 <= t < 20.0 for t in ts)

    def test_diurnal_tide_shapes_arrivals(self):
        # amp 0.9, period == duration: the first half (sin > 0) must
        # carry visibly more arrivals than the second half
        cfg = TraceConfig(duration_s=60.0, base_rate=5.0,
                          diurnal_amp=0.9, diurnal_period_s=60.0)
        tr = synth_trace([TenantSpec("t")], cfg, seed=4)
        first = sum(1 for e in tr if e.t < 30.0)
        second = len(tr) - first
        assert first > second * 1.5

    def test_sessions_share_prefix(self):
        cfg = TraceConfig(duration_s=30.0, base_rate=4.0,
                          shared_prefix_len=6)
        tr = synth_trace([TenantSpec("a", sessions=1)], cfg, seed=2)
        turns = [e for e in tr if e.session is not None]
        assert len(turns) >= 2
        sid = turns[0].session
        prefix = turns[0].prompt[:6]
        for e in turns:
            assert e.session == sid
            assert np.array_equal(e.prompt[:6], prefix)

    def test_tenant_mix_and_classes(self):
        cfg = TraceConfig(duration_s=60.0, base_rate=5.0)
        tr = synth_trace(_MIX, cfg, seed=9)
        by = {t.name: sum(1 for e in tr if e.tenant == t.name)
              for t in _MIX}
        assert by["acme"] > by["bulk"]          # 3x the share
        assert {e.slo_class for e in tr if e.tenant == "bulk"} \
            == {"batch"}
        # relative deadlines ride the event, not the wall clock
        assert all(e.deadline_s == 30.0 for e in tr
                   if e.tenant == "acme")

    def test_heavy_tail_prompt_lengths(self):
        cfg = TraceConfig(duration_s=120.0, base_rate=5.0,
                          prompt_len_min=4, prompt_len_max=16,
                          prompt_tail_alpha=1.5)
        tr = synth_trace([TenantSpec("t")], cfg, seed=3)
        lens = [int(e.prompt.shape[0]) for e in tr]
        assert min(lens) >= 4 and max(lens) <= 16
        assert len(set(lens)) > 3               # a real spread, not flat

    def test_empty_mix_refused(self):
        with pytest.raises(ValueError, match="TenantSpec"):
            synth_trace([], TraceConfig())


class TestReplay:
    def test_replay_reports_per_class_exactly_once(self, models):
        loop = ServingLoop(_factory(models), max_batch=B,
                           queue_capacity=32)
        cfg = TraceConfig(duration_s=8.0, base_rate=2.0,
                          prompt_len_min=4, prompt_len_max=10,
                          max_new_max=4)
        tr = synth_trace(_MIX, cfg, seed=21)
        rep = replay_trace(tr, loop, speed=400.0)
        loop.close()
        assert rep.submitted == len(tr)
        assert rep.completed + sum(
            st["shed"] for st in rep.per_class.values()) == len(tr)
        assert rep.goodput_per_chip > 0.0
        for cls, st in rep.per_class.items():
            assert st["submitted"] >= st["completed"]
            assert cls in SLO_CLASSES

    def test_replay_asserts_on_duplicate_result(self):
        class EchoTwice:
            def __init__(self):
                self._out = []

            def submit(self, req):
                self._out.extend([
                    Completed(req.rid, 0.0, tokens=req.prompt,
                              n_tok=4, meta={}),
                    Completed(req.rid, 0.0, tokens=req.prompt,
                              n_tok=4, meta={}),
                ])
                return None

            def run_round(self):
                return False

            def drain_results(self):
                out, self._out = self._out, []
                return out

        tr = synth_trace([TenantSpec("t")],
                         TraceConfig(duration_s=2.0, base_rate=2.0),
                         seed=1)
        with pytest.raises(AssertionError, match="exactly-once"):
            replay_trace(tr, EchoTwice(), speed=1e4)


# -- chaos: flood injector + skew knob --------------------------------------


class TestBatchFlood:
    class _Sink:
        def __init__(self, refuse_after=None):
            self.reqs = []
            self._refuse_after = refuse_after

        def submit(self, req):
            if self._refuse_after is not None \
                    and len(self.reqs) >= self._refuse_after:
                return Overloaded(req.rid, 0.0, reason="queue full",
                                  meta={})
            self.reqs.append(req)
            return None

    def test_flood_is_batch_class_and_deterministic(self):
        a, b = self._Sink(), self._Sink()
        for sink in (a, b):
            inj = BatchFloodInjector(sink, per_tick=2, prompt_len=6)
            for _ in range(3):
                inj.tick()
            assert inj.submitted == 6 and inj.rejected == 0
        assert [r.rid for r in a.reqs] == [r.rid for r in b.reqs]
        for ra, rb in zip(a.reqs, b.reqs):
            assert ra.slo_class == "batch" and ra.tenant == "flood"
            assert np.array_equal(ra.prompt, rb.prompt)

    def test_flood_schedule_respected(self):
        sink = self._Sink()
        inj = BatchFloodInjector(sink, per_tick=3, flood_on=(1,))
        assert inj.tick() == 0
        assert inj.tick() == 3
        assert inj.tick() == 0
        assert inj.submitted == 3

    def test_rejections_counted_not_raised(self):
        sink = self._Sink(refuse_after=2)
        inj = BatchFloodInjector(sink, per_tick=4)
        assert inj.tick() == 2
        assert inj.submitted == 2 and inj.rejected == 2


class TestTenantSkewKnob:
    def test_plain_list_without_knob(self):
        arr = bursty_arrivals(4, burst=2, gap_s=1.0)
        assert arr == [0.0, 0.0, 1.0, 1.0]

    def test_skew_labels_deterministic_9_to_1(self):
        out = bursty_arrivals(20, burst=5, gap_s=1.0,
                              tenants=[("heavy", 9.0), ("light", 1.0)])
        labels = [name for _, name in out]
        assert labels.count("heavy") == 18 and labels.count("light") == 2
        # offsets unchanged vs the knobless call
        assert [t for t, _ in out] == bursty_arrivals(20, burst=5,
                                                      gap_s=1.0)
        # deterministic: same call, same labels
        assert out == bursty_arrivals(20, burst=5, gap_s=1.0,
                                      tenants=[("heavy", 9.0),
                                               ("light", 1.0)])

    def test_bad_shares_refused(self):
        with pytest.raises(ValueError, match="positive shares"):
            bursty_arrivals(4, burst=2, gap_s=1.0, tenants=[("t", 0.0)])
