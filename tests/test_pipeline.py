"""End-to-end pipeline tests — the full capsule tree on the 8-device CPU
mesh (SURVEY §4: the MNIST config shape as CI smoke test)."""

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.lenet import LeNet
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.observe.backends import MemoryBackend


def synthetic_classification(n=512, num_classes=4, dim=16, seed=0):
    """Linearly separable synthetic data — converges fast, no downloads."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(num_classes, dim)).astype(np.float32) * 3.0
    labels = rng.integers(0, num_classes, size=n)
    x = protos[labels] + rng.normal(size=(n, dim)).astype(np.float32)
    return {"x": x.astype(np.float32), "label": labels.astype(np.int32)}


class MLP(__import__("flax").linen.Module):
    num_classes: int = 4

    @__import__("flax").linen.compact
    def __call__(self, batch, train: bool = False):
        import flax.linen as nn

        x = batch["x"]
        x = nn.relu(nn.Dense(32)(x))
        logits = nn.Dense(self.num_classes)(x)
        out = rt.Attributes(batch)
        out["logits"] = logits
        return out


class Accuracy(rt.Metric):
    """The reference example's metric (examples/mnist.py:20-39)."""

    def __init__(self, tag="accuracy", **kwargs):
        super().__init__(**kwargs)
        self._tag = tag
        self._correct = 0
        self._count = 0
        self.last = None

    def launch(self, attrs=None):
        batch = attrs.batch
        pred = np.asarray(batch["logits"]).argmax(-1)
        label = np.asarray(batch["label"])
        self._correct += int((pred == label).sum())
        self._count += len(label)

    def reset(self, attrs=None):
        if not self._count:
            return
        value = self._correct / self._count
        self.last = value
        if attrs is not None and attrs.tracker is not None:
            attrs.tracker.scalars.append(
                rt.Attributes(step=self._step, data={self._tag: value})
            )
        self._correct = 0
        self._count = 0


def build_pipeline(tmp_path, data, *, epochs=3, batch=64, backend=None, seed=0):
    backend = backend or MemoryBackend()
    train_ds = rt.Dataset(rt.ArraySource(data), batch_size=batch, shuffle=True, seed=3)
    eval_ds = rt.Dataset(rt.ArraySource(data), batch_size=batch)
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=5e-2),
        ],
    )
    acc = Accuracy()
    looper_train = rt.Looper(
        capsules=[train_ds, model, rt.Tracker(backend)], progress=False
    )
    looper_eval = rt.Looper(
        capsules=[
            eval_ds,
            model,
            rt.Meter(keys=["logits", "label"], capsules=[acc]),
            rt.Tracker(backend),
        ],
        grad_enabled=False,
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper_train, looper_eval],
        tag="e2e",
        num_epochs=epochs,
        project_root=str(tmp_path),
        seed=seed,
    )
    return launcher, acc, backend


def test_full_pipeline_converges(tmp_path, devices):
    data = synthetic_classification()
    launcher, acc, backend = build_pipeline(tmp_path, data)
    launcher.launch()
    assert acc.last is not None and acc.last > 0.95, f"accuracy {acc.last}"
    # tracker got loss records
    tags = {tag for _, rec in backend.scalars for tag in rec}
    assert "losses/ce" in tags and "accuracy" in tags


def test_print_launcher_config_dump(tmp_path):
    data = synthetic_classification(n=64)
    launcher, _, _ = build_pipeline(tmp_path, data)
    text = repr(launcher)
    # reference §3.5: repr recursively dumps the full tree config
    for fragment in ("Launcher", "Looper", "Module", "Dataset", "Tracker"):
        assert fragment in text


def test_versioned_project_dirs(tmp_path):
    data = synthetic_classification(n=64, num_classes=2)
    for expected in ("v0", "v1"):
        launcher, _, _ = build_pipeline(tmp_path, data, epochs=1)
        launcher.launch()
        assert (tmp_path / "e2e" / expected).is_dir()


def test_grad_accum_pipeline(tmp_path):
    data = synthetic_classification(n=256)
    backend = MemoryBackend()
    train_ds = rt.Dataset(rt.ArraySource(data), batch_size=32, shuffle=True)
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=5e-2),
        ],
    )
    looper = rt.Looper(capsules=[train_ds, model, rt.Tracker(backend)], progress=False)
    launcher = rt.Launcher(
        capsules=[looper],
        tag="accum",
        num_epochs=2,
        gradient_accumulation_steps=4,
        project_root=str(tmp_path),
    )
    launcher.launch()
    # 256/32 = 8 micro-batches/epoch -> 2 effective steps/epoch -> 4 total
    assert model.step == 4
