"""Multi-tenant serving — cross-process proofs + the SLO bench guard
(spawn-heavy, heavy tail; ISSUE 18 acceptance).

The unit zone lives in ``tests/test_tenants.py``; this file proves the
tentpole where it is actually dangerous:

- kill BETWEEN preempt and resume (tier-1 acceptance): a batch-class
  request is preempted inside a worker process (its resume ticket is
  worker-side state), the worker is SIGKILLed before the resume, and
  supervision still resolves the request to EXACTLY ONE typed result —
  bit-equal to the cold oracle, because the supervisor's request shadow
  salvages the ORIGINAL request and determinism does the rest;
- per-class telemetry across the wire (tier-1): a worker's class
  counters and ClassLatency histograms ride the STEP reply and merge
  fleet-wide under the documented merge-then-recompute rule;
- the SLO bench guard (tier-1 acceptance): interactive p95 TTFT with a
  deterministic batch flood underneath stays within 1.25x of the
  batch-free baseline, while the flood's batch work actually completes
  in the troughs;
- mixed-tenant trace replay over the REAL process fleet (``slow``):
  the seeded loadgen drives two worker processes through a router and
  every event resolves exactly once with per-class attainment reported.
"""

import time

import numpy as np
import pytest

from rocket_tpu.serve import (
    Completed,
    FleetRouter,
    ProcReplica,
    Request,
    TenantSpec,
    TraceConfig,
    WorkerSpec,
    replay_trace,
    synth_trace,
)
from rocket_tpu.testing import workers as tw
from rocket_tpu.testing.chaos import BatchFloodInjector

pytestmark = [pytest.mark.tenants, pytest.mark.procfleet,
              pytest.mark.serving]

BUILDER = "rocket_tpu.testing.workers:build_tiny_loop"
SPAWN_S = 240.0     # worker spawn includes a jax import + model init


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(17)
    return rng.integers(1, tw.VOCAB, size=(8, tw.P)).astype(np.int32)


def _await_corpse(rep, timeout=10.0):
    deadline = time.monotonic() + timeout
    while rep.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.proc.poll() is not None, "worker survived SIGKILL"


def _cold_serve(prompt_rows):
    """rid-index -> (tokens, n_tok) from a fresh in-process loop over
    the SAME builder the workers run — the uninterrupted oracle."""
    loop = tw.build_tiny_loop()
    try:
        for i, p in enumerate(prompt_rows):
            assert loop.submit(Request(rid=i, prompt=p)) is None
        out = {}
        for res in loop.run_until_idle():
            assert isinstance(res, Completed), res
            out[res.rid] = np.asarray(res.tokens)
    finally:
        loop.close()
    return out


# -- kill between preempt and resume (tier-1 acceptance) ---------------------


def test_preempt_then_kill_resolves_exactly_once_bit_equal(prompts):
    """Acceptance: the preempted batch request's resume ticket dies with
    the SIGKILLed worker; the supervisor shadow salvages the ORIGINAL
    request, the heal re-routes it, and the caller still observes
    exactly one typed result — bit-equal to never having been
    preempted (or killed) at all."""
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"max_batch": 2, "kvstore_page_tokens": 3})
    a = ProcReplica(spec, "ten-a", spawn_timeout_s=SPAWN_S,
                    rpc_timeout_s=SPAWN_S)
    b = ProcReplica(spec, "ten-b", spawn_timeout_s=SPAWN_S,
                    rpc_timeout_s=SPAWN_S)
    router = FleetRouter([a, b])
    try:
        # pin the scenario to worker a: a batch row decoding next to a
        # standard row, then two interactive arrivals force preemption
        assert a.submit(Request(rid="bat", prompt=prompts[0],
                                slo_class="batch", tenant="bulk"))
        assert a.submit(Request(rid="std", prompt=prompts[1]))
        a.pump()                       # both admitted, one decode round
        for i, rid in ((2, "i2"), (3, "i3")):
            assert a.submit(Request(rid=rid, prompt=prompts[i],
                                    slo_class="interactive"))
        a.pump()                       # round boundary: batch evicted
        pre_kill = dict(a.counters)    # snapshot BEFORE the respawn reset
        assert pre_kill.get("preempted") == 1.0
        assert pre_kill.get("class/batch/preempted") == 1.0

        # the window under test: ticket parked worker-side, no result
        a.kill()
        _await_corpse(a)

        results = router.run_until_idle()
        assert sorted(r.rid for r in results) == ["bat", "i2", "i3",
                                                  "std"]
        assert all(isinstance(r, Completed) for r in results), results
        oracle = _cold_serve([prompts[i] for i in range(4)])
        for rid, i in (("bat", 0), ("std", 1), ("i2", 2), ("i3", 3)):
            (res,) = [r for r in results if r.rid == rid]
            assert np.array_equal(np.asarray(res.tokens), oracle[i]), rid
        assert router.counters.heals == 1
        assert a.spawns == 2           # the corpse was respawned
    finally:
        router.close()


# -- per-class telemetry across the wire (tier-1) ----------------------------


def test_class_counters_and_slo_latency_cross_process(prompts):
    spec = WorkerSpec(builder=BUILDER)
    rep = ProcReplica(spec, "ten-t", spawn_timeout_s=SPAWN_S,
                      rpc_timeout_s=SPAWN_S)
    router = FleetRouter([rep])
    try:
        assert router.submit(Request(rid="i0", prompt=prompts[0],
                                     tenant="acme",
                                     slo_class="interactive")) is None
        (res,) = router.run_until_idle()
        assert isinstance(res, Completed)
        # the worker's per-class counters rode the STEP reply
        assert rep.counters.get("class/interactive/submitted") == 1.0
        assert rep.counters.get("class/interactive/completed") == 1.0
        # ...and so did its ClassLatency; the router merges windows
        merged = router.slo_latency()
        assert merged.ttft_ms["interactive"].count == 1
        assert merged.e2e_ms["interactive"].count == 1
        att = merged.attainment({"interactive": 1e9})
        assert att["interactive"] == 1.0
        # per-class routing split on the fleet side
        snap = router.counters.snapshot()
        assert snap["class/interactive/routed"] == 1.0
    finally:
        router.close()


# -- the SLO bench guard (tier-1 acceptance) ---------------------------------


def _interactive_trace():
    return synth_trace(
        [TenantSpec("acme", "interactive", share=1.0)],
        TraceConfig(duration_s=6.0, base_rate=2.5, prompt_len_min=4,
                    prompt_len_max=10, max_new_min=2, max_new_max=4,
                    vocab=tw.VOCAB),
        seed=29)


def _warm(loop):
    """Serve a couple of throwaway requests so every measured TTFT is a
    warm one (compiles otherwise land in the first sample)."""
    rng = np.random.default_rng(5)
    for i in range(2):
        p = rng.integers(1, tw.VOCAB, size=6).astype(np.int32)
        assert loop.submit(Request(rid=f"warm{i}", prompt=p,
                                   max_new_tokens=3)) is None
    loop.run_until_idle()


def _interactive_p95(flood):
    """Replay the SAME seeded interactive trace; ``flood`` adds the
    deterministic batch flood under it.  Returns (p95_ms, loop)."""
    loop = tw.build_tiny_loop(max_batch=3, queue_capacity=32,
                              class_slot_budget={"batch": 6})
    _warm(loop)
    trace = _interactive_trace()
    if flood:
        inj = BatchFloodInjector(loop, per_tick=1, prompt_len=6,
                                 max_new_tokens=8, vocab=tw.VOCAB)

        def pump():
            inj.tick()
            return loop.run_round()

        replay_trace(trace, loop, speed=30.0, pump=pump)
        assert inj.submitted > 0
    else:
        replay_trace(trace, loop, speed=30.0)
    p95 = loop.slo_latency.ttft_ms["interactive"].percentile(95)
    assert p95 is not None
    return float(p95), loop


def test_interactive_p95_within_1p25x_under_batch_flood():
    """Acceptance: with a batch flood filling every trough, interactive
    p95 TTFT stays within 1.25x of the batch-free baseline (plus a
    small absolute CPU-noise floor), the flood is held back by
    weighted fairness + preemption rather than starved out — batch
    work really completes underneath."""
    base_p95, base_loop = _interactive_p95(flood=False)
    base_loop.close()
    flood_p95, flood_loop = _interactive_p95(flood=True)
    counters = flood_loop.counters
    flood_loop.close()
    assert flood_p95 <= base_p95 * 1.25 + 10.0, (
        f"interactive p95 {flood_p95:.1f}ms under flood vs "
        f"{base_p95:.1f}ms batch-free"
    )
    # the troughs were actually filled: batch completed AND the fairness
    # machinery (not idle luck) was exercised
    assert counters.class_counts["batch"]["completed"] >= 1
    assert counters.class_counts["interactive"]["completed"] > 0


# -- mixed-tenant replay over the real process fleet (slow) ------------------


@pytest.mark.slow
@pytest.mark.resilience
def test_trace_replay_over_process_fleet(prompts):
    """The loadgen's stated purpose: a seeded mixed-tenant trace drives
    TWO worker processes through the router; every event resolves to
    exactly one typed result (replay_trace asserts it) and the report
    carries per-class attainment and goodput-per-chip."""
    spec = WorkerSpec(builder=BUILDER)
    reps = [ProcReplica(spec, f"ten-f{i}", spawn_timeout_s=SPAWN_S,
                        rpc_timeout_s=SPAWN_S) for i in range(2)]
    router = FleetRouter(reps)
    try:
        trace = synth_trace(
            [TenantSpec("acme", "interactive", share=3.0, sessions=2),
             TenantSpec("corp", "standard", share=2.0),
             TenantSpec("bulk", "batch", share=1.0)],
            TraceConfig(duration_s=6.0, base_rate=2.0, prompt_len_min=4,
                        prompt_len_max=10, shared_prefix_len=4,
                        max_new_min=2, max_new_max=4, vocab=tw.VOCAB),
            seed=31)
        report = replay_trace(trace, router, speed=10.0, chips=2)
        assert report.submitted == len(trace)
        assert report.completed > 0
        assert report.goodput_per_chip > 0.0
        for cls, stats in report.per_class.items():
            assert stats["submitted"] > 0
            if stats["completed"] > 0:
                assert "ttft_p95_ms" in stats, (cls, stats)
        # the merged fleet view fed the report's attainment gauges
        assert router.slo_latency().ttft_ms["interactive"].count > 0
    finally:
        router.close()
