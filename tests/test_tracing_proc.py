"""Distributed request tracing — cross-process proofs (spawn-heavy,
heavy tail).

The unit zone (TraceContext, wire v3 frames, OffsetEstimator, flow
events, critpath math, timeline stitching over synthetic dumps) lives in
``tests/test_tracectx.py``; this file proves the tentpole end to end
across REAL process boundaries:

- stitched-timeline accounting (tier-1 acceptance): a request served
  through a pool-armed prefill replica AND a decode worker process
  yields ONE clock-aligned timeline whose critical-path segment sum
  matches the supervisor-measured e2e within 5%, with a valid
  single-id ``s -> t... -> f`` flow chain spanning both lanes;
- heal on the critical path (tier-1 acceptance): a request surviving a
  SIGKILL + heal mid-decode shows the heal segment dominating its
  stitched critical path, and the ``serve_critpath/*`` export
  attributes it.
"""

import json
import os
import time

import numpy as np
import pytest

from rocket_tpu.observe import trace as obs_trace
from rocket_tpu.observe.critpath import (
    aggregate,
    analyze_chrome,
    register_critpath_source,
)
from rocket_tpu.observe.export import (
    collect,
    prometheus_text,
    unregister_source,
)
from rocket_tpu.observe.timeline import request_timelines, stitch_timeline
from rocket_tpu.serve import (
    Completed,
    FleetRouter,
    KVPagePool,
    KVPoolClient,
    PrefillReplica,
    ProcReplica,
    Request,
    WorkerSpec,
    write_offsets,
)
from rocket_tpu.testing import workers as tw

pytestmark = [pytest.mark.tracing, pytest.mark.procfleet,
              pytest.mark.serving]

BUILDER = "rocket_tpu.testing.workers:build_tiny_loop"
SPAWN_S = 240.0     # worker spawn includes a jax import + model init
PAGE = 3


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(31)
    return rng.integers(1, tw.VOCAB, size=(8, tw.P)).astype(np.int32)


@pytest.fixture
def sup_tracer():
    """The supervisor-side global tracer, armed + anchored + labeled the
    way a serving binary would before spawning traced workers."""
    tracer = obs_trace.arm(1 << 15)
    tracer.clear()
    tracer.set_anchor()
    saved = dict(tracer.meta)
    tracer.meta.update({"role": "supervisor", "pid": os.getpid()})
    yield tracer
    tracer.clear()
    tracer.meta.clear()
    tracer.meta.update(saved)
    obs_trace.disarm()


def _await_corpse(rep, timeout=10.0):
    deadline = time.monotonic() + timeout
    while rep.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.proc.poll() is not None, "worker survived SIGKILL"


def _drive_until(router, want_rid, timeout_s=180.0):
    """Pump the router until ``want_rid``'s typed result lands; returns
    (result, supervisor-measured e2e from this call's entry in ms)."""
    t0 = time.perf_counter_ns()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        router.pump()
        for res in router.drain_results():
            if res.rid == want_rid:
                return res, (time.perf_counter_ns() - t0) / 1e6
    raise AssertionError(f"no result for {want_rid!r} within "
                         f"{timeout_s}s")


# -- stitched-timeline accounting (tier-1 acceptance) -------------------------


def test_stitched_timeline_accounts_supervisor_e2e(tmp_path, sup_tracer,
                                                   prompts):
    """Acceptance: one request through the pool-armed prefill lane and a
    TRACED decode worker process stitches into ONE timeline — worker
    events rebased by the estimated clock offset — whose per-request
    segment sum matches the supervisor's own e2e stopwatch within 5%,
    and whose flow chain is a valid single-id ``s -> t... -> f``."""
    from rocket_tpu.models.generate import ContinuousBatcher

    trace_dir = str(tmp_path)
    pool = KVPagePool(page_tokens=PAGE)
    spec = WorkerSpec(builder=BUILDER,
                      kwargs={"kvstore_page_tokens": PAGE},
                      kvpool=pool.address)
    decode = ProcReplica(spec, "tl-d0", spawn_timeout_s=SPAWN_S,
                         rpc_timeout_s=SPAWN_S,
                         env={"ROCKET_TPU_TRACE_DIR": trace_dir})
    model, draft, params, dparams = tw.tiny_models()

    def bat_factory():
        return ContinuousBatcher(model, draft, params, dparams,
                                 total_len=tw.TOTAL, n_draft=tw.NDRAFT,
                                 eos_token=None)

    prefill = PrefillReplica(bat_factory, "tl-p0",
                             kvpool=KVPoolClient.connect(pool.address),
                             page_tokens=PAGE, tracer=sup_tracer)
    router = FleetRouter([decode], prefill_replicas=[prefill],
                         prefill_threshold=None, tracer=sup_tracer)
    try:
        # warm request: absorbs every compile on both lanes (prefill
        # spec, admit/import, decode round) so the measured request's
        # segments are pure serving time, not one-off jit tracing
        assert router.submit(Request(rid="warm", prompt=prompts[0])) \
            is None
        rw, _ = _drive_until(router, "warm")
        assert isinstance(rw, Completed)

        assert router.submit(Request(rid="meas", prompt=prompts[1])) \
            is None
        rm, e2e_ms = _drive_until(router, "meas")
        assert isinstance(rm, Completed)
        # both requests rode the disaggregated pool path, never a
        # pickled handoff
        assert router.counters.pool_handoffs == 2
        assert router.counters.handoffs == 0

        assert len(decode.clock_offset) > 0    # STEP mono_ns fed it
        write_offsets([decode], trace_dir)
    finally:
        router.close()     # orderly SHUTDOWN -> the worker dumps its ring
        pool.close()
    sup_tracer.dump_json(os.path.join(trace_dir, "supervisor.json"))

    out_path = os.path.join(trace_dir, "timeline.json")
    doc = stitch_timeline(trace_dir, out_path=out_path)
    with open(out_path) as f:
        assert json.load(f)["traceEvents"]      # written doc is valid JSON
    meta = doc["metadata"]
    assert meta["stitched_from"] == 2
    assert meta["unaligned_files"] == []
    assert {lane["role"] for lane in meta["lanes"]} \
        == {"supervisor", "worker"}
    (wlane,) = [ln for ln in meta["lanes"] if ln["role"] == "worker"]
    assert wlane["aligned"] == "offset"

    # ONE per-request timeline spanning both process lanes, ordered on
    # the stitched clock: route (supervisor) precedes admit (worker)
    # precedes terminal precedes delivery (supervisor) — allow the
    # offset estimator's rtt/2 error bound at the clock boundaries
    tl = request_timelines(doc)["meas"]
    assert len({ev["pid"] for ev in tl}) == 2
    names = [ev["name"] for ev in tl]
    for needed in ("fleet/route", "fleet/prefill", "fleet/pool_handoff",
                   "serve/admit", "serve/complete", "fleet/delivered"):
        assert needed in names, (needed, sorted(set(names)))

    def first_ts(name):
        return next(ev["ts"] for ev in tl if ev["name"] == name)

    slack_us = 2_000.0
    assert first_ts("fleet/route") <= first_ts("serve/admit") + slack_us
    assert first_ts("serve/admit") \
        <= first_ts("serve/complete") + slack_us
    assert first_ts("serve/complete") \
        <= first_ts("fleet/delivered") + slack_us

    # flow chain: one id, starts once, finishes once, steps between —
    # and every event carries the Chrome flow schema fields
    flows = [ev for ev in doc["traceEvents"]
             if ev.get("ph") in ("s", "t", "f")
             and (ev.get("args") or {}).get("rid") == "meas"]
    flows.sort(key=lambda ev: ev["ts"])
    assert len({ev["id"] for ev in flows}) == 1
    assert {ev["cat"] for ev in flows} == {"request"}
    phases = [ev["ph"] for ev in flows]
    assert phases[0] == "s" and phases[-1] == "f"
    assert phases.count("s") == 1 and phases.count("f") == 1
    assert len(phases) >= 3 and set(phases[1:-1]) == {"t"}
    for ev in flows:
        assert {"name", "ph", "id", "cat", "ts", "pid", "tid"} \
            <= set(ev), ev
    (fin,) = [ev for ev in flows if ev["ph"] == "f"]
    assert fin.get("bp") == "e"
    assert fin["args"].get("outcome") == "complete"

    # the acceptance number: the critical-path decomposition accounts
    # for the supervisor-measured e2e within 5%
    paths = {str(p.rid): p for p in analyze_chrome(doc)}
    p = paths["meas"]
    assert p.segments["prefill"] > 0.0      # prefill-lane span + admit
    assert p.segments["pool_fetch"] > 0.0   # pages imported via pool
    assert p.segments["decode_rounds"] > 0.0
    assert p.ttft_ms is not None and p.ttft_ms <= e2e_ms
    assert abs(p.accounted_ms - e2e_ms) <= 0.05 * e2e_ms, (
        f"segment sum {p.accounted_ms:.2f}ms vs supervisor e2e "
        f"{e2e_ms:.2f}ms (>{0.05 * e2e_ms:.2f}ms apart): {p.segments}"
    )


# -- heal on the critical path (tier-1 acceptance) ----------------------------


def test_heal_dominates_salvaged_request_critpath(tmp_path, sup_tracer,
                                                  prompts):
    """Acceptance: SIGKILL a replica mid-decode — the salvaged request's
    stitched path shows the heal segment (promoted past head-sampling,
    ``fleet/requeued`` carries heal_ms) DOMINATING its critical path,
    and the ``serve_critpath/*`` metrics source attributes it."""
    trace_dir = str(tmp_path)
    spec = WorkerSpec(builder=BUILDER)
    reps = [ProcReplica(spec, f"hl-{i}", spawn_timeout_s=SPAWN_S,
                        rpc_timeout_s=SPAWN_S,
                        env={"ROCKET_TPU_TRACE_DIR": trace_dir})
            for i in range(2)]
    router = FleetRouter(reps, tracer=sup_tracer)
    rids = [f"r{i}" for i in range(4)]
    results = []
    try:
        for i, rid in enumerate(rids):
            assert router.submit(
                Request(rid=rid, prompt=prompts[i])) is None
        # a couple of rounds so decode is genuinely in flight (each
        # request needs 4+ rounds), then unannounced host loss
        for _ in range(2):
            router.pump()
        results += router.drain_results()
        victim = next(r for r in reps if r._outstanding)
        victim.kill()
        _await_corpse(victim)

        results += router.run_until_idle()
        assert sorted(r.rid for r in results) == sorted(rids)
        assert router.counters.heals == 1

        requeued = [f for _k, n, _ts, _d, _t, f in sup_tracer.events()
                    if n == "fleet/requeued"]
        assert requeued, "heal salvaged nothing traceable"
        assert all(f["heal_ms"] > 0.0 for f in requeued)
        salvaged = sorted({str(f["rid"]) for f in requeued})

        write_offsets(reps, trace_dir)
    finally:
        router.close()
    sup_tracer.dump_json(os.path.join(trace_dir, "supervisor.json"))

    # supervisor dump + both workers' orderly-exit dumps (the killed
    # worker's ring died with it — its REPLACEMENT dumps instead)
    doc = stitch_timeline(trace_dir)
    assert doc["metadata"]["stitched_from"] == 3

    paths = {str(p.rid): p for p in analyze_chrome(doc)}
    p = paths[salvaged[0]]
    assert p.segments["heal"] > 0.0
    # a heal is a respawn — process + jax import + build — which dwarfs
    # the tiny model's decode: it IS the salvaged request's critical path
    assert p.dominant == "heal", p.segments

    # per-class attribution rides the serve_critpath/* export source
    stats = aggregate(paths.values())
    name = register_critpath_source(stats)
    try:
        snap = collect()
        heal_keys = [k for k, v in snap.items()
                     if k.startswith("serve_critpath/")
                     and k.endswith("/heal_ms_total") and v > 0.0]
        assert heal_keys, sorted(snap)
        assert "rocket_tpu_serve_critpath_" in prometheus_text()
    finally:
        unregister_source(name)
