"""Prefix-cache tier tests — rocket_tpu.serve.kvstore end to end.

Four layers:

- units: the rolling page-hash chain (determinism, prefix extension,
  granularity separation), KVHandoff.split_pages / from_pages for the
  f32 AND rank-4 int8-scale layouts with per-page nbytes accounting;
- eviction edges (ISSUE 11 satellite): byte-budget boundary (evict
  exactly enough to fit, never more), pinned in-flight pages never
  evicted, LRU leaf-first ordering, oversized/unfittable inserts
  rejected with occupancy intact, layout-signature mismatch loud;
- the acceptance oracle: greedy decode from a cached prefix is
  BIT-EQUAL to decode after a full prefill, f32 and int8, both at the
  batcher layer (prefill_from_pages) and through a ServingLoop with the
  store armed (the fleet session-affinity hop lives in test_fleet.py);
- the export source: rocket_tpu_serve_kvstore_* gauges aggregate
  across stores with hit_rate recomputed, not summed.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rocket_tpu.models.generate import ContinuousBatcher, KVHandoff
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
from rocket_tpu.serve import Completed, Request, ServingLoop
from rocket_tpu.serve.kvstore import (
    PrefixKVStore,
    page_hashes,
    register_kvstore_source,
)

pytestmark = pytest.mark.kvcache

B, P, TOTAL, NDRAFT, PAGE = 3, 12, 24, 4, 4


def _lm(seed=1, **kw):
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64, **kw
    )
    m = TransformerLM(cfg)
    p = m.init(
        jax.random.PRNGKey(seed),
        {"tokens": np.zeros((1, P), np.int32),
         "positions": np.zeros((1, P), np.int32)},
    )["params"]
    return m, p


def _models(int8=False):
    kw = {"kv_cache_int8": True} if int8 else {}
    model, params = _lm(seed=1, **kw)
    draft, _ = _lm(seed=1, **kw)
    _, dparams = _lm(seed=7, **kw)
    return model, draft, params, dparams


def _bat(models, **kw):
    model, draft, params, dparams = models
    return ContinuousBatcher(model, draft, params, dparams,
                             total_len=TOTAL, n_draft=NDRAFT,
                             eos_token=None, **kw)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(13)
    return rng.integers(1, 64, size=(8, P)).astype(np.int32)


def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


# -- units: the rolling hash chain ---------------------------------------


class TestPageHashes:
    def test_deterministic_and_prefix_extending(self):
        toks = np.arange(1, 17, dtype=np.int32)
        h1 = page_hashes(toks, PAGE)
        h2 = page_hashes(toks, PAGE)
        assert h1 == h2 and len(h1) == 4
        # the chain over a longer sequence EXTENDS the shorter one's —
        # this is what makes a cached chain reusable by a longer prompt
        assert page_hashes(toks[:8], PAGE) == h1[:2]

    def test_digest_commits_to_whole_prefix(self):
        a = np.arange(1, 17, dtype=np.int32)
        b = a.copy()
        b[0] = 63              # differ only in page 0
        ha, hb = page_hashes(a, PAGE), page_hashes(b, PAGE)
        # every digest after the divergence differs, even though pages
        # 1..3 hold identical tokens: the chain is content-addressed on
        # the ENTIRE prefix, not the page alone
        assert all(x != y for x, y in zip(ha, hb))

    def test_granularities_never_collide(self):
        toks = np.arange(1, 17, dtype=np.int32)
        assert not set(page_hashes(toks, 4)) & set(page_hashes(toks, 8))

    def test_limit_and_tail_remainder(self):
        toks = np.arange(1, 17, dtype=np.int32)
        assert len(page_hashes(toks, PAGE, limit=15)) == 3
        assert len(page_hashes(toks[:14], PAGE)) == 3  # tail never hashes
        assert len(page_hashes(toks[:3], PAGE)) == 0


# -- units: paging the handoff -------------------------------------------


class TestSplitJoinPages:
    @pytest.mark.parametrize("int8", [False, True])
    def test_split_pages_layouts_and_nbytes(self, prompts, int8):
        models = _models(int8)
        h = _bat(models).prefill_handoff(prompts[0]).to_host()
        n_tok = int(np.asarray(h.n_tok)[0])
        pages = h.split_pages(PAGE)
        assert len(pages) == (n_tok - 1) // PAGE
        buf = np.asarray(h.buf)[0]
        for i, page in enumerate(pages):
            assert page.page_tokens == PAGE
            assert np.array_equal(page.tokens, buf[i * PAGE:(i + 1) * PAGE])
            assert page.nbytes > 0
        # per-page accounting sums below the whole row (pages carry only
        # their slots' KV, the handoff the full buffer)
        assert sum(p.nbytes for p in pages) <= h.nbytes
        leaves = jax.tree_util.tree_leaves(pages[0].cache_t)
        if int8:
            assert any(a.ndim == 4 and a.dtype == np.int8 for a in leaves)
            assert any(a.ndim == 4 and a.dtype == np.float32
                       for a in leaves)   # the rank-4 per-slot scales
        else:
            assert all(a.dtype != np.int8 for a in leaves)

    @pytest.mark.parametrize("int8", [False, True])
    def test_from_pages_rebuilds_covered_prefix(self, prompts, int8):
        models = _models(int8)
        bat = _bat(models)
        h = bat.prefill_handoff(prompts[0]).to_host()
        pages = h.split_pages(PAGE)
        slots = int(models[0].config.max_seq)
        re = KVHandoff.from_pages(pages, total_len=TOTAL,
                                  slots_t=slots, slots_d=slots)
        covered = len(pages) * PAGE
        assert int(np.asarray(re.n_tok)[0]) == covered
        assert np.array_equal(np.asarray(re.buf)[0, :covered],
                              np.asarray(h.buf)[0, :covered])
        # KV slots inside the covered prefix are bit-equal to the full
        # prefill's; beyond it they are zero (== fresh-prefill tail)
        for full, reb in ((h.cache_t, re.cache_t), (h.cache_d, re.cache_d)):
            for a, b in zip(jax.tree_util.tree_leaves(full),
                            jax.tree_util.tree_leaves(reb)):
                a, b = np.asarray(a), np.asarray(b)
                if a.ndim != 4:
                    continue
                assert np.array_equal(a[:, :covered], b[:, :covered])
                assert not np.any(b[:, covered:])

    def test_from_pages_validates(self, prompts):
        models = _models()
        pages = _bat(models).prefill_handoff(prompts[0]) \
            .to_host().split_pages(PAGE)
        with pytest.raises(ValueError):
            KVHandoff.from_pages([], total_len=TOTAL,
                                 slots_t=64, slots_d=64)
        with pytest.raises(ValueError):
            # covered prefix + the to-be-recomputed final position must
            # fit the buffer
            KVHandoff.from_pages(pages, total_len=len(pages) * PAGE,
                                 slots_t=64, slots_d=64)


# -- eviction edges ------------------------------------------------------


def _store_with_chain(prompts, *, pages_fit, extra_bytes=0, **kw):
    """A store whose budget fits exactly ``pages_fit`` of the uniform
    pages split from prompts[0]'s finished row."""
    models = _models()
    h = _bat(models).prefill_handoff(prompts[0]).to_host()
    pages = h.split_pages(PAGE)
    per = pages[0].nbytes
    assert all(p.nbytes == per for p in pages)
    store = PrefixKVStore(page_tokens=PAGE,
                          capacity_bytes=per * pages_fit + extra_bytes,
                          **kw)
    return store, h, pages, per


class TestEvictionEdges:
    def test_budget_boundary_evicts_exactly_to_fit(self, prompts):
        # the prefill handoff's reusable prefix is P = 12 tokens -> a
        # 3-page chain; the budget fits exactly those 3 pages
        store, h, pages, per = _store_with_chain(prompts, pages_fit=3)
        assert store.insert(h) == 3
        assert store.occupancy_bytes == 3 * per
        # a foreign single-page chain displaces exactly ONE LRU page
        other = page_hashes(np.full(PAGE, 63, np.int32), PAGE)
        assert store.put_pages(other, pages[:1]) == 1
        snap = store.snapshot()
        assert snap["evictions"] == 1
        assert snap["occupancy_bytes"] == 3 * per
        assert snap["occupancy_bytes"] <= snap["capacity_bytes"]

    def test_lru_is_leaf_first(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=3)
        store.insert(h)
        chain = page_hashes(np.asarray(h.buf)[0], PAGE,
                            limit=int(np.asarray(h.n_tok)[0]) - 1)
        other = page_hashes(np.full(PAGE, 63, np.int32), PAGE)
        store.put_pages(other, pages[:1])
        # the DEEPEST page of the cold chain went, the shared root stayed
        assert chain[-1] not in store._table
        assert chain[0] in store._table

    def test_pinned_pages_never_evict(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=3)
        store.insert(h)
        match = store.lookup(np.asarray(h.buf)[0, :P])  # pins pages 0, 1
        assert match is not None and len(match.hashes) == 2
        # only page 2 is evictable: a 4-page foreign chain stores its
        # first page (displacing page 2), then stops — the pins (and the
        # chain's own just-stored page) block everything further
        foreign = page_hashes(np.full(4 * PAGE, 63, np.int32), PAGE)
        stored = store.put_pages(foreign, pages[:4])
        assert stored == 1
        snap = store.snapshot()
        assert snap["rejected"] == 1
        assert snap["occupancy_bytes"] <= snap["capacity_bytes"]
        for hsh in match.hashes:           # the pinned pages survived
            assert hsh in store._table
        assert foreign[0] in store._table  # never self-evicted (no holes)
        store.release(match)
        # released pins are evictable again: the rejected pages now fit
        assert store.put_pages(foreign, pages[:4]) == 2
        snap = store.snapshot()
        assert snap["occupancy_bytes"] <= snap["capacity_bytes"]

    def test_unpin_all_stops_leaks(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=3)
        store.insert(h)
        assert store.lookup(np.asarray(h.buf)[0, :P]) is not None
        assert store.snapshot()["pinned"] == 2
        store.unpin_all()                  # the heal path's leak stopper
        assert store.snapshot()["pinned"] == 0

    def test_oversized_page_rejected_whole_chain_stops(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=0,
                                                 extra_bytes=1)
        assert store.insert(h) == 0        # nothing fits
        snap = store.snapshot()
        assert snap["rejected"] == 1 and snap["pages"] == 0
        assert snap["occupancy_bytes"] == 0

    def test_layout_mismatch_is_loud(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=8)
        store.insert(h)
        h8 = _bat(_models(int8=True)).prefill_handoff(prompts[1])
        with pytest.raises(ValueError, match="layout"):
            store.insert(h8)

    def test_dedup_across_requests(self, prompts):
        store, h, pages, per = _store_with_chain(prompts, pages_fit=8)
        first = store.insert(h)
        assert first == len(pages)
        assert store.insert(h) == 0        # identical prefix: all dedup
        assert store.snapshot()["dedup_hits"] == len(pages)


# -- the acceptance oracle -----------------------------------------------


class TestCachedPrefixOracle:
    @pytest.mark.parametrize("int8", [False, True])
    def test_cached_prefix_bit_equal_to_full_prefill(self, prompts, int8):
        """Greedy decode from a cached prefix is bit-equal to decode
        after a full prefill — handoff state AND every token to
        completion, f32 and int8 KV layouts (acceptance oracle)."""
        models = _models(int8)
        pre = _bat(models)
        h_full = pre.prefill_handoff(prompts[0][None, :])
        store = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)
        store.insert(h_full.to_host())
        match = store.lookup(prompts[0])
        assert match is not None
        # lookup caps at len - 1: the final position's logits must be
        # recomputed to sample the first new token
        assert match.tokens == (P - 1) // PAGE * PAGE
        h_cached = pre.prefill_from_pages(prompts[0][None, :], match.pages)
        store.release(match)
        for field in ("buf", "n_tok", "done", "cache_t", "cache_d"):
            assert _tree_equal(getattr(h_full, field),
                               getattr(h_cached, field)), field

        def decode(h):
            dec = _bat(models)
            dec.start(jnp.asarray(prompts[:B], jnp.int32))
            for r in range(B):
                dec.retire(r)
            dec.admit_prefilled(0, h)
            while not bool(np.asarray(dec.state[2])[0]):
                dec.step()
            return dec.row_tokens(0)

        tok_full, n_full = decode(h_full)
        tok_cached, n_cached = decode(h_cached)
        assert n_full == n_cached
        assert np.array_equal(tok_full, tok_cached)

    def test_partial_prefix_match_longest_wins(self, prompts):
        models = _models()
        bat = _bat(models)
        store = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)
        store.insert(bat.prefill_handoff(prompts[0]).to_host())
        # a prompt sharing only the first page matches exactly one page
        mixed = prompts[0].copy()
        mixed[PAGE:] = prompts[1][PAGE:]
        match = store.lookup(mixed)
        assert match is not None and match.tokens == PAGE
        h_cached = bat.prefill_from_pages(mixed[None, :], match.pages)
        store.release(match)
        h_full = bat.prefill_handoff(mixed[None, :])
        assert _tree_equal(h_full.cache_t, h_cached.cache_t)
        assert _tree_equal(h_full.buf, h_cached.buf)

    def test_suffix_prefill_guards(self, prompts):
        models = _models()
        bat = _bat(models)
        h = bat.prefill_handoff(prompts[0]).to_host()
        pages = h.split_pages(PAGE)
        with pytest.raises(ValueError, match="prefix"):
            # hash-collision guard: pages must match the prompt tokens
            bat.prefill_from_pages(prompts[1][None, :], pages[:2])

    def test_rolling_cache_refused(self, prompts):
        kw = dict(decode_rolling_cache=True, attention_window=16)
        models = (_lm(seed=1, **kw)[0], _lm(seed=1, **kw)[0],
                  _lm(seed=1, **kw)[1], _lm(seed=7, **kw)[1])
        model, draft, params, dparams = models
        bat = ContinuousBatcher(model, draft, params, dparams,
                                total_len=TOTAL, n_draft=NDRAFT,
                                eos_token=None)
        assert not bat.prefix_cache_ok
        h = _bat(_models()).prefill_handoff(prompts[0]).to_host()
        with pytest.raises(ValueError, match="rolling"):
            bat.prefill_from_pages(prompts[0][None, :],
                                   h.split_pages(PAGE))


# -- serving-loop integration --------------------------------------------


class TestLoopIntegration:
    def _factory(self, models):
        def factory():
            return _bat(models)
        return factory

    def test_hit_path_bit_equal_and_counted(self, prompts):
        models = _models()
        store = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)

        def run(kv):
            loop = ServingLoop(self._factory(models), max_batch=B,
                               queue_capacity=8, kvstore=kv)
            loop.submit(Request("r", prompts[0]))
            out = loop.run_until_idle()
            snap = loop.counters.snapshot()
            loop.close()
            return out, snap

        (cold,), _ = run(None)
        (miss,), snap_miss = run(store)     # miss: full prefill + export
        (hit,), snap_hit = run(store)       # hit: suffix prefill
        assert isinstance(cold, Completed)
        assert np.array_equal(cold.tokens, miss.tokens)
        assert np.array_equal(cold.tokens, hit.tokens)
        assert snap_miss["kv_hits"] == 0
        assert snap_hit["kv_hits"] == 1
        assert snap_hit["kv_hit_tokens"] == (P - 1) // PAGE * PAGE
        assert store.snapshot()["pinned"] == 0   # released after import

    def test_rolling_cache_loop_refused(self):
        kw = dict(decode_rolling_cache=True, attention_window=16)
        model, params = _lm(seed=1, **kw)
        draft, _ = _lm(seed=1, **kw)
        _, dparams = _lm(seed=7, **kw)

        def factory():
            return ContinuousBatcher(model, draft, params, dparams,
                                     total_len=TOTAL, n_draft=NDRAFT,
                                     eos_token=None)

        store = PrefixKVStore(page_tokens=PAGE)
        with pytest.raises(ValueError, match="rolling"):
            ServingLoop(factory, max_batch=B, queue_capacity=8,
                        kvstore=store)


# -- the export source ---------------------------------------------------


class TestExportSource:
    def test_fleet_wide_gauges_recompute_hit_rate(self, prompts):
        from rocket_tpu.observe.export import (
            prometheus_text,
            unregister_source,
        )

        models = _models()
        h = _bat(models).prefill_handoff(prompts[0]).to_host()
        a = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)
        b = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)
        a.insert(h)
        m = a.lookup(prompts[0])            # a: 1 lookup, 1 hit
        a.release(m)
        b.lookup(prompts[1])                # b: 1 lookup, 0 hits
        name = register_kvstore_source([a, b])
        try:
            text = prometheus_text()
            assert "rocket_tpu_serve_kvstore_hits 1" in text
            assert "rocket_tpu_serve_kvstore_lookups 2" in text
            # recomputed from summed hits/lookups (0.5), NOT the summed
            # per-store rates (1.0)
            assert "rocket_tpu_serve_kvstore_hit_rate 0.5" in text
        finally:
            unregister_source(name)
