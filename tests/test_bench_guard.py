"""bench.py scan auto-guard (VERDICT r3 next #7): a scan config that
fails the bounded fresh-process AOT compile check falls back to unrolled
layers with a logged note, instead of producing a suspect number — plus
the tracing-overhead guard (ISSUE 4 acceptance): arming the structured
tracer adds ZERO jit traces and <5% host overhead per train iteration
and per serve round."""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture()
def bench(devices):
    import bench as bench_mod

    return bench_mod


def _tiny_structural():
    # Small enough that the subprocess compiles in seconds on CPU.
    return dict(
        hidden=64, n_layers=2, n_heads=4, max_seq=128, vocab_size=256,
        scan_layers=True, attention="dot",
    )


def test_scan_compile_ok_on_cpu(bench):
    ok, detail = bench.scan_compile_ok(_tiny_structural(), batch=2, seq=64)
    assert ok, detail


def test_scan_compile_check_times_out(bench):
    # A sub-second budget cannot finish interpreter start + compile: the
    # guard must report broken, not hang.
    ok, detail = bench.scan_compile_ok(
        _tiny_structural(), batch=2, seq=64, timeout_s=0.5
    )
    assert not ok and "did not finish" in detail
    # a different timeout is a different cache key: the stale short-budget
    # False must not leak into default-budget callers
    ok2, _ = bench.scan_compile_ok(_tiny_structural(), batch=2, seq=64)
    assert ok2


def test_resolve_scan_guard_falls_back(bench):
    t = dict(bench.GPT2_TUNE, scan_layers=True)
    out, note = bench.resolve_scan_guard(
        t, check=lambda *a, **k: (False, "compile did not finish")
    )
    assert out["scan_layers"] is False
    assert note and "fell back to unrolled" in note
    # everything else untouched
    assert out["batch"] == t["batch"] and out["block_q"] == t["block_q"]


def test_resolve_scan_guard_keeps_healthy_scan(bench):
    t = dict(bench.GPT2_TUNE, scan_layers=True)
    out, note = bench.resolve_scan_guard(
        t, check=lambda *a, **k: (True, "ok")
    )
    assert out["scan_layers"] is True and note is None


def test_resolve_scan_guard_threads_attention_impl(bench):
    # The guard must AOT-check the SAME attention implementation the
    # bench will run: a dot-attention scan config checked as flash (or
    # vice versa) validates a different executable than the one timed.
    seen = {}

    def check(structural, batch, seq):
        seen.update(structural)
        return True, "ok"

    t = dict(bench.GPT2_TUNE, scan_layers=True, attention="dot")
    bench.resolve_scan_guard(t, check=check)
    assert seen["attention"] == "dot"


def test_resolve_scan_guard_noop_without_scan(bench):
    calls = []
    t = dict(bench.GPT2_TUNE)  # scan_layers False by default
    out, note = bench.resolve_scan_guard(
        t, check=lambda *a, **k: calls.append(1) or True
    )
    assert out is t and note is None and not calls


def test_tune_matches_headline_canonicalization(bench):
    from rocket_tpu.ops.flash import auto_blocks

    # an old record with explicit blocks and missing later-added knobs
    # (attention/window/mu_dtype) still describes today's headline config
    bq, bk = auto_blocks(bench.GPT2_TUNE["seq"])
    explicit = dict(bench.GPT2_TUNE, block_q=bq, block_k=bk)
    for k in ("attention", "window", "mu_dtype"):
        explicit.pop(k)
    assert bench._tune_matches_headline(explicit)
    assert bench._tune_matches_headline(dict(bench.GPT2_TUNE))
    # any real divergence — or an unknown knob — is a different config
    assert not bench._tune_matches_headline(dict(bench.GPT2_TUNE, batch=8))
    assert not bench._tune_matches_headline(dict(bench.GPT2_TUNE, bogus=1))
    assert not bench._tune_matches_headline(None)


def test_last_good_ladder_reports_current_gpt2_tune(bench):
    """VERDICT r5 #5: the ladder's gpt2 entry must be a measurement of
    the CURRENT ``GPT2_TUNE`` (the promoted bs16 sweep winner), not the
    superseded bs8 plain record."""
    gpt2 = bench._last_good_ladder().get("gpt2")
    assert gpt2 is not None and gpt2.get("value")
    assert bench._tune_matches_headline(gpt2.get("tune")), gpt2.get("tune")
    assert gpt2["tune"]["batch"] == bench.GPT2_TUNE["batch"]
    # the promoted record must not still look like sweep output
    assert "sweep_point" not in gpt2


def test_bench_emits_stale_ladder_when_backend_unreachable(tmp_path):
    """The driver contract for tunnel-down rounds (VERDICT r4 next #7b):
    a plain `python bench.py` whose backend probes all fail must exit 0
    and emit the last-good measured ladder marked stale, gpt2 last —
    not a null record."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        # an unknown platform makes the probe subprocesses fail fast
        "JAX_PLATFORMS": "bogus_backend",
        "BENCH_PROBE_TIMEOUT": "20",
        "BENCH_PROBE_ATTEMPTS": "1",
    })
    env.pop("BENCH_NO_STALE", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    assert recs, proc.stdout
    assert all(r.get("stale") is True and r.get("value") for r in recs)
    assert recs[-1]["config"] == "gpt2"  # headline record stays last
    assert "measured_age_s" in recs[-1]
    # the re-emitted gpt2 record must describe the CURRENT headline
    # config (VERDICT r5 #5: it used to replay the superseded bs8 tune)
    import bench as bench_mod

    assert bench_mod._tune_matches_headline(recs[-1].get("tune")), \
        recs[-1].get("tune")
    assert recs[-1]["tune"]["batch"] == bench_mod.GPT2_TUNE["batch"]


# -- tracing-overhead guard (ISSUE 4 acceptance) --------------------------
#
# The tentpole promise of observe.trace is "zero device syncs, lock-light,
# cheap enough to leave armed in production".  These tests hold the hot
# paths to that: with tracing armed, a train iteration and a serve round
# must (a) trace zero additional jitted step bodies and (b) stay within
# 5% host overhead of the disarmed run (plus an absolute floor for
# scheduler noise on tiny CPU steps — same tolerance discipline as
# tests/test_serving_resilience.py::test_host_overhead_under_5pct).


@pytest.mark.tracing
class TestTracingOverheadGuard:
    def test_train_iteration_overhead_and_trace_count(self, devices):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.core.capsule import Capsule
        from rocket_tpu.launch.loop import Looper
        from rocket_tpu.observe.trace import disarm, get_tracer
        from rocket_tpu.runtime import Runtime

        class JitProbe(Capsule):
            def __init__(self):
                super().__init__()
                self.fn = jax.jit(lambda x: x * 2.0 + 1.0)
                self.x = jnp.ones((256, 256), jnp.float32)

            def launch(self, attrs=None):
                self.x = self.fn(self.x)

        repeats, trials = 50, 5

        def cycle_times(tracing):
            runtime = Runtime(tracing=tracing)
            probe = JitProbe()
            looper = Looper(capsules=[probe], repeats=repeats,
                            progress=False)
            looper.bind(runtime)
            attrs = Attributes()
            looper.setup(attrs)
            looper.launch(attrs)            # warmup cycle (compiles)
            looper.reset(attrs)
            jax.block_until_ready(probe.x)
            traces_before = probe.fn._cache_size()
            out = []
            for _ in range(trials):
                t0 = time.perf_counter()
                looper.launch(attrs)
                jax.block_until_ready(probe.x)
                out.append(time.perf_counter() - t0)
                looper.reset(attrs)
            # armed or not, the loop traced ZERO new step bodies
            assert probe.fn._cache_size() == traces_before
            return out

        try:
            bare = float(np.median(cycle_times(False))) / repeats
            armed = float(np.median(cycle_times(True))) / repeats
        finally:
            disarm()
            get_tracer().clear()
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed iter {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )

    def test_serve_round_overhead_and_trace_count(self, devices):
        import jax
        import numpy as np

        from rocket_tpu.models.generate import ContinuousBatcher, _spec_round
        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from rocket_tpu.observe.trace import Tracer
        from rocket_tpu.serve import Request, ServingLoop

        B, P, TOTAL, NDRAFT = 3, 8, 24, 4

        def _lm(seed):
            cfg = TransformerConfig(
                vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
            )
            m = TransformerLM(cfg)
            p = m.init(
                jax.random.PRNGKey(seed),
                {"tokens": np.zeros((1, P), np.int32),
                 "positions": np.zeros((1, P), np.int32)},
            )["params"]
            return m, p

        model, params = _lm(1)
        draft, _ = _lm(1)
        _, dparams = _lm(7)
        rng = np.random.default_rng(13)
        prompts = rng.integers(1, 64, size=(B, P)).astype(np.int32)

        def factory():
            return ContinuousBatcher(
                model, draft, params, dparams,
                total_len=TOTAL, n_draft=NDRAFT, eos_token=None,
            )

        rounds = 8

        def round_times(tracer):
            loop = ServingLoop(factory, max_batch=B, queue_capacity=8,
                               watchdog_timeout=30.0, tracer=tracer)
            for i in range(B):
                loop.submit(Request(rid=i, prompt=prompts[i]))
            loop.run_round()  # admits + settles
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                loop.run_round()
                out.append(time.perf_counter() - t0)
            loop.close()
            return out

        bare = float(np.median(round_times(Tracer(enabled=False))))
        traces_before = _spec_round._cache_size()
        armed_tracer = Tracer(capacity=1024, enabled=True)
        armed = float(np.median(round_times(armed_tracer)))
        # arming recorded real spans without tracing a single new body
        assert _spec_round._cache_size() == traces_before
        assert any(e[1] == "serve/round" for e in armed_tracer.events())
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed round {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )


# -- distributed-tracing guard (ISSUE 19 acceptance) -----------------------
#
# The request-tracing tentpole's promise: stamping a TraceContext on
# every request and emitting its flow chain (s -> t... -> f) at sampling
# rate 1.0 is pure host bookkeeping — a crc32, a dataclass, a ring
# append per hop.  Armed, a serve round must trace ZERO new jitted
# bodies and stay within 5% host overhead of the disarmed loop (same
# tolerance discipline as the guards above).


@pytest.mark.tracing
class TestTraceCtxGuard:
    def test_ctx_stamped_round_overhead_and_trace_count(self, devices):
        import jax
        import numpy as np

        from rocket_tpu.models.generate import ContinuousBatcher, _spec_round
        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from rocket_tpu.observe.trace import (
            Tracer,
            get_sampling,
            set_sampling,
        )
        from rocket_tpu.serve import Request, ServingLoop

        B, P, TOTAL, NDRAFT = 3, 8, 24, 4

        def _lm(seed):
            cfg = TransformerConfig(
                vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
            )
            m = TransformerLM(cfg)
            p = m.init(
                jax.random.PRNGKey(seed),
                {"tokens": np.zeros((1, P), np.int32),
                 "positions": np.zeros((1, P), np.int32)},
            )["params"]
            return m, p

        model, params = _lm(1)
        draft, _ = _lm(1)
        _, dparams = _lm(7)
        rng = np.random.default_rng(13)
        prompts = rng.integers(1, 64, size=(B, P)).astype(np.int32)

        def factory():
            return ContinuousBatcher(
                model, draft, params, dparams,
                total_len=TOTAL, n_draft=NDRAFT, eos_token=None,
            )

        rounds = 8

        def round_times(tracer):
            loop = ServingLoop(factory, max_batch=B, queue_capacity=8,
                               watchdog_timeout=30.0, tracer=tracer)
            for i in range(B):
                loop.submit(Request(rid=i, prompt=prompts[i]))
            loop.run_round()  # admits + settles
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                loop.run_round()
                out.append(time.perf_counter() - t0)
            loop.run_until_idle()  # terminal "f" flow events emit here
            loop.close()
            return out

        rate, seed = get_sampling()
        set_sampling(1.0, 0)     # every request stamped AND flow-traced
        try:
            bare = float(np.median(round_times(Tracer(enabled=False))))
            traces_before = _spec_round._cache_size()
            armed_tracer = Tracer(capacity=4096, enabled=True)
            armed = float(np.median(round_times(armed_tracer)))
        finally:
            set_sampling(rate, seed)
        # ctx stamping + flow emission traced zero new jitted bodies...
        assert _spec_round._cache_size() == traces_before
        # ...while really recording every request's full flow chain
        phases = [f.get("ph") for k, n, _ts, _d, _t, f
                  in armed_tracer.events()
                  if k == "F" and n == "serve/request"]
        assert phases.count("s") == B and phases.count("f") == B
        assert "t" in phases
        assert armed <= bare * 1.05 + 5e-4, (
            f"ctx-stamped round {armed * 1e3:.3f}ms vs bare "
            f"{bare * 1e3:.3f}ms"
        )


# -- async-loop guard (ISSUE 5 acceptance) --------------------------------
#
# The non-blocking Looper's promise: with readback deferred k iterations,
# the per-iteration HOST dispatch gap (the time the chip could sit idle
# between steps) drops strictly below the synchronous loop's — which pays
# a device wait every iteration to float the fresh loss — while tracing
# zero additional step bodies and adding <5% host overhead when nothing
# consumes the readback at all.  The model is sized so the device step
# clearly dominates python dispatch on CPU, making the gap comparison
# meaningful rather than noise-vs-noise.


class TestAsyncLoopGuard:
    REPEATS = 12
    BATCH = 128

    def _data(self):
        import numpy as np

        rng = np.random.default_rng(0)
        n = self.REPEATS * self.BATCH
        protos = rng.normal(size=(4, 64)).astype(np.float32) * 3.0
        labels = rng.integers(0, 4, size=n)
        x = (protos[labels] + rng.normal(size=(n, 64))).astype(np.float32)
        return {"x": x, "label": labels.astype(np.int32)}

    def _build(self, lag, reader):
        import flax.linen as nn

        import rocket_tpu as rt
        from rocket_tpu.models.objectives import cross_entropy

        class WideMLP(nn.Module):
            @nn.compact
            def __call__(self, batch, train=False):
                x = batch["x"]
                x = nn.relu(nn.Dense(512)(x))
                x = nn.relu(nn.Dense(512)(x))
                out = rt.Attributes(batch)
                out["logits"] = nn.Dense(4)(x)
                return out

        model = rt.Module(
            WideMLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=1e-2),
            ],
        )
        capsules = [
            rt.Dataset(rt.ArraySource(self._data()), batch_size=self.BATCH,
                       device_prefetch=2),
            model,
        ]
        if reader is not None:
            capsules.append(reader)
        looper = rt.Looper(capsules=capsules, progress=False,
                           readback_lag=lag)
        # Single-device mesh: dispatch of an executable sharded over the 8
        # FAKE cpu devices blocks on the previous step (an artifact of the
        # forced-host-platform device emulation, not of the loop) — which
        # would drown the readback-wait difference this guard measures.
        # On one device the CPU client pipelines dispatches like a real
        # accelerator, making the gap comparison meaningful.
        import jax

        from rocket_tpu.parallel.mesh import data_parallel_mesh

        looper.bind(rt.Runtime(mesh=data_parallel_mesh(jax.devices()[:1])))
        attrs = rt.Attributes()
        looper.setup(attrs)
        return looper, model, attrs

    @staticmethod
    def _sync_reader():
        import rocket_tpu as rt

        class SyncReader(rt.Capsule):
            """The classic loop: floats THIS iteration's loss during
            dispatch — a device wait on the hot path every iteration."""

            def __init__(self):
                super().__init__(statefull=False, priority=300)
                self.seen = 0

            def launch(self, attrs=None):
                if attrs is not None and attrs.step_logs is not None:
                    float(attrs.step_logs["loss"])
                    self.seen += 1

        return SyncReader()

    @staticmethod
    def _lagged_reader():
        import rocket_tpu as rt

        class LaggedReader(rt.Capsule):
            """Consumes the k-lagged host floats — no device wait."""

            def __init__(self):
                super().__init__(statefull=False, priority=300)
                self.seen = 0

            def launch(self, attrs=None):
                if attrs is None or attrs.looper is None:
                    return
                lagged = attrs.looper.get("lagged_logs")
                if lagged is not None:
                    float(lagged["loss"])
                    self.seen += 1

        return LaggedReader()

    def _gap_ms(self, lag, reader, trials=3):
        import jax

        looper, model, attrs = self._build(lag, reader)
        looper.launch(attrs)  # warmup cycle (compiles)
        looper.reset(attrs)
        jax.block_until_ready(model.state.params)
        gaps = []
        for _ in range(trials):
            looper.launch(attrs)
            gaps.append(looper.last_dispatch_gap_ms)
            looper.reset(attrs)
            jax.block_until_ready(model.state.params)
        # the async plumbing traced ZERO new step bodies across cycles
        assert model._steps["sync"]._cache_size() == 1
        return min(gaps)

    def test_async_dispatch_gap_beats_sync(self, devices):
        sync_reader = self._sync_reader()
        gap_sync = self._gap_ms(0, sync_reader)
        lagged_reader = self._lagged_reader()
        gap_async = self._gap_ms(2, lagged_reader)
        # both variants actually consumed loss values every cycle
        assert sync_reader.seen >= self.REPEATS
        assert lagged_reader.seen > 0
        assert gap_async < gap_sync, (
            f"async gap {gap_async:.3f}ms not below sync {gap_sync:.3f}ms"
        )
        # CPU-proxy threshold: the async gap is pure host dispatch — it
        # must sit well under the device-wait-dominated sync gap, not
        # merely shave a sliver off it.
        assert gap_async < 0.5 * gap_sync + 0.3, (
            f"async gap {gap_async:.3f}ms vs sync {gap_sync:.3f}ms"
        )

    def test_lag_machinery_overhead_bounded(self, devices):
        import jax
        import numpy as np

        def cycle_times(lag, trials=5):
            looper, model, attrs = self._build(lag, None)
            looper.launch(attrs)  # warmup cycle (compiles)
            looper.reset(attrs)
            jax.block_until_ready(model.state.params)
            out = []
            for _ in range(trials):
                t0 = time.perf_counter()
                looper.launch(attrs)
                jax.block_until_ready(model.state.params)
                out.append(time.perf_counter() - t0)
                looper.reset(attrs)
            return out

        def measure():
            bare = float(np.median(cycle_times(0))) / self.REPEATS
            armed = float(np.median(cycle_times(2))) / self.REPEATS
            return bare, armed

        # On this CPU proxy an iter is ~8ms of pure host dispatch and a
        # looper's lifetime inherits its build-time allocator/thread
        # placement luck — measured build-to-build spread is ±30%, so
        # the TPU-grade <5% bound is not resolvable here.  Bound the
        # overhead at 1.5x instead, which still catches the regression
        # classes this guard exists for (an extra dispatch per iter, a
        # param-tree copy through the lag ring), and retry once with
        # fresh builds so a transient bad draw — unlike a systematic
        # regression, which fails both — doesn't flake the suite.
        bare, armed = measure()
        if armed > bare * 1.5 + 5e-4:
            bare, armed = measure()
        assert armed <= bare * 1.5 + 5e-4, (
            f"lagged iter {armed * 1e3:.3f}ms vs sync {bare * 1e3:.3f}ms"
        )


# -- emergency-tier guard (ISSUE 8 acceptance) -----------------------------
#
# The emergency checkpoint tier's promise: staging a host snapshot every
# ``emergency_every`` iterations is an ASYNC readback — zero device syncs
# and zero extra jit traces on the happy path, with the flush-to-disk cost
# paid only inside a SIGTERM grace window.  This guard holds the armed
# train loop to <5% host overhead over the unarmed one (same tolerance
# discipline as the tracing guard above).


@pytest.mark.elastic
class TestElasticGuard:
    def test_emergency_capture_overhead_and_trace_count(self, devices,
                                                        tmp_path):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.core.capsule import Capsule
        from rocket_tpu.launch.loop import Looper
        from rocket_tpu.persist.checkpoint import Checkpointer
        from rocket_tpu.runtime import Runtime

        class JitProbe(Capsule):
            """Stateful so the emergency capture has real device arrays to
            stage every iteration."""

            def __init__(self):
                super().__init__(statefull=True)
                self.fn = jax.jit(lambda x: x * 2.0 + 1.0)
                self.x = jnp.ones((256, 256), jnp.float32)

            def launch(self, attrs=None):
                self.x = self.fn(self.x)

            def state_dict(self):
                return Attributes(x=self.x)

            def load_state_dict(self, state):
                self.x = state["x"]

        repeats, trials = 50, 5

        def cycle_times(armed, tag):
            runtime = Runtime()
            runtime.project_dir = str(tmp_path / tag)
            os.makedirs(runtime.project_dir, exist_ok=True)
            probe = JitProbe()
            capsules = [probe]
            ck = None
            if armed:
                # save_every=None: the durable cadence never fires — every
                # per-iteration cost measured here is the emergency stage.
                ck = Checkpointer(save_every=None, emergency_every=1,
                                  save_on_preemption=False)
                capsules.append(ck)
            looper = Looper(capsules=capsules, repeats=repeats,
                            progress=False)
            looper.bind(runtime)
            attrs = Attributes()
            looper.setup(attrs)
            looper.launch(attrs)            # warmup cycle (compiles)
            looper.reset(attrs)
            jax.block_until_ready(probe.x)
            traces_before = probe.fn._cache_size()
            out = []
            for _ in range(trials):
                t0 = time.perf_counter()
                looper.launch(attrs)
                jax.block_until_ready(probe.x)
                out.append(time.perf_counter() - t0)
                looper.reset(attrs)
            # armed or not, the loop traced ZERO new step bodies
            assert probe.fn._cache_size() == traces_before
            if ck is not None:
                # the tier really staged a capture every iteration
                assert ck._etier is not None
                assert ck._etier.captures >= repeats * trials
                assert ck._etier.staged_iter is not None
            looper.destroy(attrs)           # discards + deactivates the tier
            return out

        bare = float(np.median(cycle_times(False, "bare"))) / repeats
        armed = float(np.median(cycle_times(True, "armed"))) / repeats
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed iter {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )


# -- int8 KV-cache decode guard (autotuner ISSUE acceptance) ---------------
#
# The quantized cache's promise is BANDWIDTH, paid for with per-page
# quantize/dequantize inside the same compiled step.  These guards pin the
# two ways that deal can silently go bad on the host side: a shape or
# dtype leak that makes the decode round retrace per emitted token, and
# host-visible per-round overhead beyond the bf16-cache baseline.


@pytest.mark.serving
class TestQuantGuard:
    B, P, TOTAL, NDRAFT = 2, 6, 20, 3

    def _batcher(self, kv_cache_int8):
        import jax
        import numpy as np

        from rocket_tpu.models.generate import ContinuousBatcher
        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
        )
        model = TransformerLM(cfg)
        params = model.init(
            jax.random.PRNGKey(1),
            {"tokens": np.zeros((1, self.P), np.int32),
             "positions": np.zeros((1, self.P), np.int32)},
        )["params"]
        bat = ContinuousBatcher(
            model, model, params, params, total_len=self.TOTAL,
            n_draft=self.NDRAFT, kv_cache_int8=kv_cache_int8,
        )
        prompts = np.random.default_rng(13).integers(
            1, 64, size=(self.B, self.P)
        ).astype(np.int32)
        bat.start(prompts)
        return bat

    def test_zero_retraces_per_emitted_token(self, devices):
        from rocket_tpu.models.generate import _spec_round

        bat = self._batcher(kv_cache_int8=True)
        bat.step()  # compile round 0 (admits no new shapes afterwards)
        traces_after_warmup = _spec_round._cache_size()
        for _ in range(6):
            bat.step()
        assert _spec_round._cache_size() == traces_after_warmup, (
            "int8 KV decode retraced after warmup — a per-token shape or "
            "dtype leak in the quantized cache plumbing"
        )

    def test_host_overhead_vs_bf16_cache_under_5pct(self, devices):
        import numpy as np

        def round_times(kv_cache_int8, rounds=8):
            bat = self._batcher(kv_cache_int8)
            bat.step()  # compile
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                n_tok, done = bat.step()  # returns HOST arrays: synced
                out.append(time.perf_counter() - t0)
            return out

        bare = float(np.median(round_times(False)))
        quant = float(np.median(round_times(True)))
        assert quant <= bare * 1.05 + 5e-4, (
            f"int8 round {quant * 1e3:.3f}ms vs bf16 {bare * 1e3:.3f}ms"
        )


# -- goodput / retrace-ledger guard (ISSUE 9 acceptance) -------------------
#
# The ledger's promise mirrors the tracer's: routing every named jit edge
# through ``ledger_call`` must add ZERO jit traces and <5% host overhead
# per train iteration and per serve round while armed — the disarmed path
# is one global attribute check, and the armed warm path is two
# ``_cache_size()`` reads plus two clock reads.  These guards hold both
# hot paths to that (same tolerance discipline as the tracing guard).


@pytest.mark.goodput
class TestGoodputGuard:
    def test_train_iteration_overhead_and_trace_count(self, devices):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.core.capsule import Capsule
        from rocket_tpu.launch.loop import Looper
        from rocket_tpu.observe.ledger import (
            arm_ledgers,
            disarm_ledgers,
            get_retrace_ledger,
            ledger_call,
        )
        from rocket_tpu.runtime import Runtime

        class JitProbe(Capsule):
            """Dispatches through the ledger chokepoint, exactly like
            every ``_AnnotatedStep`` does in a real run."""

            def __init__(self):
                super().__init__()
                self.fn = jax.jit(lambda x: x * 2.0 + 1.0)
                self.x = jnp.ones((256, 256), jnp.float32)

            def launch(self, attrs=None):
                self.x = ledger_call(self.fn, "probe/dispatch", self.x)

        # earlier suite tests (any Launcher run) may have left counts on
        # the global ledger — the bare run reads it, so start pristine
        disarm_ledgers()
        get_retrace_ledger().reset()
        repeats, trials = 50, 5

        def cycle_times(armed):
            if armed:
                arm_ledgers()
            probe = JitProbe()
            looper = Looper(capsules=[probe], repeats=repeats,
                            progress=False)
            looper.bind(Runtime())
            attrs = Attributes()
            looper.setup(attrs)
            looper.launch(attrs)            # warmup cycle (compiles)
            looper.reset(attrs)
            jax.block_until_ready(probe.x)
            traces_before = probe.fn._cache_size()
            out = []
            for _ in range(trials):
                t0 = time.perf_counter()
                looper.launch(attrs)
                jax.block_until_ready(probe.x)
                out.append(time.perf_counter() - t0)
                looper.reset(attrs)
            # armed or not, the ledgered edge traced ZERO new bodies —
            # and the sentinel never escalated a steady-state dispatch
            assert probe.fn._cache_size() == traces_before
            assert get_retrace_ledger().sentinel_dumps == 0
            return out

        try:
            bare = float(np.median(cycle_times(False))) / repeats
            armed = float(np.median(cycle_times(True))) / repeats
            # the armed run really ran under the ledger: the probe edge
            # went warm and its warmup compile was recorded
            ledger = get_retrace_ledger()
            assert "probe/dispatch" in ledger._warm
            assert any(r.name == "probe/dispatch" for r in ledger.records())
        finally:
            disarm_ledgers()
            get_retrace_ledger().reset()
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed iter {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )

    def test_serve_round_overhead_and_trace_count(self, devices):
        import jax
        import numpy as np

        from rocket_tpu.models.generate import ContinuousBatcher, _spec_round
        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from rocket_tpu.observe.ledger import (
            arm_ledgers,
            disarm_ledgers,
            get_retrace_ledger,
        )
        from rocket_tpu.observe.trace import Tracer
        from rocket_tpu.serve import Request, ServingLoop

        B, P, TOTAL, NDRAFT = 3, 8, 24, 4

        def _lm(seed):
            cfg = TransformerConfig(
                vocab_size=64, hidden=32, n_layers=2, n_heads=4, max_seq=64,
            )
            m = TransformerLM(cfg)
            p = m.init(
                jax.random.PRNGKey(seed),
                {"tokens": np.zeros((1, P), np.int32),
                 "positions": np.zeros((1, P), np.int32)},
            )["params"]
            return m, p

        model, params = _lm(1)
        draft, _ = _lm(1)
        _, dparams = _lm(7)
        rng = np.random.default_rng(13)
        prompts = rng.integers(1, 64, size=(B, P)).astype(np.int32)

        def factory():
            return ContinuousBatcher(
                model, draft, params, dparams,
                total_len=TOTAL, n_draft=NDRAFT, eos_token=None,
            )

        rounds = 8

        def round_times():
            loop = ServingLoop(factory, max_batch=B, queue_capacity=8,
                               watchdog_timeout=30.0,
                               tracer=Tracer(enabled=False))
            for i in range(B):
                loop.submit(Request(rid=i, prompt=prompts[i]))
            loop.run_round()  # admits + settles
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                loop.run_round()
                out.append(time.perf_counter() - t0)
            loop.close()
            return out

        disarm_ledgers()
        get_retrace_ledger().reset()
        bare = float(np.median(round_times()))
        traces_before = _spec_round._cache_size()
        try:
            arm_ledgers()
            armed = float(np.median(round_times()))
            ledger = get_retrace_ledger()
            # the armed rounds dispatched through the ledger without a
            # single new jit trace or sentinel escalation — the batcher's
            # per-prompt edges are exempt, the inline n_draft compiles
            # run under expect_compile, and steady-state decode is warm
            assert _spec_round._cache_size() == traces_before
            assert ledger.sentinel_dumps == 0
            assert "generate/spec_round" in ledger._warm
        finally:
            disarm_ledgers()
            get_retrace_ledger().reset()
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed round {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )


# -- prefix-cache tier guard (ISSUE 11 acceptance) -------------------------
#
# The kvstore's promise: a cache-hit admission dispatches ONLY warm
# executables (the suffix prefill and the import scatter compile once at
# their shape, then every same-shape hit reuses them), the armed store
# adds <5% host overhead to the decode hot path it never touches, and on
# a ~90%-shared-prefix multi-turn trace the cached TTFT p50 drops by a
# CPU-proxy fraction of the shared prefill.  On TPU the drop approaches
# the shared fraction itself (prefill dominates TTFT); on CPU the page
# import transfer and the first decode round dilute it, so the guard
# asserts >= 0.35x the shared fraction over median-of-5 trials.


@pytest.mark.kvcache
class TestKVStoreGuard:
    B, P, TOTAL, NDRAFT, PAGE = 3, 12, 24, 4, 4

    def _models(self, hidden=32, n_layers=2, max_seq=64, prompt=None):
        import jax
        import numpy as np

        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        prompt = self.P if prompt is None else prompt
        cfg = dict(vocab_size=64, hidden=hidden, n_layers=n_layers,
                   n_heads=4, max_seq=max_seq)
        out = []
        for seed in (1, 7):
            m = TransformerLM(TransformerConfig(**cfg))
            p = m.init(
                jax.random.PRNGKey(seed),
                {"tokens": np.zeros((1, prompt), np.int32),
                 "positions": np.zeros((1, prompt), np.int32)},
            )["params"]
            out.append((m, p))
        (model, params), (_, dparams) = out
        return model, model, params, dparams

    def _bat(self, models, total_len=None):
        from rocket_tpu.models.generate import ContinuousBatcher

        model, draft, params, dparams = models
        return ContinuousBatcher(
            model, draft, params, dparams,
            total_len=self.TOTAL if total_len is None else total_len,
            n_draft=self.NDRAFT, eos_token=None,
        )

    def test_zero_retraces_per_cache_hit_admit(self, devices):
        import numpy as np

        from rocket_tpu.models.generate import (
            _spec_import_row,
            _spec_round,
            _spec_suffix_prefill,
        )
        from rocket_tpu.serve import Completed, Request, ServingLoop
        from rocket_tpu.serve.kvstore import PrefixKVStore

        models = self._models()
        store = PrefixKVStore(page_tokens=self.PAGE,
                              capacity_bytes=1 << 30)
        rng = np.random.default_rng(13)
        prompt = rng.integers(1, 64, size=self.P).astype(np.int32)

        def serve(p):
            loop = ServingLoop(lambda: self._bat(models),
                               max_batch=self.B, queue_capacity=8,
                               kvstore=store)
            loop.submit(Request("r", p))
            (out,) = loop.run_until_idle()
            snap = loop.counters.snapshot()
            loop.close()
            assert isinstance(out, Completed)
            return snap

        serve(prompt)                       # miss: stores the pages
        snap = serve(prompt)                # first hit: compiles suffix
        assert snap["kv_hits"] == 1
        warm = (_spec_suffix_prefill._cache_size(),
                _spec_import_row._cache_size(),
                _spec_round._cache_size())
        for _ in range(3):                  # every further same-shape hit
            snap = serve(prompt)
            assert snap["kv_hits"] == 1
        assert (_spec_suffix_prefill._cache_size(),
                _spec_import_row._cache_size(),
                _spec_round._cache_size()) == warm, (
            "a cache-hit admission traced a new executable after warmup "
            "— a shape or dtype leak in the suffix-prefill/import path"
        )

    def test_decode_round_overhead_vs_cache_off_under_5pct(self, devices):
        import numpy as np

        from rocket_tpu.serve import Request, ServingLoop
        from rocket_tpu.serve.kvstore import PrefixKVStore

        models = self._models()
        rng = np.random.default_rng(13)
        prompt = rng.integers(1, 64, size=self.P).astype(np.int32)

        def round_times(store, rounds=8):
            loop = ServingLoop(lambda: self._bat(models),
                               max_batch=self.B, queue_capacity=8,
                               kvstore=store)
            loop.submit(Request("r", prompt))
            loop.run_round()                # admit + compile
            out = []
            for _ in range(rounds):
                t0 = time.perf_counter()
                loop.run_round()
                out.append(time.perf_counter() - t0)
            loop.run_until_idle()
            loop.close()
            return out

        bare = float(np.median(round_times(None)))
        armed = float(np.median(round_times(
            PrefixKVStore(page_tokens=self.PAGE, capacity_bytes=1 << 30))))
        assert armed <= bare * 1.05 + 5e-4, (
            f"armed round {armed * 1e3:.3f}ms vs bare {bare * 1e3:.3f}ms"
        )

    def test_cached_ttft_p50_drop_meets_cpu_proxy(self, devices):
        import numpy as np

        from rocket_tpu.serve import Request, ServingLoop
        from rocket_tpu.serve.kvstore import PrefixKVStore

        # CPU-proxy demo-trace shape: long prompts so prefill dominates
        # the dispatch (224 of 256 prompt tokens shared = 87.5%)
        PROMPT, PAGE, SHARED, NEW, TURNS = 256, 32, 224, 8, 7
        frac = SHARED / PROMPT
        models = self._models(hidden=128, max_seq=PROMPT + 16,
                              prompt=PROMPT)
        rng = np.random.default_rng(5)
        header = rng.integers(1, 64, size=SHARED)

        def turn(t):
            tail = np.random.default_rng(100 + t).integers(
                1, 64, size=PROMPT - SHARED)
            return np.concatenate([header, tail]).astype(np.int32)

        def run(store):
            t0 = time.perf_counter()
            loop = ServingLoop(
                lambda: self._bat(models, total_len=PROMPT + NEW),
                max_batch=1, queue_capacity=4,
                clock=lambda: time.perf_counter() - t0, kvstore=store)
            for t in range(TURNS):
                loop.submit(Request(rid=t, prompt=turn(t)))
                loop.run_until_idle(max_rounds=1_000_000)
            p50 = loop.latency.summary()["ttft_ms/p50"]
            loop.close()
            return p50

        warm = PrefixKVStore(page_tokens=PAGE, capacity_bytes=1 << 30)
        run(warm)                           # compile both paths
        run(warm)
        colds, cacheds = [], []
        for _ in range(3):
            colds.append(run(None))
            cacheds.append(run(PrefixKVStore(page_tokens=PAGE,
                                             capacity_bytes=1 << 30)))
        cold = float(np.median(colds))
        cached = float(np.median(cacheds))
        drop = 1.0 - cached / cold
        assert drop >= 0.35 * frac, (
            f"cached TTFT p50 {cached:.1f}ms vs cold {cold:.1f}ms — drop "
            f"{drop:.0%} under the CPU proxy of the {frac:.0%} shared "
            f"prefill fraction (expected >= {0.35 * frac:.0%})"
        )


# -- warm-start guard (ISSUE 15 acceptance) --------------------------------
#
# The warm-start tier's promise: a SECOND spawn of an identical
# WorkerSpec against the same persistent compile-cache dir reaches READY
# with its goodput ``compile`` bucket under HALF the cold spawn's — every
# ledgered edge either deserializes from the AOT store or retrieves from
# the XLA disk cache — and produces bit-equal tokens.  ``cold_vs_warm``
# measures exactly that (two sequential subprocess spawns sharing one
# fresh cache dir); the guard holds the ratio and persists the record so
# ``experiments/bench_runs.jsonl`` keeps a committed CPU-proxy line.


@pytest.mark.warmstart
class TestWarmStartGuard:
    def test_second_spawn_compiles_under_half_of_cold(self, bench):
        rec = bench.bench_cold_vs_warm(0, 0)
        bench._persist_record(rec)
        cold, warm = rec["cold"], rec["warm"]
        # the cold spawn really compiled (and the worker reported it)
        assert cold["compile_s"] > 0, rec
        # the warm spawn hit the persistent cache, not the compiler
        assert warm["cache_hits"] > 0, rec
        assert warm["compile_s"] < 0.5 * cold["compile_s"], rec["guard"]
        # warm start is an optimization, never a numerics change
        assert rec["bit_equal"] is True, rec
        assert rec["guard"].startswith("warm<0.5x cold"), rec["guard"]


@pytest.mark.trainserve
class TestSwapGuard:
    """Live weight hot-swap guard (ISSUE 17 acceptance): the whole point
    of swapping in place is that it beats tearing the replica down — the
    swap must add ZERO jit traces (params are a jit argument: same
    shapes/dtypes/shardings), and its wall time, charged to the ``swap``
    goodput bucket, must stay well under a cold loop rebuild."""

    def test_swap_zero_retrace_and_beats_cold_rebuild(self, devices,
                                                      tmp_path):
        import numpy as np

        from rocket_tpu.models.generate import _spec_round
        from rocket_tpu.serve.types import Request
        from rocket_tpu.testing import workers as tw

        path = tw.save_tiny_publication(str(tmp_path), step=10,
                                        seed_target=5)

        t0 = time.perf_counter()
        loop = tw.build_tiny_loop()
        cold_build_s = time.perf_counter() - t0

        def serve_one(rid):
            loop.submit(Request(rid=rid,
                                prompt=np.arange(1, 7, dtype=np.int32),
                                max_new_tokens=8))
            for _ in range(200):
                loop.run_round()
                if loop.drain_results():
                    return

        serve_one("warm")           # warm every decode shape
        traces_before = _spec_round._cache_size()
        assert loop.swap_weights(path)
        serve_one("post")
        assert _spec_round._cache_size() == traces_before, (
            "hot-swap retraced — the swapped params changed a jit "
            "signature (shape/dtype/sharding leak)"
        )
        swap_s = loop.counters.swap_ms_total / 1e3
        assert 0.0 < swap_s < 0.5 * cold_build_s, (
            f"swap {swap_s:.3f}s vs cold rebuild {cold_build_s:.3f}s — "
            "the swap path is paying a rebuild-class cost"
        )


@pytest.mark.tenants
class TestTenantGuard:
    """Batch preemption guard (ISSUE 18 acceptance): \"cheap\" means the
    park-and-resume machinery is pure host work — exporting a victim's
    KV pages, parking the ticket, and re-admitting it later must reuse
    the admit/decode shapes the loop already compiled.  A steady-state
    preempt/resume cycle adds ZERO jit traces to the decode round."""

    def test_preempt_resume_zero_retrace(self, devices):
        import numpy as np

        from rocket_tpu.models.generate import _spec_round
        from rocket_tpu.serve.types import Request
        from rocket_tpu.testing import workers as tw

        loop = tw.build_tiny_loop(max_batch=2, kvstore_page_tokens=3)
        rng = np.random.default_rng(23)
        prompts = rng.integers(1, tw.VOCAB,
                               size=(8, tw.P)).astype(np.int32)

        def cycle(tag, i0):
            # a batch row decoding next to a standard row; two
            # interactive arrivals evict the batch row at the round
            # boundary, and run-to-idle parks AND resumes it
            assert loop.submit(Request(rid=f"{tag}-bat",
                                       prompt=prompts[i0],
                                       slo_class="batch")) is None
            assert loop.submit(Request(rid=f"{tag}-std",
                                       prompt=prompts[i0 + 1])) is None
            loop.run_round()
            for j in (2, 3):
                assert loop.submit(Request(rid=f"{tag}-i{j}",
                                           prompt=prompts[i0 + j],
                                           slo_class="interactive"
                                           )) is None
            res = loop.run_until_idle()
            assert sorted(r.rid for r in res) == sorted(
                f"{tag}-{s}" for s in ("bat", "std", "i2", "i3"))

        try:
            cycle("warm", 0)        # compiles every shape involved
            assert loop.counters.preempted >= 1
            assert loop.counters.resumed >= 1
            traces = _spec_round._cache_size()
            pre, res = loop.counters.preempted, loop.counters.resumed
            cycle("run", 4)         # steady state: same shapes again
            assert loop.counters.preempted > pre
            assert loop.counters.resumed > res
            assert _spec_round._cache_size() == traces, (
                "preempt/resume retraced — parking or re-admitting a "
                "batch row changed a jit signature (shape/dtype leak "
                "in the KV export/import path)"
            )
        finally:
            loop.close()


class TestZeroGuard:
    """ZeRO-1 guard (ISSUE 12): the sharding plan's per-device optimizer
    bytes must drop >= (N-1)/N on an N-way data axis, and turning
    ``zero_stage=1`` on must not add jit retraces to the step loop."""

    def test_7b_adam_optimizer_bytes_drop(self, devices):
        """The 7B-Adam memory plan: zero_stage=1 divides the per-device
        optimizer bytes by the data-axis size (a few replicated scalars —
        optax step counts — are all that remains un-sharded)."""
        import jax
        import jax.numpy as jnp
        import optax

        import rocket_tpu as rt
        from rocket_tpu.engine.adapter import FlaxModel
        from rocket_tpu.engine.precision import Policy
        from rocket_tpu.engine.state import TrainState, memory_plan
        from rocket_tpu.models.transformer import (
            TransformerConfig, TransformerLM,
        )
        from rocket_tpu.parallel.mesh import MeshSpec
        from rocket_tpu.parallel.sharding import specs_for_state

        N = 8
        cfg = TransformerConfig.llama2_7b(scan_layers=True)
        runtime = rt.Runtime(mesh=MeshSpec(data=N).build(devices))
        policy = Policy.from_string("bf16_full")
        adapter = FlaxModel(TransformerLM(cfg))
        adapter.configure(runtime.mesh, runtime.rules)
        adapter.apply_policy(policy)
        tx = optax.adamw(1e-5)

        def init_fn():
            batch = {"tokens": jnp.zeros((N, 512), jnp.int32)}
            params, mutable = adapter.init_variables(
                jax.random.PRNGKey(0), batch)
            params = policy.cast_to_param(params)
            return TrainState.create(params, tx, mutable=mutable)

        abstract = jax.eval_shape(init_fn)
        param_specs = adapter.partition_specs(abstract.params, runtime.rules)
        repl = specs_for_state(
            runtime.mesh, abstract, param_specs=param_specs, zero_stage=0)
        zero = specs_for_state(
            runtime.mesh, abstract, param_specs=param_specs, zero_stage=1)
        repl_opt = memory_plan(
            abstract, repl.state_specs, runtime.mesh)["opt_bytes"]
        zero_opt = memory_plan(
            abstract, zero.state_specs, runtime.mesh)["opt_bytes"]
        # 7B Adam: ~25GB of replicated moments to begin with
        assert repl_opt > 20 * (1 << 30)
        # >= (N-1)/N drop == the shard is <= 1/N (+ scalar-count slack)
        assert zero_opt <= repl_opt / N + 1024, (
            f"zero_stage=1 optimizer shard {zero_opt / (1 << 30):.2f} GB "
            f"vs replicated {repl_opt / (1 << 30):.2f} GB — expected a "
            f">= {(N - 1) / N:.0%} drop"
        )
        # stage 3 divides the PARAM storage bytes by N as well
        s3 = specs_for_state(
            runtime.mesh, abstract, param_specs=param_specs, zero_stage=3)
        repl_param = memory_plan(
            abstract, repl.state_specs, runtime.mesh)["param_bytes"]
        s3_param = memory_plan(
            abstract, s3.state_specs, runtime.mesh)["param_bytes"]
        assert s3_param <= repl_param / N + (1 << 20), (
            f"zero_stage=3 param storage {s3_param / (1 << 30):.2f} GB vs "
            f"replicated {repl_param / (1 << 30):.2f} GB — expected a "
            f">= {(N - 1) / N:.0%} drop"
        )
        # offload books the optimizer shard against the host tier instead
        off = memory_plan(
            abstract, s3.state_specs, runtime.mesh, zero_offload=True)
        assert off["opt_bytes"] == 0
        assert off["host_opt_bytes"] > 0
        assert off["total_bytes"] == off["param_bytes"] + off["other_bytes"]

    def test_zero_stage1_no_retrace_per_step(self, devices):
        """The ZeRO constraints live INSIDE the jitted step: stepping N
        times adds ZERO traces over the unsharded step's count (one trace
        per distinct input-sharding signature — the first output's
        XLA-normalized specs cost one warmup retrace on both paths), and
        the steady-state count never grows with further steps."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from rocket_tpu.engine import Objective, TrainState, build_train_step
        from rocket_tpu.parallel.mesh import MeshSpec
        from rocket_tpu.parallel.sharding import specs_for_state

        mesh = MeshSpec(data=4, tensor=2).build(devices)
        params = {
            "w1": jnp.ones((32, 64), jnp.float32),
            "w2": jnp.ones((64, 32), jnp.float32),
        }
        pspecs = {"w1": P(None, "tensor"), "w2": P("tensor", None)}
        tx = optax.adamw(1e-2)
        abstract = jax.eval_shape(lambda: TrainState.create(params, tx))

        def apply_fn(p, mutable, rng, batch, train):
            out = dict(batch)
            out["pred"] = jnp.tanh(batch["x"] @ p["w1"]) @ p["w2"]
            return out, mutable

        loss = Objective("mse", lambda b: jnp.mean((b["pred"] - b["y"]) ** 2))
        batch_sh = NamedSharding(mesh, P("data"))

        def trace_counts(zero_stage):
            plan = specs_for_state(
                mesh, abstract, param_specs=pspecs, zero_stage=zero_stage)
            steps = build_train_step(
                apply_fn, [loss], tx,
                shard_plan=plan if zero_stage else None)
            state = jax.device_put(
                TrainState.create(params, tx), plan.state_shardings)
            rng = np.random.default_rng(0)
            for _ in range(2):  # warmup: first output normalizes shardings
                batch = {
                    "x": jax.device_put(jnp.asarray(
                        rng.normal(size=(8, 32)), jnp.float32), batch_sh),
                    "y": jax.device_put(jnp.asarray(
                        rng.normal(size=(8, 32)), jnp.float32), batch_sh),
                }
                state, _ = steps["sync"](state, batch)
            warm = steps["sync"]._cache_size()
            for _ in range(5):
                batch = {
                    "x": jax.device_put(jnp.asarray(
                        rng.normal(size=(8, 32)), jnp.float32), batch_sh),
                    "y": jax.device_put(jnp.asarray(
                        rng.normal(size=(8, 32)), jnp.float32), batch_sh),
                }
                state, _ = steps["sync"](state, batch)
            return warm, steps["sync"]._cache_size()

        base_warm, base_final = trace_counts(0)
        for stage in (1, 2, 3):
            zero_warm, zero_final = trace_counts(stage)
            assert zero_final == zero_warm, (
                f"zero_stage={stage} retraces per step"
            )
            # <= not ==: stages whose outputs carry explicit shard-plan
            # constraints skip the baseline's one-time output-sharding
            # normalization retrace, so they can legitimately trace FEWER
            assert zero_final <= base_final, (
                f"zero_stage={stage} traced {zero_final}x "
                f"vs baseline {base_final}x"
            )


class TestPipelineGuard:
    """Pipeline-schedule guard (ISSUE 13): interleaved(v=2)'s MEASURED
    bubble fraction — read back from the goodput ledger's per-stage
    ``pipeline/bubble/stage<p>`` buckets, not the analytic plan — must sit
    strictly below GPipe's on the same lockstep proxy run, and the bench
    record's memory columns must realize the 1F1B ≤P residency bound."""

    def test_interleaved_measured_bubble_below_gpipe(self, bench):
        measured = bench.measure_pipeline_schedules()
        gp_b = measured["gpipe"]["bubble_fraction"]
        il_b = measured["interleaved"]["bubble_fraction"]
        assert 0.0 < il_b < gp_b, measured
        # the buckets themselves were populated per stage (the fleet
        # metrics export reads these same keys)
        for sched, cols in measured.items():
            waits = cols["stage_wait_s"]
            assert len(waits) == bench.PIPELINE_PROXY["n_stages"]
            assert all(w >= 0.0 for w in waits) and sum(waits) > 0.0, (
                sched, waits,
            )
        # analytic columns ride along and agree with the ordering
        assert (measured["interleaved"]["bubble_fraction_plan"]
                < measured["gpipe"]["bubble_fraction_plan"])
        assert measured["1f1b"]["live_microbatches"] <= 2
        assert measured["gpipe"]["live_microbatches"] == (
            bench.PIPELINE_PROXY["n_micro"]
        )

    def test_pipeline_record_memory_columns(self, bench):
        """The mem_* columns come from memory_plan() on the pipelined
        proxy transformer; 1F1B's live-activation bound is P/M of
        GPipe's stash on the same config."""
        gp = bench._pipeline_memory_columns("gpipe", 1)
        fb = bench._pipeline_memory_columns("1f1b", 1)
        for cols in (gp, fb):
            assert cols["mem_param_bytes"] > 0
            assert cols["mem_opt_bytes"] > cols["mem_param_bytes"]
            assert cols["mem_total_bytes"] >= (
                cols["mem_param_bytes"] + cols["mem_opt_bytes"]
            )
        # state bytes identical across schedules; only residency moves
        assert gp["mem_total_bytes"] == fb["mem_total_bytes"]
        # P=2, M=4: 1F1B holds min(P, M)=2 of GPipe's 4 live microbatches
        assert 2 * fb["mem_live_activation_bytes"] == (
            gp["mem_live_activation_bytes"]
        )
