"""Engine tests: jitted step semantics on the 8-fake-device CPU mesh.

Covers SURVEY §4's required pyramid slices: in-step loss reduction,
grad-accum equivalence, bf16 policy, and 1-vs-8-device data-parallel parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from rocket_tpu.engine import (
    Objective,
    Policy,
    TrainState,
    build_eval_step,
    build_train_step,
)
from rocket_tpu.parallel.mesh import MeshSpec, single_device_mesh
from rocket_tpu.parallel.sharding import batch_sharding


def _linear_apply(params, mutable, rng, batch, train):
    out = dict(batch)
    out["pred"] = batch["x"] @ params["w"]
    return out, mutable


def _mse(batch):
    return jnp.mean((batch["pred"] - batch["y"]) ** 2)


def _make_state(accum=1, rng_seed=0):
    w = jnp.ones((4, 1), jnp.float32)
    tx = optax.sgd(0.1)
    return (
        TrainState.create(
            {"w": w},
            tx,
            rng=jax.random.PRNGKey(rng_seed),
            gradient_accumulation_steps=accum,
        ),
        tx,
    )


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def test_train_step_reduces_loss():
    state, tx = _make_state()
    steps = build_train_step(_linear_apply, [Objective("mse", _mse)], tx)
    batch = _batch()
    losses = []
    for _ in range(20):
        state, logs = steps["sync"](state, batch)
        losses.append(float(logs["loss"]))
    assert losses[-1] < losses[0] * 0.1
    assert int(state.step) == 20


def test_grad_accum_matches_large_batch():
    """n micro-batches with accumulation == one batch of n× size (reference
    semantics: accelerate accumulate(), module.py:211)."""
    big = _batch(n=16, seed=1)
    halves = [
        {k: v[:8] for k, v in big.items()},
        {k: v[8:] for k, v in big.items()},
    ]

    state_big, tx = _make_state()
    steps_big = build_train_step(_linear_apply, [Objective("mse", _mse)], tx)
    state_big, _ = steps_big["sync"](state_big, big)

    state_acc, tx2 = _make_state(accum=2)
    steps_acc = build_train_step(
        _linear_apply, [Objective("mse", _mse)], tx2, gradient_accumulation_steps=2
    )
    state_acc, _ = steps_acc["micro"](state_acc, halves[0])
    state_acc, _ = steps_acc["sync"](state_acc, halves[1])

    np.testing.assert_allclose(
        np.asarray(state_big.params["w"]),
        np.asarray(state_acc.params["w"]),
        rtol=1e-5,
    )
    assert int(state_acc.step) == 1


def test_bf16_policy_computes_in_bf16():
    captured = {}

    def apply(params, mutable, rng, batch, train):
        captured["dtype"] = params["w"].dtype
        out = dict(batch)
        out["pred"] = (batch["x"].astype(params["w"].dtype) @ params["w"]).astype(
            jnp.float32
        )
        return out, mutable

    state, tx = _make_state()
    steps = build_train_step(
        apply, [Objective("mse", _mse)], tx, policy=Policy.from_string("bf16")
    )
    state, _ = steps["sync"](state, _batch())
    assert captured["dtype"] == jnp.bfloat16
    # master params stay f32
    assert state.params["w"].dtype == jnp.float32


def test_data_parallel_matches_single_device(devices):
    """1-device vs 8-fake-device sharded batch produce identical updates
    (SURVEY §4 numerical parity requirement)."""
    batch = _batch(n=16, seed=2)

    state1, tx1 = _make_state()
    steps1 = build_train_step(_linear_apply, [Objective("mse", _mse)], tx1)
    state1, logs1 = steps1["sync"](state1, jax.device_put(batch, devices[0]))

    mesh = MeshSpec().build(devices)
    sharded = jax.device_put(batch, batch_sharding(mesh, ndim=2))
    state8, tx8 = _make_state()
    steps8 = build_train_step(_linear_apply, [Objective("mse", _mse)], tx8)
    state8, logs8 = steps8["sync"](state8, sharded)

    np.testing.assert_allclose(
        np.asarray(state1.params["w"]), np.asarray(state8.params["w"]), rtol=1e-5
    )
    np.testing.assert_allclose(float(logs1["loss"]), float(logs8["loss"]), rtol=1e-5)


def test_eval_step_returns_outputs():
    state, _ = _make_state()
    eval_step = build_eval_step(_linear_apply, [Objective("mse", _mse)])
    out, logs = eval_step(state, _batch())
    assert "pred" in out
    assert "loss" in logs


class TestParamsEma:
    def _module(self, decay):
        import rocket_tpu as rt
        from rocket_tpu.models.lenet import LeNet
        from rocket_tpu.models.objectives import cross_entropy

        runtime = rt.Runtime()
        mod = rt.Module(
            LeNet(num_classes=10),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=1e-2, ema_decay=decay),
            ],
        )
        mod.bind(runtime)
        mod.setup()
        return mod

    def _batch(self):
        rng = np.random.default_rng(0)
        return {
            "image": jnp.asarray(rng.normal(size=(8, 28, 28, 1)), jnp.float32),
            "label": jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32),
        }

    def _run(self, mod, n=3):
        import rocket_tpu as rt

        attrs = rt.Attributes(
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
        )
        for _ in range(n):
            attrs.batch = self._batch()
            mod.launch(attrs)
        return mod

    def test_decay_zero_tracks_params_exactly(self, devices):
        mod = self._run(self._module(decay=0.0))
        ema = mod.ema_params
        assert ema is not None
        for a, b in zip(
            jax.tree_util.tree_leaves(ema),
            jax.tree_util.tree_leaves(mod.state.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        mod.destroy()

    def test_ema_lags_params(self, devices):
        mod = self._run(self._module(decay=0.9))
        ema = mod.ema_params
        params = mod.state.params
        diffs = [
            float(jnp.abs(a - b).max())
            for a, b in zip(
                jax.tree_util.tree_leaves(ema),
                jax.tree_util.tree_leaves(params),
            )
        ]
        assert any(d > 0 for d in diffs)  # lags behind the live params
        assert all(np.isfinite(d) for d in diffs)
        mod.destroy()

    def test_init_copies_do_not_alias_params(self, devices):
        # jnp.asarray would alias the param buffers; with the donated
        # train step that is "attempt to donate the same buffer twice"
        # on TPU (donation is a no-op on CPU, so only the aliasing itself
        # is checkable here).
        from rocket_tpu.engine.ema import params_ema

        params = {"w": jnp.arange(4, dtype=jnp.float32)}
        state = params_ema(0.9).init(params)
        assert state.ema["w"] is not params["w"]
        assert (state.ema["w"].unsafe_buffer_pointer()
                != params["w"].unsafe_buffer_pointer())

    def test_no_ema_returns_none(self, devices):
        import rocket_tpu as rt
        from rocket_tpu.models.lenet import LeNet
        from rocket_tpu.models.objectives import cross_entropy

        runtime = rt.Runtime()
        mod = rt.Module(
            LeNet(num_classes=10),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=1e-2),
            ],
        )
        mod.bind(runtime)
        mod.setup()
        self._run(mod)
        assert mod.ema_params is None
        mod.destroy()


def test_eval_with_ema_uses_ema_weights(devices):
    """Module(eval_with_ema=True): the jitted eval step runs the EMA
    weights — with decay=1.0 the EMA never moves off init, so eval logits
    must equal the INITIAL model's, not the trained one's."""
    import rocket_tpu as rt
    from rocket_tpu.models.lenet import LeNet
    from rocket_tpu.models.objectives import cross_entropy

    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(8, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32),
    }

    runtime = rt.Runtime()
    mod = rt.Module(
        LeNet(num_classes=10),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=5e-2, ema_decay=1.0),
        ],
        eval_with_ema=True,
    )
    mod.bind(runtime)
    mod.setup()
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    attrs.batch = batch
    mod.launch(attrs)  # materializes; EMA snapshot = init params
    init_eval = rt.Attributes(
        looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
    )
    init_eval.batch = dict(batch)
    mod.launch(init_eval)
    frozen_logits = np.asarray(init_eval.batch["logits"])

    for _ in range(3):  # train more; live params move, EMA (decay=1) doesn't
        attrs.batch = dict(batch)
        mod.launch(attrs)
    later_eval = rt.Attributes(
        looper=rt.Attributes(grad_enabled=False, state=rt.Attributes())
    )
    later_eval.batch = dict(batch)
    mod.launch(later_eval)
    np.testing.assert_array_equal(
        np.asarray(later_eval.batch["logits"]), frozen_logits
    )
    # sanity: live params DID move away from init
    diffs = [
        float(jnp.abs(a - b).max())
        for a, b in zip(
            jax.tree_util.tree_leaves(mod.state.params),
            jax.tree_util.tree_leaves(mod.ema_params),
        )
    ]
    assert any(d > 0 for d in diffs)
    mod.destroy()


def test_eval_with_ema_requires_decay(devices):
    import rocket_tpu as rt
    from rocket_tpu.models.lenet import LeNet
    from rocket_tpu.models.objectives import cross_entropy

    mod = rt.Module(
        LeNet(num_classes=10),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=1e-2),  # no ema_decay
        ],
        eval_with_ema=True,
    )
    mod.bind(rt.Runtime())
    with pytest.raises(RuntimeError, match="ema_decay"):
        mod.setup()
