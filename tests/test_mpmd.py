"""MPMD pipeline runner tests (parallel/mpmd.py): per-stage 1F1B
scheduling, transport discipline, bit-equality of the threaded and
lockstep drivers against the single-controller reference, measured
residency bounds, goodput bubble buckets, and the stage<->process
mapping helpers.  The 2-process SocketEndpoint run (real OS processes,
TCP loopback) is the slow tail."""

import os
import socket
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.observe.ledger import get_goodput, get_retrace_ledger
from rocket_tpu.parallel import multihost
from rocket_tpu.parallel.mpmd import (
    ChunkPrograms,
    QueueTransport,
    SocketEndpoint,
    merge_chunk_grads,
    run_lockstep,
    run_pipeline,
    run_reference,
    split_chunks,
    stage_schedule,
)


def _layer(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stack(rng, n_layers, width):
    keys = jax.random.split(rng, n_layers)
    return {
        "w": jnp.stack([
            jax.random.normal(k, (width, width)) * 0.3 for k in keys
        ]),
        "b": jnp.zeros((n_layers, width)),
    }


def _problem(n_layers=4, width=8, n_micro=4, micro_b=2):
    params = _stack(jax.random.PRNGKey(0), n_layers, width)
    micros = jax.random.normal(
        jax.random.PRNGKey(1), (n_micro, micro_b, width)
    )
    target = jax.random.normal(jax.random.PRNGKey(2), (micro_b, width))
    return params, micros, lambda y: jnp.mean((y - target) ** 2)


def _sched_kwargs(schedule):
    return {"schedule": schedule,
            "n_chunks": 2 if schedule == "interleaved" else 1}


# -- per-stage scheduler ----------------------------------------------------


def test_stage_schedule_1f1b_bounds_inflight():
    """1F1B at stage p: P-1-p warmup forwards, strict alternation, then
    cooldown — the running forward-residual count never exceeds P - p,
    and each backward lands in ascending microbatch order."""
    P, M = 4, 8
    for p in range(P):
        items = stage_schedule("1f1b", p, P, M)
        assert len(items) == 2 * M
        live = peak = 0
        bwd_seen = []
        for kind, m, c in items:
            assert c == 0
            live += 1 if kind == "fwd" else -1
            peak = max(peak, live)
            if kind == "bwd":
                bwd_seen.append(m)
        assert live == 0
        assert peak <= P - p, (p, peak)
        assert bwd_seen == sorted(bwd_seen)


def test_stage_schedule_gpipe_and_interleaved_order():
    P, M, v = 2, 4, 2
    gp = stage_schedule("gpipe", 0, P, M)
    assert gp == (
        [("fwd", m, 0) for m in range(M)] + [("bwd", m, 0) for m in range(M)]
    )
    il = stage_schedule("interleaved", 0, P, M, n_chunks=v)
    # chunk slot ascending on the forward, descending on the backward;
    # ascending micro within each chunk (the accumulation-order contract)
    assert il[:M] == [("fwd", m, 0) for m in range(M)]
    assert il[M:2 * M] == [("fwd", m, 1) for m in range(M)]
    assert il[2 * M:3 * M] == [("bwd", m, 1) for m in range(M)]
    assert il[3 * M:] == [("bwd", m, 0) for m in range(M)]


def test_stage_schedule_validation():
    with pytest.raises(ValueError, match="unknown schedule"):
        stage_schedule("zigzag", 0, 2, 4)
    with pytest.raises(ValueError, match="requires schedule='interleaved'"):
        stage_schedule("1f1b", 0, 2, 4, n_chunks=2)
    with pytest.raises(ValueError, match="out of range"):
        stage_schedule("gpipe", 2, 2, 4)


def test_split_merge_round_trip():
    params, _, _ = _problem(n_layers=8)
    for P, v in [(2, 1), (2, 2), (4, 1)]:
        per_stage = split_chunks(params, P, v)
        merged = merge_chunk_grads(per_stage, P, v)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            params, merged,
        )
    with pytest.raises(ValueError, match="not divisible"):
        split_chunks(params, 3, 1)


# -- threaded driver vs the single-controller reference ---------------------


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "interleaved"])
def test_run_pipeline_bit_equal_to_reference(schedule):
    """The CPU-emulated MPMD run (one thread per stage, QueueTransport)
    is BITWISE equal to the single-controller replay of the same chunk
    programs — the fixed accumulation-order contract, not a tolerance."""
    params, micros, loss_fn = _problem()
    kw = _sched_kwargs(schedule)
    res = run_pipeline(_layer, params, micros, loss_fn, n_stages=2,
                       goodput=False, **kw)
    ref_loss, ref_grads = run_reference(
        _layer, params, micros, loss_fn, n_stages=2,
        n_chunks=kw["n_chunks"],
    )
    assert np.array_equal(np.asarray(res.loss), np.asarray(ref_loss))
    mismatched = [
        jax.tree_util.keystr(path)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(res.grads),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        )
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]
    assert not mismatched, mismatched


def test_run_pipeline_1f1b_measured_residency():
    """The ≤P residency bound is MEASURED, not just planned: under 1F1B
    stage p peaks at ≤ P - p live microbatches while GPipe stashes all
    M of them."""
    params, micros, loss_fn = _problem(n_micro=8)
    P = 2
    fb = run_pipeline(_layer, params, micros, loss_fn, n_stages=P,
                      schedule="1f1b", goodput=False)
    for r in fb.reports:
        assert r.max_live <= P - r.stage, (r.stage, r.max_live)
    gp = run_pipeline(_layer, params, micros, loss_fn, n_stages=P,
                      schedule="gpipe", goodput=False)
    assert [r.max_live for r in gp.reports] == [8, 8]
    assert fb.plan["live_microbatches"] <= P < gp.plan["live_microbatches"]


def test_chunk_programs_exempt_from_retrace_sentinel():
    """The MPMD jit edges are shape-polymorphic across configs — they
    must be registered exempt so the zero-retrace sentinel never fires
    on a legitimate config change."""
    programs = ChunkPrograms(_layer)
    exempt = get_retrace_ledger()._exempt
    assert {programs.FWD, programs.BWD, programs.LOSS} <= exempt


# -- lockstep driver: the bubble-measurement vehicle ------------------------


def test_run_lockstep_bit_equal_and_goodput_buckets():
    """Lockstep tick rounds keep the same loss/grad bits as the threaded
    driver and the reference, and every stage's structural wait lands in
    its pipeline/bubble/stage<p> goodput bucket."""
    params, micros, loss_fn = _problem(n_micro=4)
    gp = get_goodput()
    was_armed = gp.armed
    try:
        gp.start_run()
        res = run_lockstep(_layer, params, micros, loss_fn, n_stages=2,
                           schedule="gpipe")
        gp.end_run()
        snap = gp.snapshot()
    finally:
        gp.armed = was_armed
    ref_loss, ref_grads = run_reference(
        _layer, params, micros, loss_fn, n_stages=2
    )
    assert np.array_equal(np.asarray(res.loss), np.asarray(ref_loss))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        res.grads, ref_grads,
    )
    for p in range(2):
        key = f"pipeline/bubble/stage{p}_s"
        assert key in snap, sorted(snap)
        assert snap[key] == pytest.approx(res.reports[p].wait_s)
    # GPipe on 2 stages must show a real fill/drain bubble
    assert res.bubble_fraction > 0.0
    assert res.plan["bubble_fraction"] == pytest.approx(1 / 5)


def test_run_lockstep_interleaved_lower_tick_bubble():
    """Structural claim at tick granularity (immune to timer noise): the
    interleaved(v=2) walk spreads the same fill/drain idle rounds over
    ~2x as many (half-size) work items, so its idle-per-tick fraction is
    strictly below GPipe's — the ~1/v bubble cut the bench guard then
    confirms in measured seconds."""
    params, micros, loss_fn = _problem(n_layers=8, n_micro=8)

    def tick_bubble(schedule):
        res = run_lockstep(_layer, params, micros, loss_fn, n_stages=2,
                           goodput=False, **_sched_kwargs(schedule))
        # wait_s = idle_rounds x mean item seconds exactly, so the tick
        # counts are recoverable from the report without trusting wall
        # time: idle_rounds = wait_s / (busy_s / n_items)
        idle = sum(
            round(r.wait_s / (r.busy_s / r.n_items)) for r in res.reports
        )
        items = sum(r.n_items for r in res.reports)
        return idle / (idle + items)

    gp_b = tick_bubble("gpipe")
    il_b = tick_bubble("interleaved")
    assert 0.0 < il_b < gp_b, (gp_b, il_b)


# -- stage <-> process mapping helpers --------------------------------------


def test_stage_process_groups_mapping():
    assert multihost.stage_process_groups(2, 8) == [
        [0, 1, 2, 3], [4, 5, 6, 7]
    ]
    assert multihost.stage_process_groups(4, 4) == [[0], [1], [2], [3]]
    with pytest.raises(ValueError, match="do not split"):
        multihost.stage_process_groups(3, 8)
    assert multihost.stage_of_process(2, process_id=5, n_processes=8) == 1
    assert multihost.stage_peers(2, process_id=5, n_processes=8) == [
        4, 5, 6, 7
    ]
    assert multihost.stage_neighbors(4, 0) == (3, 1)
    assert multihost.stage_neighbors(4, 3) == (2, 0)
    with pytest.raises(ValueError, match="out of range"):
        multihost.stage_neighbors(4, 4)
    # single-process degradation: everything is stage 0
    assert multihost.stage_process_groups(1, 1) == [[0]]
    assert multihost.stage_of_process(1, process_id=0, n_processes=1) == 0


# -- socket transport -------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_socket_endpoint_reorders_tagged_frames():
    """The TCP endpoint delivers by (src, tag), not arrival order — the
    reorder buffer is what lets a 1F1B consumer pull the frame its
    schedule wants next."""
    port = _free_port()
    holder = {}

    def serve():
        ep = SocketEndpoint.listen(port, stage=1)
        holder["server"] = ep
        ep.send(0, ("a", 1, 1), jnp.full((2,), 7.0))
        ep.send(0, ("a", 1, 0), jnp.full((2,), 3.0))
        ep.send(0, ("a", 1, 2), jnp.full((2,), 9.0))

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    client = SocketEndpoint.connect("127.0.0.1", port, stage=0)
    try:
        t.join(timeout=30)
        # ask for the SECOND-sent frame first
        v0, _ = client.recv(1, ("a", 1, 0), timeout=30)
        v1, _ = client.recv(1, ("a", 1, 1), timeout=30)
        np.testing.assert_array_equal(np.asarray(v0), np.full((2,), 3.0))
        np.testing.assert_array_equal(np.asarray(v1), np.full((2,), 7.0))
        # a frame whose src does not match the expected peer is an error
        # (the third frame is still in flight, so _next has one to read)
        with pytest.raises(ValueError, match="expected frames from"):
            client._next(src=5, timeout=30)
    finally:
        client.close()
        holder["server"].close()


@pytest.mark.slow
def test_mpmd_two_real_processes_bit_equal(tmp_path):
    """REAL 2-process MPMD: two OS processes, one pipeline stage each,
    activations/cotangents over TCP loopback (SocketEndpoint) — the
    merged result is bit-equal to the single-controller program."""
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(worker))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, "mpmd", str(port), "2", str(stage),
             str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for stage in range(2)
    ]
    outs = []
    for stage, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=300)
        outs.append(out)
        assert proc.returncode == 0, f"stage {stage} failed:\n{out}"
        assert f"MPMD-OK {stage}" in out, out

    params, micros, loss_fn = _problem()
    ref_loss, ref_grads = run_reference(
        _layer, params, micros, loss_fn, n_stages=2
    )
    g0 = np.load(tmp_path / "mpmd_stage0.npz")
    g1 = np.load(tmp_path / "mpmd_stage1.npz")
    merged = merge_chunk_grads(
        [{0: {"w": g0["w"], "b": g0["b"]}}, {0: {"w": g1["w"], "b": g1["b"]}}],
        n_stages=2, n_chunks=1,
    )
    assert np.array_equal(float(g1["loss"]), np.asarray(ref_loss))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        merged, ref_grads,
    )
    # the residency bound held across real processes too
    assert int(g0["max_live"]) <= 2 and int(g1["max_live"]) <= 1
