"""Trace-name lint (ISSUE 9 satellite): every literal trace event name
in the library follows the lowercase ``cat/name`` slash convention.

The merged cross-host timeline, the flight-recorder tail, the Prometheus
export, and the goodput/ledger counters all key off these names; a
dot-separated or CamelCase stray silently forks the namespace (this lint
caught ``quant.int8_matmul.fallback`` and ``tune.probe.dead``, renamed to
slash form when it landed).  The scan is AST-based so multi-line calls
are seen and docstring examples are not.
"""

import ast
import os
import re
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "rocket_tpu")

# The emitting calls whose first positional argument is an event name.
_EMITTERS = {"span", "counter", "instant", "health"}

# lowercase slug segments joined by '/' — at least one slash (a bare
# word has no category and collides with everything).  Dots are allowed
# INSIDE a segment (e.g. a dotted metric suffix), never as the separator.
_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_.]+)+$")


def _called_name(func):
    """The trailing identifier of the call target: ``span`` for both the
    module-level convenience and ``tracer.span``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _literal_name(node):
    """First-arg string literal, with f-string ``{...}`` holes filled by
    a placeholder segment (``f"{prefix}/depth"`` lints as ``x/depth``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("x")
        return "".join(parts)
    return None


def _scan_file(path):
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:  # pragma: no cover - the suite would be broken
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if _called_name(node.func) not in _EMITTERS:
            continue
        name = _literal_name(node.args[0])
        if name is None:
            continue  # computed names are the caller's responsibility
        out.append((path, node.lineno, name))
    return out


def _all_sites():
    sites = []
    for dirpath, _dirnames, filenames in os.walk(PKG):
        for fname in filenames:
            if fname.endswith(".py"):
                sites.extend(_scan_file(os.path.join(dirpath, fname)))
    return sites


@pytest.mark.goodput
def test_library_emits_trace_events():
    # the lint is only meaningful if the scan actually sees the emitters
    names = {name for _p, _l, name in _all_sites()}
    assert {"serve/submit", "ledger/compile",
            "quant/int8_matmul/fallback"} <= names


@pytest.mark.goodput
def test_trace_names_follow_slash_convention():
    bad = [
        f"{os.path.relpath(path, REPO)}:{line}: {name!r}"
        for path, line, name in _all_sites()
        if not _NAME_RE.match(name)
    ]
    assert not bad, (
        "trace event names must be lowercase 'cat/name' slugs "
        "(see docs/observability.md):\n  " + "\n  ".join(bad)
    )
