"""Trace-name lint (ISSUE 9 satellite): every literal trace event name
in the library follows the lowercase ``cat/name`` slash convention.

The merged cross-host timeline, the flight-recorder tail, the Prometheus
export, and the goodput/ledger counters all key off these names; a
dot-separated or CamelCase stray silently forks the namespace (this lint
caught ``quant.int8_matmul.fallback`` and ``tune.probe.dead``, renamed to
slash form when it landed).  The scan is AST-based so multi-line calls
are seen and docstring examples are not.
"""

import ast
import os
import re
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "rocket_tpu")

# The emitting calls whose first positional argument is an event name.
# ``_instant`` is FleetRouter's tracer-guarded wrapper — same first-arg
# contract, so its fleet/* names lint too.
_EMITTERS = {"span", "counter", "instant", "health", "flow", "_instant"}

# lowercase slug segments joined by '/' — at least one slash (a bare
# word has no category and collides with everything).  Dots are allowed
# INSIDE a segment (e.g. a dotted metric suffix), never as the separator.
_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_.]+)+$")


def _called_name(func):
    """The trailing identifier of the call target: ``span`` for both the
    module-level convenience and ``tracer.span``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _literal_name(node):
    """First-arg string literal, with f-string ``{...}`` holes filled by
    a placeholder segment (``f"{prefix}/depth"`` lints as ``x/depth``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("x")
        return "".join(parts)
    return None


def _scan_file(path):
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:  # pragma: no cover - the suite would be broken
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if _called_name(node.func) not in _EMITTERS:
            continue
        name = _literal_name(node.args[0])
        if name is None:
            continue  # computed names are the caller's responsibility
        out.append((path, node.lineno, name))
    return out


def _all_sites():
    sites = []
    for dirpath, _dirnames, filenames in os.walk(PKG):
        for fname in filenames:
            if fname.endswith(".py"):
                sites.extend(_scan_file(os.path.join(dirpath, fname)))
    return sites


@pytest.mark.goodput
def test_library_emits_trace_events():
    # the lint is only meaningful if the scan actually sees the emitters
    names = {name for _p, _l, name in _all_sites()}
    assert {"serve/submit", "ledger/compile",
            "quant/int8_matmul/fallback",
            # multi-tenant serving: preemption lifecycle markers
            "serve/preempt", "serve/resume",
            # distributed request tracing: the stitched-timeline and
            # critical-path event vocabulary (docs/observability.md)
            "serve/request", "serve/pool_fetch", "serve/first_token",
            "serve/new_weights", "fleet/delivered", "fleet/requeued",
            "pool/fetch",
            # ZeRO host-offload round trip (engine/offload.py)
            "offload/d2h", "offload/h2d"} <= names


# -- jax.jit chokepoint lint (ISSUE 15 satellite) ----------------------------
#
# Every ``jax.jit`` call site in the library must either dispatch through
# ``observe.ledger.ledger_call`` (the retrace sentinel + warm-start
# chokepoint) or appear below with the reason it legitimately doesn't.
# The assertion is STRICT set equality: a new jit edge fails until it is
# consciously classified here, and a removed one fails until its stale
# entry is dropped — sites can't silently dodge the sentinel or the
# WarmupPlan.  Keys are ``(path-under-rocket_tpu, enclosing def/assign)``.

KNOWN_JIT_SITES = {
    # ledgered: dispatch routes through ledger_call
    ("engine/step.py", "steps"): "ledgered via _AnnotatedStep (sync)",
    ("engine/step.py", "build_train_step"):
        "ledgered via _AnnotatedStep (micro)",
    ("engine/step.py", "build_window_step"):
        "ledgered via _AnnotatedStep (window)",
    ("engine/step.py", "build_eval_step"):
        "ledgered via _AnnotatedStep (eval)",
    ("models/generate.py", "_spec_prefill"):
        "ledgered: ContinuousBatcher.start",
    ("models/generate.py", "_spec_round"):
        "ledgered: ContinuousBatcher.step",
    ("models/generate.py", "_spec_admit"):
        "ledgered: ContinuousBatcher.admit",
    ("models/generate.py", "_spec_import_row"):
        "ledgered: admit_prefilled / kvstore import",
    ("models/generate.py", "_spec_suffix_prefill"):
        "ledgered: cached-prefix suffix prefill",
    # exempt: one-shot or deliberately unledgered edges, with reasons
    ("models/generate.py", "_prefill_cache"):
        "exempt: chunked-prefill helper, inner edge of ledgered entries",
    ("models/generate.py", "_chunk_step"):
        "exempt: chunked-prefill helper, inner edge of ledgered entries",
    ("models/generate.py", "_spec_batched_run"):
        "exempt: one-dispatch offline path, not the serving loop",
    ("models/generate.py", "_chunk_probs"):
        "exempt: offline eval utility (perplexity chunks)",
    ("ops/quant.py", "_int8_matmul_kernel_call"):
        "exempt: kernel micro-dispatch, traced via quant/* instants",
    ("observe/meter.py", "_launch_in_step"):
        "exempt: MFU meter's own probe, must not perturb the ledger",
    ("parallel/mpmd.py", "__init__"):
        "exempt: per-stage MPMD programs, single compile at stage build",
    ("parallel/multihost.py", "_replicate_fn"):
        "exempt: one-shot replication helper at setup",
    ("core/module.py", "materialize"):
        "exempt: one-shot sharded state init, before any step exists",
}


def _enclosing_context(tree, target):
    """Name of the nearest enclosing def (or assignment target) holding
    ``target`` — the stable, line-number-free identity of a jit site."""
    class _Finder(ast.NodeVisitor):
        def __init__(self):
            self.stack = []
            self.found = None

        def generic_visit(self, node):
            if node is target:
                self.found = self.stack[-1] if self.stack else "<module>"
            if self.found is None:
                super().generic_visit(node)

        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            name = node.targets[0].id \
                if isinstance(node.targets[0], ast.Name) else None
            if name:
                self.stack.append(name)
            self.generic_visit(node)
            if name:
                self.stack.pop()

    finder = _Finder()
    finder.visit(tree)
    return finder.found or "<module>"


def _jit_sites():
    """Every ``jax.jit`` attribute reference in the library — direct
    calls, decorators, and ``functools.partial(jax.jit, ...)`` all
    contain the ``jax.jit`` Attribute node."""
    sites = set()
    for dirpath, _dirnames, filenames in os.walk(PKG):
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:  # pragma: no cover
                    continue
            rel = os.path.relpath(path, PKG)
            for node in ast.walk(tree):
                if (isinstance(node, ast.Attribute) and node.attr == "jit"
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "jax"):
                    sites.add((rel, _enclosing_context(tree, node)))
    return sites


@pytest.mark.goodput
def test_every_jit_site_is_ledgered_or_exempt():
    found = _jit_sites()
    known = set(KNOWN_JIT_SITES)
    new = sorted(found - known)
    stale = sorted(known - found)
    assert not new and not stale, (
        "jax.jit site inventory drifted.\n"
        "NEW sites (route them through ledger_call, or classify them in "
        "KNOWN_JIT_SITES with a reason):\n  "
        + "\n  ".join(f"{p}::{ctx}" for p, ctx in new)
        + "\nSTALE entries (the site is gone — drop them):\n  "
        + "\n  ".join(f"{p}::{ctx}" for p, ctx in stale)
    )


@pytest.mark.goodput
def test_trace_names_follow_slash_convention():
    bad = [
        f"{os.path.relpath(path, REPO)}:{line}: {name!r}"
        for path, line, name in _all_sites()
        if not _NAME_RE.match(name)
    ]
    assert not bad, (
        "trace event names must be lowercase 'cat/name' slugs "
        "(see docs/observability.md):\n  " + "\n  ".join(bad)
    )
