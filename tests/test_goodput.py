"""Goodput ledger, retrace sentinel, and metrics export (ISSUE 9).

Covers the tentpole's acceptance criteria beyond the overhead guards in
tests/test_bench_guard.py::TestGoodputGuard:

- goodput buckets (plus the explicit ``unattributed`` remainder) sum to
  the measured wall window within 1% on a real instrumented Looper run;
- an injected shape-change retrace escalates into EXACTLY ONE sentinel
  flight dump naming the executable and the offending shapes — deduped
  per (edge, signature), suppressed by ``exempt`` / ``expect_compile``;
- the new gauge/counter events round-trip through the Chrome-trace
  schema, and ``memory_stats()`` telemetry is a silent no-op on CPU;
- ``/metrics`` serves parseable Prometheus text (version 0.0.4) and the
  export CLI merges per-replica snapshots (counters sum, percentiles
  take the worst replica);
- flight-dump retention keeps the newest N dirs, and registered dump
  writers drop ``goodput.json`` into every dump.
"""

import json
import os
import re
import sys
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture()
def clean_ledgers():
    """Pristine global ledgers on entry AND exit — earlier suite tests
    (any Launcher run arms them) must not leak counts in either
    direction."""
    from rocket_tpu.observe.ledger import (
        disarm_ledgers,
        get_retrace_ledger,
        set_step_cost,
    )

    def _pristine():
        disarm_ledgers()
        get_retrace_ledger().reset()
        get_retrace_ledger().set_recorder(None)
        set_step_cost(None, None, None)

    _pristine()
    yield
    _pristine()


# -- retrace sentinel -------------------------------------------------------


@pytest.mark.goodput
class TestRetraceSentinel:
    def _dump_dirs(self, out_dir):
        from rocket_tpu.observe.recorder import FlightRecorder

        if not os.path.isdir(out_dir):
            return []
        return sorted(
            e for e in os.listdir(out_dir)
            if FlightRecorder._DUMP_DIR.match(e)
        )

    def test_shape_change_triggers_exactly_one_dump(
        self, devices, tmp_path, clean_ledgers
    ):
        import jax
        import jax.numpy as jnp

        from rocket_tpu.observe.ledger import (
            arm_ledgers,
            get_retrace_ledger,
            ledger_call,
        )
        from rocket_tpu.observe.recorder import FlightRecorder
        from rocket_tpu.observe.trace import Tracer

        tracer = Tracer(capacity=256, enabled=True)
        rec = FlightRecorder(tracer=tracer, out_dir=str(tmp_path))
        arm_ledgers(recorder=rec)
        ledger = get_retrace_ledger()

        fn = jax.jit(lambda x: x * 2.0)
        ledger_call(fn, "probe/sentinel", jnp.ones((2,)))   # cold compile
        ledger_call(fn, "probe/sentinel", jnp.ones((2,)))   # marks warm
        assert ledger.sentinel_dumps == 0
        assert not self._dump_dirs(tmp_path)

        # the injected shape change: one retrace, one dump
        ledger_call(fn, "probe/sentinel", jnp.ones((3,)))
        assert ledger.retraces == 1
        assert ledger.sentinel_dumps == 1
        dumps = self._dump_dirs(tmp_path)
        assert len(dumps) == 1
        # the dump names the executable in its directory slug...
        assert "retrace-probe-sentinel" in dumps[0]
        # ...and the trace.json carries the sentinel instant with the
        # executable name and the offending shapes
        with open(tmp_path / dumps[0] / "trace.json") as f:
            doc = json.load(f)
        sentinels = [e for e in doc["traceEvents"]
                     if e["name"] == "ledger/retrace"]
        assert len(sentinels) == 1
        assert sentinels[0]["ph"] == "i"
        assert sentinels[0]["args"]["executable"] == "probe/sentinel"
        assert "float32[3]" in sentinels[0]["args"]["shapes"]

        # dedup: the SAME (edge, signature) retracing again — here via a
        # fresh executable dispatched under the same ledger name — must
        # not produce a second dump
        fn2 = jax.jit(lambda x: x * 2.0)
        ledger_call(fn2, "probe/sentinel", jnp.ones((3,)))
        assert ledger.retraces == 2
        assert ledger.sentinel_dumps == 1
        assert len(self._dump_dirs(tmp_path)) == 1

        # the ledger recorded both the cold compile and the retrace
        recs = [(r.name, r.retrace) for r in ledger.records()]
        assert ("probe/sentinel", False) in recs
        assert ("probe/sentinel", True) in recs

    def test_exempt_and_expected_compiles_do_not_dump(
        self, devices, tmp_path, clean_ledgers
    ):
        import jax
        import jax.numpy as jnp

        from rocket_tpu.observe.ledger import RetraceLedger
        from rocket_tpu.observe.recorder import FlightRecorder
        from rocket_tpu.observe.trace import Tracer

        rec = FlightRecorder(tracer=Tracer(enabled=False),
                             out_dir=str(tmp_path))
        ledger = RetraceLedger()
        ledger.armed = True
        ledger.set_recorder(rec)

        # exempt edge: per-prompt-length polymorphism is by design
        fn = jax.jit(lambda x: x + 1.0)
        ledger.exempt("probe/poly")
        ledger.call(fn, "probe/poly", jnp.ones((2,)))
        ledger.call(fn, "probe/poly", jnp.ones((2,)))    # warm
        ledger.call(fn, "probe/poly", jnp.ones((3,)))    # retrace, exempt
        assert ledger.retraces == 1 and ledger.sentinel_dumps == 0

        # expect_compile scope: the serve loop's deliberate inline compile
        g = jax.jit(lambda x: x - 1.0)
        ledger.call(g, "probe/ladder", jnp.ones((2,)))
        ledger.call(g, "probe/ladder", jnp.ones((2,)))   # warm
        with ledger.expect_compile("probe/ladder"):
            ledger.call(g, "probe/ladder", jnp.ones((3,)))
        assert ledger.retraces == 2 and ledger.sentinel_dumps == 0
        # outside the scope the same edge escalates again
        ledger.call(g, "probe/ladder", jnp.ones((4,)))
        assert ledger.sentinel_dumps == 1
        assert not os.path.isdir(tmp_path) or len(os.listdir(tmp_path)) == 1


# -- goodput accounting -----------------------------------------------------


@pytest.mark.goodput
class TestGoodputAccounting:
    def test_buckets_sum_to_wall_time_within_1pct(
        self, devices, clean_ledgers
    ):
        import jax
        import jax.numpy as jnp

        from rocket_tpu.core.attributes import Attributes
        from rocket_tpu.core.capsule import Capsule
        from rocket_tpu.launch.loop import Looper
        from rocket_tpu.observe.ledger import (
            arm_ledgers,
            disarm_ledgers,
            get_goodput,
            ledger_call,
        )
        from rocket_tpu.runtime import Runtime

        class JitProbe(Capsule):
            def __init__(self):
                super().__init__()
                self.fn = jax.jit(lambda x: x * 2.0 + 1.0)
                self.x = jnp.ones((256, 256), jnp.float32)

            def launch(self, attrs=None):
                self.x = ledger_call(self.fn, "probe/dispatch", self.x)

        arm_ledgers()
        probe = JitProbe()
        looper = Looper(capsules=[probe], repeats=40, progress=False)
        looper.bind(Runtime())
        attrs = Attributes()
        looper.setup(attrs)
        for _ in range(3):
            looper.launch(attrs)
            jax.block_until_ready(probe.x)
            looper.reset(attrs)
        disarm_ledgers()

        snap = get_goodput().snapshot()
        assert snap["total_s"] > 0.0
        # the instrumented cycles actually fed the measured buckets
        assert snap["productive_s"] > 0.0
        assert snap["compile_s"] > 0.0  # the warmup trace was charged
        attributed = sum(
            v for k, v in snap.items()
            if k.endswith("_s") and k not in ("total_s",)
        )
        # ISSUE 9 acceptance: buckets sum to wall time within 1% — by
        # construction the identity is exact (unattributed_s is the
        # remainder), so this also guards against double-counting pushing
        # the attributed total PAST the window
        assert abs(attributed - snap["total_s"]) <= 0.01 * snap["total_s"]
        assert 0.0 <= snap["goodput_frac"] <= 1.0

    def test_snapshot_freezes_after_end_run(self, clean_ledgers):
        import time

        from rocket_tpu.observe.ledger import GoodputLedger

        gp = GoodputLedger()
        gp.start_run()
        gp.add("productive", 0.010)
        gp.end_run()
        total1 = gp.snapshot()["total_s"]
        time.sleep(0.02)
        snap = gp.snapshot()
        assert snap["total_s"] == total1
        # the remainder keeps the identity exact even on a tiny window
        assert snap["productive_s"] == pytest.approx(0.010)
        gp.end_run()  # idempotent
        assert gp.snapshot()["total_s"] == total1

    def test_save_and_table(self, tmp_path, clean_ledgers):
        from rocket_tpu.observe.ledger import GoodputLedger

        gp = GoodputLedger()
        gp.start_run()
        gp.add("productive", 0.5)
        gp.note_preemption_loss(0.25, steps_replayed=3)
        gp.end_run()
        path = gp.save(str(tmp_path / "proj" / "goodput.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["productive_s"] == pytest.approx(0.5)
        assert doc["preemption_loss_s"] == pytest.approx(0.25)
        text = gp.table()
        assert "goodput over" in text and "productive" in text


# -- device telemetry -------------------------------------------------------


@pytest.mark.goodput
class TestDeviceTelemetry:
    def test_memory_watermarks_cpu_emits_nothing(self, devices):
        from rocket_tpu.observe.ledger import memory_watermarks
        from rocket_tpu.observe.trace import Tracer

        # conftest forces JAX_PLATFORMS=cpu: no memory_stats() there —
        # the contract is "emit nothing", never crash
        t = Tracer(capacity=64, enabled=True)
        out = memory_watermarks(tracer=t)
        assert out == {}
        assert t.events() == []

    def test_gauges_round_trip_chrome_schema(self, devices, clean_ledgers):
        from rocket_tpu.observe.ledger import emit_gauges, set_step_cost
        from rocket_tpu.observe.trace import Tracer

        set_step_cost(flops=1.0e12, bytes_accessed=2.0e9, device_kind=None)
        t = Tracer(capacity=64, enabled=True)
        gauges = emit_gauges(0.1, tracer=t)
        assert set(gauges) == {"device/mfu", "device/mbu"}
        assert gauges["device/mfu"] > 0.0
        doc = t.to_chrome()
        counters = {e["name"]: e for e in doc["traceEvents"]
                    if e["ph"] == "C"}
        assert set(counters) == {"device/mfu", "device/mbu"}
        # Chrome counter tracks read their series from args
        assert counters["device/mfu"]["args"]["mfu"] == pytest.approx(
            gauges["device/mfu"]
        )

    def test_gauges_noop_without_cost_hint(self, devices, clean_ledgers):
        from rocket_tpu.observe.ledger import emit_gauges, set_step_cost
        from rocket_tpu.observe.trace import Tracer

        set_step_cost(None, None, None)
        t = Tracer(capacity=64, enabled=True)
        assert emit_gauges(0.1, tracer=t) == {}
        assert emit_gauges(0.0, tracer=t) == {}
        assert t.events() == []

    def test_executable_cost_cold_path(self, devices):
        import jax
        import jax.numpy as jnp

        from rocket_tpu.observe.ledger import executable_cost

        fn = jax.jit(lambda x: x @ x)
        cost = executable_cost(fn, jnp.ones((16, 16)))
        # CPU backends may or may not report cost_analysis — both are
        # valid; what is NOT valid is raising
        if cost is not None:
            assert set(cost) == {"flops", "bytes_accessed"}


# -- metrics export ---------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]* (NaN|[-+]?[0-9.]+(e[-+]?\d+)?)$"
)


def _assert_prometheus_parses(text):
    lines = [l for l in text.splitlines() if l]
    assert lines, "empty exposition"
    for line in lines:
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _PROM_SAMPLE.match(line), f"unparseable sample: {line!r}"
    # every sample is declared
    assert any(l.startswith("# TYPE ") and l.endswith(" gauge")
               for l in lines)


@pytest.mark.goodput
class TestMetricsExport:
    def test_prometheus_text_parses(self, clean_ledgers):
        from rocket_tpu.observe.export import prometheus_text

        text = prometheus_text({
            "goodput/productive_s": 1.5,
            "serve/latency/p99": 0.25,
            "ledger/compiles": 3.0,
        })
        _assert_prometheus_parses(text)
        assert "rocket_tpu_goodput_productive_s 1.5" in text
        assert "rocket_tpu_serve_latency_p99 0.25" in text

    def test_live_collect_exports(self, clean_ledgers):
        from rocket_tpu.observe.export import collect, prometheus_text
        from rocket_tpu.observe.ledger import arm_ledgers, get_goodput

        arm_ledgers()
        get_goodput().add("productive", 0.1)
        snap = collect()
        assert snap["goodput/productive_s"] == pytest.approx(0.1)
        assert "ledger/compiles" in snap
        _assert_prometheus_parses(prometheus_text(snap))

    def test_register_source_and_failure_isolation(self, clean_ledgers):
        from rocket_tpu.observe.export import (
            collect,
            register_source,
            unregister_source,
        )

        register_source("probe", lambda: {"hits": 7})
        register_source("broken", lambda: 1 / 0)
        try:
            snap = collect()
            assert snap["probe/hits"] == 7.0
            assert not any(k.startswith("broken/") for k in snap)
        finally:
            unregister_source("probe")
            unregister_source("broken")

    def test_merge_counters_sum_and_percentile_max(self):
        from rocket_tpu.observe.export import merge_counters

        merged = merge_counters([
            {"serve/ok": 10.0, "serve/latency/p99": 0.5,
             "serve/latency/p50": 0.1,
             "serve_kvpool/fetches": 4.0,
             "serve_kvpool/occupancy_bytes": 1024.0,
             "serve_kvpool/capacity_bytes": 4096.0,
             "serve_kvstore/occupancy_bytes": 100.0},
            {"serve/ok": 5.0, "serve/latency/p99": 0.9,
             "serve/latency/p50": 0.05,
             "serve_kvpool/fetches": 3.0,
             "serve_kvpool/occupancy_bytes": 768.0,
             "serve_kvpool/capacity_bytes": 4096.0,
             "serve_kvstore/occupancy_bytes": 50.0},
        ])
        assert merged["serve/ok"] == 15.0           # counters SUM
        assert merged["serve/latency/p99"] == 0.9   # percentiles MAX
        assert merged["serve/latency/p50"] == 0.1
        # the pool is a singleton: its gauges MAX, its counters still SUM
        assert merged["serve_kvpool/fetches"] == 7.0
        assert merged["serve_kvpool/occupancy_bytes"] == 1024.0
        assert merged["serve_kvpool/capacity_bytes"] == 4096.0
        # per-replica kvstore occupancies are distinct stores — SUM
        assert merged["serve_kvstore/occupancy_bytes"] == 150.0

    def test_metrics_endpoint(self, clean_ledgers):
        from rocket_tpu.observe.export import MetricsServer
        from rocket_tpu.observe.ledger import arm_ledgers

        arm_ledgers()
        srv = MetricsServer(port=0).start()
        try:
            assert srv.running and srv.port > 0
            url = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
                assert r.status == 200
                assert "version=0.0.4" in r.headers["Content-Type"]
                _assert_prometheus_parses(r.read().decode())
            with urllib.request.urlopen(f"{url}/metrics.json",
                                        timeout=5) as r:
                doc = json.loads(r.read().decode())
                assert "goodput/total_s" in doc
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{url}/bogus", timeout=5)
        finally:
            srv.stop()
        assert not srv.running

    def test_export_cli_merges_snapshots(self, tmp_path, capsys):
        from rocket_tpu.observe.export import _main

        a = tmp_path / "replica0.json"
        b = tmp_path / "replica1.json"
        a.write_text(json.dumps(
            {"serve/ok": 10.0, "serve/latency/p99": 0.5,
             "serve_kvpool/bytes_moved": 2048.0,
             "serve_kvpool/occupancy_bytes": 512.0}))
        b.write_text(json.dumps(
            {"serve/ok": 5.0, "serve/latency/p99": 0.9,
             "serve_kvpool/bytes_moved": 1024.0,
             "serve_kvpool/occupancy_bytes": 640.0}))
        out = tmp_path / "fleet.json"
        assert _main([str(a), str(b), "--format", "json",
                      "-o", str(out)]) == 0
        with open(out) as f:
            merged = json.load(f)
        assert merged["serve/ok"] == 15.0
        assert merged["serve/latency/p99"] == 0.9
        assert merged["serve_kvpool/bytes_moved"] == 3072.0      # SUM
        assert merged["serve_kvpool/occupancy_bytes"] == 640.0   # MAX
        # prom format to stdout parses too
        capsys.readouterr()  # drain the first call's "wrote ..." notice
        assert _main([str(a), str(b)]) == 0
        _assert_prometheus_parses(capsys.readouterr().out)


# -- flight-dump retention + goodput rider ----------------------------------


@pytest.mark.goodput
class TestDumpRetention:
    def test_keep_last_prunes_oldest(self, tmp_path):
        from rocket_tpu.observe.recorder import FlightRecorder
        from rocket_tpu.observe.trace import Tracer

        rec = FlightRecorder(tracer=Tracer(enabled=False),
                             out_dir=str(tmp_path), keep_last=3)
        for i in range(5):
            rec.dump(f"round-{i}")
        dirs = sorted(os.listdir(tmp_path))
        assert len(dirs) == 3
        # lexicographic name order is creation order: the survivors are
        # the NEWEST three (seq 003..005), oldest two pruned
        assert [d.split("-")[2] for d in dirs] == ["003", "004", "005"]
        assert all("round" in d for d in dirs)

    def test_keep_last_zero_is_unbounded(self, tmp_path):
        from rocket_tpu.observe.recorder import FlightRecorder
        from rocket_tpu.observe.trace import Tracer

        rec = FlightRecorder(tracer=Tracer(enabled=False),
                             out_dir=str(tmp_path), keep_last=0)
        for i in range(5):
            rec.dump(f"round-{i}")
        assert len(os.listdir(tmp_path)) == 5

    def test_goodput_rides_along_in_dumps(self, tmp_path, clean_ledgers):
        from rocket_tpu.observe.ledger import (
            get_goodput,
            goodput_dump_writer,
        )
        from rocket_tpu.observe.recorder import (
            FlightRecorder,
            add_dump_writer,
            remove_dump_writer,
        )
        from rocket_tpu.observe.trace import Tracer

        gp = get_goodput()
        gp.start_run()
        gp.add("productive", 0.125)
        add_dump_writer(goodput_dump_writer)
        add_dump_writer(goodput_dump_writer)  # idempotent
        try:
            rec = FlightRecorder(tracer=Tracer(enabled=False),
                                 out_dir=str(tmp_path))
            path = rec.dump("watchdog")
            with open(os.path.join(path, "goodput.json")) as f:
                doc = json.load(f)
            assert doc["productive_s"] == pytest.approx(0.125)
            # core dump artifacts still present alongside the rider
            assert os.path.exists(os.path.join(path, "trace.json"))
            assert os.path.exists(os.path.join(path, "tail.txt"))
        finally:
            remove_dump_writer(goodput_dump_writer)
            remove_dump_writer(goodput_dump_writer)  # tolerant
