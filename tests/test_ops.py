"""Attention op tests: flash (Pallas, interpret on CPU) and ring (seq
parallel) against the dot-attention oracle, values AND gradients."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.ops.attention import dot_attention
from rocket_tpu.ops.flash import flash_attention
from rocket_tpu.ops.ring import ring_attention
from rocket_tpu.parallel.context import mesh_context
from rocket_tpu.parallel.mesh import MeshSpec
from rocket_tpu.parallel.sharding import batch_sharding


def _qkv(B=2, S=256, H=4, D=32, kv_heads=None, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    kv_heads = kv_heads or H
    shape_q = (B, S, H, D)
    shape_kv = (B, S, kv_heads, D)
    q = jnp.asarray(rng.normal(size=shape_q), dtype)
    k = jnp.asarray(rng.normal(size=shape_kv), dtype)
    v = jnp.asarray(rng.normal(size=shape_kv), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dot_forward(causal):
    q, k, v = _qkv()
    out_flash = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    out_dot = dot_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dot), atol=2e-5, rtol=2e-5
    )


def test_flash_matches_dot_gradients():
    q, k, v = _qkv(S=128)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2
        )

    def loss_dot(q, k, v):
        return jnp.sum(dot_attention(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dot = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dot, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_gqa():
    q, k, v = _qkv(H=8, kv_heads=2, S=128)
    out_flash = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    out_dot = dot_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dot), atol=2e-5, rtol=2e-5
    )


def test_flash_bf16_matches_dot():
    """The kernels run their matmuls on the raw input dtype (bf16 on MXU
    rather than f32 upcasts); bf16 values and grads must still track the
    dot oracle within bf16 resolution."""
    q, k, v = _qkv(S=128, dtype=jnp.bfloat16)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        return jnp.sum(out.astype(jnp.float32))

    def loss_dot(q, k, v):
        return jnp.sum(dot_attention(q, k, v, causal=True).astype(jnp.float32))

    out_flash = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    out_dot = dot_attention(q, k, v, causal=True)
    assert out_flash.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out_flash, np.float32), np.asarray(out_dot, np.float32),
        atol=2e-2, rtol=2e-2,
    )
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dot = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dot, "qkv"):
        assert bool(jnp.isfinite(gf.astype(jnp.float32)).all()), f"d{name} nan"
        # bf16 grads: both sides round to bf16 but in different orders, so
        # the tolerance is bf16-epsilon scaled by the grad magnitude (~S).
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gd, np.float32),
            atol=1.0, rtol=0.1, err_msg=f"d{name} mismatch",
        )


def test_flash_mixed_dtype_inputs():
    """bf16 q with f32 k/v (values kept in higher precision) must trace and
    run — the wrapper normalizes k/v to q's dtype for the kernels."""
    q, _, _ = _qkv(S=128, dtype=jnp.bfloat16)
    _, k, v = _qkv(S=128, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    grads = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64
            ).astype(jnp.float32)
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert tuple(g.dtype for g in grads) == (jnp.bfloat16, jnp.float32, jnp.float32)
    for g in grads:
        assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def _packed_segments(B, S, seed=3):
    """Two documents per row, boundary varying per row."""
    rng = np.random.default_rng(seed)
    bounds = rng.integers(S // 4, 3 * S // 4, size=B)
    seg = np.zeros((B, S), np.int32)
    for i, c in enumerate(bounds):
        seg[i, c:] = 1
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_segment_ids_match_dot(causal):
    """Packed sequences keep the blocked kernel: flash with segment_ids
    equals masked dot attention (VERDICT r2 weak #7)."""
    q, k, v = _qkv(S=256)
    seg = _packed_segments(2, 256)
    out_flash = flash_attention(
        q, k, v, causal=causal, segment_ids=seg, block_q=64, block_k=64
    )
    out_dot = dot_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dot), atol=2e-5, rtol=2e-5
    )


def test_flash_segment_ids_gradients():
    q, k, v = _qkv(S=128)
    seg = _packed_segments(2, 128)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, segment_ids=seg,
                block_q=64, block_k=64,
            ) ** 2
        )

    def loss_dot(q, k, v):
        return jnp.sum(
            dot_attention(q, k, v, causal=True, segment_ids=seg) ** 2
        )

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dot = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dot, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_fallback_on_odd_shapes():
    # S=100 not a block multiple -> transparently uses dot
    q, k, v = _qkv(S=100)
    out = flash_attention(q, k, v, causal=True)
    ref = dot_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dot(devices, causal):
    mesh = MeshSpec(data=2, seq=4).build(devices)
    q, k, v = _qkv(B=4, S=256, H=4, D=32)
    sharding = batch_sharding(mesh, ndim=4, seq_dim=1)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    with mesh_context(mesh):
        out_ring = jax.jit(
            functools.partial(ring_attention, causal=causal)
        )(qs, ks, vs)
    out_dot = dot_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dot), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ring_segment_ids_match_dot(devices, causal):
    """Segment ids rotate around the ring with their K/V chunk — packed
    batches mask correctly at ring scale (VERDICT r2 weak #7)."""
    mesh = MeshSpec(data=2, seq=4).build(devices)
    q, k, v = _qkv(B=4, S=256, H=4, D=32)
    seg = _packed_segments(4, 256)
    sharding = batch_sharding(mesh, ndim=4, seq_dim=1)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    segs = jax.device_put(seg, batch_sharding(mesh, ndim=2, seq_dim=1))
    with mesh_context(mesh):
        out_ring = jax.jit(
            functools.partial(ring_attention, causal=causal)
        )(qs, ks, vs, segment_ids=segs)
    out_dot = dot_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dot), atol=2e-5, rtol=2e-5
    )


def test_ring_segment_ids_gradients(devices):
    mesh = MeshSpec(data=1, seq=4).build(devices[:4])
    q, k, v = _qkv(B=2, S=128, H=2, D=16)
    seg = _packed_segments(2, 128)
    sharding = batch_sharding(mesh, ndim=4, seq_dim=1)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    segs = jax.device_put(seg, batch_sharding(mesh, ndim=2, seq_dim=1))

    with mesh_context(mesh):
        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, causal=True, segment_ids=segs) ** 2
            )

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)

    def loss_dot(q, k, v):
        return jnp.sum(
            dot_attention(q, k, v, causal=True, segment_ids=seg) ** 2
        )

    g_dot = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dot, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-3,
            err_msg=f"d{name} mismatch",
        )


def test_ring_gradients_match_dot(devices):
    mesh = MeshSpec(data=1, seq=4).build(devices[:4])
    q, k, v = _qkv(B=2, S=128, H=2, D=16)
    sharding = batch_sharding(mesh, ndim=4, seq_dim=1)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    with mesh_context(mesh):
        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)

    def loss_dot(q, k, v):
        return jnp.sum(dot_attention(q, k, v, causal=True) ** 2)

    g_dot = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dot, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-3,
            err_msg=f"d{name} mismatch",
        )


# ---------------------------------------------------------------------------
# fused (logits-free) linear cross-entropy
# ---------------------------------------------------------------------------


def test_linear_cross_entropy_matches_full_logits():
    """Chunked logits-free NLL == optax CE over the materialized logits,
    values and gradients (both x and the table), including a ragged final
    chunk (N not a multiple of chunk_size)."""
    import optax
    from rocket_tpu.ops.fused_ce import linear_cross_entropy

    rng = np.random.default_rng(0)
    N, H, V = 190, 32, 257  # ragged: 190 % 64 != 0
    x = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    table = jnp.asarray(rng.normal(size=(V, H)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, V, size=(N,)), jnp.int32)

    def fused(x, table):
        return linear_cross_entropy(x, table, targets, chunk_size=64).mean()

    def full(x, table):
        logits = x @ table.T
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    np.testing.assert_allclose(
        float(fused(x, table)), float(full(x, table)), rtol=1e-6
    )
    gf = jax.grad(fused, argnums=(0, 1))(x, table)
    gd = jax.grad(full, argnums=(0, 1))(x, table)
    for a, b, name in zip(gf, gd, ("dx", "dtable")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
            err_msg=f"{name} mismatch",
        )


def test_linear_cross_entropy_bf16_finite():
    from rocket_tpu.ops.fused_ce import linear_cross_entropy

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(128, 32)), jnp.bfloat16)
    table = jnp.asarray(rng.normal(size=(256, 32)), jnp.bfloat16)
    targets = jnp.asarray(rng.integers(0, 256, size=(128,)), jnp.int32)
    nll = linear_cross_entropy(x, table, targets, chunk_size=64)
    assert nll.dtype == jnp.float32
    assert bool(jnp.isfinite(nll).all())
    g = jax.grad(
        lambda x, t: linear_cross_entropy(x, t, targets, chunk_size=64).mean(),
        argnums=(0, 1),
    )(x, table)
    assert all(bool(jnp.isfinite(a.astype(jnp.float32)).all()) for a in g)


@pytest.mark.slow
def test_long_context_16k_ring_training_step(devices):
    """Long-context smoke (SURVEY first-class requirement): one real
    train step of a tiny TransformerLM at 16,384 tokens with ring
    attention over seq=8 — each device holds a 2k shard; the full
    [S, S] score matrix (1GB+ in f32) never exists anywhere.

    Runs in a FRESH subprocess (tests/long_context_worker.py): inside a
    long pytest session the accumulated XLA:CPU state makes this
    largest-in-the-suite program abort (SIGABRT at result fetch) even
    with >100GB free — in a clean interpreter it passes in seconds.
    A SIGABRT gets ONE retry after a pause: the same abort also fires
    under transient host memory/thread pressure (e.g. a concurrent
    pytest process), and a retried clean pass distinguishes that from
    a real regression."""
    import subprocess
    import sys
    import time

    worker = os.path.join(os.path.dirname(__file__), "long_context_worker.py")
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable, worker], timeout=600.0,
            capture_output=True, text=True,
        )
        if proc.returncode == 0 or proc.returncode != -6:
            break
        time.sleep(10.0)  # transient pressure: give the host a beat
    assert proc.returncode == 0, (proc.stdout or "") + (proc.stderr or "")
    assert "long-context-ok" in proc.stdout


def test_auto_blocks_shape_aware_defaults():
    """Library defaults encode the measured-best tiling (VERDICT r4 #5)
    without rerouting irregular flash-eligible shapes to dot: S=197
    (ViT-B/16) must keep its single-S-block kernel path."""
    from rocket_tpu.ops.flash import auto_blocks

    assert auto_blocks(1024) == (512, 1024)  # the measured GPT-2 best
    assert auto_blocks(2048) == (512, 1024)
    assert auto_blocks(8192) == (512, 1024)
    assert auto_blocks(512) == (512, 512)
    assert auto_blocks(256) == (256, 256)
    assert auto_blocks(128) == (128, 128)
    assert auto_blocks(197) == (197, 197)   # ViT: one S-sized block
    assert auto_blocks(768) == (256, 256)


def test_sliding_window_attention_matches_reference_mask(devices):
    """window=W (Mistral-style) must equal a hand-masked softmax in both
    the dot path and the flash kernel (fwd AND grads), and window >= S
    must reduce to full causal."""
    from rocket_tpu.ops.attention import dot_attention
    from rocket_tpu.ops.flash import flash_attention

    B, S, H, D, W = 2, 256, 2, 16, 96
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D))
               for i in range(3))

    def reference(q, k, v):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        pos = jnp.arange(S)
        mask = (pos[:, None] >= pos[None, :]) & (
            pos[:, None] - pos[None, :] < W)
        logits = jnp.where(mask[None, None], logits, -1e30)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(logits, axis=-1), v)

    want = reference(q, k, v)
    got_dot = dot_attention(q, k, v, causal=True, window=W)
    np.testing.assert_allclose(np.asarray(got_dot), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    got_flash = flash_attention(q, k, v, causal=True, window=W,
                                block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got_flash), np.asarray(want),
                               rtol=2e-3, atol=2e-3)

    # grads through the custom_vjp kernels
    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(reference), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=W, block_q=128, block_k=128)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)

    # window >= S degenerates to plain causal
    full = dot_attention(q, k, v, causal=True)
    wide = dot_attention(q, k, v, causal=True, window=S + 7)
    np.testing.assert_allclose(np.asarray(wide), np.asarray(full),
                               rtol=1e-6, atol=1e-6)

    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=False, window=W)


def test_sliding_window_with_segments_and_gqa(devices):
    """window composes with packed segment_ids and GQA-grouped K/V: the
    flash kernel must match the dot path with both masks active."""
    from rocket_tpu.ops.attention import dot_attention
    from rocket_tpu.ops.flash import flash_attention

    B, S, H, KV, D, W = 2, 256, 4, 2, 16, 64
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, KV, D))
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, KV, D))
    seg = jnp.asarray(
        np.repeat(np.arange(4), S // 4)[None].repeat(B, 0), jnp.int32
    )
    want = dot_attention(q, k, v, causal=True, segment_ids=seg, window=W)
    got = flash_attention(q, k, v, causal=True, segment_ids=seg, window=W,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
