"""Train-while-serve tests — verified publication, live weight hot-swap,
rejected torn publishes, bounded rollback, kill-mid-swap healing.

Three layers (see docs/reliability.md "Live weight updates"):

- units (no subprocess): the publisher's two-phase commit + pruning,
  publication election skipping torn saves, the versioned wire
  handshake, the swap-version merge semantics on the export surface,
  the chaos injectors, and the WeightFeed's offer/reject bookkeeping;
- in-process swap path: verify → locate → check_reshard → host restore
  → donation swap, bit-equal to a fresh-built server on the published
  seed; rejected garbled/uncommitted publications (counter + flight
  dump, old weights keep serving); bounded rollback; the reshard gate
  refusing an incompatible publication with the TopologyMismatch
  naming;
- process fleet (heavy tail / ``slow``): the acceptance trio — a
  seeded trace served across a live publish+swap with every request
  typed exactly once and post-swap tokens bit-equal to a fresh-loaded
  server; a torn publication rejected over the RPC path; SIGKILL
  mid-swap healing onto the newest valid publication.
"""

import os
import time

import numpy as np
import pytest

from rocket_tpu.observe import export
from rocket_tpu.persist import integrity
from rocket_tpu.persist.publish import (
    PUBLISH_SUBDIR,
    WeightPublisher,
    latest_publication,
)
from rocket_tpu.serve import ProcReplica, Request, WeightFeed, wire
from rocket_tpu.serve.feed import register_swap_source
from rocket_tpu.testing import workers as tw
from rocket_tpu.testing.chaos import (
    ProcessKillInjector,
    TornPublishInjector,
    corrupt_snapshot,
)

pytestmark = pytest.mark.trainserve

BUILDER = "rocket_tpu.testing.workers:build_tiny_loop"
SPAWN_S = 240.0     # worker spawn includes a jax import + model init
SEED_PUB = 5        # publication seed != builder default (tw.SEED_TARGET)


@pytest.fixture(autouse=True)
def _clean_export_sources():
    yield
    export.unregister_source("serve_swap")


def _serve_one(loop, rid, prompt, max_new=8, rounds=200):
    loop.submit(Request(rid=rid, prompt=prompt, max_new_tokens=max_new))
    out = []
    for _ in range(rounds):
        loop.run_round()
        out.extend(loop.drain_results())
        if out:
            return out[0]
    raise AssertionError(f"request {rid} never completed")


@pytest.fixture(scope="module")
def prompt():
    return np.arange(1, 7, dtype=np.int32)


@pytest.fixture(scope="module")
def oracle_tokens(prompt):
    """rid-free oracles: expected tokens for the boot seed and the
    publication seed, from fresh single-purpose loops."""
    boot = _serve_one(tw.build_tiny_loop(), "oracle-boot", prompt)
    pub = _serve_one(tw.build_tiny_loop(seed_target=SEED_PUB),
                     "oracle-pub", prompt)
    assert not np.array_equal(boot.tokens, pub.tokens), \
        "seeds must produce distinguishable tokens"
    return {"boot": np.asarray(boot.tokens), "pub": np.asarray(pub.tokens)}


# -- publisher units ---------------------------------------------------------


class TestPublisher:
    def test_two_phase_commit_and_manifest(self, tmp_path, devices):
        path = tw.save_tiny_publication(str(tmp_path), step=7,
                                        seed_target=SEED_PUB)
        assert os.path.isfile(os.path.join(path, integrity.COMMIT_MARKER))
        manifest = integrity.read_manifest(path)
        assert manifest["iter_idx"] == 7
        assert manifest.get("mesh") is not None
        ok, reason = integrity.verify(path, deep=True)
        assert ok, reason
        assert latest_publication(str(tmp_path)) == (7, path)

    def test_election_orders_by_step_and_skips_torn(self, tmp_path,
                                                    devices):
        p1 = tw.save_tiny_publication(str(tmp_path), step=10)
        p2 = tw.save_tiny_publication(str(tmp_path), step=20)
        assert latest_publication(str(tmp_path)) == (20, p2)
        # tearing the newest makes it INVISIBLE: election falls back
        corrupt_snapshot(p2, "uncommit")
        assert latest_publication(str(tmp_path)) == (10, p1)
        # a garbled publication still LOOKS committed shallow...
        corrupt_snapshot(p1, "garble")
        assert latest_publication(str(tmp_path)) == (10, p1)
        # ...and only the deep election catches it
        assert latest_publication(str(tmp_path), deep=True) is None

    def test_prune_keeps_newest_and_rollback_target(self, tmp_path,
                                                    devices):
        import jax

        _, _, params, _ = tw.tiny_models()
        pub = WeightPublisher(str(tmp_path), keep=2)
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(-1), ("data",))
        paths = [pub.publish({"params": params}, step=s, mesh=mesh)
                 for s in (1, 2, 3)]
        assert not os.path.isdir(paths[0])       # pruned
        assert os.path.isdir(paths[1])           # the rollback target
        assert os.path.isdir(paths[2])
        assert pub.publishes == 3

    def test_keep_below_two_refused(self, tmp_path):
        with pytest.raises(ValueError, match="rollback"):
            WeightPublisher(str(tmp_path), keep=1)

    def test_publish_subdir_not_in_trainer_election(self, tmp_path,
                                                    devices):
        """A params-only publication must never be elected by a trainer
        resume — the publish subdir stays out of DEFAULT_SUBDIRS."""
        assert PUBLISH_SUBDIR not in integrity.DEFAULT_SUBDIRS
        tw.save_tiny_publication(str(tmp_path), step=5)
        assert integrity.latest_valid(str(tmp_path),
                                      do_quarantine=False) is None

    def test_checkpointer_publishes_on_cadence(self, tmp_path, devices):
        """Checkpointer(publish_every=2) drops committed publications on
        the training cadence, stamped with the training step."""
        import rocket_tpu as rt
        from rocket_tpu.models.objectives import cross_entropy

        from test_pipeline import MLP, synthetic_classification

        data = synthetic_classification(n=128)
        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=2e-2),
            ],
        )
        looper = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=32,
                           shuffle=True, seed=7),
                model,
                rt.Checkpointer(save_every=None, publish_every=2),
            ],
            progress=False,
        )
        launcher = rt.Launcher(capsules=[looper], tag="pub",
                               num_epochs=1, project_root=str(tmp_path),
                               seed=0)
        launcher.launch()
        root = str(tmp_path / "pub" / "v0")
        latest = latest_publication(root)
        assert latest is not None
        version, path = latest
        # 4 iterations/epoch at batch 32 → publishes after iters 1 and 3
        assert version == 3
        ok, reason = integrity.verify(path, deep=True)
        assert ok, reason
        # keep=2: at most two publications retained
        pubs = os.listdir(os.path.join(root, PUBLISH_SUBDIR))
        assert len([d for d in pubs if not d.startswith("_")]) <= 2


# -- wire handshake units ----------------------------------------------------


class TestWireProtocol:
    def test_hello_roundtrip(self):
        spec = wire.WorkerSpec(builder=BUILDER)
        assert wire.check_hello(wire.hello_payload(spec)) is spec

    def test_bare_spec_is_version_zero(self):
        with pytest.raises(wire.ProtocolMismatch) as ei:
            wire.check_hello(wire.WorkerSpec(builder=BUILDER))
        assert ei.value.theirs == 0 and ei.value.side == "worker"

    def test_mismatch_names_remedy(self):
        with pytest.raises(wire.ProtocolMismatch) as ei:
            wire.check_hello({"proto": wire.PROTOCOL_VERSION + 1,
                              "spec": wire.WorkerSpec(builder=BUILDER)})
        msg = str(ei.value)
        assert "Remedy" in msg and "PROTOCOL_VERSION" in msg
        assert ei.value.ours == wire.PROTOCOL_VERSION
        assert ei.value.theirs == wire.PROTOCOL_VERSION + 1

    def test_hello_without_spec_refused(self):
        with pytest.raises(ValueError, match="WorkerSpec"):
            wire.check_hello({"proto": wire.PROTOCOL_VERSION})

    def test_ready_checks_both_directions(self):
        info = wire.check_ready({"proto": wire.PROTOCOL_VERSION, "pid": 1})
        assert info["pid"] == 1
        with pytest.raises(wire.ProtocolMismatch) as ei:
            wire.check_ready({"pid": 1})    # pre-versioning READY
        assert ei.value.theirs == 0 and ei.value.side == "supervisor"


# -- in-process swap path ----------------------------------------------------


class TestSwap:
    def test_swap_bit_equal_to_fresh_server(self, tmp_path, devices,
                                            prompt, oracle_tokens):
        loop = tw.build_tiny_loop()
        before = _serve_one(loop, "pre", prompt)
        assert np.array_equal(before.tokens, oracle_tokens["boot"])
        path = tw.save_tiny_publication(str(tmp_path), step=10,
                                        seed_target=SEED_PUB)
        assert loop.swap_weights(path)
        assert loop.weights_version == 10
        assert loop.counters.swaps == 1
        assert loop.counters.weights_version == 10
        assert loop.counters.swap_ms_total > 0.0
        after = _serve_one(loop, "post", prompt)
        assert np.array_equal(after.tokens, oracle_tokens["pub"])

    def test_swap_trainer_layout_partial_restore(self, tmp_path, devices,
                                                 prompt, oracle_tokens):
        """A trainer publishes its whole TrainState; the swap locates the
        params subtree through the manifest and restores ONLY it."""
        loop = tw.build_tiny_loop()
        path = tw.save_tiny_publication(str(tmp_path), step=20,
                                        seed_target=SEED_PUB,
                                        trainer_layout=True)
        assert loop.swap_weights(path)
        after = _serve_one(loop, "post", prompt)
        assert np.array_equal(after.tokens, oracle_tokens["pub"])

    def test_inflight_rows_survive_swap(self, tmp_path, devices, prompt,
                                        oracle_tokens):
        """A row mid-decode keeps its KV pages across the swap and
        finishes — typed exactly once, no failure, no eviction."""
        loop = tw.build_tiny_loop()
        loop.submit(Request(rid="inflight", prompt=prompt,
                            max_new_tokens=12))
        for _ in range(3):          # start decoding, don't finish
            loop.run_round()
        assert loop.load > 0
        path = tw.save_tiny_publication(str(tmp_path), step=30,
                                        seed_target=SEED_PUB)
        assert loop.swap_weights(path)
        out = []
        for _ in range(200):
            loop.run_round()
            out.extend(loop.drain_results())
            if out:
                break
        assert len(out) == 1 and out[0].rid == "inflight"
        assert type(out[0]).__name__ == "Completed"
        assert loop.counters.failed == 0

    def test_garbled_publication_rejected(self, tmp_path, devices, prompt,
                                          oracle_tokens):
        """Deep verify catches a garbled leaf: counter + flight dump,
        serving continues on the old weights untouched."""
        from rocket_tpu.models.generate import ContinuousBatcher
        from rocket_tpu.observe.recorder import FlightRecorder
        from rocket_tpu.serve.loop import ServingLoop

        model, draft, params, dparams = tw.tiny_models()
        rec = FlightRecorder(out_dir=str(tmp_path / "flightrec"))
        loop = ServingLoop(
            lambda: ContinuousBatcher(model, draft, params, dparams,
                                      total_len=tw.TOTAL,
                                      n_draft=tw.NDRAFT, eos_token=None),
            max_batch=tw.B, recorder=rec,
        )
        path = tw.save_tiny_publication(str(tmp_path), step=40,
                                        seed_target=SEED_PUB)
        corrupt_snapshot(path, "garble")
        assert not loop.swap_weights(path)
        assert loop.counters.publish_rejected == 1
        assert loop.counters.swaps == 0
        assert loop.weights_version == -1
        # the flight dump landed for the post-mortem
        assert rec.last_dump is not None
        assert "publish-rejected" in rec.last_dump
        # old weights keep serving bit-correct
        out = _serve_one(loop, "still-boot", prompt)
        assert np.array_equal(out.tokens, oracle_tokens["boot"])

    def test_uncommitted_publication_rejected(self, tmp_path, devices):
        loop = tw.build_tiny_loop()
        path = tw.save_tiny_publication(str(tmp_path), step=50)
        corrupt_snapshot(path, "uncommit")
        assert not loop.swap_weights(path)
        assert loop.counters.publish_rejected == 1

    def test_incompatible_publication_refused_by_reshard_gate(
            self, tmp_path, devices):
        """A publication whose shapes do not match the serving model is
        a model change, not a hot-swap — the check_reshard gate refuses
        it with the TopologyMismatch naming, serving untouched."""
        import jax

        from rocket_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        cfg = TransformerConfig(vocab_size=tw.VOCAB, hidden=tw.HIDDEN * 2,
                                n_layers=tw.LAYERS, n_heads=tw.HEADS,
                                max_seq=tw.MAX_SEQ)
        wrong = TransformerLM(cfg).init(
            jax.random.PRNGKey(0),
            {"tokens": np.zeros((1, tw.P), np.int32),
             "positions": np.zeros((1, tw.P), np.int32)},
        )["params"]
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(-1), ("data",))
        pub = WeightPublisher(str(tmp_path))
        path = pub.publish({"params": wrong}, step=60, mesh=mesh)
        loop = tw.build_tiny_loop()
        assert not loop.swap_weights(path)
        assert loop.counters.publish_rejected == 1
        assert loop.weights_version == -1

    def test_rollback_is_bounded_to_previous_version(self, tmp_path,
                                                     devices, prompt,
                                                     oracle_tokens):
        loop = tw.build_tiny_loop()
        p1 = tw.save_tiny_publication(str(tmp_path), step=10,
                                      seed_target=SEED_PUB)
        p2 = tw.save_tiny_publication(str(tmp_path), step=20,
                                      seed_target=11)
        assert loop.swap_weights(p1) and loop.swap_weights(p2)
        assert loop.weights_version == 20
        # divergence noticed → one bounded step back
        assert loop.rollback_weights()
        assert loop.weights_version == 10
        assert loop.counters.swap_rollbacks == 1
        out = _serve_one(loop, "rolled", prompt)
        assert np.array_equal(out.tokens, oracle_tokens["pub"])
        # bounded: there is no version before the previous one
        assert not loop.rollback_weights()
        assert loop.weights_version == 10

    def test_watchdog_rebuild_after_swap_keeps_swapped_weights(
            self, tmp_path, devices, prompt, oracle_tokens):
        """The donation swap deletes the factory closure's original
        leaves — a watchdog rebuild must come back on the SWAPPED
        weights, not the donated-away originals."""
        loop = tw.build_tiny_loop()
        path = tw.save_tiny_publication(str(tmp_path), step=70,
                                        seed_target=SEED_PUB)
        assert loop.swap_weights(path)
        loop._rebuild()
        out = _serve_one(loop, "rebuilt", prompt)
        assert np.array_equal(out.tokens, oracle_tokens["pub"])


# -- chaos injector units ----------------------------------------------------


class TestInjectors:
    def test_torn_publish_injector_schedules(self, tmp_path, devices):
        import jax

        _, _, params, _ = tw.tiny_models()
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(-1), ("data",))
        pub = TornPublishInjector(
            WeightPublisher(str(tmp_path), keep=3),
            tear_on={0: "uncommit", 2: "garble"},
        )
        p0 = pub.publish({"params": params}, step=1, mesh=mesh)
        p1 = pub.publish({"params": params}, step=2, mesh=mesh)
        p2 = pub.publish({"params": params}, step=3, mesh=mesh)
        assert pub.published == 3 and pub.tears == 2
        # torn #0: no marker → invisible even shallow
        assert not integrity.verify(p0)[0]
        # untouched #1: fully valid
        assert integrity.verify(p1, deep=True)[0]
        # garbled #2: committed shallow, caught only deep
        assert integrity.verify(p2)[0]
        assert not integrity.verify(p2, deep=True)[0]
        # delegation: the wrapped publisher's own counter advanced
        assert pub.publishes == 3

    def test_swap_tick_schedule(self):
        class FakeReplica:
            kills = 0

            def kill(self):
                self.kills += 1

        rep = FakeReplica()
        inj = ProcessKillInjector(rep, kill_on=(), swap_kill_on=(1,))
        assert not inj.swap_tick()      # beat 0: spared
        assert inj.swap_tick()          # beat 1: killed
        assert not inj.swap_tick()      # beat 2: spared
        assert rep.kills == 1 and inj.kills == 1
        # the pump-tick clock is independent
        assert inj.ticks == 0


# -- feed + export surface ---------------------------------------------------


class _FakeReplica:
    def __init__(self, rid, accept=True):
        self.replica_id = rid
        self._accept = accept
        self.weights_version = -1
        self.swap_calls = 0
        self.rollback_calls = 0

    def swap_weights(self, path, version, deep_verify=True):
        self.swap_calls += 1
        if self._accept:
            self.weights_version = version
            return True
        return False

    def rollback_weights(self):
        self.rollback_calls += 1
        self.weights_version = max(-1, self.weights_version - 10)
        return True


class TestWeightFeed:
    def test_poll_offers_only_to_stale_replicas(self, tmp_path, devices):
        tw.save_tiny_publication(str(tmp_path), step=10)
        fresh, stale = _FakeReplica("a"), _FakeReplica("b")
        fresh.weights_version = 10
        feed = WeightFeed(str(tmp_path), [fresh, stale])
        assert feed.poll() == 1
        assert fresh.swap_calls == 0 and stale.swap_calls == 1
        assert stale.weights_version == 10
        # a second poll is a no-op: everyone is current
        assert feed.poll() == 0 and stale.swap_calls == 1

    def test_rejected_publication_not_reoffered(self, tmp_path, devices):
        tw.save_tiny_publication(str(tmp_path), step=10)
        rep = _FakeReplica("a", accept=False)
        feed = WeightFeed(str(tmp_path), [rep])
        assert feed.poll() == 0
        assert feed.rejects == 1 and rep.swap_calls == 1
        # known-bad path: never offered again
        assert feed.poll() == 0 and rep.swap_calls == 1
        # a NEWER publication supersedes the rejection
        rep._accept = True
        tw.save_tiny_publication(str(tmp_path), step=20)
        assert feed.poll() == 1 and rep.weights_version == 20

    def test_rollback_fans_out(self, tmp_path, devices):
        reps = [_FakeReplica("a"), _FakeReplica("b")]
        feed = WeightFeed(str(tmp_path), reps)
        assert feed.rollback() == 2
        assert all(r.rollback_calls == 1 for r in reps)
        assert feed.rollbacks == 2

    def test_swap_source_on_export_surface(self, tmp_path, devices):
        tw.save_tiny_publication(str(tmp_path), step=10)
        feed = WeightFeed(str(tmp_path), [_FakeReplica("a")])
        assert register_swap_source(feed) == "serve_swap"
        feed.poll()
        snap = export.collect()
        assert snap["serve_swap/swaps"] == 1.0
        assert snap["serve_swap/version"] == 10.0

    def test_version_gauge_merges_max_counters_sum(self):
        merged = export.merge_counters([
            {"serve_swap/swaps": 2.0, "serve_swap/version": 10.0,
             "serve_fleet/r0/weights_version": 10.0},
            {"serve_swap/swaps": 1.0, "serve_swap/version": 20.0,
             "serve_fleet/r0/weights_version": 20.0},
        ])
        assert merged["serve_swap/swaps"] == 3.0        # counter: SUM
        assert merged["serve_swap/version"] == 20.0     # gauge: MAX
        assert merged["serve_fleet/r0/weights_version"] == 20.0


# -- goodput bucket ----------------------------------------------------------


def test_swap_goodput_bucket(tmp_path, devices):
    """Swap wall time lands in the ``swap`` bucket (not unattributed),
    and the counter agrees with the ledger."""
    from rocket_tpu.observe.ledger import (
        GoodputLedger,
        arm_ledgers,
        disarm_ledgers,
        get_goodput,
    )

    assert "swap" in GoodputLedger.BUCKETS
    assert "swap" in GoodputLedger.NESTED
    loop = tw.build_tiny_loop()
    path = tw.save_tiny_publication(str(tmp_path), step=10,
                                    seed_target=SEED_PUB)
    arm_ledgers()
    try:
        before = get_goodput().snapshot().get("swap_s", 0.0)
        assert loop.swap_weights(path)
        delta_s = get_goodput().snapshot()["swap_s"] - before
    finally:
        disarm_ledgers()
    assert delta_s > 0.0
    assert abs(delta_s * 1e3 - loop.counters.swap_ms_total) \
        < 0.2 * loop.counters.swap_ms_total + 50.0


# -- process fleet acceptance ------------------------------------------------


def _drain_replica(rep, want, timeout=60.0):
    results = []
    deadline = time.monotonic() + timeout
    while len(results) < want and time.monotonic() < deadline:
        rep.pump()
        results.extend(rep.drain_results())
    return results


def test_live_swap_across_process_fleet(tmp_path, devices, prompt,
                                        oracle_tokens):
    """Acceptance (a): a seeded trace served during a live publish —
    every request typed exactly once, post-swap tokens bit-equal to a
    fresh-loaded server at the published step."""
    rep = ProcReplica(wire.WorkerSpec(builder=BUILDER), "ts-0",
                      spawn_timeout_s=SPAWN_S, rpc_timeout_s=SPAWN_S)
    try:
        assert rep.submit(Request(rid="pre", prompt=prompt,
                                  max_new_tokens=8))
        pre = _drain_replica(rep, 1)
        assert [r.rid for r in pre] == ["pre"]
        assert np.array_equal(pre[0].tokens, oracle_tokens["boot"])

        # the trainer publishes; the feed pushes it to the fleet
        tw.save_tiny_publication(str(tmp_path), step=10,
                                 seed_target=SEED_PUB)
        feed = WeightFeed(str(tmp_path), [rep])
        assert feed.poll() == 1
        assert rep.weights_version == 10
        assert feed.snapshot()["version"] == 10.0

        assert rep.submit(Request(rid="post", prompt=prompt,
                                  max_new_tokens=8))
        post = _drain_replica(rep, 1)
        assert [r.rid for r in post] == ["post"]
        assert np.array_equal(post[0].tokens, oracle_tokens["pub"])

        # rollback over the wire restores the boot-equivalent? No — the
        # previous version was the factory seed, never published; the
        # worker correctly refuses a rollback with no published prior.
        assert not rep.rollback_weights()
    finally:
        rep.close()


def test_torn_publication_rejected_across_fleet(tmp_path, devices, prompt,
                                                oracle_tokens):
    """Acceptance (b): a garbled publication is rejected worker-side —
    counter visible over the RPC surface, old weights keep serving, and
    the feed stops re-offering the known-bad path."""
    rep = ProcReplica(wire.WorkerSpec(builder=BUILDER), "ts-torn",
                      spawn_timeout_s=SPAWN_S, rpc_timeout_s=SPAWN_S)
    try:
        path = tw.save_tiny_publication(str(tmp_path), step=10,
                                        seed_target=SEED_PUB)
        corrupt_snapshot(path, "garble")
        feed = WeightFeed(str(tmp_path), [rep])
        assert feed.poll() == 0
        assert feed.rejects == 1
        assert rep.weights_version == -1
        assert rep.counters.get("publish_rejected") == 1.0
        # serving is untouched: boot weights, bit-correct
        assert rep.submit(Request(rid="still", prompt=prompt,
                                  max_new_tokens=8))
        out = _drain_replica(rep, 1)
        assert np.array_equal(out[0].tokens, oracle_tokens["boot"])
        assert feed.poll() == 0 and feed.pushes == 1   # not re-offered
    finally:
        rep.close()


@pytest.mark.slow
def test_kill_mid_swap_heals_onto_newest_valid(tmp_path, devices, prompt,
                                               oracle_tokens):
    """Acceptance (c): SIGKILL just before the swap RPC — the supervisor
    discovers the corpse, salvages exactly-once, and the respawn
    elastic-restores onto the newest VALID publication."""
    spec = wire.WorkerSpec(builder=BUILDER, restore_dir=str(tmp_path))
    # nothing published yet: the spawn falls back to... nothing to
    # restore would fail — publish v1 BEFORE the first spawn.
    tw.save_tiny_publication(str(tmp_path), step=10,
                             seed_target=SEED_PUB)
    rep = ProcReplica(spec, "ts-kill", spawn_timeout_s=SPAWN_S,
                      rpc_timeout_s=SPAWN_S)
    inj = ProcessKillInjector(rep, kill_on=(), swap_kill_on=(0,))
    try:
        # the worker restored the v1 publication at spawn
        assert rep.submit(Request(rid="pre", prompt=prompt,
                                  max_new_tokens=8))
        pre = _drain_replica(rep, 1)
        assert np.array_equal(pre[0].tokens, oracle_tokens["pub"])

        # a NEWER publication lands; a torn one lands after it
        p2 = tw.save_tiny_publication(str(tmp_path), step=20,
                                      seed_target=11)
        p3 = tw.save_tiny_publication(str(tmp_path), step=30,
                                      seed_target=13)
        corrupt_snapshot(p3, "uncommit")

        # in-flight work at the moment of death → must salvage
        assert rep.submit(Request(rid="inflight", prompt=prompt,
                                  max_new_tokens=8))

        inj.swap_tick()                       # SIGKILL before the RPC
        deadline = time.monotonic() + 10.0
        while rep.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not rep.swap_weights(p2, 20)   # hits the corpse
        assert rep.health.value == "draining"

        final, salvaged = rep.heal()
        # exactly-once: the unanswered request salvages, nothing final
        assert [r.rid for r in salvaged] == ["inflight"]
        assert not final
        # the respawn elected the newest VALID snapshot: the committed
        # v20 publication, not the torn v30
        assert rep.submit(Request(rid="post", prompt=prompt,
                                  max_new_tokens=8))
        post = _drain_replica(rep, 1)
        oracle20 = _serve_one(tw.build_tiny_loop(seed_target=11),
                              "oracle20", prompt)
        assert np.array_equal(post[0].tokens, oracle20.tokens)
    finally:
        rep.close()
