"""Worker process for the REAL multi-process CPU test (not a pytest file).

Spawned N times by tests/test_multiprocess.py with a shared coordinator
port.  Performs an actual ``jax.distributed.initialize`` rendezvous on
localhost — NO monkeypatching — then exercises every ``process_count > 1``
code path the monkeypatch-only tests could not execute for real
(VERDICT r2 weak #5): broadcast_object, process_allgather, barriers,
assert_equal, per-host data sharding, and a multi-host Orbax
save + restore through the full Launcher pipeline.

Usage: python multiproc_worker.py <port> <num_processes> <process_id> <dir>

MPMD mode (tests/test_mpmd.py): one PIPELINE STAGE per process, boundary
activations/cotangents over a TCP-loopback SocketEndpoint instead of a
jax.distributed rendezvous — the pod deployment shape of
``rocket_tpu.parallel.mpmd`` with real process isolation.

Usage: python multiproc_worker.py mpmd <port> <n_stages> <stage> <dir>
"""

import os
import sys

# Per-process local CPU devices; global device count = N * this.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

import glob

import numpy as np


def main() -> None:
    port, nprocs, pid, workdir = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )

    from rocket_tpu.parallel import multihost

    # 1) real rendezvous (before any jax computation)
    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.process_index() == pid
    assert len(jax.devices()) == 2 * nprocs, jax.devices()

    # 2) host-level collectives, for real
    multihost.sync_global_devices("mp-test-barrier")

    obj = {"run": "v7", "seed": 1234} if pid == 0 else None
    got = multihost.broadcast_object(obj)
    assert got == {"run": "v7", "seed": 1234}, got

    mine = np.asarray([pid], np.int32)
    gathered = multihost.process_allgather(mine)
    np.testing.assert_array_equal(
        np.sort(np.ravel(gathered)), np.arange(nprocs)
    )

    multihost.assert_equal(got["seed"], "seed disagrees across hosts")

    # 3) full pipeline with per-host batch sharding + multi-host Orbax
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import lm_cross_entropy
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM

    rng = np.random.default_rng(0)  # identical data on every host
    data = {"tokens": rng.integers(0, 64, size=(32, 16)).astype(np.int32)}
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_layers=1, n_heads=2, max_seq=16,
        attention="dot",
    )

    def build():
        module = rt.Module(
            TransformerLM(cfg),
            capsules=[rt.Loss(lm_cross_entropy(), name="lm"),
                      rt.Optimizer(learning_rate=1e-2)],
        )
        looper = rt.Looper(
            capsules=[
                rt.Dataset(rt.ArraySource(data), batch_size=8, shuffle=True),
                module,
                rt.Checkpointer(save_every=2, keep_last=2),
            ],
            progress=False,
        )
        launcher = rt.Launcher(
            capsules=[looper], tag="mp", num_epochs=1, project_root=workdir,
        )
        return launcher, module

    launcher, module = build()
    launcher.launch()
    steps = int(module.step)
    assert steps == 4, steps
    # every host must agree on the trained state
    p0 = np.asarray(
        multihost.to_host_global(module.state.params)["embed"]["embedding"]
    )
    multihost.assert_equal(p0.sum(), "params diverged across hosts")

    # 4) multi-host restore: resume from the mid-epoch snapshot and finish
    ckpts = sorted(glob.glob(os.path.join(workdir, "mp", "v0", "weights", "*")))
    assert len(ckpts) >= 2, ckpts
    launcher2, module2 = build()
    launcher2.resume(ckpts[-2])
    launcher2.launch()
    assert int(module2.step) == steps, (int(module2.step), steps)

    # 5) round-4 features across REAL processes: multi-optimizer param
    #    groups + the fused accumulation window in one jitted step
    runtime = rt.Runtime(gradient_accumulation_steps=2)

    def embed_filter(path, leaf):
        return any(
            "embed" in str(getattr(part, "key", "")).lower()
            for part in path
        )

    module3 = rt.Module(
        TransformerLM(cfg),
        capsules=[
            rt.Loss(lm_cross_entropy(), name="lm"),
            rt.Optimizer(learning_rate=0.0, params_filter=embed_filter,
                         tag="lr_embed"),
            rt.Optimizer(learning_rate=1e-2,
                         params_filter=lambda p, x: not embed_filter(p, x),
                         tag="lr_rest"),
        ],
        fuse_accumulation=True,
    )
    module3.bind(runtime)
    module3.setup()
    loader = rt.DataLoader(
        rt.ArraySource(data), batch_size=8,
        sharding=runtime.batch_sharding(ndim=2), prefetch=0,
    )
    attrs = rt.Attributes(
        looper=rt.Attributes(grad_enabled=True, state=rt.Attributes())
    )
    for batch in loader.iterate():
        attrs.batch = batch
        module3.launch(attrs)
    # 4 launches / window 2 -> 2 effective steps; frozen embed group
    assert int(module3.state.step) == 2, int(module3.state.step)
    import flax.linen as flax_nn

    flat = flax_nn.meta.unbox(
        multihost.to_host_global(module3.state.params)
    )
    multihost.assert_equal(
        float(np.asarray(flat["embed"]["embedding"]).sum()),
        "fused-window params diverged across hosts",
    )
    assert float(attrs.looper.state["lr_rest"]) == 1e-2
    module3.destroy()

    multihost.sync_global_devices("mp-test-done")
    print(f"WORKER-OK {pid}", flush=True)
    multihost.shutdown()


def mpmd_main() -> None:
    port, n_stages, stage, workdir = (
        int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]), sys.argv[5]
    )
    import jax.numpy as jnp

    from rocket_tpu.parallel.mpmd import (
        ChunkPrograms,
        SocketEndpoint,
        run_stage,
        split_chunks,
    )

    # the SAME seeded problem on every process (tests/test_mpmd.py
    # _problem()): params/micros never cross the transport, only
    # boundary activations and cotangents do
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    params = {
        "w": jnp.stack([jax.random.normal(k, (8, 8)) * 0.3 for k in keys]),
        "b": jnp.zeros((4, 8)),
    }
    micros = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 8))
    target = jax.random.normal(jax.random.PRNGKey(2), (2, 8))

    def layer(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y):
        return jnp.mean((y - target) ** 2)

    if stage == 0:
        endpoint = SocketEndpoint.listen(port, stage=stage)
    else:
        endpoint = SocketEndpoint.connect("127.0.0.1", port, stage=stage)
    try:
        programs = ChunkPrograms(layer, loss_fn)
        chunk_params = split_chunks(params, n_stages)[stage]
        grads, loss, report = run_stage(
            stage, n_stages, programs, chunk_params, endpoint, n_micro=4,
            schedule="1f1b", micros=micros if stage == 0 else None,
            goodput=False,
        )
    finally:
        endpoint.close()
    out = {
        "w": np.asarray(grads[0]["w"]),
        "b": np.asarray(grads[0]["b"]),
        "max_live": report.max_live,
    }
    if loss is not None:
        out["loss"] = np.asarray(loss)
    np.savez(os.path.join(workdir, f"mpmd_stage{stage}.npz"), **out)
    print(f"MPMD-OK {stage}", flush=True)


if __name__ == "__main__":
    if sys.argv[1] == "mpmd":
        mpmd_main()
    else:
        main()
