"""Async train hot-path tests — device prefetch, non-blocking Looper,
lagged readback, donation.

Covers the PR-5 acceptance criteria:

- the async loop (``readback_lag>=1``, ``device_prefetch>=1``, donation on)
  is BIT-IDENTICAL to the synchronous loop: same params, same optimizer
  state, same per-iteration loss series — for every lag × prefetch-depth
  combination, including a run resumed from a checkpoint;
- ``attrs.looper.lagged_logs`` delivers exactly the k-iterations-old host
  floats of the sync loss series;
- donation adds zero extra jit traces across warm cycles (micro AND sync
  accumulation paths) and changes no results; ``donate=False`` and
  ``Runtime(donate_train_state=False)`` are working escape hatches;
- Throughput in lag mode counts samples at dispatch but times windows
  against the lagged readback, so pipeline-fill dispatches never inflate
  samples/sec (fake-clock unit test);
- a mid-epoch SIGTERM with steps still in flight commits a valid
  checkpoint, and auto-resume completes the run on the sync trajectory.
"""

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.persist import integrity
from rocket_tpu.testing import SigtermInjector

from test_pipeline import MLP, synthetic_classification


class LossRecorder(rt.Capsule):
    """Host-side per-iteration loss trace (sync read — test-only)."""

    def __init__(self):
        super().__init__(statefull=False, priority=400)
        self.losses = []

    def launch(self, attrs=None):
        if attrs is None or attrs.step_logs is None:
            return
        looper = attrs.looper
        if looper is not None and not looper.grad_enabled:
            return
        loss = attrs.step_logs.get("loss")
        if loss is not None:
            self.losses.append(float(loss))


class LaggedRecorder(rt.Capsule):
    """Records the host floats the non-blocking loop publishes as
    ``attrs.looper.lagged_logs`` — the observer-side view of readback."""

    def __init__(self):
        super().__init__(statefull=False, priority=300)
        self.losses = []

    def launch(self, attrs=None):
        if attrs is None or attrs.looper is None:
            return
        lagged = attrs.looper.get("lagged_logs")
        if lagged is None:
            return
        loss = lagged.get("loss")
        if loss is not None:
            self.losses.append(float(loss))


def _tree(tmp_path, data, *, tag, epochs, lag=0, depth=1, extra=(),
          save_every=100, resume=None, donate=None, runtime=None):
    """Standard tree: 256 samples / batch 64 = 4 iterations per epoch,
    parameterized by readback lag and device-prefetch depth."""
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
        donate=donate,
    )
    recorder = LossRecorder()
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=7, device_prefetch=depth),
            model,
            *extra,
            recorder,
            rt.Checkpointer(save_every=save_every),
        ],
        progress=False,
        readback_lag=lag,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag=tag, num_epochs=epochs,
        project_root=str(tmp_path), seed=0, runtime=runtime,
    )
    if resume is not None:
        launcher.resume(resume)
    return launcher, model, recorder


def _flat(tree):
    import jax

    return np.concatenate([
        np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(tree)
    ])


# -- acceptance: bitwise trajectory equality ---------------------------------


@pytest.mark.parametrize("lag", [1, 2])
@pytest.mark.parametrize("depth", [0, 1, 2])
def test_async_bitwise_matches_sync(tmp_path, devices, lag, depth):
    """THE acceptance test: the async loop never changes the dispatched
    program or its order, so params, optimizer state and the loss series
    are bit-identical to the synchronous loop's."""
    data = synthetic_classification(n=256)
    ref, model_ref, rec_ref = _tree(tmp_path, data, tag="sync-ref", epochs=2)
    ref.launch()
    assert len(rec_ref.losses) == 8

    run, model, rec = _tree(
        tmp_path, data, tag=f"async-{lag}-{depth}", epochs=2,
        lag=lag, depth=depth,
    )
    run.launch()
    assert rec.losses == rec_ref.losses  # exact float equality, no tolerance
    np.testing.assert_array_equal(
        _flat(model.state.params), _flat(model_ref.state.params)
    )
    np.testing.assert_array_equal(
        _flat(model.state.opt_state), _flat(model_ref.state.opt_state)
    )
    assert run._capsules[0].last_dispatch_gap_ms is not None


def test_lagged_logs_trail_sync_series(tmp_path, devices):
    """``lagged_logs`` is exactly the sync loss series delayed: an observer
    dispatched during iteration ``i`` sees the snapshot popped at the end of
    iteration ``i-1``, i.e. step ``i-1-k`` — so over an 8-iteration epoch it
    records the first ``8-k-1`` sync losses, in order."""
    lag = 2
    data = synthetic_classification(n=512)  # 8 iters/epoch at bs 64
    ref, _, rec_ref = _tree(tmp_path, data, tag="lag-ref", epochs=1)
    ref.launch()
    assert len(rec_ref.losses) == 8

    lagged = LaggedRecorder()
    run, _, rec = _tree(tmp_path, data, tag="lag-obs", epochs=1, lag=lag,
                        depth=2, extra=[lagged])
    run.launch()
    assert rec.losses == rec_ref.losses
    assert lagged.losses == rec_ref.losses[: 8 - lag - 1]


def test_cycle_end_drain_delivers_final_losses(tmp_path, devices):
    """The snapshots still in the lag window at cycle end reach observers
    via ``looper.drained_logs`` (published before children reset) — the
    launch-time lagged series plus the drained tail is exactly the full
    sync loss series, nothing vanishes with the window."""

    class DrainRecorder(LaggedRecorder):
        def __init__(self):
            super().__init__()
            self.drained = []

        def reset(self, attrs=None):
            if attrs is None or attrs.looper is None:
                return
            for snap in attrs.looper.get("drained_logs") or ():
                loss = snap.get("loss")
                if loss is not None:
                    self.drained.append(float(loss))

    lag = 2
    data = synthetic_classification(n=512)  # 8 iters/epoch at bs 64
    ref, _, rec_ref = _tree(tmp_path, data, tag="drain-ref", epochs=1)
    ref.launch()
    assert len(rec_ref.losses) == 8

    obs = DrainRecorder()
    run, _, rec = _tree(tmp_path, data, tag="drain-obs", epochs=1, lag=lag,
                        depth=1, extra=[obs])
    run.launch()
    assert rec.losses == rec_ref.losses
    assert obs.losses == rec_ref.losses[: 8 - lag - 1]
    assert obs.losses + obs.drained == rec_ref.losses


@pytest.mark.resilience
def test_sigterm_midflight_commits_and_resumes(tmp_path, devices):
    """Chaos: SIGTERM mid-epoch with up to k steps in flight still commits
    a verifiable checkpoint (the save's D2H copy is the sync point), and
    auto-resume — itself async — finishes on the sync trajectory."""
    data = synthetic_classification(n=256)
    ref, model_ref, rec_ref = _tree(tmp_path, data, tag="ca-ref", epochs=2)
    ref.launch()

    run_b, _, rec_b = _tree(
        tmp_path, data, tag="ca", epochs=2, lag=2, depth=2,
        extra=[SigtermInjector(at_iter=2)],
    )
    run_b.launch()
    assert len(rec_b.losses) == 3  # iters 0..2, then the grace-window stop
    snap = tmp_path / "ca" / "v0" / "weights" / "000002"
    assert snap.is_dir()
    ok, reason = integrity.verify(str(snap))
    assert ok, reason

    run_c, model_c, rec_c = _tree(
        tmp_path, data, tag="ca", epochs=2, lag=2, depth=2, resume="auto",
    )
    run_c.launch()
    stitched = rec_b.losses + rec_c.losses
    assert len(stitched) == 8
    np.testing.assert_allclose(stitched, rec_ref.losses, rtol=1e-6, atol=0)
    np.testing.assert_allclose(
        _flat(model_c.state.params), _flat(model_ref.state.params),
        rtol=1e-6, atol=0,
    )


# -- donation ----------------------------------------------------------------


class StepTraceProbe(rt.Capsule):
    """Snapshots the jit cache sizes of the module's micro/sync steps every
    iteration — ``Module.destroy`` drops ``_steps``, so trace counts must be
    observed while the run is live."""

    def __init__(self, model):
        super().__init__(statefull=False, priority=200)
        self._model = model
        self.sizes = set()

    def launch(self, attrs=None):
        steps = self._model._steps
        if steps and "sync" in steps:
            self.sizes.add((
                steps["micro"]._cache_size(), steps["sync"]._cache_size(),
            ))


def test_donation_zero_retrace_and_bitwise(tmp_path, devices):
    """Donation (the default) adds zero jit traces across warm cycles on
    BOTH accumulation paths and changes no results vs ``donate=False``."""
    data = synthetic_classification(n=256)
    run_a, model_a, rec_a = _tree(
        tmp_path, data, tag="don-on", epochs=2, lag=1,
        runtime=rt.Runtime(gradient_accumulation_steps=2),
    )
    probe = StepTraceProbe(model_a)
    run_a._capsules[0]._capsules.append(probe)
    run_a.launch()
    assert model_a._donate is True  # resolved from the runtime default
    # each step body traced exactly once, no retraces across warm cycles
    assert max(probe.sizes) == (1, 1)
    assert all(m <= 1 and s <= 1 for m, s in probe.sizes)

    run_b, model_b, rec_b = _tree(
        tmp_path, data, tag="don-off", epochs=2, lag=1, donate=False,
        runtime=rt.Runtime(gradient_accumulation_steps=2),
    )
    run_b.launch()
    assert model_b._donate is False
    assert rec_a.losses == rec_b.losses
    np.testing.assert_array_equal(
        _flat(model_a.state.params), _flat(model_b.state.params)
    )
    np.testing.assert_array_equal(
        _flat(model_a.state.opt_state), _flat(model_b.state.opt_state)
    )


def test_runtime_donate_escape_hatch(devices):
    """``Runtime(donate_train_state=False)`` turns donation off for every
    Module that did not pin it explicitly; the default resolves to True."""
    import jax.numpy as jnp

    data = synthetic_classification(n=64)
    batch = {"x": jnp.asarray(data["x"]), "label": jnp.asarray(data["label"])}

    def build(runtime, donate=None):
        model = rt.Module(
            MLP(),
            capsules=[
                rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                rt.Optimizer(learning_rate=2e-2),
            ],
            donate=donate,
        )
        model.bind(runtime)
        model.setup()
        attrs = rt.Attributes(
            batch=batch,
            looper=rt.Attributes(grad_enabled=True, state=rt.Attributes()),
        )
        model.launch(attrs)
        return model

    assert build(rt.Runtime())._donate is True
    assert build(rt.Runtime(donate_train_state=False))._donate is False
    # explicit Module donate pins over the runtime either way
    assert build(rt.Runtime(donate_train_state=False), donate=True)._donate \
        is True


# -- throughput accounting under lag -----------------------------------------


class TestThroughputLagMode:
    def _attrs(self, lag):
        looper = rt.Attributes(
            readback_lag=lag, lagged_logs=None,
            state=rt.Attributes(), grad_enabled=True,
        )
        return rt.Attributes(
            looper=looper,
            batch={"x": np.zeros((8, 4), np.float32)},
            tracker=None,
        )

    def test_pipeline_fill_never_inflates_rate(self):
        """Dispatches before the first readback return in microseconds —
        they must count samples, not mint absurd rates."""
        from rocket_tpu.observe.profile import Throughput

        times = iter([0.0, 10.0, 20.0, 30.0])
        tp = Throughput(ema=0.5, log_every=1000, clock=lambda: next(times))
        attrs = self._attrs(lag=2)
        tp.set(attrs)
        tp.launch(attrs)  # t=0: first dispatch opens the window
        assert tp._ema is None
        tp.launch(attrs)  # t=10: still filling, nothing read back
        assert tp._ema is None
        assert len(tp._inflight) == 2  # samples counted at dispatch

        attrs.looper.lagged_logs = rt.Attributes(loss=0.1)
        tp.launch(attrs)  # t=20: first completed step -> 8 samples / 20s
        assert tp._ema == pytest.approx(8 / 20.0)
        tp.launch(attrs)  # t=30: one more readback -> 8/10, EMA-blended
        assert tp._ema == pytest.approx(0.5 * (8 / 20.0) + 0.5 * (8 / 10.0))
        assert attrs.looper.state["throughput"].endswith("/s")

    def test_sync_mode_unchanged(self):
        from rocket_tpu.observe.profile import Throughput

        times = iter([0.0, 1.0, 2.0])
        tp = Throughput(ema=0.5, log_every=1000, clock=lambda: next(times))
        attrs = self._attrs(lag=0)
        tp.set(attrs)
        tp.launch(attrs)  # t=0: baseline only
        assert tp._ema is None
        tp.launch(attrs)  # t=1: 8 samples / 1s
        assert tp._ema == pytest.approx(8.0)

    def test_cycle_end_drain_credits_inflight(self):
        """Cycle end: the Looper publishes the drained window; the steps
        still in flight are credited off it instead of being dropped
        (which silently under-counted k steps of samples every cycle)."""
        from rocket_tpu.observe.profile import Throughput

        times = iter([0.0, 10.0, 20.0, 30.0])
        tp = Throughput(ema=0.5, log_every=1000, clock=lambda: next(times))
        attrs = self._attrs(lag=2)
        tp.set(attrs)
        tp.launch(attrs)  # t=0: window opens
        tp.launch(attrs)  # t=10
        tp.launch(attrs)  # t=20 — nothing read back yet
        assert tp._ema is None and len(tp._inflight) == 3
        attrs.looper.drained_logs = [rt.Attributes(loss=0.1)] * 3
        tp.reset(attrs)  # t=30: 3 completed steps -> 24 samples / 30s
        assert len(tp._inflight) == 0
        assert tp._ema == pytest.approx(24 / 30.0)

    def test_cycle_reset_clears_inflight(self):
        from rocket_tpu.observe.profile import Throughput

        times = iter([0.0, 10.0, 0.0])
        tp = Throughput(ema=0.5, log_every=1000, clock=lambda: next(times))
        attrs = self._attrs(lag=2)
        tp.set(attrs)
        tp.launch(attrs)
        tp.launch(attrs)
        assert len(tp._inflight) == 2
        tp.set(attrs)  # next cycle: stale in-flight sizes must not leak
        assert len(tp._inflight) == 0
        assert tp._ema is None
