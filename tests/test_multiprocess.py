"""REAL multi-process coordination test (VERDICT r2 weak #5).

Spawns 2 OS processes that rendezvous via ``jax.distributed.initialize`` on
localhost and execute the actual ``process_count > 1`` branches of
``parallel/multihost.py`` — broadcast_object, process_allgather, barriers,
assert_equal — plus per-host batch sharding and a coordinated multi-host
Orbax save/restore through the Launcher.  No monkeypatching anywhere.
"""

import contextlib
import os
import socket
import subprocess
import sys

import pytest

N_PROCS = 2
TIMEOUT_S = 420


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_real_multiprocess_pipeline(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(worker))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    # the worker pins its own platform/flags; scrub any test-process leakage
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    # Workers write to files, not PIPEs: a worker blocked on a full pipe
    # buffer would stall before the rendezvous barrier and turn the real
    # error into an opaque timeout.
    logs = [tmp_path / f"worker{pid}.log" for pid in range(N_PROCS)]
    procs = []
    with contextlib.ExitStack() as stack:
        for pid in range(N_PROCS):
            log_file = stack.enter_context(open(logs[pid], "w"))
            procs.append(subprocess.Popen(
                [sys.executable, worker, str(port), str(N_PROCS), str(pid),
                 str(tmp_path)],
                stdout=log_file,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            ))
        try:
            for p in procs:
                p.wait(timeout=TIMEOUT_S)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()
            outputs = [log.read_text() for log in logs]
            pytest.fail(
                "multi-process workers timed out\n" + "\n---\n".join(outputs)
            )
    for pid, p in enumerate(procs):
        out = logs[pid].read_text()
        assert p.returncode == 0, (
            f"worker {pid} exited {p.returncode}\n{out}"
        )
        assert f"WORKER-OK {pid}" in out, out
