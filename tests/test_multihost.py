"""Multi-host bring-up + gather transport tests (SURVEY §5.8).

Single-process environment: the rendezvous path is proven by monkeypatching
``jax.distributed.initialize`` (VERDICT r1 weakness #3 asked for exactly
this), and the gather path by resharding on the 8-fake-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rocket_tpu.parallel import multihost


@pytest.fixture(autouse=True)
def _reset_initialized(monkeypatch):
    monkeypatch.setattr(multihost, "_initialized", False)


def test_initialize_noop_single_process(monkeypatch):
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: called.append(kw))
    for marker in (
        "JAX_COORDINATOR_ADDRESS",
        "TPU_WORKER_HOSTNAMES",
        "MEGASCALE_COORDINATOR_ADDRESS",
        "SLURM_NTASKS",
        "OMPI_COMM_WORLD_SIZE",
    ):
        monkeypatch.delenv(marker, raising=False)
    multihost.initialize()
    assert called == []  # no pod environment -> no rendezvous


def test_initialize_noop_single_worker_tpu_vm(monkeypatch):
    """A lone TPU VM sets TPU_WORKER_HOSTNAMES=localhost — that is NOT a
    pod; rendezvous must be skipped."""
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: called.append(kw))
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    multihost.initialize()
    assert called == []


def test_initialize_autodetect_on_tpu_pod(monkeypatch):
    """On a TPU pod (>1 worker hostnames), initialize() must call
    jax.distributed.initialize() with NO arguments so jax auto-detects the
    coordinator — Orbax async multi-host saves depend on the KV store this
    creates."""
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: called.append(kw))
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    multihost.initialize()
    assert called == [{}]


def test_initialize_explicit_coordinator(monkeypatch):
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: called.append(kw))
    multihost.initialize("10.0.0.1:1234", num_processes=4, process_id=2)
    assert called == [
        {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }
    ]


def test_initialize_idempotent(monkeypatch):
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: called.append(kw))
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1")
    multihost.initialize()
    multihost.initialize()
    assert len(called) == 1


def test_to_host_global_non_leading_dim_sharding(devices):
    """A leaf sharded along BOTH leading and trailing dims reassembles to the
    exact global array (ADVICE r1: dim-0-start dedup truncated these)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rocket_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(data=2, tensor=4).build(devices)
    arr = jnp.arange(8 * 12, dtype=jnp.float32).reshape(8, 12)
    sharded = jax.device_put(arr, NamedSharding(mesh, P("data", "tensor")))
    out = multihost._replicate_on_mesh([sharded])[0]
    np.testing.assert_array_equal(out, np.asarray(arr))

    # column-only sharding (the logits-on-tensor-axis shape from ADVICE)
    sharded2 = jax.device_put(arr, NamedSharding(mesh, P(None, "tensor")))
    out2 = multihost._replicate_on_mesh([sharded2])[0]
    np.testing.assert_array_equal(out2, np.asarray(arr))
