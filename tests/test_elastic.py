"""Elastic restore + preemption-grade persistence (ISSUE 8 acceptance).

The trajectory that must hold end-to-end: kill a 4-device run mid-epoch,
``resume("auto")`` on 2 devices, kill again, resume on all 8 — and the
stitched loss trajectory plus final params match the uninterrupted run.
Alongside it:

- the manifest's ``mesh`` section records the saving topology and
  :func:`~rocket_tpu.persist.integrity.check_reshard` raises a typed
  :class:`~rocket_tpu.persist.integrity.TopologyMismatch` (leaf path +
  remedy) for illegal cross-mesh restores;
- the emergency tier bounds hard-preemption loss to ≤1 step when the
  durable cadence is stale;
- snapshot election orders on (iter, mtime), not directory name;
- ``tree_shardings`` errors name the offending leaf;
- the SIGTERM handler chain layers deterministically (recorder dump →
  emergency flush → previous handler) and is re-entrancy-safe.
"""

import json
import os
import signal

import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu.models.objectives import cross_entropy
from rocket_tpu.parallel.mesh import MeshSpec
from rocket_tpu.parallel.sharding import ShardingRules, tree_shardings
from rocket_tpu.persist import emergency, integrity
from rocket_tpu.persist.integrity import TopologyMismatch
from rocket_tpu.testing import (
    HardPreemptionInjector,
    SigtermInjector,
    SimulatedKill,
)

from test_pipeline import MLP, synthetic_classification
from test_resilience import LossRecorder

pytestmark = [pytest.mark.resilience, pytest.mark.elastic]


def _mesh(n):
    import jax

    return MeshSpec(data=n).build(jax.devices()[:n])


def _tree(tmp_path, data, *, tag, epochs, mesh=None, extra=(),
          save_every=100, emergency_every=None, resume=None, seed=0,
          zero_stage=0):
    """The chaos tree of test_resilience, parameterized by mesh: 256
    samples / batch 64 = 4 iterations per epoch on any device count."""
    model = rt.Module(
        MLP(),
        capsules=[
            rt.Loss(cross_entropy(labels_key="label"), name="ce"),
            rt.Optimizer(learning_rate=2e-2),
        ],
    )
    recorder = LossRecorder()
    looper = rt.Looper(
        capsules=[
            rt.Dataset(rt.ArraySource(data), batch_size=64, shuffle=True,
                       seed=7),
            model,
            *extra,
            recorder,
            rt.Checkpointer(save_every=save_every,
                            emergency_every=emergency_every),
        ],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag=tag, num_epochs=epochs, mesh=mesh,
        project_root=str(tmp_path), seed=seed, zero_stage=zero_stage,
    )
    if resume is not None:
        launcher.resume(resume)
    return launcher, model, recorder


def _flat(params):
    import jax

    return np.concatenate([
        np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(params)
    ])


# -- the acceptance trajectory: 4 devices -> kill -> 2 -> kill -> 8 ----------


def test_kill_on_4_resume_on_2_then_8_matches_uninterrupted(tmp_path,
                                                            devices):
    """THE elastic acceptance test: SIGTERM a 4-device run mid-epoch,
    resume("auto") the same tag on 2 devices, SIGTERM again, finish on all
    8 — stitched losses and final params match the uninterrupted run."""
    data = synthetic_classification(n=256)

    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="eref", epochs=2)
    launcher_a.launch()
    assert len(rec_a.losses) == 8

    # Stage 1: 4 devices, preempted at iteration 2 of epoch 0.
    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="elastic", epochs=2, mesh=_mesh(4),
        extra=[SigtermInjector(at_iter=2)],
    )
    launcher_b.launch()
    assert len(rec_b.losses) == 3
    snap = tmp_path / "elastic" / "v0" / "weights" / "000002"
    assert snap.is_dir()
    # the snapshot is stamped with its saving topology
    mesh_meta = integrity.manifest_mesh(str(snap))
    assert mesh_meta is not None
    assert mesh_meta["device_count"] == 4
    assert mesh_meta["axes"]["data"] == 4
    assert any(name == "batch" for name, _ in mesh_meta["rules"])

    # Stage 2: shrink to 2 devices, preempted again.  A resumed mid-epoch
    # cycle runs one extra no-step iteration when the dataset exhausts
    # (loop.py clears step_logs for it), and that call still ticks the
    # injector — so at_iter=2 lands on global step 4, after steps 3-4.
    launcher_c, model_c, rec_c = _tree(
        tmp_path, data, tag="elastic", epochs=2, mesh=_mesh(2),
        extra=[SigtermInjector(at_iter=2)], resume="auto",
    )
    launcher_c.launch()
    assert len(rec_c.losses) == 2  # global iters 3, 4
    snap_c = tmp_path / "elastic" / "v1" / "weights" / "000005"
    assert snap_c.is_dir()
    assert integrity.manifest_mesh(str(snap_c))["device_count"] == 2

    # Stage 3: grow to all 8 devices, run to completion (global 5, 6, 7).
    launcher_d, model_d, rec_d = _tree(
        tmp_path, data, tag="elastic", epochs=2, mesh=_mesh(8),
        resume="auto",
    )
    launcher_d.launch()
    assert len(rec_d.losses) == 3

    stitched = rec_b.losses + rec_c.losses + rec_d.losses
    assert len(stitched) == 8
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _flat(model_d.state.params), _flat(model_a.state.params),
        rtol=1e-5, atol=1e-6,
    )


def test_weights_only_resume_across_meshes(tmp_path, devices):
    """Weights saved on 4 devices seed a fresh 8-device run (and the
    other direction) without tripping the legacy topology guard."""
    data = synthetic_classification(n=256)
    launcher, model, _ = _tree(tmp_path, data, tag="wo", epochs=1,
                               mesh=_mesh(4), save_every=4)
    launcher.launch()
    snap = str(tmp_path / "wo" / "v0" / "weights" / "000003")

    launcher2, model2, rec2 = _tree(tmp_path, data, tag="wo", epochs=1,
                                    mesh=_mesh(8))
    launcher2.resume(snap, load_capsules=False)
    launcher2.launch()
    assert len(rec2.losses) == 4  # fresh run, full epoch
    # step counter fresh (weights-only), but weights came from the snapshot
    assert int(model2.state.step) == 4


# -- emergency tier: ≤1 step lost on a hard preemption -----------------------


def test_hard_preemption_emergency_bounds_loss_to_one_step(tmp_path,
                                                           devices):
    """With the durable cadence deliberately stale (save_every=100) and
    the emergency tier armed, a HARD preemption (no grace window) at
    iteration 5 leaves an emergency snapshot of iteration 4 — resume loses
    exactly the killed step, not the whole run."""
    data = synthetic_classification(n=256)

    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="href", epochs=2)
    launcher_a.launch()

    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="hard", epochs=2, emergency_every=1,
        extra=[HardPreemptionInjector(at_iter=5)],
    )
    with pytest.raises(SimulatedKill):
        launcher_b.launch()
    # The recorder (priority 400) runs before the injector (150), so iter
    # 5's step ran and its loss was recorded — but its update is lost: the
    # Checkpointer (100) never got to capture it, leaving iter 4 as the
    # freshest emergency snapshot.
    assert len(rec_b.losses) == 6
    edir = tmp_path / "hard" / "v0" / "emergency"
    snaps = sorted(edir.iterdir())
    assert [s.name for s in snaps] == ["000004"]
    assert (snaps[0] / integrity.EMERGENCY_MARKER).is_file()
    ok, reason = integrity.verify(str(snaps[0]))
    assert ok, reason
    # no durable grace-window snapshot was written (cadence 100 never hit)
    assert not (tmp_path / "hard" / "v0" / "weights").exists()

    # resume("auto") elects the emergency snapshot and replays from there:
    # global iters 5, 6, 7 remain.
    launcher_c, model_c, rec_c = _tree(tmp_path, data, tag="hard", epochs=2,
                                       resume="auto")
    launcher_c.launch()
    assert len(rec_c.losses) == 3  # exactly one step was lost and replayed
    # the killed step is replayed exactly once, bit-for-bit deterministic
    np.testing.assert_allclose(rec_b.losses[5], rec_c.losses[0], rtol=1e-6)
    stitched = rec_b.losses[:5] + rec_c.losses
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        _flat(model_c.state.params), _flat(model_a.state.params),
        rtol=1e-5, atol=1e-7,
    )


def test_durable_snapshot_newer_than_emergency_wins(tmp_path, devices):
    """The (iter, mtime) election prefers whichever tier is NEWER: a
    polite preemption's grace-window durable save outranks the staled
    emergency flush of an earlier iteration."""
    data = synthetic_classification(n=256)
    launcher, _, _ = _tree(
        tmp_path, data, tag="newer", epochs=2, emergency_every=1,
        save_every=2, extra=[SigtermInjector(at_iter=2)],
    )
    launcher.launch()
    root = str(tmp_path / "newer")
    best = integrity.latest_valid(root, do_quarantine=False)
    # the grace-window durable snapshot (iter 2) wins; any emergency
    # capture was discarded/superseded by it
    assert best is not None and "weights" in best
    assert best.endswith("000002")


# -- manifest mesh section + check_reshard -----------------------------------


def _manifest_for(arrays, mesh, rules=None, **kw):
    return integrity.build_manifest(
        {"module_0": {"state": arrays}}, mesh=mesh, rules=rules, **kw
    )


def test_manifest_mesh_section_schema(tmp_path, devices):
    import jax

    mesh = _mesh(4)
    manifest = _manifest_for(
        {"w": np.zeros((8, 4), np.float32)}, mesh, ShardingRules(),
        iter_idx=3,
    )
    assert manifest["schema"] == integrity.SCHEMA_VERSION
    section = manifest["mesh"]
    assert section["device_count"] == 4
    assert section["axes"] == {"data": 4, "pipe": 1, "fsdp": 1,
                               "expert": 1, "seq": 1, "tensor": 1}
    rules = dict((name, axes) for name, axes in section["rules"])
    assert rules["embed"] == "fsdp"
    # per-leaf records carry the saved spec slot (None for host leaves)
    rec = manifest["items"]["module_0"]["structure"][0]
    assert "spec" in rec
    # the whole thing must survive a JSON round-trip (manifest.json)
    assert json.loads(json.dumps(manifest)) == manifest


def test_check_reshard_shape_mismatch_is_model_change(devices):
    import jax

    mesh = _mesh(2)
    manifest = _manifest_for({"w": np.zeros((8, 4), np.float32)}, mesh)
    target = {"state": {"w": jax.ShapeDtypeStruct(
        (16, 4), np.float32,
        sharding=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()),
    )}}
    with pytest.raises(TopologyMismatch, match=r"w.*model change"):
        integrity.check_reshard(manifest, {"module_0": target})


def test_check_reshard_missing_axis_names_leaf_and_remedy(devices):
    import jax

    mesh = _mesh(2)
    manifest = _manifest_for({"w": np.zeros((8, 4), np.float32)}, mesh)

    class FakeSharding:
        """A sharding whose spec names an axis its mesh lacks — the state
        a hand-built restore target can reach (NamedSharding validates at
        construction, so fake the duck type)."""

        def __init__(self, mesh, spec):
            self.mesh, self.spec = mesh, spec

    leaf = jax.ShapeDtypeStruct((8, 4), np.float32)
    leaf.sharding = FakeSharding(mesh, jax.sharding.PartitionSpec("bogus"))
    with pytest.raises(TopologyMismatch, match=r"w.*'bogus'.*size 1 is"):
        integrity.check_reshard(manifest, {"module_0": {"state": {"w": leaf}}})


def test_check_reshard_rank_overflow(devices):
    import jax

    mesh = _mesh(2)
    manifest = _manifest_for({"w": np.zeros((8,), np.float32)}, mesh)
    target = {"state": {"w": jax.ShapeDtypeStruct(
        (8,), np.float32,
        sharding=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "data")),
    )}}
    with pytest.raises(TopologyMismatch, match=r"w.*rank-1"):
        integrity.check_reshard(manifest, {"module_0": target})


def test_check_reshard_uneven_division_is_legal(devices):
    """GSPMD pads ragged shards: dim 6 over a 4-way axis must NOT raise."""
    import jax

    mesh = _mesh(4)
    manifest = _manifest_for({"w": np.zeros((6, 4), np.float32)}, mesh)
    target = {"state": {"w": jax.ShapeDtypeStruct(
        (6, 4), np.float32,
        sharding=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data", None)),
    )}}
    integrity.check_reshard(manifest, {"module_0": target})  # no raise


# -- (iter, mtime) snapshot election -----------------------------------------


def _fake_snapshot(path, iter_idx, mtime=None):
    """A minimal committed snapshot dir that passes shallow verify."""
    os.makedirs(os.path.join(path, "module_0"), exist_ok=True)
    manifest = integrity.build_manifest(
        {"module_0": {"w": np.zeros((2,), np.float32)}}, iter_idx=iter_idx,
    )
    integrity.write_manifest(path, manifest)
    integrity.write_commit_marker(path)
    if mtime is not None:
        os.utime(path, (mtime, mtime))


def test_latest_valid_orders_on_iter_not_dirname(tmp_path):
    """Regression (ISSUE 8 satellite): a backdated directory NAME must not
    outrank a snapshot whose manifest records a later iteration."""
    root = str(tmp_path / "proj")
    newer = os.path.join(root, "weights", "000002")   # small name, iter 50
    older = os.path.join(root, "weights", "000100")   # big name, iter 5
    _fake_snapshot(newer, iter_idx=50)
    _fake_snapshot(older, iter_idx=5)
    assert integrity.latest_valid(root, do_quarantine=False) == newer


def test_latest_valid_breaks_iter_ties_on_mtime(tmp_path):
    """Same iteration in both tiers: the later WRITE wins."""
    import time

    root = str(tmp_path / "proj")
    durable = os.path.join(root, "weights", "000004")
    flushed = os.path.join(root, "emergency", "000004")
    now = time.time()
    _fake_snapshot(durable, iter_idx=4, mtime=now - 60)
    _fake_snapshot(flushed, iter_idx=4, mtime=now)
    assert integrity.latest_valid(root, do_quarantine=False) == flushed
    # flip the clock: the durable one becomes the later write
    os.utime(durable, (now + 60, now + 60))
    assert integrity.latest_valid(root, do_quarantine=False) == durable


def test_resolve_restore_path_fallback_orders_on_iter(tmp_path):
    """The explicit-path fallback scan uses the same (iter, mtime) key."""
    root = str(tmp_path / "proj")
    broken = os.path.join(root, "weights", "000200")
    newer = os.path.join(root, "weights", "000002")   # iter 50
    older = os.path.join(root, "weights", "000100")   # iter 5
    _fake_snapshot(broken, iter_idx=200)
    _fake_snapshot(newer, iter_idx=50)
    _fake_snapshot(older, iter_idx=5)
    os.remove(os.path.join(broken, integrity.COMMIT_MARKER))
    assert integrity.resolve_restore_path(broken) == newer


# -- tree_shardings error paths ----------------------------------------------


def test_tree_shardings_missing_mesh_axis_names_leaf(devices):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
    tree = {"layer": {"kernel": P("data"), "bias": P("tensor")}}
    with pytest.raises(ValueError, match=r"bias.*'tensor'.*size 1 is free"):
        tree_shardings(mesh, tree)


def test_tree_shardings_unknown_logical_axis_names_leaf(devices):
    tree = {"blk": {"w": ("embed",), "v": ("no_such_axis",)}}
    with pytest.raises(KeyError, match=r"v.*no_such_axis"):
        tree_shardings(_mesh(2), tree)


def test_tree_shardings_rank_mismatch_names_leaf(devices):
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(2)
    tree = {"emb": {"table": P(None, "data")}}
    shapes = {"emb": {"table": (16,)}}
    with pytest.raises(ValueError, match=r"table.*rank 1"):
        tree_shardings(mesh, tree, shapes=shapes)
    # matching rank passes and yields NamedShardings
    out = tree_shardings(mesh, tree, shapes={"emb": {"table": (16, 4)}})
    assert out["emb"]["table"].mesh is mesh


# -- SIGTERM handler layering ------------------------------------------------


class _Chain:
    """Arms recorder + emergency tier + a recording previous handler
    around the checkpoint orchestrator, and cleans all of it up."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.order = []

    def __enter__(self):
        from rocket_tpu.observe import recorder as flightrec
        from rocket_tpu.persist import checkpoint as cp

        self.flightrec, self.cp = flightrec, cp
        rec = flightrec.FlightRecorder(out_dir=str(self.tmp_path / "fr"))
        dump = rec.dump
        rec.dump = lambda reason="manual": (
            self.order.append("dump"), dump(reason))[1]
        flightrec.install(rec, sigterm=False)
        self.rec = rec

        tier = emergency.EmergencyTier(str(self.tmp_path / "proj"))
        flush = tier.flush
        tier.flush = lambda reason="preemption": (
            self.order.append("flush"), flush(reason))[1]
        emergency.activate(tier)
        self.tier = tier

        self._saved_prev = dict(cp._PREV_HANDLER)
        cp._PREV_HANDLER["handler"] = self._prev
        return self

    def _prev(self, signum, frame):
        self.order.append("prev")

    def stage(self, iter_idx=7):
        self.tier.capture(
            {"module_0": {"w": np.ones((2,), np.float32)}},
            iter_idx=iter_idx,
        )

    def __exit__(self, *exc):
        self.flightrec.uninstall()
        emergency.deactivate(self.tier)
        self.cp._PREV_HANDLER.clear()
        self.cp._PREV_HANDLER.update(self._saved_prev)
        self.cp._preempted.clear()


def test_sigterm_chain_order_dump_flush_prev(tmp_path, devices):
    """Satellite: one SIGTERM delivery runs recorder dump FIRST, emergency
    flush SECOND, the previous handler LAST."""
    from rocket_tpu.persist import checkpoint as cp

    with _Chain(tmp_path) as chain:
        chain.stage()
        cp._on_sigterm(signal.SIGTERM, None)
        assert chain.order == ["dump", "flush", "prev"]
        assert cp._preempted.is_set()
        assert chain.tier.flushes == 1
        assert (tmp_path / "proj" / "emergency" / "000007").is_dir()


def test_sigterm_reentrant_delivery_flushes_once(tmp_path, devices):
    """A second SIGTERM landing while the first handler chain is still
    running (prev handler re-raises) must not dump or flush again."""
    from rocket_tpu.persist import checkpoint as cp

    with _Chain(tmp_path) as chain:
        chain.stage()
        prev = chain._prev

        def reentrant(signum, frame):
            prev(signum, frame)
            if chain.order.count("prev") == 1:
                cp._on_sigterm(signum, frame)  # the second delivery

        cp._PREV_HANDLER["handler"] = reentrant
        cp._on_sigterm(signal.SIGTERM, None)
        assert chain.order == ["dump", "flush", "prev"]
        assert chain.tier.flushes == 1
        assert chain.tier.captures == 1


def test_sigterm_chain_with_recorder_handler_installed_first(tmp_path,
                                                             devices):
    """Install order recorder-first: the checkpoint orchestrator chains
    INTO the recorder's own handler — still exactly one dump."""
    from rocket_tpu.observe import recorder as flightrec
    from rocket_tpu.persist import checkpoint as cp

    with _Chain(tmp_path) as chain:
        chain.stage()
        # the recorder's own handler is the "previous" one in the chain
        cp._PREV_HANDLER["handler"] = flightrec._on_sigterm
        saved = dict(flightrec._PREV_SIGTERM)
        flightrec._PREV_SIGTERM["handler"] = chain._prev
        try:
            cp._on_sigterm(signal.SIGTERM, None)
        finally:
            flightrec._PREV_SIGTERM.clear()
            flightrec._PREV_SIGTERM.update(saved)
        # recorder's handler ran but did NOT dump a second time
        assert chain.order == ["dump", "flush", "prev"]


def test_second_flush_without_new_capture_is_noop(tmp_path, devices):
    tier = emergency.EmergencyTier(str(tmp_path / "p"))
    tier.capture({"m": {"w": np.zeros((2,), np.float32)}}, iter_idx=1)
    assert tier.flush("first") is not None
    assert tier.flush("second") is None  # nothing staged: idempotent
    assert tier.flushes == 1


# -- ZeRO-1 snapshots across data-axis sizes ---------------------------------


def test_zero1_snapshot_reshards_onto_larger_data_axis(tmp_path, devices):
    """A ``zero_stage=1`` run preempted on a 4-way data axis resumes on
    an 8-way axis: the restored optimizer mirrors must RE-PARTITION over
    the new data axis (8-way, not 4-way, and certainly not replicated),
    and the stitched trajectory still matches an uninterrupted unsharded
    reference — ZeRO is a placement change, never a numerics change."""
    import jax

    data = synthetic_classification(n=256)

    def _opt_mirror_specs(model):
        """PartitionSpecs of the Dense_0 kernel's optimizer mirrors."""
        out = []
        for leaf in jax.tree_util.tree_leaves(model.state.opt_state):
            if getattr(leaf, "shape", None) == (16, 32):
                out.append(leaf.sharding.spec)
        return out

    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="zref", epochs=1)
    launcher_a.launch()
    assert len(rec_a.losses) == 4

    # Stage 1: zero_stage=1 on 4 devices, preempted at iteration 2.
    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="zelastic", epochs=1, mesh=_mesh(4),
        zero_stage=1, extra=[SigtermInjector(at_iter=2)],
    )
    launcher_b.launch()
    assert len(rec_b.losses) == 3
    specs_b = _opt_mirror_specs(model_b)
    assert specs_b and all("data" in str(s) for s in specs_b), specs_b
    snap = tmp_path / "zelastic" / "v0" / "weights" / "000002"
    assert snap.is_dir()
    assert integrity.manifest_mesh(str(snap))["axes"]["data"] == 4

    # Stage 2: resume on all 8 devices, still zero_stage=1.
    launcher_c, model_c, rec_c = _tree(
        tmp_path, data, tag="zelastic", epochs=1, mesh=_mesh(8),
        zero_stage=1, resume="auto",
    )
    launcher_c.launch()
    assert len(rec_c.losses) == 1

    specs_c = _opt_mirror_specs(model_c)
    assert specs_c, "no optimizer mirrors found"
    for spec in specs_c:
        assert "data" in str(spec), (
            f"restored optimizer mirror replicated ({spec}) — the reshard "
            f"must re-partition over the new data axis"
        )
    # 8-way for real: each device holds 1/8 of the (16, 32) mirror
    mirror = next(
        leaf for leaf in jax.tree_util.tree_leaves(model_c.state.opt_state)
        if getattr(leaf, "shape", None) == (16, 32)
    )
    shard_shapes = {s.data.shape for s in mirror.addressable_shards}
    assert shard_shapes == {(2, 32)}, shard_shapes

    stitched = rec_b.losses + rec_c.losses
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-6)


# -- ZeRO stage transitions across restores ----------------------------------


def test_zero_stage1_snapshot_resumes_at_stage3_on_new_mesh(tmp_path,
                                                            devices):
    """A stage-1 snapshot (4-way data axis) resumes into a stage-3 run on
    8 devices: params re-partition into the zero storage domain, the
    manifest carries the saving stage, and the stitched trajectory still
    matches the uninterrupted unsharded reference — a ZeRO stage change
    across a restore is a placement change, never a numerics change."""
    import jax

    data = synthetic_classification(n=256)

    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="ztref", epochs=1)
    launcher_a.launch()
    assert len(rec_a.losses) == 4

    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="ztrans13", epochs=1, mesh=_mesh(4),
        zero_stage=1, extra=[SigtermInjector(at_iter=2)],
    )
    launcher_b.launch()
    assert len(rec_b.losses) == 3
    snap = tmp_path / "ztrans13" / "v0" / "weights" / "000002"
    assert snap.is_dir()
    meta = integrity.manifest_mesh(str(snap))
    assert meta["axes"]["data"] == 4
    assert meta["zero_stage"] == 1  # manifests stamp the saving stage

    launcher_c, model_c, rec_c = _tree(
        tmp_path, data, tag="ztrans13", epochs=1, mesh=_mesh(8),
        zero_stage=3, resume="auto",
    )
    launcher_c.launch()
    assert len(rec_c.losses) == 1

    # stage-3 storage domain for real: the restored Dense_0 kernel is
    # data-sliced across all 8 devices, not replicated
    kernel = next(
        leaf for leaf in jax.tree_util.tree_leaves(model_c.state.params)
        if getattr(leaf, "shape", None) == (16, 32)
    )
    assert "data" in str(kernel.sharding.spec), kernel.sharding.spec
    assert {s.data.shape for s in kernel.addressable_shards} == {(2, 32)}

    stitched = rec_b.losses + rec_c.losses
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _flat(model_c.state.params), _flat(model_a.state.params),
        rtol=1e-5, atol=1e-6,
    )


def test_zero_stage3_snapshot_resumes_at_stage0_on_new_mesh(tmp_path,
                                                            devices):
    """The inverse transition: a stage-3 run (params stored sharded on a
    4-way axis) is preempted and resumed as a plain unsharded stage-0 run
    on 2 devices — everything gathers back to replicated and the
    trajectory stitches against the uninterrupted reference."""
    import jax

    data = synthetic_classification(n=256)

    launcher_a, model_a, rec_a = _tree(tmp_path, data, tag="ztref0", epochs=1)
    launcher_a.launch()
    assert len(rec_a.losses) == 4

    launcher_b, model_b, rec_b = _tree(
        tmp_path, data, tag="ztrans30", epochs=1, mesh=_mesh(4),
        zero_stage=3, extra=[SigtermInjector(at_iter=2)],
    )
    launcher_b.launch()
    assert len(rec_b.losses) == 3
    snap = tmp_path / "ztrans30" / "v0" / "weights" / "000002"
    assert integrity.manifest_mesh(str(snap))["zero_stage"] == 3

    launcher_c, model_c, rec_c = _tree(
        tmp_path, data, tag="ztrans30", epochs=1, mesh=_mesh(2),
        zero_stage=0, resume="auto",
    )
    launcher_c.launch()
    assert len(rec_c.losses) == 1

    # back to stage 0: params and optimizer mirrors fully replicated
    for leaf in jax.tree_util.tree_leaves(model_c.state.params):
        assert "data" not in str(leaf.sharding.spec), leaf.sharding.spec
    for leaf in jax.tree_util.tree_leaves(model_c.state.opt_state):
        if hasattr(leaf, "sharding"):
            assert "data" not in str(leaf.sharding.spec), leaf.sharding.spec

    stitched = rec_b.losses + rec_c.losses
    np.testing.assert_allclose(stitched, rec_a.losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        _flat(model_c.state.params), _flat(model_a.state.params),
        rtol=1e-5, atol=1e-6,
    )
