"""notebook_launch (reference @notebook / notebook_launcher parity,
``rocket/core/launcher.py:202-253``): inline 1-process mode, fork-N local
workers with a real jax.distributed rendezvous, and the backend-already-
initialized guard."""

import os
import subprocess
import sys

import pytest

from rocket_tpu.launch.notebook import in_notebook, notebook_launch


def test_single_process_runs_inline():
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2

    assert notebook_launch(fn, args=(21,)) == 42
    assert calls == [21]


def test_fork_refused_once_backends_exist(devices):
    """This pytest process has live CPU backends (the devices fixture), so
    fork-N must refuse with the accelerate-style guidance."""
    with pytest.raises(RuntimeError, match="already initialized"):
        notebook_launch(lambda: None, num_processes=2)


def test_not_in_notebook():
    assert in_notebook() is False


@pytest.mark.slow
def test_fork_n_workers_rendezvous(tmp_path):
    """Fresh parent (no JAX backends) forks 2 workers that rendezvous via
    jax.distributed and run real host collectives over a notebook-style
    closure."""
    parent = os.path.join(os.path.dirname(__file__), "notebook_parent.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(parent))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, parent, str(tmp_path)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "NOTEBOOK-PARENT-OK" in out.stdout, out.stdout + out.stderr
