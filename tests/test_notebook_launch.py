"""notebook_launch (reference @notebook / notebook_launcher parity,
``rocket/core/launcher.py:202-253``): inline 1-process mode, fork-N local
workers with a real jax.distributed rendezvous, and the backend-already-
initialized guard."""

import os
import subprocess
import sys

import pytest

from rocket_tpu.launch.notebook import in_notebook, notebook_launch


def test_single_process_runs_inline():
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2

    assert notebook_launch(fn, args=(21,)) == 42
    assert calls == [21]


def test_fork_refused_once_backends_exist(devices):
    """This pytest process has live CPU backends (the devices fixture), so
    fork-N must refuse with the accelerate-style guidance."""
    with pytest.raises(RuntimeError, match="already initialized"):
        notebook_launch(lambda: None, num_processes=2)


def test_not_in_notebook():
    assert in_notebook() is False


@pytest.mark.slow
def test_fork_n_workers_rendezvous(tmp_path):
    """Fresh parent (no JAX backends) forks 2 workers that rendezvous via
    jax.distributed and run real host collectives over a notebook-style
    closure."""
    parent = os.path.join(os.path.dirname(__file__), "notebook_parent.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(parent))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, parent, str(tmp_path)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "NOTEBOOK-PARENT-OK" in out.stdout, out.stdout + out.stderr


def test_plain_launch_reroutes_in_notebook(devices, tmp_path, monkeypatch):
    """Reference @notebook sugar: a plain launch() inside a Jupyter kernel
    that requests num_procs>1 reroutes through notebook_launch instead of
    running single-process (VERDICT r3 missing #2)."""
    import numpy as np
    import rocket_tpu as rt
    from rocket_tpu.launch import notebook as nb
    from rocket_tpu.models.objectives import cross_entropy
    from test_pipeline import MLP, synthetic_classification

    calls = {}
    monkeypatch.setattr(nb, "in_notebook", lambda: True)

    def fake_launch(fn, args=(), num_processes=1, **kw):
        calls["n"] = num_processes
        calls["fn"] = fn

    monkeypatch.setattr(nb, "notebook_launch", fake_launch)

    data = synthetic_classification(n=64)
    model = rt.Module(
        MLP(),
        capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                  rt.Optimizer(learning_rate=1e-2)],
    )
    looper = rt.Looper(
        capsules=[rt.Dataset(rt.ArraySource(data), batch_size=32), model],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag="nb", num_epochs=1,
        project_root=str(tmp_path),
    )
    attrs = rt.Attributes(launcher=rt.Attributes(num_procs=2))
    launcher.launch(attrs)
    assert calls["n"] == 2  # rerouted, did not run inline
    assert model.state is None  # nothing trained in this process

    # matching process count (workers re-entering): runs inline
    calls.clear()
    attrs2 = rt.Attributes(launcher=rt.Attributes(num_procs=1))
    launcher.launch(attrs2)
    assert "n" not in calls
    assert model.step == 2  # 64/32 batches x 1 epoch


def test_plain_launch_runs_inline_outside_notebook(devices, tmp_path):
    """No kernel: the requested num_procs is informational and launch runs
    in-process (the reference decorator also only reroutes in-notebook)."""
    import rocket_tpu as rt
    from rocket_tpu.models.objectives import cross_entropy
    from test_pipeline import MLP, synthetic_classification

    data = synthetic_classification(n=64)
    model = rt.Module(
        MLP(),
        capsules=[rt.Loss(cross_entropy(labels_key="label"), name="ce"),
                  rt.Optimizer(learning_rate=1e-2)],
    )
    looper = rt.Looper(
        capsules=[rt.Dataset(rt.ArraySource(data), batch_size=32), model],
        progress=False,
    )
    launcher = rt.Launcher(
        capsules=[looper], tag="nb2", num_epochs=1,
        project_root=str(tmp_path),
    )
    launcher.launch(rt.Attributes(launcher=rt.Attributes(num_procs=4)))
    assert model.step == 2
