from rocket_tpu.parallel.mesh import (
    AXIS_NAMES,
    DATA_AXES,
    MeshSpec,
    data_parallel_mesh,
    single_device_mesh,
)
from rocket_tpu.parallel.sharding import (
    DEFAULT_RULES,
    P,
    ShardingRules,
    batch_sharding,
    named_sharding,
    replicated,
    tree_shardings,
)
from rocket_tpu.parallel import collectives, multihost

__all__ = [
    "AXIS_NAMES",
    "DATA_AXES",
    "MeshSpec",
    "data_parallel_mesh",
    "single_device_mesh",
    "DEFAULT_RULES",
    "P",
    "ShardingRules",
    "batch_sharding",
    "named_sharding",
    "replicated",
    "tree_shardings",
    "collectives",
    "multihost",
]
