"""Device mesh construction — the parallelism substrate.

The reference has no mesh concept: its only strategy is DDP data-parallel via
accelerate/NCCL (``rocket/core/module.py:106``, SURVEY §2.2).  The TPU build
makes the mesh explicit and first-class: every run owns a
:class:`jax.sharding.Mesh` with six named axes

    ``('data', 'pipe', 'fsdp', 'expert', 'seq', 'tensor')``

covering data / pipeline / ZeRO-style parameter / expert (MoE) / sequence
(ring) / tensor parallelism.  Axes of size 1 cost nothing, so a single spec
type degrades gracefully from a v4-32 GSPMD run to one CPU device — the
"MNIST stays CPU-runnable" requirement (SURVEY §7.4).

Axis order is chosen for ICI locality: ``tensor`` (highest-bandwidth, most
latency-sensitive collectives) is innermost so its groups map to physically
adjacent chips; ``data`` (lowest-frequency gradient psum) is outermost and may
ride DCN across slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis names, outermost to innermost.
AXIS_NAMES: Tuple[str, ...] = ("data", "pipe", "fsdp", "expert", "seq", "tensor")

DATA_AXES: Tuple[str, ...] = ("data", "fsdp")  # batch dim shards over these


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. ``-1`` on exactly one axis means "fill with the
    remaining devices" (default: ``data``)."""

    data: int = -1
    pipe: int = 1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def sizes(self, num_devices: int) -> Tuple[int, ...]:
        raw = [self.data, self.pipe, self.fsdp, self.expert, self.seq, self.tensor]
        fills = [i for i, s in enumerate(raw) if s == -1]
        if len(fills) > 1:
            raise ValueError(f"MeshSpec: at most one -1 axis, got {raw}")
        fixed = math.prod(s for s in raw if s != -1)
        if fills:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"MeshSpec {raw}: fixed axes product {fixed} does not "
                    f"divide device count {num_devices}"
                )
            raw[fills[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise ValueError(
                f"MeshSpec {raw}: product {fixed} != device count {num_devices}"
            )
        return tuple(raw)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices) if devices is not None else jax.devices()
        shape = self.sizes(len(devices))
        if len(devices) == 1:
            device_array = np.asarray(devices).reshape(shape)
        else:
            try:
                device_array = mesh_utils.create_device_mesh(
                    shape, devices=devices
                )
            except (ValueError, AssertionError):
                # Topology-aware layout unavailable (e.g. CPU fake devices)
                device_array = np.asarray(devices).reshape(shape)
        return Mesh(device_array, AXIS_NAMES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """A trivial 1-device mesh — lets all sharded code paths run unmodified
    on one chip or CPU."""
    device = device or jax.devices()[0]
    return MeshSpec(data=1).build([device])


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """All devices on the ``data`` axis — the reference's DDP topology."""
    return MeshSpec().build(devices)
