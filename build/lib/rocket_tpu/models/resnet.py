"""ResNet — the BASELINE.json "ResNet-50 on CIFAR-10 / ImageNet" configs.

Standard bottleneck ResNet (v1.5: stride in the 3x3) in flax with BatchNorm,
exercising the framework's *mutable collections* path (``batch_stats``
threads through :class:`~rocket_tpu.engine.state.TrainState.mutable` and is
updated inside the jitted train step).  CNNs parallelize by data — conv
kernels are replicated (the reference's DDP contract, SURVEY §2.2); the
batch dim shards over the mesh data axes.

Batch contract: reads ``batch['image']`` (NHWC), writes ``batch['logits']``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.models.layers import image_input


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    norm: Any = None
    conv: Any = None

    @nn.compact
    def __call__(self, x):
        norm, conv = self.norm, self.conv
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=self.strides)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1), strides=self.strides)(
                residual
            )
            residual = norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Bottleneck ResNet; ``stage_sizes=[3,4,6,3]`` is ResNet-50."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    small_images: bool = False  # CIFAR stem (3x3, no maxpool)
    image_key: str = "image"
    logits_key: str = "logits"
    # Compute dtype; None = follow the input. The Module clones this in from
    # the precision policy at materialization (honest bf16, VERDICT r1 #5).
    dtype: Any = None

    @nn.compact
    def __call__(self, batch, train: bool = False):
        x = image_input(batch[self.image_key], self.dtype)
        cdtype = x.dtype
        conv = partial(nn.Conv, use_bias=False, dtype=cdtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=cdtype,
        )
        if self.small_images:
            x = conv(self.width, (3, 3))(x)
        else:
            x = conv(self.width, (7, 7), strides=(2, 2))(x)
        x = norm()(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = BottleneckBlock(
                    self.width * 2 ** stage, strides=strides, norm=norm, conv=conv
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes, dtype=cdtype)(x)
        out = Attributes(batch)
        out[self.logits_key] = logits
        return out


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    # 18-layer variant uses basic blocks in the original; bottleneck-[2,2,2,2]
    # here keeps one block implementation (2x params of true R18 — fine for
    # the throughput ladder, documented divergence).
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, **kw)
