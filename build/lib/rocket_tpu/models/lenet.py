"""LeNet — the reference's canonical example model.

Capability parity: ``/root/reference/examples/mnist.py:42-79`` defines a
LeNet-5-style CNN (2 conv + 3 dense) used for the MNIST pipeline.  This is
the idiomatic flax version following the framework's batch-rewriting model
contract: ``__call__(batch, train)`` reads ``batch['image']`` (NHWC) and
returns the batch with ``batch['logits']`` added (reference contract:
``attrs.batch = module.forward(attrs.batch)``, ``module.py:139``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from rocket_tpu.core.attributes import Attributes


class LeNet(nn.Module):
    """2×conv + 3×dense classifier (MNIST-shaped by default)."""

    num_classes: int = 10
    image_key: str = "image"
    logits_key: str = "logits"

    @nn.compact
    def __call__(self, batch, train: bool = False):
        x = batch[self.image_key]
        if x.ndim == 3:  # NHW -> NHWC
            x = x[..., None]
        x = x.astype(jnp.float32)
        x = nn.Conv(6, kernel_size=(5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.Conv(16, kernel_size=(5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        logits = nn.Dense(self.num_classes)(x)
        out = Attributes(batch)
        out[self.logits_key] = logits
        return out
