from rocket_tpu.observe.backends import (
    JsonlBackend,
    MemoryBackend,
    TensorBoardBackend,
    TrackerBackend,
)
from rocket_tpu.utils.logging import RankAwareLogger, get_logger
from rocket_tpu.observe.meter import Meter, Metric
from rocket_tpu.observe.profile import Profiler, Throughput, annotate, debug_mode
from rocket_tpu.observe.tracker import Tracker

__all__ = [
    "JsonlBackend",
    "MemoryBackend",
    "Meter",
    "Metric",
    "Profiler",
    "Throughput",
    "annotate",
    "debug_mode",
    "RankAwareLogger",
    "TensorBoardBackend",
    "Tracker",
    "TrackerBackend",
    "get_logger",
]
