"""Meter / Metric — distributed evaluation metrics.

Capability parity: reference ``rocket/core/meter.py:30-206``:

- ``Meter`` runs **only in eval cycles** (``meter.py:84-85``), gathers the
  listed batch keys across all ranks (``gather_for_metrics``, ``:93``),
  rebuilds ``attrs.batch`` with the gathered values (``:96-103``), then
  dispatches to its child ``Metric`` capsules (``:105``);
- ``Metric`` is the user-subclassed accumulator: ``set`` pins the step to the
  epoch (``:142-158``), ``launch`` accumulates, ``reset`` finalizes + clears
  (``:160-206``; e.g. ``Accuracy`` in ``examples/mnist.py:20-39``).

TPU-first: the gather is :func:`rocket_tpu.parallel.multihost.to_host_global`
on global jax Arrays, and the duplicate-padding removal that accelerate hides
inside ``gather_for_metrics`` is explicit here — the data loader marks padded
rows in the batch's ``_valid`` mask and the Meter drops them before the
metrics see the data (static batch shapes on device, exact sample counts on
host; SURVEY §7.4).
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence

import numpy as np

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule
from rocket_tpu.core.dispatcher import Dispatcher
from rocket_tpu.parallel.multihost import to_host_global


class Metric(Capsule):
    """Abstract per-cycle metric accumulator (reference
    ``meter.py:108-206``). Subclass and implement ``launch`` (accumulate from
    ``attrs.batch``) and ``reset`` (finalize: push to tracker / loop state,
    clear accumulators)."""

    def __init__(
        self,
        statefull: bool = False,
        priority: int = 1000,
        logger: Optional[Any] = None,
    ) -> None:
        super().__init__(statefull=statefull, priority=priority, logger=logger)
        self._step = 0

    def set(self, attrs: Optional[Attributes] = None) -> None:
        """Pin the record step to the current epoch (reference
        ``meter.py:142-158``)."""
        if attrs is not None and attrs.launcher is not None:
            self._step = int(attrs.launcher.epoch_idx or 0)

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        raise NotImplementedError

    def reset(self, attrs: Optional[Attributes] = None) -> None:
        raise NotImplementedError


class Meter(Dispatcher):
    """Gather batch keys globally, then run child metrics on exact
    (dedup-masked) host arrays.

    Parameters
    ----------
    keys:
        Batch keys to gather (sorted, reference ``meter.py:54-61``).
    capsules:
        Child :class:`Metric` instances.
    mask_key:
        Valid-row mask published by the data loader (drop padded rows).
    """

    def __init__(
        self,
        keys: Sequence[str],
        capsules: Iterable[Capsule] = (),
        mask_key: str = "_valid",
        statefull: bool = False,
        priority: int = 1000,
        logger: Optional[Any] = None,
    ) -> None:
        super().__init__(
            capsules=capsules, statefull=statefull, priority=priority, logger=logger
        )
        self._keys: List[str] = sorted(keys)
        self._mask_key = mask_key

    def guard(self) -> None:
        super().guard()
        for capsule in self._capsules:
            if not isinstance(capsule, Metric):
                raise TypeError(
                    f"Meter children must be Metrics, got "
                    f"{type(capsule).__name__}"
                )

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        if attrs is None or attrs.batch is None:
            return
        looper = attrs.looper
        if looper is not None and looper.grad_enabled:
            return  # eval-only (reference ``meter.py:84-85``)
        batch = attrs.batch
        wanted = {}
        for key in self._keys:
            value = batch.get(key) if hasattr(batch, "get") else None
            if value is None:
                raise KeyError(
                    f"Meter: key {key!r} missing from batch "
                    f"(has {sorted(batch) if hasattr(batch, 'keys') else '?'})"
                )
            wanted[key] = value
        mask_value = batch.get(self._mask_key) if hasattr(batch, "get") else None
        if mask_value is not None:
            wanted[self._mask_key] = mask_value
        # ONE host gather for the whole pytree (one DCN collective per
        # iteration, not one per key).
        host_tree = to_host_global(wanted)
        mask = None
        if mask_value is not None:
            mask = host_tree.pop(self._mask_key).astype(bool)
        gathered = Attributes(batch)
        for key, host in host_tree.items():
            if mask is not None and np.ndim(host) >= 1 and len(host) == len(mask):
                host = host[mask]
            gathered[key] = host
        attrs.batch = gathered
        for capsule in self._capsules:
            capsule.launch(attrs)
