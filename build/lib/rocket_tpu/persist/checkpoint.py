"""Checkpointer — periodic full-state snapshots.

Capability parity: reference ``rocket/core/checkpoint.py:20-169``:

- priority **100**: runs last in each iteration so it sees the post-step
  state (SURVEY §2.3);
- requires a project dir, i.e. a Launcher ``tag`` (``checkpoint.py:74-81``);
- every ``save_every`` iterations writes ``<project>/<output_dir_format>``
  (default ``weights/{:06d}``, reference ``weights/{:03d}`` at
  ``checkpoint.py:61``) containing every registered capsule's state
  (``accelerator.save_state``, ``:116-129``);
- persists ``iter_idx + 1`` so a restored run does not immediately re-save
  (``checkpoint.py:134-149``).

TPU-first fixes over the reference (SURVEY §2.4): saving is **not** gated on
the main process — Orbax checkpoints are multi-host-coordinated (every host
writes its own parameter shards, then host 0 commits), and saves are async:
the step loop keeps running while buffers drain to disk.  ``keep_last``
retention prunes old snapshots (the reference keeps everything).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule
from rocket_tpu.persist.orbax_io import default_io


class Checkpointer(Capsule):
    def __init__(
        self,
        save_every: int = 1000,
        output_dir_format: str = "weights/{:06d}",
        keep_last: Optional[int] = None,
        save_on_cycle_end: bool = False,
        statefull: bool = True,
        priority: int = 100,
        logger: Optional[Any] = None,
    ) -> None:
        super().__init__(statefull=statefull, priority=priority, logger=logger)
        if save_every < 1:
            raise ValueError("save_every must be >= 1")
        self._save_every = int(save_every)
        self._format = output_dir_format
        self._keep_last = keep_last
        self._save_on_cycle_end = save_on_cycle_end
        self._iter_idx = 0
        self._saved_dirs: list = []

    # -- lifecycle -----------------------------------------------------------

    def setup(self, attrs: Optional[Attributes] = None) -> None:
        super().setup(attrs)
        if self._runtime.project_dir is None:
            raise RuntimeError(
                "Checkpointer needs a project dir — give the Launcher a tag "
                "(reference checkpoint.py:75-81)"
            )
        # Seed retention from snapshots already on disk so keep_last keeps
        # bounding disk after a restart (in-memory-only tracking forgets
        # pre-crash snapshots).  A FULL resume is a continuation of the prior
        # run, so its snapshot dir joins the retention window too; a
        # weights-only resume is a new run seeded from pretrained weights —
        # never delete those.
        self._saved_dirs = []
        spec = getattr(self._runtime, "resume_spec", None)
        if spec is not None and spec.load_capsules:
            prior_root = self._strip_format(str(spec.path))
            if prior_root is not None and prior_root != self._runtime.project_dir:
                self._saved_dirs += self._snapshots_under(prior_root)
        self._saved_dirs += self._snapshots_under(self._runtime.project_dir)

    def _format_parts(self):
        import re

        field = re.search(r"\{[^}]*\}", self._format)
        if field is None:
            return None
        return self._format[: field.start()], self._format[field.end():]

    def _strip_format(self, snapshot_path: str):
        """Invert output_dir_format: the project root a snapshot was written
        under, or None if the path doesn't match the format."""
        import re

        parts = self._format_parts()
        if parts is None:
            return None
        prefix, suffix = parts
        tail = re.compile(
            re.escape(os.sep) + re.escape(prefix) + r"\d+" + re.escape(suffix) + r"$"
        )
        match = tail.search(snapshot_path)
        if match is None:
            return None
        return snapshot_path[: match.start()]

    def _snapshots_under(self, root: str) -> list:
        """Snapshot dirs under ``root`` matching output_dir_format, ordered
        by iteration index."""
        import glob
        import re

        parts = self._format_parts()
        if parts is None:
            path = os.path.join(root, self._format)
            return [path] if os.path.isdir(path) else []
        prefix, suffix = parts
        pattern = re.compile(re.escape(prefix) + r"(\d+)" + re.escape(suffix) + r"$")
        found = []
        for dirpath in glob.glob(os.path.join(root, prefix + "*" + suffix)):
            match = pattern.match(os.path.relpath(dirpath, root))
            if match and os.path.isdir(dirpath):
                found.append((int(match.group(1)), dirpath))
        found.sort()
        return [p for _, p in found]

    # -- cycle ---------------------------------------------------------------

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        # (idx + 1) cadence: first save after save_every iterations, not a
        # useless step-0 snapshot (reference checkpoint.py:116-120 semantics).
        if (self._iter_idx + 1) % self._save_every == 0:
            self.save()
        self._iter_idx += 1

    def reset(self, attrs: Optional[Attributes] = None) -> None:
        if self._save_on_cycle_end:
            self.save()

    def destroy(self, attrs: Optional[Attributes] = None) -> None:
        default_io().wait()  # make the last snapshot durable
        super().destroy(attrs)

    # -- save ----------------------------------------------------------------

    def save(self) -> str:
        """Snapshot every registered capsule's state (reference
        ``checkpoint.py:83-132``); async, multi-host coordinated."""
        path = os.path.join(
            self._runtime.project_dir, self._format.format(self._iter_idx)
        )
        items = {}
        for capsule in self._runtime.checkpointables:
            state = capsule.state_dict()
            if state:
                items[capsule._ckpt_key] = state
        if not items:
            self._logger.warning("nothing to checkpoint — no stateful state yet")
            return path
        default_io().save(path, items, force=True)
        self._logger.info("checkpoint -> %s", path)
        # Retention across restarts comes from the setup() disk scan, not
        # from persisting this list.
        self._saved_dirs.append(path)
        self._prune()
        return path

    def _prune(self) -> None:
        if self._keep_last is None or len(self._saved_dirs) <= self._keep_last:
            return
        if self._runtime is not None and not self._runtime.is_main_process:
            # host 0 owns retention; others just forget the path
            self._saved_dirs = self._saved_dirs[-self._keep_last :]
            return
        default_io().wait()  # never delete around an in-flight save
        while len(self._saved_dirs) > self._keep_last:
            victim = self._saved_dirs.pop(0)
            shutil.rmtree(victim, ignore_errors=True)

    # -- state ---------------------------------------------------------------

    def state_dict(self) -> Attributes:
        # +1: a restored run should not instantly re-save (reference
        # ``checkpoint.py:134-149``).
        return Attributes(iter_idx=self._iter_idx + 1)

    def load_state_dict(self, state: Attributes) -> None:
        if not state:
            return
        self._iter_idx = int(state["iter_idx"])
