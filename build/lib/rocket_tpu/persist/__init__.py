from rocket_tpu.persist.checkpoint import Checkpointer
from rocket_tpu.persist.orbax_io import CheckpointIO, default_io

__all__ = ["Checkpointer", "CheckpointIO", "default_io"]
