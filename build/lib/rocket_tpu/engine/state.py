"""TrainState — the explicit, immutable pytree that replaces mutable
framework objects.

In the reference, training state is scattered across mutable registries
inside the ``Accelerator`` (``_models``, ``_optimizers``, ``_schedulers``,
``_custom_objects`` — SURVEY §7.1; e.g. ``rocket/core/module.py:106``,
``optimizer.py:109``).  The TPU build makes it one functional pytree that a
jitted, donated-argument ``train_step(state, batch)`` threads through the
run — the shape XLA wants (static structure, buffer donation, no Python
mutation in the hot path).

Contents:

- ``step``        — effective optimizer-step counter (int32 scalar array).
- ``params``      — model parameters (possibly sharded via GSPMD).
- ``opt_state``   — optax optimizer state.
- ``rng``         — PRNG key threaded through stochastic layers (dropout).
- ``mutable``     — non-parameter model collections (e.g. BatchNorm
  ``batch_stats``); empty dict when unused.
- ``grad_accum``  — running gradient sum for micro-batching; ``None`` when
  ``gradient_accumulation_steps == 1`` (reference's ``accumulate()`` window,
  ``module.py:211``).
- ``micro``       — micro-step counter inside the accumulation window.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    mutable: Any = struct.field(default_factory=dict)
    grad_accum: Optional[Any] = None
    micro: Optional[jax.Array] = None

    @classmethod
    def create(
        cls,
        params: Any,
        tx: Any,
        rng: Optional[jax.Array] = None,
        mutable: Optional[Any] = None,
        gradient_accumulation_steps: int = 1,
    ) -> "TrainState":
        """Build an initial state from params + an optax transform.

        ``tx.init`` runs under ``jax.eval_shape``-compatible tracing, so this
        is safe to call inside ``jax.jit`` for sharded initialization.
        """
        opt_state = tx.init(params)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        grad_accum = None
        micro = None
        if gradient_accumulation_steps > 1:
            grad_accum = jax.tree_util.tree_map(jnp.zeros_like, params)
            micro = jnp.zeros((), dtype=jnp.int32)
        return cls(
            step=jnp.zeros((), dtype=jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=rng,
            mutable=mutable if mutable is not None else {},
            grad_accum=grad_accum,
            micro=micro,
        )


def param_count(params: Any) -> int:
    """Total number of parameters in a pytree."""
    return sum(
        int(x.size) for x in jax.tree_util.tree_leaves(params) if hasattr(x, "size")
    )


def abstract_state(
    init_fn: Callable[[], TrainState],
) -> TrainState:
    """Shape/dtype skeleton of a state without allocating it — used to derive
    shardings before real (possibly distributed) initialization."""
    return jax.eval_shape(init_fn)
