"""Mixed-precision policy — the AMP/grad-scaler replacement.

Reference: ``mixed_precision`` Launcher arg (``launcher.py:100,187``) +
``accelerator.autocast()`` (``module.py:210``) + torch grad-scaler.  On TPU,
bf16 has the same exponent range as f32, so there is no loss-scaling; a
policy is just three dtypes: params are kept in ``param_dtype``, activations
computed in ``compute_dtype``, step outputs (loss/metrics) in
``output_dtype``.  XLA fuses the casts into adjacent ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


def _cast_floating(tree: Any, dtype: Any) -> Any:
    def cast(leaf: Any) -> Any:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    @classmethod
    def from_string(cls, name: str) -> "Policy":
        """Accepts the reference's ``mixed_precision`` vocabulary: ``'no'``
        (all f32), ``'bf16'`` (f32 params, bf16 compute — the autocast
        analogue), ``'bf16_full'`` (bf16 params too, halves HBM), ``'fp16'``
        is accepted as an alias of ``'bf16'`` (TPU has no fp16 path)."""
        name = (name or "no").lower()
        if name in ("no", "none", "f32", "fp32", "float32"):
            return cls()
        if name in ("bf16", "bfloat16", "fp16", "float16"):
            return cls(compute_dtype=jnp.bfloat16)
        if name in ("bf16_full", "pure_bf16"):
            return cls(
                param_dtype=jnp.bfloat16,
                compute_dtype=jnp.bfloat16,
            )
        raise ValueError(f"unknown mixed_precision {name!r}")

    def cast_to_compute(self, tree: Any) -> Any:
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree: Any) -> Any:
        return _cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        return _cast_floating(tree, self.output_dtype)
