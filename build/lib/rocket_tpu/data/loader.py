"""DataLoader — deterministic, sharded, device-prefetching batch pipeline.

Replaces the reference's ``torch.utils.data.DataLoader`` +
``accelerator.prepare(dataloader)`` pair (``rocket/core/dataset.py:100-180``)
with a TPU-first design:

- **Static shapes**: every batch has the same global shape.  The last partial
  batch is padded by wrap-around and marked in a ``_valid`` boolean mask
  instead of being shape-shifted — a shape change would force an XLA
  recompile of the whole train step.  The mask is the explicit form of
  accelerate's ``gather_for_metrics`` duplicate-dedup (``meter.py:93``,
  SURVEY §7.4).
- **Per-host sharding**: each process materializes only its slice of the
  global batch; :func:`jax.make_array_from_process_local_data` assembles the
  logical global array laid out over the mesh's data axes (replaces
  accelerate's per-rank dataloader sharding, ``dataset.py:175-180``).
- **Deterministic order + mid-epoch resume**: the epoch permutation is a pure
  function of ``(seed, epoch)``; resuming at batch *k* replays the
  permutation and skips — the equivalent of ``skip_first_batches``
  (``dataset.py:205-210``) without touching data state.
- **Prefetch double-buffering**: a background thread stages collated host
  batches; device transfer is issued ahead so H2D rides under compute
  (replaces torch pin-memory workers, SURVEY §2.1).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

import jax
import numpy as np

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.utils.placement import collate as default_collate


class DataLoader:
    """Parameters
    ----------
    source:
        Map-style source (``__len__`` + ``__getitem__``).
    batch_size:
        **Global** batch size (across all hosts/devices).
    shuffle / seed:
        Seeded epoch permutation; order is reproducible across restarts.
    drop_last:
        Drop the trailing partial batch instead of pad+mask.
    collate_fn:
        Sample-list -> batch pytree (default stacks arrays, passes the rest
        through as lists — reference ``torch_collate`` semantics).
    sharding:
        ``jax.sharding.NamedSharding`` for the batch's leading dim (from
        ``runtime.batch_sharding()``). ``None`` keeps batches on host.
    prefetch:
        Number of batches staged ahead (0 disables the background thread).
    """

    def __init__(
        self,
        source: Any,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        sharding: Optional[Any] = None,
        prefetch: int = 2,
        mask_key: str = "_valid",
    ) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.source = source
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        self.sharding = sharding
        self.prefetch = int(prefetch)
        self.mask_key = mask_key
        self.epoch = 0

        procs = jax.process_count()
        if self.batch_size % procs != 0:
            raise ValueError(
                f"global batch_size {batch_size} must divide evenly over "
                f"{procs} processes"
            )
        self.local_batch_size = self.batch_size // procs

    # -- length -------------------------------------------------------------

    def __len__(self) -> int:
        n = len(self.source)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    # -- index plan ---------------------------------------------------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.source)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch))
            return rng.permutation(n)
        return np.arange(n)

    def _batch_indices(self, epoch: int) -> Iterator[tuple]:
        """Yield ``(global_indices, valid_mask)`` per batch, already padded
        to the static global batch size."""
        order = self._epoch_order(epoch)
        n = len(order)
        num_batches = len(self)
        for b in range(num_batches):
            lo = b * self.batch_size
            hi = lo + self.batch_size
            idx = order[lo:hi]
            valid = np.ones(len(idx), dtype=bool)
            if len(idx) < self.batch_size:  # wrap-around pad + mask
                pad = self.batch_size - len(idx)
                idx = np.concatenate([idx, order[:pad]])
                valid = np.concatenate([valid, np.zeros(pad, dtype=bool)])
            yield idx, valid

    # -- batch materialization ---------------------------------------------

    def _host_batch(self, idx: np.ndarray, valid: np.ndarray) -> Any:
        """Collate THIS process's slice of the global batch."""
        p = jax.process_index()
        lo = p * self.local_batch_size
        hi = lo + self.local_batch_size
        samples = [self.source[int(i)] for i in idx[lo:hi]]
        batch = self.collate_fn(samples)
        if not isinstance(batch, (dict, Attributes)):
            batch = Attributes(data=batch)
        batch = Attributes(batch)
        batch[self.mask_key] = valid[lo:hi]
        return batch

    def _to_device(self, host_batch: Any) -> Any:
        if self.sharding is None:
            return host_batch

        def place(leaf: Any) -> Any:
            leaf = np.asarray(leaf)
            sh = self.sharding
            if leaf.ndim < 1:
                return jax.device_put(leaf)
            if leaf.ndim != len(sh.spec):
                # spec was built for a particular rank; re-rank it: leading
                # dim sharded over data axes, the rest replicated.
                from rocket_tpu.parallel.sharding import batch_sharding

                sh = batch_sharding(sh.mesh, ndim=leaf.ndim)
            return jax.make_array_from_process_local_data(sh, leaf)

        return jax.tree_util.tree_map(place, host_batch)

    # -- iteration ----------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self.iterate(epoch=self.epoch)

    def iterate(self, epoch: int = 0, skip_batches: int = 0) -> Iterator[Any]:
        """Iterate one epoch; ``skip_batches`` replays the permutation and
        fast-forwards (mid-epoch resume, reference ``skip_first_batches``,
        ``dataset.py:205-210``)."""
        plan = self._batch_indices(epoch)
        for _ in range(skip_batches):
            next(plan, None)
        if self.prefetch <= 0:
            for idx, valid in plan:
                yield self._to_device(self._host_batch(idx, valid))
            return
        yield from self._prefetch_iter(plan)

    def _prefetch_iter(self, plan: Iterator[tuple]) -> Iterator[Any]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        error: list = []

        def producer() -> None:
            try:
                for idx, valid in plan:
                    q.put(self._host_batch(idx, valid))
            except BaseException as exc:  # propagate into consumer
                error.append(exc)
            finally:
                q.put(sentinel)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        staged = None
        while True:
            item = q.get()
            if item is sentinel:
                if error:
                    raise error[0]
                break
            device_batch = self._to_device(item)
            if staged is not None:
                yield staged
            staged = device_batch
        if staged is not None:
            yield staged
