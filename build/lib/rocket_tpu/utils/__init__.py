from rocket_tpu.utils.collections import (
    apply_to_collection,
    is_collection,
    tree_map,
)
from rocket_tpu.utils.placement import (
    collate,
    register_collate_hook,
    register_default_move_hook,
    register_move_hook,
    to_device,
)

__all__ = [
    "apply_to_collection",
    "is_collection",
    "tree_map",
    "collate",
    "to_device",
    "register_collate_hook",
    "register_move_hook",
    "register_default_move_hook",
]
