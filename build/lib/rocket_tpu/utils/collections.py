"""Structure-preserving maps over nested containers.

Capability parity: reference ``rocket/utils/collections.py`` (``is_collection``,
``apply_to_mapping:26``, ``apply_to_sequence:45``, ``apply_to_collection:61``).
In the TPU build these are thin, registry-aware wrappers over
``jax.tree_util`` — pytrees are the idiomatic generalization of the
reference's hand-rolled container walk, and they preserve custom node types
(e.g. :class:`~rocket_tpu.core.attributes.Attributes`) the same way the
reference's copy+update dance preserved mapping subclasses.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence, Type

import jax


def is_collection(value: Any) -> bool:
    """True for mappings and non-string sequences
    (reference ``collections.py:7-24``)."""
    if isinstance(value, (str, bytes)):
        return False
    return isinstance(value, (Mapping, Sequence))


def apply_to_collection(
    data: Any,
    dtype: Type | tuple,
    func: Callable[..., Any],
    *args: Any,
    **kwargs: Any,
) -> Any:
    """Apply ``func`` to every leaf of ``data`` that is an instance of
    ``dtype``; other leaves pass through unchanged.  Container structure
    (including dict subclasses) is preserved.

    Reference ``collections.py:61-71`` — here delegated to ``jax.tree_util``
    with ``is_leaf`` set so that matching instances are treated as leaves even
    if they are themselves containers.
    """

    def mapper(leaf: Any) -> Any:
        if isinstance(leaf, dtype):
            return func(leaf, *args, **kwargs)
        return leaf

    return jax.tree_util.tree_map(
        mapper, data, is_leaf=lambda x: isinstance(x, dtype)
    )


def tree_map(func: Callable[..., Any], tree: Any, *rest: Any, **kwargs: Any) -> Any:
    """Alias for ``jax.tree_util.tree_map`` (exported for symmetry)."""
    return jax.tree_util.tree_map(func, tree, *rest, **kwargs)
