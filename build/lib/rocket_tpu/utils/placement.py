"""Collate and device-placement with user-registerable type hooks.

Capability parity: reference ``rocket/utils/torch.py`` — ``torch_collate``
(:30, hook table ``COLLATE_MAPPINGS``: only tensors stack, everything else
passes through as lists) and ``torch_move``/``move`` (:59-95, hook table
``MOVE_MAPPINGS`` + ``register_move_hook``/``register_default_move_hook``).

TPU-first differences: "move to device" becomes ``jax.device_put`` with an
optional :class:`jax.sharding.Sharding`, so the same call that placed a batch
on one GPU in the reference now lays a **global** batch out across a device
mesh.  Numpy is the host-side interchange format; torch tensors (cpu) are
converted transparently when torch is importable so reference-style torch
Datasets keep working.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Type

import jax
import numpy as np

# -- hook tables -------------------------------------------------------------

CollateHook = Callable[[Sequence[Any]], Any]
MoveHook = Callable[[Any, Any], Any]

COLLATE_HOOKS: Dict[Type, CollateHook] = {}
MOVE_HOOKS: Dict[Type, MoveHook] = {}
_DEFAULT_MOVE_HOOK: Optional[MoveHook] = None


def register_collate_hook(dtype: Type, func: CollateHook) -> None:
    """Register a stacker for a leaf type (reference ``torch.py:17-26``)."""
    COLLATE_HOOKS[dtype] = func


def register_move_hook(dtype: Type, func: MoveHook) -> None:
    """Register a device-placement hook for a leaf type
    (reference ``torch.py:88-92``)."""
    MOVE_HOOKS[dtype] = func


def register_default_move_hook(func: MoveHook) -> None:
    """Fallback hook for unmatched leaf types (reference ``torch.py:94-95``)."""
    global _DEFAULT_MOVE_HOOK
    _DEFAULT_MOVE_HOOK = func


def _to_numpy(value: Any) -> Any:
    """Best-effort conversion of a leaf to a numpy array; None if not array-like."""
    if isinstance(value, np.ndarray):
        return value
    if isinstance(value, (np.generic, int, float, bool)):
        return np.asarray(value)
    if isinstance(value, jax.Array):
        return np.asarray(value)
    # torch cpu tensors from reference-style datasets
    tt = _torch_tensor_type()
    if tt is not None and isinstance(value, tt):
        return value.detach().cpu().numpy()
    return None


def _torch_tensor_type():
    try:
        import torch

        return torch.Tensor
    except Exception:  # torch not importable — numpy-only mode
        return None


# -- collate -----------------------------------------------------------------

def collate(samples: Sequence[Any]) -> Any:
    """Stack a list of samples (pytrees) into a batch pytree.

    Array-like leaves (numpy / jax / torch-cpu / python scalars) are stacked
    along a new leading axis into numpy arrays; any other leaf type passes
    through as a plain list — the reference's "only tensors collate" contract
    (``rocket/utils/torch.py:17-34``).
    """
    if not samples:
        return samples
    first = samples[0]
    for dtype, hook in COLLATE_HOOKS.items():
        if isinstance(first, dtype):
            return hook(samples)
    if isinstance(first, dict):
        out = {key: collate([s[key] for s in samples]) for key in first}
        return type(first)(out)
    if isinstance(first, (list, tuple)) and not isinstance(first, str):
        transposed = [collate(list(group)) for group in zip(*samples)]
        if isinstance(first, tuple):
            return tuple(transposed)
        return transposed
    arr = _to_numpy(first)
    if arr is not None:
        return np.stack([_to_numpy(s) for s in samples])
    return list(samples)


# -- device placement --------------------------------------------------------

def _adapt_sharding(sharding: Any, ndim: int) -> Any:
    """Fit a NamedSharding's PartitionSpec to a leaf's rank: truncate extra
    dims, pad missing ones with None (replicated).  Lets one batch sharding
    (leading dim over the data axes) serve mixed-rank leaves — images,
    labels, masks — the way the reference's per-leaf ``.to(device)`` did."""
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(sharding, NamedSharding):
        return sharding
    spec = tuple(sharding.spec)
    if len(spec) == ndim:
        return sharding
    if len(spec) > ndim:
        spec = spec[:ndim]
    else:
        spec = spec + (None,) * (ndim - len(spec))
    return NamedSharding(sharding.mesh, PartitionSpec(*spec))


def to_device(data: Any, sharding: Any = None) -> Any:
    """Place every array leaf of ``data`` on device(s).

    ``sharding`` may be a :class:`jax.sharding.Sharding`, a device, or None
    (commit to the default device).  Structure is preserved; non-array leaves
    pass through unless a move hook matches (reference ``torch.py:59-95``).
    """

    def move_leaf(leaf: Any) -> Any:
        for dtype, hook in MOVE_HOOKS.items():
            if isinstance(leaf, dtype):
                return hook(leaf, sharding)
        arr = leaf if isinstance(leaf, (np.ndarray, jax.Array)) else _to_numpy(leaf)
        if arr is not None:
            if sharding is None:
                return jax.device_put(arr)
            return jax.device_put(arr, _adapt_sharding(sharding, arr.ndim))
        if _DEFAULT_MOVE_HOOK is not None:
            return _DEFAULT_MOVE_HOOK(leaf, sharding)
        return leaf

    return jax.tree_util.tree_map(move_leaf, data)
