from rocket_tpu.ops.attention import attend, dot_attention
from rocket_tpu.ops.flash import flash_attention
from rocket_tpu.ops.ring import ring_attention

__all__ = ["attend", "dot_attention", "flash_attention", "ring_attention"]
