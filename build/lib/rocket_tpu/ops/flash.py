"""Flash attention — blocked online-softmax Pallas TPU kernel, fwd + bwd.

The attention matrix never materializes in HBM: the kernel streams K/V
blocks through VMEM, keeping a running row-max ``m``, normalizer ``l`` and
f32 output accumulator in VMEM scratch that persists across the innermost
(sequential) grid dimension — O(S) memory instead of O(S²), MXU-tiled
matmuls with f32 accumulation.  The backward pass is the standard two-kernel
split (dq; dk+dv) over the saved logsumexp, wired through ``jax.custom_vjp``
(pallas_call has no autodiff of its own).

Layout: kernels run on ``[B, H, S, D]``; the public wrapper takes the
model-side ``[B, S, H, D]`` and transposes (XLA folds the transpose into
neighboring ops).  Causal skipping: fully-masked K blocks are skipped with
``pl.when`` (half the work for causal attention); the diagonal block masks
with a large negative constant (never ``-inf`` — ``exp(-inf - -inf)`` is
NaN).

Falls back transparently (see :func:`flash_attention`) when shapes don't
meet the tiling constraints or a CPU backend is active (interpret mode is
used on CPU so the same tests cover the kernel logic everywhere).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: K blocks entirely above the diagonal contribute nothing.
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, MASK_VALUE)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [bq, bk]
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = correction * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l_final = l_ref[:, :1]
        safe_l = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # lse broadcast across the 128-lane dim (TPU tiling needs the last
        # two block dims (bq, 128) — same layout as jax's reference kernel).
        lse_ref[0, 0] = jnp.broadcast_to(
            m_ref[:, :1] + jnp.log(safe_l), lse_ref.shape[2:]
        )


def _flash_fwd(q, k, v, causal: bool, scale: float,
               block_q: int, block_k: int):
    B, H, S, D = q.shape
    nq, nk = S // block_q, S // block_k
    grid = (B, H, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale: float, causal: bool,
               block_q: int, block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]  # [bq, 1] (lane-broadcast layout)
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - delta)
        acc_ref[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        # dV += Pᵀ dO
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        # dK += dSᵀ Q * scale
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool, scale: float,
               block_q: int, block_k: int):
    B, H, S, D = q.shape
    nq, nk = S // block_q, S // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    common_in = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, H, nq, nk),
        in_specs=common_in,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    kv_in = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 128), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, 128), lambda b, h, ki, qi: (b, h, qi, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, H, nk, nq),
        in_specs=kv_in,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, g, causal, scale, block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Flash attention on ``[B, S, H, D]`` (K/V may be GQA-grouped).

    Falls back to :func:`rocket_tpu.ops.attention.dot_attention` when the
    kernel's constraints don't hold (segment_ids given, S not a multiple of
    the block sizes, tiny head_dim).
    """
    from rocket_tpu.ops.attention import _repeat_kv, dot_attention

    B, S, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if (
        segment_ids is not None
        or S % block_q != 0
        or S % block_k != 0
        or D % 8 != 0
    ):
        return dot_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, scale=scale
        )
    k, v = _repeat_kv(k, v, H)
    # [B, S, H, D] -> [B, H, S, D] for the kernel
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    o = _flash(qt, kt, vt, causal, scale, block_q, block_k)
    return o.swapaxes(1, 2)
