from rocket_tpu.launch.launcher import Launcher
from rocket_tpu.launch.loop import Looper

__all__ = ["Launcher", "Looper"]
