"""Attributes — the inter-capsule blackboard.

A ``dict`` subclass with attribute-style access where *missing keys read as
``None``* instead of raising.  This is the single data bus through which
capsules communicate: well-known keys are ``attrs.batch`` (the current batch /
model outputs), ``attrs.looper`` (iteration-loop protocol), ``attrs.launcher``
(run topology), ``attrs.tracker`` (buffered log records), plus arbitrary user
keys.

Capability parity: reference ``rocket/core/capsule.py:23-35`` (``Attributes =
adict``).  Re-implemented from scratch — the semantics we preserve are
(a) dot read of a missing key -> ``None``, (b) dot write/delete mutate the
mapping, (c) nested plain dicts are promoted to ``Attributes`` so chained dot
access works.
"""

from __future__ import annotations

from typing import Any

import jax


class Attributes(dict):
    """Dot-access dictionary blackboard; missing attribute reads return ``None``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        for key, value in list(self.items()):
            if isinstance(value, dict) and not isinstance(value, Attributes):
                super().__setitem__(key, Attributes(value))

    # -- attribute protocol -------------------------------------------------

    def __getattr__(self, key: str) -> Any:
        # Dunder lookups must keep normal semantics (pickling, copy, etc.).
        if key.startswith("__") and key.endswith("__"):
            raise AttributeError(key)
        return self.get(key)

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        self.pop(key, None)

    # -- item protocol ------------------------------------------------------

    def __setitem__(self, key: Any, value: Any) -> None:
        if isinstance(value, dict) and not isinstance(value, Attributes):
            value = Attributes(value)
        super().__setitem__(key, value)

    # update/setdefault/|= bypass __setitem__ in CPython — route them through
    # it so nested-dict promotion holds on every write path.
    def update(self, *args: Any, **kwargs: Any) -> None:
        for key, value in dict(*args, **kwargs).items():
            self[key] = value

    def setdefault(self, key: Any, default: Any = None) -> Any:
        if key not in self:
            self[key] = default
        return self[key]

    def __ior__(self, other: Any) -> "Attributes":
        self.update(other)
        return self

    def copy(self) -> "Attributes":
        return Attributes(self)

    def __repr__(self) -> str:  # compact, stable for tree dumps
        body = ", ".join(f"{k}={v!r}" for k, v in self.items())
        return f"Attributes({body})"


def _flatten_with_keys(attrs: Attributes):
    try:
        keys = sorted(attrs)
    except TypeError:  # mixed-type keys — fall back to insertion order
        keys = list(attrs)
    children = [(jax.tree_util.DictKey(k), attrs[k]) for k in keys]
    return children, tuple(keys)


def _unflatten(keys, children) -> Attributes:
    return Attributes(zip(keys, children))


# Registered as a pytree node (sorted keys, mirroring dict flattening) so
# Attributes-valued batches work with jax.tree_util / device_put / jit.
jax.tree_util.register_pytree_with_keys(
    Attributes, _flatten_with_keys, _unflatten
)
