"""Scheduler — learning-rate schedule.

Capability parity: reference ``rocket/core/scheduler.py:20-143`` — wraps the
user's LR scheduler and steps it once per iteration when grads are enabled
(``scheduler.py:112-113``).

TPU-first split: optax schedules are pure functions of the step counter, so
there is nothing to "step" at runtime — the parent
:class:`~rocket_tpu.core.module.Module` passes this capsule's ``schedule``
into the sibling ``Optimizer``'s ``build_tx`` (the schedule becomes the
optax learning rate, evaluated at ``state.step`` inside the jitted update).
The capsule exists for tree-shape parity, config introspection, and to own
the schedule definition in the pipeline description.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule


class Scheduler(Capsule):
    """Parameters
    ----------
    schedule:
        An ``optax.Schedule`` — any ``step -> learning_rate`` callable (e.g.
        ``optax.cosine_decay_schedule(...)``, ``optax.warmup_cosine_decay_
        schedule(...)``).
    """

    def __init__(
        self,
        schedule: Callable[[int], Any],
        statefull: bool = False,
        priority: int = 1000,
        logger: Optional[Any] = None,
    ) -> None:
        super().__init__(statefull=statefull, priority=priority, logger=logger)
        if not callable(schedule):
            raise TypeError("Scheduler expects an optax schedule (callable)")
        self._schedule = schedule

    @property
    def schedule(self) -> Callable[[int], Any]:
        return self._schedule

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        """No runtime work: the schedule is evaluated inside the jitted step
        (reference stepped eagerly at ``scheduler.py:112-113``)."""
