"""Core capsule protocol (reference ``rocket/core/__init__.py:1-12``)."""

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule
from rocket_tpu.core.dispatcher import Dispatcher
from rocket_tpu.core.events import Events
from rocket_tpu.core.loss import Loss
from rocket_tpu.core.module import Module
from rocket_tpu.core.optimizer import Optimizer
from rocket_tpu.core.scheduler import Scheduler

__all__ = [
    "Attributes",
    "Capsule",
    "Dispatcher",
    "Events",
    "Loss",
    "Module",
    "Optimizer",
    "Scheduler",
]
