"""Capsule — the base lifecycle component.

A capsule is a unit of pipeline behavior driven by lifecycle events
(:class:`~rocket_tpu.core.events.Events`).  Capsules never call each other;
they read/write the :class:`~rocket_tpu.core.attributes.Attributes` blackboard
and are ordered inside a :class:`~rocket_tpu.core.dispatcher.Dispatcher` by
integer ``priority`` (higher runs first).

Capability parity: reference ``rocket/core/capsule.py:71-440``.  Differences
by design (TPU-first):

- Instead of an ``Accelerator``, every capsule is bound to a
  :class:`rocket_tpu.runtime.Runtime` (mesh + process topology + checkpoint /
  tracker registries) via :meth:`bind` — the analogue of reference
  ``Capsule.accelerate`` (``capsule.py:256-273``).
- ``state_dict``/``load_state_dict`` exchange **pytrees** (plain dicts of
  arrays/scalars), so capsule state participates directly in Orbax
  checkpoints instead of accelerate's pickled ``_custom_objects``
  (``capsule.py:331-416``).
- Statefulness is opt-in via ``statefull=True`` (reference spelling kept for
  user familiarity, ``capsule.py:104-113``); stateful capsules register with
  the runtime checkpoint registry in :meth:`setup` (``capsule.py:135-139``)
  and deregister LIFO in :meth:`destroy` (``capsule.py:165-174``) — the
  Dispatcher's reverse-order destroy upholds the LIFO invariant.
"""

from __future__ import annotations

from typing import Any, Optional

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.events import Events
from rocket_tpu.utils.logging import RankAwareLogger, get_logger


class Capsule:
    """Base lifecycle component.

    Parameters
    ----------
    statefull:
        If ``True``, the capsule's :meth:`state_dict` is included in
        checkpoints (registered with the runtime at setup).
    priority:
        Dispatch order inside a Dispatcher; higher value runs earlier.
        Default 1000.
    logger:
        Optional custom logger; defaults to a rank-aware logger named after
        the concrete class.
    """

    def __init__(
        self,
        statefull: bool = False,
        priority: int = 1000,
        logger: Optional[RankAwareLogger] = None,
    ) -> None:
        self._runtime = None
        self._statefull = statefull
        self._priority = priority
        self._logger = logger or get_logger(type(self).__name__)
        self._registered = False
        self._ckpt_key: Optional[str] = None

    # -- lifecycle ----------------------------------------------------------

    def setup(self, attrs: Optional[Attributes] = None) -> None:
        """One-time initialization. Registers stateful capsules for
        checkpointing (reference ``capsule.py:116-141``)."""
        self.check_runtime()
        if self._statefull and not self._registered:
            # Idempotent: the same capsule mounted in two pipeline branches
            # (train + eval looper) is set up twice but registers once —
            # the analogue of the reference's dedupe scans
            # (``module.py:87-99``, ``dataset.py:158-171``).
            self._ckpt_key = self._runtime.register_for_checkpointing(self)
            self._registered = True
        self._logger.debug("%s.setup done", type(self).__name__)

    def destroy(self, attrs: Optional[Attributes] = None) -> None:
        """One-time teardown. Deregisters from the checkpoint registry
        (reference pops LIFO, ``capsule.py:165-174``; here removal is by
        identity — see ``Runtime.deregister_checkpointable``)."""
        if self._statefull and self._registered:
            self.check_runtime()
            self._runtime.deregister_checkpointable(self)
            self._registered = False
        self.clear()

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        """Per-iteration work event (reference ``capsule.py:178-195``)."""

    def set(self, attrs: Optional[Attributes] = None) -> None:
        """Cycle-start event (reference ``capsule.py:197-214``)."""

    def reset(self, attrs: Optional[Attributes] = None) -> None:
        """Cycle-end event (reference ``capsule.py:216-233``)."""

    # -- dispatch -----------------------------------------------------------

    def dispatch(self, event: Events, attrs: Optional[Attributes] = None) -> None:
        """Route an event to the matching handler
        (reference ``capsule.py:235-254``)."""
        handler = getattr(self, Events(event).value, None)
        if handler is None:
            raise ValueError(f"{type(self).__name__}: unknown event {event!r}")
        handler(attrs)

    # -- runtime binding ----------------------------------------------------

    def bind(self, runtime: Any) -> None:
        """Inject the runtime (mesh/topology/registries) top-down.

        Analogue of reference ``Capsule.accelerate`` (``capsule.py:256-273``).
        Re-binding with a different runtime replaces the old one.
        """
        self._runtime = runtime

    def clear(self) -> None:
        """Drop the runtime binding (reference ``capsule.py:275-306``)."""
        self._runtime = None

    def check_runtime(self) -> None:
        """Raise unless a runtime has been bound
        (reference ``capsule.py:308-329``)."""
        if self._runtime is None:
            raise RuntimeError(
                f"{type(self).__name__} has no runtime bound. Capsules must "
                f"be part of a Launcher tree (which binds the runtime during "
                f"setup), or call .bind(runtime) explicitly."
            )

    @property
    def runtime(self) -> Any:
        return self._runtime

    @property
    def priority(self) -> int:
        return self._priority

    @property
    def statefull(self) -> bool:
        return self._statefull

    # -- state --------------------------------------------------------------

    def state_dict(self) -> Attributes:
        """Pytree of checkpointable state (reference ``capsule.py:331-375``)."""
        return Attributes()

    def load_state_dict(self, state: Attributes) -> None:
        """Restore from :meth:`state_dict` output
        (reference ``capsule.py:377-416``)."""
        if state:
            raise RuntimeError(
                f"{type(self).__name__}.load_state_dict got non-empty state "
                f"but defines none."
            )

    # -- introspection ------------------------------------------------------

    def __repr__(self) -> str:
        """Config dump: class name + non-private scalar config
        (reference ``capsule.py:419-440``)."""
        hidden = {"_runtime", "_logger", "_registered", "_capsules"}
        fields = []
        for key, value in vars(self).items():
            if key in hidden:
                continue
            text = repr(value)
            if len(text) > 120:
                text = f"<{type(value).__name__}>"
            fields.append(f"{key.lstrip('_')}={text}")
        return f"{type(self).__name__}({', '.join(fields)})"
