"""Lifecycle event vocabulary for capsules.

Capability parity: reference ``rocket/core/capsule.py:38-68``.

The five events partition a run:

- ``SETUP``   — once, before anything else: allocate resources, build jitted
  steps, register stateful components with the runtime checkpoint registry.
- ``SET``     — start of every cycle (epoch / eval pass): reset iterators,
  open tracker buffers, publish per-cycle protocol keys on the blackboard.
- ``LAUNCH``  — the work event, fired once per iteration (or once per cycle
  for composite loop owners).
- ``RESET``   — end of every cycle: flush buffers, drop per-cycle keys.
- ``DESTROY`` — once, after the run: release resources in reverse order.
"""

from __future__ import annotations

import enum


class Events(str, enum.Enum):
    SETUP = "setup"
    SET = "set"
    LAUNCH = "launch"
    RESET = "reset"
    DESTROY = "destroy"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value
