"""Parameter EMA as an optax transform (engine layer).

Lives in ``engine`` so ``engine.step`` can read the EMA without an upward
dependency on ``core`` (core.optimizer re-exports the public names).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class EmaState(NamedTuple):
    """Optax state slot holding the parameter EMA tree."""

    ema: Any


def params_ema(decay: float) -> optax.GradientTransformation:
    """Maintain an exponential moving average of the PARAMETERS inside the
    optimizer state (``ema = decay * ema + (1-decay) * new_params``).

    Chain it LAST: it assumes the incoming ``updates`` are the final
    deltas, i.e. the new params are ``optax.apply_updates(params,
    updates)``.  The EMA tree lives in ``opt_state`` so it shards,
    donates, and checkpoints with the rest of the train state for free;
    read it back with :func:`find_params_ema` (or ``Module.ema_params``).
    """

    def init(params):
        # Real copies, not aliases: jnp.asarray on a jax.Array is a no-op,
        # and an EMA that shares buffers with state.params breaks the
        # donated train step on TPU ("attempt to donate the same buffer
        # twice") — same reason reseed_ema copies.
        return EmaState(ema=jax.tree_util.tree_map(jnp.copy, params))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("params_ema requires params in update()")
        new_params = optax.apply_updates(params, updates)
        new_ema = jax.tree_util.tree_map(
            lambda e, p: decay * e + (1.0 - decay) * p,
            state.ema,
            new_params,
        )
        return updates, EmaState(ema=new_ema)

    return optax.GradientTransformation(init, update)


def _is_ema(leaf: Any) -> bool:
    return isinstance(leaf, EmaState)


def find_params_ema(opt_state: Any) -> Optional[Any]:
    """Extract the EMA parameter tree from a (nested) optax state, or None
    when no :func:`params_ema` transform is in the chain."""
    found = [
        leaf.ema
        for leaf in jax.tree_util.tree_leaves(opt_state, is_leaf=_is_ema)
        if _is_ema(leaf)
    ]
    return found[0] if found else None


def reseed_ema(opt_state: Any, params: Any) -> Any:
    """Replace every EMA slot with a fresh snapshot of ``params`` — used
    after a weights-only restore, where the optimizer state keeps its
    fresh init but the params jump to the restored values (evaluating the
    stale random-init EMA would be silently wrong)."""

    def replace(leaf):
        if _is_ema(leaf):
            # Real copies, not aliases: the donated train step would
            # otherwise receive the same buffer as params AND ema
            # ("attempt to donate the same buffer twice").
            return EmaState(
                ema=jax.tree_util.tree_map(jnp.copy, params)
            )
        return leaf

    return jax.tree_util.tree_map(replace, opt_state, is_leaf=_is_ema)
