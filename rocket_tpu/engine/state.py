"""TrainState — the explicit, immutable pytree that replaces mutable
framework objects.

In the reference, training state is scattered across mutable registries
inside the ``Accelerator`` (``_models``, ``_optimizers``, ``_schedulers``,
``_custom_objects`` — SURVEY §7.1; e.g. ``rocket/core/module.py:106``,
``optimizer.py:109``).  The TPU build makes it one functional pytree that a
jitted, donated-argument ``train_step(state, batch)`` threads through the
run — the shape XLA wants (static structure, buffer donation, no Python
mutation in the hot path).

Contents:

- ``step``        — effective optimizer-step counter (int32 scalar array).
- ``params``      — model parameters (possibly sharded via GSPMD).
- ``opt_state``   — optax optimizer state.
- ``rng``         — PRNG key threaded through stochastic layers (dropout).
- ``mutable``     — non-parameter model collections (e.g. BatchNorm
  ``batch_stats``); empty dict when unused.
- ``grad_accum``  — running gradient sum for micro-batching; ``None`` when
  ``gradient_accumulation_steps == 1`` (reference's ``accumulate()`` window,
  ``module.py:211``).
- ``micro``       — micro-step counter inside the accumulation window.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    mutable: Any = struct.field(default_factory=dict)
    grad_accum: Optional[Any] = None
    micro: Optional[jax.Array] = None

    @classmethod
    def create(
        cls,
        params: Any,
        tx: Any,
        rng: Optional[jax.Array] = None,
        mutable: Optional[Any] = None,
        gradient_accumulation_steps: int = 1,
    ) -> "TrainState":
        """Build an initial state from params + an optax transform.

        ``tx.init`` runs under ``jax.eval_shape``-compatible tracing, so this
        is safe to call inside ``jax.jit`` for sharded initialization.
        """
        opt_state = tx.init(params)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        grad_accum = None
        micro = None
        if gradient_accumulation_steps > 1:
            grad_accum = jax.tree_util.tree_map(jnp.zeros_like, params)
            micro = jnp.zeros((), dtype=jnp.int32)
        return cls(
            step=jnp.zeros((), dtype=jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=rng,
            mutable=mutable if mutable is not None else {},
            grad_accum=grad_accum,
            micro=micro,
        )


def param_count(params: Any) -> int:
    """Total number of parameters in a pytree."""
    return sum(
        int(x.size) for x in jax.tree_util.tree_leaves(params) if hasattr(x, "size")
    )


def abstract_state(
    init_fn: Callable[[], TrainState],
) -> TrainState:
    """Shape/dtype skeleton of a state without allocating it — used to derive
    shardings before real (possibly distributed) initialization."""
    return jax.eval_shape(init_fn)


def _leaf_device_bytes(leaf: Any, spec: Any, mesh: Any) -> int:
    """Per-device bytes of one leaf under a PartitionSpec: each dim is
    divided (ceil) by the product of its mesh-axis sizes."""
    import math

    shape = list(getattr(leaf, "shape", ()))
    itemsize = jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize
    axes = dict(mesh.shape)
    entries = tuple(spec) if spec is not None else ()
    for i, entry in enumerate(entries[: len(shape)]):
        names = (
            () if entry is None
            else (entry,) if isinstance(entry, str) else tuple(entry)
        )
        divisor = int(math.prod([axes.get(str(n), 1) for n in names] or [1]))
        shape[i] = -(-shape[i] // divisor)  # ceil
    return int(math.prod(shape or [1])) * itemsize


def memory_plan(
    abstract: TrainState,
    state_specs: TrainState,
    mesh: Any,
    zero_offload: bool = False,
) -> dict:
    """Per-device byte accounting of a TrainState under a spec tree.

    Returns ``{'param_bytes', 'opt_bytes', 'other_bytes', 'total_bytes',
    'host_opt_bytes'}`` — what the sharding plan says each device holds at
    steady state (arguments only; activations/temps are the compiler's
    side).  This is the number the bench ladder reports and the ZeRO guard
    asserts on.  ``state_specs`` already encodes the ZeRO stage: at stage
    2 the grad-accum buffers, and at stage 3 the params themselves, carry
    data-composed specs, so the per-stage memory formula (see the stage
    decision table in ``docs/performance.md``) falls out of the same spec
    arithmetic with no stage special-casing here.

    ``zero_offload=True`` moves the optimizer-state bytes to the host
    tier: ``opt_bytes`` drops out of the device ``total_bytes`` and is
    reported as ``host_opt_bytes`` instead (each host holds its shard-
    owners' opt state in RAM; the double-buffered prefetch transiently
    re-materializes one step's worth on device during the update).
    """
    from jax.sharding import PartitionSpec

    is_spec = lambda x: isinstance(x, PartitionSpec)

    def section_bytes(tree: Any, specs: Any) -> int:
        leaves = jax.tree_util.tree_leaves(tree)
        spec_leaves = jax.tree_util.tree_leaves(specs, is_leaf=is_spec)
        return sum(
            _leaf_device_bytes(leaf, spec, mesh)
            for leaf, spec in zip(leaves, spec_leaves)
        )

    param_bytes = section_bytes(abstract.params, state_specs.params)
    opt_bytes = section_bytes(abstract.opt_state, state_specs.opt_state)
    total_bytes = section_bytes(abstract, state_specs)
    other_bytes = total_bytes - param_bytes - opt_bytes
    host_opt_bytes = 0
    if zero_offload:
        host_opt_bytes = opt_bytes
        opt_bytes = 0
        total_bytes = param_bytes + other_bytes
    return {
        "param_bytes": param_bytes,
        "opt_bytes": opt_bytes,
        "other_bytes": other_bytes,
        "total_bytes": total_bytes,
        "host_opt_bytes": host_opt_bytes,
    }
