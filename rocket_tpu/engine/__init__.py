from rocket_tpu.engine.adapter import FlaxModel, ModelAdapter, state_shardings
from rocket_tpu.engine.muon import hidden_matrices, muon, orthogonalize
from rocket_tpu.engine.precision import Policy
from rocket_tpu.engine.state import TrainState, param_count
from rocket_tpu.engine.step import (
    Objective,
    build_eval_step,
    build_loss_fn,
    build_train_step,
)

__all__ = [
    "FlaxModel",
    "ModelAdapter",
    "hidden_matrices",
    "muon",
    "orthogonalize",
    "Objective",
    "Policy",
    "TrainState",
    "build_eval_step",
    "build_loss_fn",
    "build_train_step",
    "param_count",
    "state_shardings",
]
