from rocket_tpu.engine.precision import Policy

__all__ = ["Policy"]
