"""Host-RAM offload of ZeRO shard-owner optimizer state (engine tier).

ZeRO stages 1-3 already cut the per-chip optimizer state to ``O/N``; with
``zero_offload=True`` even that shard leaves HBM between steps.  The
discipline is the ``data/loader.py`` ``device_prefetch`` one applied to
opt state: a background thread runs the D2H writeback of step *k*'s
optimizer state and the H2D prefetch for step *k+1* while the main thread
dispatches step *k+1*'s forward/backward, so on the happy path the
transfer hides entirely behind compute and the ``offload_wait`` goodput
bucket stays near zero.

The Module drives it at each sync boundary::

    state = state.replace(opt_state=offloader.fetch(state.opt_state))
    state, logs = sync_step(state, batch)
    offloader.stash(state.opt_state)

``stash`` hands the fresh (device) opt state to the worker thread and
returns immediately; ``fetch`` joins the round trip — booking any wait
into the goodput ledger — and returns the device copy placed under the
plan's opt shardings.  Ordering makes donation safe even off-CPU: fetch
joins the previous round trip (D2H complete) before the next step can
donate the buffers the stash was reading.

The round trip is a pure memcpy pair (``jax.device_get`` →
``jax.device_put``): bitwise exact, and neither call is a ``jax.jit``
site, so the offload path adds zero trace-cache entries per step.

``synchronous=True`` is the pessimal baseline the bench compares against:
the same round trip, run inline at ``fetch`` time, fully serialized with
compute.  The measured gap between the two walls is the overlap win.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

import jax

__all__ = ["ZeroOffloader"]


class ZeroOffloader:
    """Double-buffered host-RAM round trip for sharded optimizer state.

    Parameters
    ----------
    opt_shardings:
        Tree of :class:`jax.sharding.NamedSharding` matching the opt-state
        tree (``ShardingPlan.opt_shardings`` — the ZeRO shard domain); the
        H2D prefetch lands the state back exactly where the update step
        expects it.
    synchronous:
        Run the round trip inline at ``fetch`` instead of on the worker
        thread (serialized baseline for the overlap bench).
    """

    def __init__(self, opt_shardings: Any, synchronous: bool = False) -> None:
        self._opt_shardings = opt_shardings
        self._synchronous = bool(synchronous)
        self.rounds = 0
        self.total_wait = 0.0
        self._pending: Optional[Any] = None  # synchronous-mode stash
        self._ready: "queue.Queue" = queue.Queue(maxsize=1)
        self._work: "queue.Queue" = queue.Queue(maxsize=1)
        self._in_flight = False
        self._worker: Optional[threading.Thread] = None
        if not self._synchronous:
            self._worker = threading.Thread(
                target=self._run, name="zero-offload", daemon=True
            )
            self._worker.start()

    # -- round trip -----------------------------------------------------

    def _round_trip(self, opt_state: Any) -> Any:
        from rocket_tpu.observe.trace import get_tracer

        tracer = get_tracer()
        host = jax.device_get(opt_state)
        tracer.instant("offload/d2h", round=self.rounds)
        dev = jax.device_put(host, self._opt_shardings)
        jax.block_until_ready(dev)
        tracer.instant("offload/h2d", round=self.rounds)
        return dev

    def _run(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            try:
                self._ready.put(self._round_trip(item))
            except Exception as exc:  # surfaced to the training thread
                self._ready.put(exc)

    # -- API ------------------------------------------------------------

    def stash(self, opt_state: Any) -> None:
        """Start the async D2H writeback + H2D prefetch of ``opt_state``.

        Returns immediately; the transfer overlaps whatever the caller
        dispatches next.  At most one round trip is in flight — the Module
        calls stash once per sync boundary, strictly after fetch.
        """
        if self._synchronous:
            self._pending = opt_state
            return
        if self._in_flight:
            raise RuntimeError(
                "ZeroOffloader.stash called with a round trip already in "
                "flight — fetch() must join it first"
            )
        self._work.put(opt_state)
        self._in_flight = True

    def fetch(self, fallback: Any) -> Any:
        """Join the in-flight round trip and return the prefetched device
        copy; ``fallback`` (the caller's current opt state) is returned
        untouched when nothing was stashed (first step of a run).

        Wait time — the prefetch failing to hide behind compute — is
        booked into the goodput ledger's ``offload_wait`` bucket (nested,
        like the other inside-the-dispatch-gap buckets).
        """
        from rocket_tpu.observe.ledger import get_goodput

        if self._synchronous:
            if self._pending is None:
                return fallback
            t0 = time.perf_counter()
            dev = self._round_trip(self._pending)
            self._pending = None
            dt = time.perf_counter() - t0
            self.rounds += 1
            self.total_wait += dt
            get_goodput().add("offload_wait", dt, nested=True)
            return dev
        if not self._in_flight:
            return fallback
        t0 = time.perf_counter()
        dev = self._ready.get()
        dt = time.perf_counter() - t0
        self._in_flight = False
        self.rounds += 1
        self.total_wait += dt
        get_goodput().add("offload_wait", dt, nested=True)
        if isinstance(dev, Exception):
            raise dev
        return dev

    def close(self) -> None:
        """Stop the worker thread (idempotent; pending work is joined)."""
        if self._worker is not None and self._worker.is_alive():
            if self._in_flight:
                self._ready.get()
                self._in_flight = False
            self._work.put(None)
            self._worker.join(timeout=5.0)
        self._worker = None
