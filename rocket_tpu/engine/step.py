"""Jitted step builders — the execution core.

SURVEY §7.1: the per-iteration work the reference does in Python (forward →
loss → backward → step, ``module.py:110-142`` → ``loss.py:64-119`` →
``optimizer.py:111-147`` → ``scheduler.py:94-113``) becomes ONE pure,
donated-argument function compiled by XLA under a ``jax.sharding.Mesh``:

    ``state, logs = train_step(state, batch)``

What the compiler swallows (vs the reference's per-iteration Python):

- forward + backward — XLA-fused kernels on the MXU, bf16 per the policy
  (replaces autocast, ``module.py:210``);
- gradient all-reduce — inserted by GSPMD because the batch is sharded over
  the ``data``/``fsdp`` axes while params are replicated/sharded (replaces
  DDP's bucketed NCCL all-reduce armed in ``accelerator.prepare``,
  ``module.py:106``);
- the cross-process loss mean — the reference blocks on
  ``accelerator.gather(loss).mean()`` EVERY micro-batch purely for logging
  (``loss.py:95``, flagged as a defect in SURVEY §2.4); here ``jnp.mean``
  over the globally-sharded batch IS the global mean, compiled into the same
  program — zero extra launches;
- optimizer + scheduler step — optax transform application.

Gradient accumulation (reference ``accumulate()`` ctx + ``sync_gradients``
gating, ``module.py:211``, ``loss.py:101``, ``optimizer.py:133``) compiles to
TWO step variants instead of a data-dependent branch:

- ``micro`` — fwd/bwd, add grads into ``state.grad_accum``, no update;
- ``sync``  — fwd/bwd, apply ``(accum + g) / n`` through optax, reset.

The host picks the variant by a Python counter (the accumulation boundary is
statically known), so neither program contains dynamic control flow.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from rocket_tpu.engine.ema import find_params_ema
from rocket_tpu.engine.precision import Policy
from rocket_tpu.engine.state import TrainState
from rocket_tpu.observe.ledger import ledger_call
from rocket_tpu.observe.profile import annotate

# ``apply_fn(params, mutable, rng, batch, train)`` -> ``(batch_out, mutable)``
# — the model rewrites the batch blackboard-style, the functional analogue of
# ``attrs.batch = module.forward(attrs.batch)`` (reference ``module.py:139``).
ApplyFn = Callable[[Any, Any, jax.Array, Any, bool], Tuple[Any, Any]]

# ``objective(batch_out)`` -> scalar loss or ``(scalar, aux_logs)``.
ObjectiveFn = Callable[[Any], Any]


def _resolve_donate(donate: Optional[bool]) -> bool:
    """``donate=None`` means "auto": consult the persisted autotune record
    for this host's device/backend (``rocket_tpu.tune.store``), falling
    back to the historical default of True.  Lazy import — engine.step is
    imported by everything and must not pull the tune store eagerly."""
    if donate is not None:
        return bool(donate)
    from rocket_tpu.tune.store import runtime_default

    return bool(runtime_default("donate", default=True))


class _AnnotatedStep:
    """Wrap a jitted step so each invocation runs inside a named
    ``jax.profiler`` annotation (ISSUE 4: dispatch vs host-fetch
    attribution).  The annotation covers the HOST-side dispatch — tracing
    the args and enqueueing the async executable — which in a healthy
    pipeline is microseconds; any host fetch shows up elsewhere
    (``looper/host_fetch``).  Calls forward positionally, so donated
    buffers donate exactly as before, and every other ``PjitFunction``
    attribute (``lower``, ``_cache_size``, ...) delegates to the wrapped
    function, which stays reachable as ``.jitted``.

    Dispatch routes through :func:`~rocket_tpu.observe.ledger.ledger_call`
    (ISSUE 9): when the retrace ledger is armed, every compile at this
    edge is recorded and an unexpected post-warmup retrace escalates to a
    flight-recorder dump; disarmed, the wrapper is one attribute check."""

    __slots__ = ("jitted", "_name")

    def __init__(self, fn: Callable, name: str) -> None:
        self.jitted = fn
        self._name = name

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        with annotate(self._name):
            return ledger_call(self.jitted, self._name, *args, **kwargs)

    def __getattr__(self, attr: str) -> Any:
        return getattr(self.jitted, attr)


def _annotated_dispatch(fn: Callable, name: str) -> Callable:
    return _AnnotatedStep(fn, name)


@dataclasses.dataclass(frozen=True)
class Objective:
    """A named, weighted loss term (reference ``Loss`` capsule config,
    ``loss.py:51-62``)."""

    name: str
    fn: ObjectiveFn
    weight: float = 1.0


def _call_objective(obj: Objective, batch: Any) -> Tuple[jax.Array, Dict[str, Any]]:
    out = obj.fn(batch)
    if isinstance(out, tuple):
        value, aux = out
    else:
        value, aux = out, {}
    return jnp.asarray(value), dict(aux)


def _total_loss(
    objectives: Sequence[Objective], batch: Any
) -> Tuple[jax.Array, Dict[str, Any]]:
    logs: Dict[str, Any] = {}
    total = jnp.zeros((), dtype=jnp.float32)
    for obj in objectives:
        value, aux = _call_objective(obj, batch)
        logs[obj.name] = value
        for k, v in aux.items():
            logs[f"{obj.name}/{k}"] = v
        total = total + obj.weight * value.astype(jnp.float32)
    logs["loss"] = total
    return total, logs


def build_loss_fn(
    apply_fn: ApplyFn,
    objectives: Sequence[Objective],
    policy: Policy,
):
    """``(params, mutable, rng, batch) -> (loss, (logs, mutable, batch_out))``
    with the precision policy applied around the forward pass."""

    def loss_fn(params, mutable, rng, batch):
        # Autocast analogue (reference ``module.py:210``): params enter the
        # model in the compute dtype; the model families cast their own
        # INPUT leaves (images/tokens) to it.  The batch itself is NOT cast —
        # supervision targets and masks must keep full precision for the
        # objectives.
        compute_params = policy.cast_to_compute(params)
        batch_out, new_mutable = apply_fn(compute_params, mutable, rng, batch, True)
        total, logs = _total_loss(objectives, batch_out)
        return total, (logs, new_mutable, batch_out)

    return loss_fn


def build_train_step(
    apply_fn: ApplyFn,
    objectives: Sequence[Objective],
    tx: optax.GradientTransformation,
    policy: Policy = Policy(),
    gradient_accumulation_steps: int = 1,
    log_grad_norm: bool = True,
    donate: Optional[bool] = True,
    skip_nonfinite: bool = False,
    shard_plan: Optional[Any] = None,
) -> Dict[str, Callable[[TrainState, Any], Tuple[TrainState, Dict[str, Any]]]]:
    """Build the jitted training step(s).

    ``shard_plan`` (a :class:`rocket_tpu.parallel.sharding.ShardingPlan`
    with ``zero_stage >= 1``) turns on ZeRO-style cross-replica
    weight-update sharding (arXiv 2004.13336) inside the step.  At
    **stage 1** gradients are pinned to the params' sharding (so the
    backward subprogram stays identical to the unsharded step), then
    sliced to the data-composed shard domain; the optax update and the
    ``params + update`` add both run on the shard; the updated params
    are all-gathered back to the base domain; the new optimizer state
    stays on the shard.  The two explicit pins around the apply-add keep
    XLA's mul+add FMA contraction on-shard — exactly the grouping the
    unsharded step fuses — which is what makes the trajectory bit-equal,
    not just numerically close.

    **Stage 2** drops the base-domain pin on gradients: fresh grads are
    constrained straight to the zero shard, so GSPMD lowers the data-axis
    gradient reduction as a **reduce-scatter into the shard owner**
    instead of an all-reduce followed by a local slice — half the comm
    volume and no full-gradient replica.  Accumulation buffers live on
    the shard too (``specs_for_state`` re-partitions them), so the
    micro-window sum is an elementwise on-shard add — still exact.
    **Stage 3** additionally stores the params themselves on the zero
    shard: the top of the forward pins ``state.params`` to the base
    compute domain (the **all-gather on demand**), the update runs
    shard-to-shard, and the new params are pinned back to — and stay on —
    the shard, keeping the jit signature and the donation path intact.
    With ``shard_plan=None`` (or ``zero_stage=0``) the step body is
    byte-identical to the pre-ZeRO one.

    Returns ``{"sync": fn}`` when not accumulating, else
    ``{"sync": fn, "micro": fn}`` — the host calls ``micro`` for the first
    ``n-1`` batches of each window and ``sync`` on the boundary (reference
    ``sync_gradients`` cadence, ``loss.py:101``/``optimizer.py:133``).

    ``skip_nonfinite=True`` compiles the divergence guard INTO the step: a
    ``lax.cond`` applies the optimizer update (and adopts the new mutable
    collections) only when the loss and the gradient norm are finite, so
    one NaN batch cannot poison params or Adam moments.  The predicate
    lives on device — no host sync, no extra trace: the guard is part of
    the single compiled step body, and the happy path costs one scalar
    ``isfinite`` + select.  Skipped sync steps leave ``step``/params/
    opt_state untouched, still reset the accumulation window, and report
    ``logs['skipped'] = 1.0``.

    Every step additionally accepts a trailing ``lr_scale`` operand (device
    scalar); ``None`` (the default call signature) compiles without it.
    The DivergenceSentinel's rollback policy passes a cooldown factor
    through it — a changed VALUE is just a new input, only the None→scalar
    transition re-traces once.

    ``donate=True`` (the default) donates the ``TrainState`` argument's
    buffers to XLA (``donate_argnums=(0,)``): the output state reuses the
    input's storage, halving peak state memory and sparing a copy per
    step.  The caller contract is that the OLD state object is dead after
    the call — the Module upholds it by overwriting ``self._state`` with
    the step's result before anything else runs, and async checkpoint
    saves are safe because Orbax's D2H snapshot completes before ``save``
    returns.  ``donate=False`` (or ``Runtime(donate_train_state=False)``)
    is the escape hatch for callers that must keep consecutive states
    alive at once.  ``donate=None`` resolves from the persisted autotune
    record (``rocket_tpu.tune.store.runtime_default("donate")``), True
    when no record exists.
    """
    if gradient_accumulation_steps < 1:
        raise ValueError("gradient_accumulation_steps must be >= 1")
    loss_fn = build_loss_fn(apply_fn, objectives, policy)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    n = gradient_accumulation_steps
    stage = getattr(shard_plan, "zero_stage", 0) if shard_plan is not None else 0
    zero = stage >= 1

    def forward_backward(state: TrainState, batch: Any):
        rng = jax.random.fold_in(state.rng, state.step)
        if state.micro is not None:
            rng = jax.random.fold_in(rng, state.micro)
        params = state.params
        if stage >= 3:
            # All-gather on demand: storage is the ZeRO shard; the compute
            # domain is the base param sharding.  One pin at the top of the
            # forward is the whole gather — backward reuses the gathered
            # buffers, so the loss/grad subprogram matches the unsharded
            # step bit-for-bit.
            params = jax.lax.with_sharding_constraint(
                params, shard_plan.param_shardings
            )
        (loss, (logs, new_mutable, _)), grads = grad_fn(
            params, state.mutable, rng, batch
        )
        if stage >= 2:
            # Stage 2+: constrain fresh grads straight to the ZeRO shard.
            # GSPMD lowers the data-axis psum feeding a sharded consumer as
            # a reduce-scatter into the shard owner — no full-gradient
            # replica ever materializes.
            grads = jax.lax.with_sharding_constraint(
                grads, shard_plan.zero_param_shardings
            )
        return loss, grads, new_mutable, logs

    def micro_step(state: TrainState, batch: Any, lr_scale=None):
        loss, grads, new_mutable, logs = forward_backward(state, batch)
        if skip_nonfinite:
            finite = jnp.isfinite(loss) & jnp.isfinite(optax.global_norm(grads))
            # A nonfinite micro-batch contributes ZERO gradient to the
            # window (cond keeps the running sum) but still advances the
            # micro counter so the host's sync cadence stays aligned.
            accum = jax.lax.cond(
                finite,
                lambda: jax.tree_util.tree_map(
                    jnp.add, state.grad_accum, grads
                ),
                lambda: state.grad_accum,
            )
            new_mutable = jax.lax.cond(
                finite, lambda: new_mutable, lambda: state.mutable
            )
            logs["skipped"] = 1.0 - finite.astype(jnp.float32)
        else:
            accum = jax.tree_util.tree_map(jnp.add, state.grad_accum, grads)
        new_state = state.replace(
            grad_accum=accum,
            mutable=new_mutable,
            micro=state.micro + 1,
        )
        return new_state, logs

    def sync_step(state: TrainState, batch: Any, lr_scale=None):
        loss, grads, new_mutable, logs = forward_backward(state, batch)
        if n > 1:
            grads = jax.tree_util.tree_map(
                lambda a, g: (a + g) / n, state.grad_accum, grads
            )
        if log_grad_norm or skip_nonfinite:
            grad_norm = optax.global_norm(grads)
        if log_grad_norm:
            logs["grad_norm"] = grad_norm

        def apply_update(grads):
            if zero:
                if stage == 1:
                    # Stage 1: pin grads to the base param domain first
                    # (forces the backward to match the unsharded step
                    # bit-for-bit), then slice them — and the params — to
                    # the ZeRO shard.  Stage 2+ grads are already on-shard
                    # (reduce-scattered in forward_backward).
                    grads = jax.lax.with_sharding_constraint(
                        grads, shard_plan.param_shardings
                    )
                    grads = jax.lax.with_sharding_constraint(
                        grads, shard_plan.zero_param_shardings
                    )
                params_in = jax.lax.with_sharding_constraint(
                    state.params, shard_plan.zero_param_shardings
                )
            else:
                params_in = state.params
            updates, new_opt_state = tx.update(
                grads, state.opt_state, params_in
            )
            if lr_scale is not None:
                updates = jax.tree_util.tree_map(
                    lambda u: u * lr_scale, updates
                )
            new_params = optax.apply_updates(params_in, updates)
            if zero:
                # The shard-domain pin BEFORE the gather keeps the
                # params+update add (and its FMA contraction) on-shard;
                # at stages 1/2 the second constraint is then a pure
                # all-gather back to the base storage domain.  Stage 3
                # params are STORED on the shard — no gather, the output
                # sharding matches the (donated) input's.
                new_params = jax.lax.with_sharding_constraint(
                    new_params, shard_plan.zero_param_shardings
                )
                if stage < 3:
                    new_params = jax.lax.with_sharding_constraint(
                        new_params, shard_plan.param_shardings
                    )
                new_opt_state = jax.lax.with_sharding_constraint(
                    new_opt_state, shard_plan.opt_shardings
                )
            return new_params, new_opt_state, state.step + 1, new_mutable

        if skip_nonfinite:
            finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            new_params, new_opt_state, new_step, kept_mutable = jax.lax.cond(
                finite,
                apply_update,
                lambda grads: (
                    state.params, state.opt_state, state.step, state.mutable
                ),
                grads,
            )
            logs["skipped"] = 1.0 - finite.astype(jnp.float32)
        else:
            new_params, new_opt_state, new_step, kept_mutable = apply_update(
                grads
            )
        replacements = dict(
            step=new_step,
            params=new_params,
            opt_state=new_opt_state,
            mutable=kept_mutable,
        )
        if n > 1:
            # The window resets in BOTH cond branches — a skipped boundary
            # discards the whole window, keeping device micro/accum aligned
            # with the host's cadence counter.
            replacements["grad_accum"] = jax.tree_util.tree_map(
                jnp.zeros_like, state.grad_accum
            )
            replacements["micro"] = jnp.zeros((), dtype=jnp.int32)
        return state.replace(**replacements), logs

    donate_argnums = (0,) if _resolve_donate(donate) else ()
    steps = {"sync": _annotated_dispatch(
        jax.jit(sync_step, donate_argnums=donate_argnums),
        "train_step/dispatch/sync",
    )}
    if n > 1:
        steps["micro"] = _annotated_dispatch(
            jax.jit(micro_step, donate_argnums=donate_argnums),
            "train_step/dispatch/micro",
        )
    return steps


def build_window_step(
    apply_fn: ApplyFn,
    objectives: Sequence[Objective],
    tx: optax.GradientTransformation,
    policy: Policy = Policy(),
    window: int = 1,
    log_grad_norm: bool = True,
    donate: Optional[bool] = True,
    pipeline_schedule: str = "gpipe",
) -> Callable[[TrainState, Tuple[Any, ...]], Tuple[TrainState, Dict[str, Any]]]:
    """Fused gradient-accumulation step: ONE jitted call consumes the whole
    ``window``-batch accumulation window, concatenated on the batch dim,
    with one forward/backward.

    Built for pipelined models (``pipeline_microbatch_size``): the
    concatenated window flows through a single GPipe pass, so the
    ``2(P-1)``-tick fill/drain bubble is paid once per EFFECTIVE step
    instead of once per micro-batch (VERDICT r3 next #5).  Also skips the
    ``grad_accum`` buffer entirely — the window's activations replace it.

    Objective semantics match the micro/sync pair: each objective is
    evaluated per window slice and averaged with equal weight (NOT one
    mean over the concatenated batch — a per-token mean would weight
    slices by their valid-token counts when masks vary).  Two documented
    divergences from micro/sync: (a) the rng folds once per EFFECTIVE
    step, not once per micro-batch — deterministic (dropout-free) models
    only, which pipelining already requires; (b) mutable collections
    would update once per window — Module rejects them at materialize.

    Slicing contract: ``batch_out`` leaves whose leading dim equals the
    concatenated window row count are treated as batch-major per-example
    outputs (the blackboard batch-rewriting contract); other leaves pass
    through to every slice's objective unsliced.

    ``pipeline_schedule`` names the schedule the pipelined model inside
    ``apply_fn`` runs (selected by ``TransformerConfig.pipeline_schedule``;
    Module threads it through automatically).  The schedule itself lives
    in the model — here it keys the dispatch edge's trace/ledger name
    (``train_step/dispatch/window_1f1b`` etc.), so retrace sentinels and
    goodput attribution separate per schedule; all schedules are bit-equal
    in loss/grads, so swapping them never changes training math.
    """
    if window < 1:
        raise ValueError("window must be >= 1")
    from rocket_tpu.parallel.pipeline import SCHEDULES

    if pipeline_schedule not in SCHEDULES:
        raise ValueError(
            f"pipeline_schedule {pipeline_schedule!r} unknown; choose "
            f"from {SCHEDULES}"
        )

    def _concat_rows(*xs):
        # Row-concat via scatter into a zeros buffer instead of
        # jnp.concatenate: GSPMD mis-partitions batch-dim concats of
        # sharded operands under a pipe/tensor mesh (the same bug
        # documented in ops/fused_ce.py padding), silently corrupting
        # the window's rows before the GPipe pass.
        n = sum(x.shape[0] for x in xs)
        out = jnp.zeros((n,) + xs[0].shape[1:], xs[0].dtype)
        off = 0
        for x in xs:
            out = jax.lax.dynamic_update_slice_in_dim(out, x, off, 0)
            off += x.shape[0]
        return out

    def window_loss(params, mutable, rng, batches: Tuple[Any, ...]):
        concat = jax.tree_util.tree_map(_concat_rows, *batches)
        compute_params = policy.cast_to_compute(params)
        batch_out, new_mutable = apply_fn(
            compute_params, mutable, rng, concat, True
        )
        sizes = [
            jax.tree_util.tree_leaves(b)[0].shape[0] for b in batches
        ]
        offsets = [0]
        for s in sizes:
            offsets.append(offsets[-1] + s)
        total = jnp.zeros((), jnp.float32)
        logs: Dict[str, Any] = {}

        def slice_out(i):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.slice_in_dim(
                    x, offsets[i], offsets[i + 1], axis=0
                )
                if hasattr(x, "ndim") and x.ndim > 0
                and x.shape[0] == offsets[-1]
                else x,
                batch_out,
            )

        for i in range(len(batches)):
            part, part_logs = _total_loss(objectives, slice_out(i))
            total = total + part / len(batches)
            for k, v in part_logs.items():
                logs[k] = logs.get(k, 0.0) + jnp.asarray(v, jnp.float32) / len(batches)
        logs["loss"] = total
        return total, (logs, new_mutable)

    grad_fn = jax.value_and_grad(window_loss, has_aux=True)

    def window_step(state: TrainState, batches: Tuple[Any, ...]):
        rng = jax.random.fold_in(state.rng, state.step)
        (loss, (logs, new_mutable)), grads = grad_fn(
            state.params, state.mutable, rng, batches
        )
        if log_grad_norm:
            logs["grad_norm"] = optax.global_norm(grads)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                mutable=new_mutable,
            ),
            logs,
        )

    donate_argnums = (0,) if _resolve_donate(donate) else ()
    edge = "train_step/dispatch/window"
    if pipeline_schedule != "gpipe":
        edge = f"{edge}_{pipeline_schedule}"
    return _annotated_dispatch(
        jax.jit(window_step, donate_argnums=donate_argnums),
        edge,
    )


def build_eval_step(
    apply_fn: ApplyFn,
    objectives: Sequence[Objective] = (),
    policy: Policy = Policy(),
    use_ema: bool = False,
    shard_plan: Optional[Any] = None,
) -> Callable[[TrainState, Any], Tuple[Any, Dict[str, Any]]]:
    """Jitted evaluation step: forward only (reference eval path — grads off
    make Loss/Optimizer/Scheduler no-ops, ``loss.py:88-89``,
    ``optimizer.py:128``).  Returns ``(batch_out, logs)`` — the augmented
    batch feeds Meter/Metric capsules downstream (``meter.py:63-105``).

    ``use_ema=True`` evaluates with the parameter EMA maintained by
    ``Optimizer(ema_decay=...)`` instead of the live params (the usual
    inference weights for EMA-trained models); requires the transform to
    be in the chain.

    ``shard_plan`` with ``zero_stage >= 1`` pins the eval params to the
    base compute domain: a no-op at stages 1/2, and the all-gather from
    ZeRO-3's sharded storage (live params OR the EMA, which lives in the
    shard-domain opt_state) at stage 3."""
    eval_stage = (
        getattr(shard_plan, "zero_stage", 0) if shard_plan is not None else 0
    )

    def eval_step(state: TrainState, batch: Any):
        params = state.params
        if use_ema:
            ema = find_params_ema(state.opt_state)
            if ema is None:
                raise ValueError(
                    "eval_with_ema: no params_ema transform in the "
                    "optimizer chain — set Optimizer(ema_decay=...)"
                )
            params = ema
        if eval_stage >= 1:
            params = jax.lax.with_sharding_constraint(
                params, shard_plan.param_shardings
            )
        params = policy.cast_to_compute(params)
        batch_out, _ = apply_fn(params, state.mutable, state.rng, batch, False)
        logs: Dict[str, Any] = {}
        if objectives:
            _, logs = _total_loss(objectives, batch_out)
        return batch_out, logs

    return _annotated_dispatch(jax.jit(eval_step), "eval_step/dispatch")
