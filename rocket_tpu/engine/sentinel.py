"""DivergenceSentinel — watch the loss for nonfinite values and spikes.

Training runs die two ways: loudly (preemption — handled by the
Checkpointer's SIGTERM path) and quietly (a NaN batch poisons the Adam
moments at iteration 40k and every later snapshot is garbage).  This
capsule handles the quiet way with three escalating policies:

- ``policy='warn'``: log a rate-limited warning and count events; the run
  continues.  The zero-risk observability baseline.
- ``policy='skip'``: arm the **in-graph** guard — at setup it sets
  ``runtime.skip_nonfinite_updates`` so the Module compiles its train step
  with ``engine.step``'s ``lax.cond`` gate: the optimizer update applies
  only when loss and grad-norm are finite.  The detection predicate lives
  on device, so the happy path costs one scalar ``isfinite`` + select and
  **no extra host sync and no extra traced step body**.  This capsule then
  only observes (warns when skips happen).
- ``policy='rollback'``: on nonfinite loss or a ``spike_factor``× jump over
  the running EMA (for ``patience`` consecutive checks), restore the
  newest *valid* snapshot of the current run (``persist.integrity.
  latest_valid``) into the sibling Module and continue at
  ``cooldown_factor`` LR for ``cooldown_steps`` iterations.  After
  ``max_rollbacks`` the sentinel votes a run-level stop instead of
  thrashing.

Host-side detection is **one iteration delayed by design**: each launch
stages the current loss with ``copy_to_host_async`` and inspects the value
staged the *previous* iteration — by then the transfer has landed, so the
read never stalls the async dispatch queue (the same discipline as the
Tracker/Meter capsules).

Mount it in the train looper between the Module and the Checkpointer
(default priority 500).  With ``policy='skip'`` and a Module that
materializes eagerly (``input_spec`` given), the Module builds its steps at
setup *before* this capsule's setup can arm the flag — pass
``Module(skip_nonfinite=True)`` explicitly in that layout.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.capsule import Capsule

POLICIES = ("warn", "skip", "rollback")


class DivergenceSentinel(Capsule):
    """Parameters
    ----------
    policy:
        ``'warn'`` | ``'skip'`` | ``'rollback'`` (see module docstring).
    metric:
        Key inspected in ``attrs.step_logs`` (default ``'loss'``).
    check_every:
        Inspect every Nth training iteration (device→host transfer cost is
        tiny, but 1 is only the right default for small steps).
    spike_factor:
        A finite loss counts as divergent when it exceeds the running EMA
        by ``spike_factor * max(|EMA|, 1e-8)``.  ``None`` disables spike
        detection (nonfinite-only).
    ema_decay / warmup:
        EMA smoothing and the number of observations before spike detection
        arms (early-training loss is legitimately wild).
    patience:
        Consecutive divergent checks required before acting (1 = act on
        first).  Nonfinite values always count; a single finite
        non-divergent check resets the streak.
    module:
        The Module to roll back (``policy='rollback'``).  ``None`` =
        auto-discover the single Module in the runtime's checkpoint
        registry at first use.
    cooldown_factor / cooldown_steps:
        Post-rollback LR scale and how many iterations it holds.
    max_rollbacks:
        Budget; exceeding it requests a run-level stop.
    """

    def __init__(
        self,
        policy: str = "warn",
        metric: str = "loss",
        check_every: int = 1,
        spike_factor: Optional[float] = 10.0,
        ema_decay: float = 0.98,
        warmup: int = 20,
        patience: int = 1,
        module: Optional[Any] = None,
        cooldown_factor: float = 0.1,
        cooldown_steps: int = 100,
        max_rollbacks: int = 3,
        statefull: bool = False,
        priority: int = 500,
        logger: Optional[Any] = None,
    ) -> None:
        super().__init__(statefull=statefull, priority=priority, logger=logger)
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        if patience < 1:
            raise ValueError("patience must be >= 1")
        self._policy = policy
        self._metric = metric
        self._check_every = int(check_every)
        self._spike_factor = spike_factor
        self._ema_decay = float(ema_decay)
        self._warmup = int(warmup)
        self._patience = int(patience)
        self._module = module
        self._cooldown_factor = float(cooldown_factor)
        self._cooldown_steps = int(cooldown_steps)
        self._max_rollbacks = int(max_rollbacks)
        # host-side detector state (intentionally NOT checkpointed: a
        # restored run re-warms its EMA, which is safer than trusting a
        # pre-divergence statistic)
        self._seen = 0
        self._ema: Optional[float] = None
        self._staged: Optional[Any] = None
        self._staged_skip: Optional[Any] = None
        self._streak = 0
        self._cooldown_until: Optional[int] = None
        self.events = 0  # divergences observed (tests / user introspection)
        self.rollbacks = 0
        self.skips = 0  # in-graph skipped updates observed (policy='skip')
        self._emitted = (0, 0, 0)  # last (skips, rollbacks, events) flushed

    # -- lifecycle -----------------------------------------------------------

    def setup(self, attrs: Optional[Attributes] = None) -> None:
        super().setup(attrs)
        if self._policy == "skip":
            # Module reads this when building its jitted steps — the guard
            # compiles INTO the step (engine.step skip_nonfinite).
            self._runtime.skip_nonfinite_updates = True
        if self._policy == "rollback" and self._runtime.project_dir is None:
            raise RuntimeError(
                "DivergenceSentinel(policy='rollback') needs snapshots to "
                "roll back to — give the Launcher a tag and mount a "
                "Checkpointer"
            )

    def reset(self, attrs: Optional[Attributes] = None) -> None:
        # Cycle boundary: drop the staged device scalars (their buffers may
        # be donated away between cycles) but keep the EMA across epochs.
        self._staged = None
        self._staged_skip = None
        self._streak = 0

    # -- iteration -----------------------------------------------------------

    def launch(self, attrs: Optional[Attributes] = None) -> None:
        if attrs is None or attrs.step_logs is None:
            return
        looper = attrs.looper
        if looper is not None and not looper.grad_enabled:
            return  # eval cycles: nothing to guard
        self._seen += 1
        if self._cooldown_until is not None and self._seen >= self._cooldown_until:
            self._cooldown_until = None
            module = self._find_module()
            if module is not None:
                module.set_lr_scale(None)
                self._logger.info("LR cooldown over — full learning rate")
        skipped = self._stage_and_read(
            attrs.step_logs.get("skipped"), "_staged_skip"
        )
        if skipped is not None and skipped >= 0.5:
            self.skips += 1
        if self._seen % self._check_every == 0:
            value = self._stage_and_read(attrs.step_logs.get(self._metric))
            if value is not None:
                if self._is_divergent(value):
                    self._streak += 1
                    if self._streak >= self._patience:
                        self._streak = 0
                        self._act(value)
                else:
                    self._streak = 0
                    self._update_ema(value)
        self._emit_scalars(attrs)

    def _stage_and_read(self, current: Any,
                        slot: str = "_staged") -> Optional[float]:
        """Stage this iteration's device scalar in ``slot``, return LAST
        iteration's as a host float — the transfer overlaps one full step,
        so the read is free by the time we make it."""
        staged = getattr(self, slot)
        setattr(self, slot, current)
        if current is not None:
            start = getattr(current, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass  # already on host (numpy / python scalar)
        if staged is None:
            return None
        try:
            return float(staged)
        except (TypeError, ValueError):
            return None

    def _emit_scalars(self, attrs: Attributes) -> None:
        """Publish sentinel counters through the Tracker's buffered scalar
        channel — ONLY when one changed, so the steady state appends
        nothing.  ``sentinel/skips`` counts in-graph skipped updates
        (engine.step's ``skipped`` log under skip_nonfinite);
        ``sentinel/rollbacks`` and ``sentinel/events`` the host-side
        actions."""
        tracker = getattr(attrs, "tracker", None)
        if tracker is None:
            return
        current = (self.skips, self.rollbacks, self.events)
        if current == self._emitted:
            return
        self._emitted = current
        tracker.scalars.append(Attributes(
            step=self._seen,
            data={
                "sentinel/skips": float(self.skips),
                "sentinel/rollbacks": float(self.rollbacks),
                "sentinel/events": float(self.events),
            },
        ))

    # -- detection -----------------------------------------------------------

    def _is_divergent(self, value: float) -> bool:
        if not math.isfinite(value):
            return True
        if (
            self._spike_factor is not None
            and self._ema is not None
            and self._seen > self._warmup
        ):
            return value - self._ema > self._spike_factor * max(
                abs(self._ema), 1e-8
            )
        return False

    def _update_ema(self, value: float) -> None:
        if self._ema is None:
            self._ema = value
        else:
            d = self._ema_decay
            self._ema = d * self._ema + (1.0 - d) * value

    # -- policies ------------------------------------------------------------

    def _act(self, value: float) -> None:
        self.events += 1
        self._record_event(value)
        if self._policy in ("warn", "skip"):
            # Under 'skip' the in-graph guard already protected the state;
            # this is the host-side observation of the same event.
            if self.events <= 10 or self.events % 100 == 0:
                self._logger.warning(
                    "divergent %s=%s at observation %d (event #%d%s)",
                    self._metric, value, self._seen, self.events,
                    ", update skipped in-graph" if self._policy == "skip"
                    else "",
                )
            return
        self._rollback(value)

    def _record_event(self, value: float) -> None:
        """Flight-recorder hook: a divergence event marks the timeline and
        dumps the last-N host events — the 'what was the system doing
        right before the loss blew up' artifact (ISSUE 4).  Lazy imports
        keep engine free of observe at import time; both calls are no-ops
        unless tracing armed a tracer / a Launcher installed a recorder."""
        try:
            from rocket_tpu.observe.recorder import active_recorder
            from rocket_tpu.observe.trace import get_tracer

            get_tracer().instant(
                "sentinel/divergence", metric=self._metric, value=value,
                event=self.events, policy=self._policy,
            )
            rec = active_recorder()
            if rec is not None:
                rec.dump(f"sentinel-{self._policy}")
        except Exception:  # observability must never break the run
            self._logger.warning(
                "sentinel: flight-recorder dump failed", exc_info=True
            )

    def _rollback(self, value: float) -> None:
        from rocket_tpu.persist import integrity
        from rocket_tpu.persist.orbax_io import default_io

        if self.rollbacks >= self._max_rollbacks:
            self._runtime.request_stop(
                f"divergence persists after {self.rollbacks} rollbacks"
            )
            self._logger.error(
                "divergent %s=%s and rollback budget exhausted — stopping",
                self._metric, value,
            )
            return
        default_io().wait()  # in-flight save must land before we scan
        path = integrity.latest_valid(
            self._runtime.project_dir,
            do_quarantine=self._runtime.is_main_process,
        )
        if path is None:
            self._runtime.request_stop("diverged with no valid snapshot")
            self._logger.error(
                "divergent %s=%s but no valid snapshot to roll back to — "
                "stopping", self._metric, value,
            )
            return
        module = self._find_module()
        if module is None:
            self._runtime.request_stop("diverged; no Module to roll back")
            self._logger.error("no Module found in checkpoint registry")
            return
        self._logger.warning(
            "divergent %s=%s — rolling back to %s, LR x%g for %d iters",
            self._metric, value, path, self._cooldown_factor,
            self._cooldown_steps,
        )
        module.restore_from(path)
        module.set_lr_scale(self._cooldown_factor)
        self._cooldown_until = self._seen + self._cooldown_steps
        self.rollbacks += 1
        # The post-rollback regime is new — re-warm the detector.
        self._ema = None
        self._staged = None
        self._streak = 0

    def _find_module(self) -> Optional[Any]:
        if self._module is not None:
            return self._module
        from rocket_tpu.core.module import Module

        modules = [
            c for c in self._runtime.checkpointables if isinstance(c, Module)
        ]
        if len(modules) == 1:
            self._module = modules[0]
            return self._module
        if not modules:
            return None
        raise RuntimeError(
            "multiple Modules in the checkpoint registry — pass module= to "
            "DivergenceSentinel"
        )
