"""Model adapters — bridge user model definitions to the engine's ApplyFn.

The reference wraps a ``torch.nn.Module`` whose ``forward(batch)`` returns an
updated batch (``rocket/core/module.py:50-60,139``).  The TPU engine needs
the functional equivalent: explicit params/mutable pytrees and a pure apply.
:class:`FlaxModel` adapts any ``flax.linen`` module with a
``__call__(batch, train=...)`` signature; anything else can implement the
:class:`ModelAdapter` protocol directly.

Sharded initialization: parameters annotated with
``flax.linen.with_partitioning`` carry *logical* axis names; this adapter
resolves them through :class:`rocket_tpu.parallel.sharding.ShardingRules`
into :class:`jax.sharding.NamedSharding` and jit-initializes with
``out_shardings`` so big models materialize directly sharded across the
mesh (no host-RAM staging, no replicate-then-shard traffic).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from rocket_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules, replicated


class ModelAdapter:
    """Protocol every engine-compatible model exposes."""

    def init_variables(self, rng: jax.Array, batch: Any) -> Tuple[Any, Any]:
        """Return ``(params, mutable)`` pytrees for a sample batch."""
        raise NotImplementedError

    def apply_fn(
        self, params: Any, mutable: Any, rng: jax.Array, batch: Any, train: bool
    ) -> Tuple[Any, Any]:
        """Pure forward: return ``(batch_out, new_mutable)``."""
        raise NotImplementedError

    def partition_specs(
        self, abstract_params: Any, rules: ShardingRules
    ) -> Any:
        """PartitionSpec pytree matching ``abstract_params`` (default:
        fully replicated)."""
        return jax.tree_util.tree_map(lambda _: PartitionSpec(), abstract_params)


class FlaxModel(ModelAdapter):
    """Adapter for ``flax.linen`` modules.

    The wrapped module's ``__call__`` takes the batch (an
    ``Attributes``/dict) plus ``train: bool`` and returns the updated batch —
    the same blackboard-rewriting contract as the reference's
    ``module.forward(attrs.batch)`` (``module.py:139``).

    Parameters
    ----------
    module:
        The linen module.
    rng_collections:
        PRNG stream names threaded during training (default ``('dropout',)``).
    mutable_collections:
        Non-param variable collections updated during training (e.g.
        ``('batch_stats',)`` for BatchNorm). Auto-detected at init.
    """

    def __init__(
        self,
        module: Any,
        rng_collections: Sequence[str] = ("dropout",),
        mutable_collections: Optional[Sequence[str]] = None,
    ) -> None:
        self.module = module
        self.rng_collections = tuple(rng_collections)
        self._mutable_collections = (
            tuple(mutable_collections) if mutable_collections is not None else None
        )
        self._mesh = None
        self._rules = None

    def configure(self, mesh, rules) -> None:
        """Give the adapter the mesh/rules so activation-sharding
        constraints inside the model (``parallel.context.constrain``)
        resolve during tracing.  Called by Module.materialize."""
        self._mesh = mesh
        self._rules = rules

    def apply_policy(self, policy) -> None:
        """Thread the precision policy's compute dtype into modules exposing
        a ``dtype`` attribute left at ``None`` (the vision model families):
        they cast their own input leaves to it, which keeps uint8 loaders
        honest under bf16 without the engine touching supervision targets.
        Called by Module.materialize before init."""
        module = self.module
        if getattr(module, "dtype", "absent") is None:
            self.module = module.clone(dtype=policy.compute_dtype)

    def _ctx(self):
        from rocket_tpu.parallel.context import mesh_context

        if self._mesh is None:
            import contextlib

            return contextlib.nullcontext()
        return mesh_context(self._mesh, self._rules)

    def _rngs(self, rng: jax.Array) -> Dict[str, jax.Array]:
        keys = jax.random.split(rng, len(self.rng_collections))
        return dict(zip(self.rng_collections, keys))

    def init_variables(self, rng: jax.Array, batch: Any) -> Tuple[Any, Any]:
        init_rngs = dict(self._rngs(rng), params=rng)
        with self._ctx():
            variables = self.module.init(init_rngs, batch, train=False)
        variables = dict(variables)
        params = variables.pop("params", {})
        mutable = variables
        if self._mutable_collections is None:
            self._mutable_collections = tuple(sorted(mutable.keys()))
        return params, mutable

    def apply_fn(
        self, params: Any, mutable: Any, rng: jax.Array, batch: Any, train: bool
    ) -> Tuple[Any, Any]:
        collections = self._mutable_collections or tuple(sorted(dict(mutable)))
        variables = {"params": params, **dict(mutable)}
        rngs = self._rngs(rng) if train else None
        with self._ctx():
            if train and collections:
                batch_out, updated = self.module.apply(
                    variables, batch, train=True, rngs=rngs, mutable=list(collections)
                )
                return batch_out, dict(updated)
            batch_out = self.module.apply(variables, batch, train=train, rngs=rngs)
        return batch_out, mutable

    def partition_specs(self, abstract_params: Any, rules: ShardingRules) -> Any:
        import flax.linen as nn

        logical = nn.get_partition_spec(abstract_params)

        def resolve(spec: Any) -> PartitionSpec:
            if not isinstance(spec, PartitionSpec):
                return PartitionSpec()
            return rules.spec(*spec)

        return jax.tree_util.tree_map(
            resolve,
            logical,
            is_leaf=lambda x: isinstance(x, PartitionSpec) or x is None,
        )


def state_shardings(
    mesh: Mesh,
    abstract_state: Any,
    param_specs: Any,
    rules: Optional[Any] = None,
    zero_stage: int = 0,
) -> Any:
    """NamedShardings for a full TrainState given the param PartitionSpecs.

    Thin wrapper over
    :func:`rocket_tpu.parallel.sharding.specs_for_state` — optimizer-state
    subtrees that structurally mirror the params (Adam mu/nu, Muon
    momenta, EMA shadows, grad-accum) inherit the param specs
    positionally; everything else resolves through the
    :class:`~rocket_tpu.parallel.sharding.PartitionRules` path rules or
    replicates.  (The old tree-path-*suffix* heuristic this replaces
    silently took the first hit's spec when two params shared a suffix
    and shape — see tests/test_sharding_rules.py for the regression.)

    ``zero_stage`` (0-3, arXiv 2004.13336) selects how much of the state
    the plan data-shards: 1 = optimizer mirrors, 2 = + grad-accum
    buffers, 3 = + the params' storage domain itself (the step
    all-gathers on demand) — see the stage decision table in
    ``docs/performance.md``.
    """
    from rocket_tpu.parallel.sharding import (
        DEFAULT_PARTITION_RULES,
        specs_for_state,
    )

    plan = specs_for_state(
        mesh,
        abstract_state,
        rules=rules if rules is not None else DEFAULT_PARTITION_RULES,
        param_specs=param_specs,
        zero_stage=zero_stage,
    )
    return plan.state_shardings
