"""Muon — momentum + Newton-Schulz orthogonalized updates (optax form).

Beyond the reference (which delegates optimizers to torch entirely): Muon
[Jordan et al., 2024 — "Muon: MomentUm Orthogonalized by Newton-Schulz"]
replaces each 2D weight matrix's momentum update with its nearest
(semi-)orthogonal matrix, approximated by a quintic Newton-Schulz
iteration.  The NS iteration is three matmuls per step per matrix — pure
MXU work, which is exactly what a TPU wants (no SVD, no host sync).

Scope contract (the paper's): Muon is for the HIDDEN 2D matrices.
Embeddings, unembeddings, biases, norms should use adamw — compose with
the capsule API's param groups (:func:`hidden_matrices` is the canonical
split)::

    from rocket_tpu.engine.muon import hidden_matrices, muon
    rest = lambda p, x: not hidden_matrices(p, x)
    rt.Module(model, capsules=[
        rt.Loss(...),
        rt.Optimizer(tx_factory=muon, learning_rate=0.02,
                     params_filter=hidden_matrices, tag="lr_muon"),
        rt.Optimizer(learning_rate=3e-4, params_filter=rest, tag="lr_adam"),
    ])

Inside this transform, non-2D leaves fall back to plain (nesterov)
momentum SGD so a whole-tree ``muon()`` still optimizes, but the grouped
spelling above is the recommended one.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

# Quintic Newton-Schulz coefficients from the Muon reference
# implementation: tuned to maximize slope at 0 subject to convergence on
# [0, 1] singular values (they converge to ~[0.7, 1.2], which is fine —
# the update only needs approximate orthogonality).
_NS_COEFFS = (3.4445, -4.7750, 2.0315)


def orthogonalize(g: jax.Array, steps: int = 5,
                  eps: float = 1e-7) -> jax.Array:
    """Approximate ``UV^T`` (from ``g = U S V^T``) via Newton-Schulz.

    Works on ``[m, n]``; iterates on the smaller Gram side for cost
    ``O(min(m,n)^2 * max(m,n))`` per step.  Three matmuls per iteration,
    no data-dependent control flow — compiles into the jitted train step.
    """
    if g.ndim != 2:
        raise ValueError(f"orthogonalize expects a matrix, got {g.shape}")
    a, b, c = _NS_COEFFS
    transpose = g.shape[0] > g.shape[1]
    x = g.T if transpose else g
    x = x / (jnp.linalg.norm(x) + eps)

    def body(x, _):
        gram = x @ x.T
        poly = b * gram + c * (gram @ gram)
        return a * x + poly @ x, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    return x.T if transpose else x


def hidden_matrices(path, leaf: Any = None) -> bool:
    """The paper's Muon scope as a param filter: 2D kernels that are not
    embedding/unembedding tables (matched by an ``embed`` path
    component).  Pass as ``Optimizer(params_filter=hidden_matrices)``;
    route everything else to adamw."""
    if getattr(leaf, "ndim", None) != 2:
        return False
    return not any(
        "embed" in str(getattr(p, "key", getattr(p, "name", ""))).lower()
        for p in path
    )


class MuonState(NamedTuple):
    momentum: Any


def muon(
    learning_rate: Union[float, optax.Schedule] = 0.02,
    momentum: float = 0.95,
    nesterov: bool = True,
    ns_steps: int = 5,
    compute_dtype: Optional[Any] = None,
) -> optax.GradientTransformation:
    """The Muon update as an ``optax.GradientTransformation``.

    Per 2D leaf: ``buf = mu * buf + g``; the (nesterov) update direction
    is Newton-Schulz orthogonalized and rescaled by
    ``sqrt(max(1, m/n))`` (the reference implementation's shape factor,
    keeping update RMS comparable across aspect ratios).  Non-2D leaves
    get the plain momentum direction.  ``compute_dtype`` (e.g.
    ``jnp.bfloat16``) runs the NS matmuls at reduced precision — the
    paper's GPU setting; the default keeps the input dtype.
    """

    def init(params):
        return MuonState(
            momentum=jax.tree_util.tree_map(jnp.zeros_like, params)
        )

    def update(updates, state, params=None):
        del params
        bufs = jax.tree_util.tree_map(
            lambda b, g: momentum * b + g, state.momentum, updates
        )

        def direction(buf, g):
            d = g + momentum * buf if nesterov else buf
            if d.ndim != 2:
                return d
            x = d.astype(compute_dtype) if compute_dtype is not None else d
            o = orthogonalize(x, steps=ns_steps).astype(d.dtype)
            scale = jnp.sqrt(
                jnp.maximum(1.0, d.shape[0] / d.shape[1])
            ).astype(d.dtype)
            return o * scale

        dirs = jax.tree_util.tree_map(direction, bufs, updates)
        return dirs, MuonState(momentum=bufs)

    tx = optax.GradientTransformation(init, update)
    return optax.chain(
        tx,
        optax.scale_by_learning_rate(learning_rate),
    )
