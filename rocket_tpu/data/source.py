"""Data sources — the map-style sample store.

The reference consumes any ``torch.utils.data.Dataset`` through a
``DataLoader`` (``rocket/core/dataset.py:100-126``).  Here a *source* is the
minimal map-style protocol — ``__len__`` + ``__getitem__ -> pytree of numpy
leaves`` — so torch datasets, HF ``datasets``, and plain arrays all plug in
without adapters (torch tensors are converted by the collate hooks in
:mod:`rocket_tpu.utils.placement`).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np


class Source:
    """Map-style sample store protocol."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, index: int) -> Any:
        raise NotImplementedError


class ArraySource(Source):
    """Wrap a pytree of equal-leading-dim arrays as a source of per-index
    pytree samples — the idiomatic in-memory dataset (MNIST-sized data lives
    happily in host RAM; bigger data should stream via grain/HF datasets)."""

    def __init__(self, data: Any) -> None:
        import jax

        self._data = data
        lengths = {
            int(np.shape(leaf)[0]) for leaf in jax.tree_util.tree_leaves(data)
        }
        if len(lengths) != 1:
            raise ValueError(
                f"ArraySource leaves disagree on leading dim: {sorted(lengths)}"
            )
        self._length = lengths.pop()

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, index: int) -> Any:
        import jax

        return jax.tree_util.tree_map(lambda leaf: leaf[index], self._data)


class IterableSource:
    """Length-free streaming sample store (reference parity: the torch
    ``DataLoader`` accepts ``IterableDataset``, ``rocket/core/dataset.py:
    100-126``; OpenWebText-scale LM training is a streaming workload).

    Contract: every ``__iter__`` call restarts the SAME deterministic
    stream — that is what makes multi-host sharding (every process filters
    its rows from the common stream) and mid-epoch resume (skip-ahead
    replays the stream) correct.  Wrap nondeterministic feeds in a cache or
    seed them per epoch via :meth:`epoch_iter`.
    """

    def __iter__(self):
        raise NotImplementedError

    def epoch_iter(self, epoch: int):
        """Stream for a given epoch — override to reshuffle/reseed per
        epoch; the default ignores ``epoch`` and restarts the stream."""
        return iter(self)


class GeneratorSource(IterableSource):
    """Adapt a zero-arg iterator factory (``lambda: open_stream()``) —
    the minimal bridge for generators, HF streaming datasets, file readers.
    An optional ``epoch_fn(epoch)`` factory reseeds per epoch."""

    def __init__(self, factory: Callable[[], Any],
                 epoch_fn: Optional[Callable[[int], Any]] = None) -> None:
        self._factory = factory
        self._epoch_fn = epoch_fn

    def __iter__(self):
        return iter(self._factory())

    def epoch_iter(self, epoch: int):
        if self._epoch_fn is not None:
            return iter(self._epoch_fn(epoch))
        return iter(self)


class MapSource(Source):
    """Apply a per-sample transform lazily (augmentation hook)."""

    def __init__(self, source: Any, fn: Callable[[Any], Any]) -> None:
        self._source = source
        self._fn = fn

    def __len__(self) -> int:
        return len(self._source)

    def __getitem__(self, index: int) -> Any:
        return self._fn(self._source[index])


class ConcatSource(Source):
    """Concatenate sources end-to-end."""

    def __init__(self, sources: Sequence[Any]) -> None:
        self._sources = list(sources)
        self._offsets = np.cumsum([0] + [len(s) for s in self._sources])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    def __getitem__(self, index: int) -> Any:
        if index < 0:
            index += len(self)
        bucket = int(np.searchsorted(self._offsets, index, side="right")) - 1
        return self._sources[bucket][index - int(self._offsets[bucket])]


class TokenFileSource(Source):
    """Memory-mapped flat-token-file source for LM training — the
    OpenWebText-style layout (one long int token stream on disk, e.g. the
    public nanoGPT ``train.bin``) sliced into fixed-length rows without
    loading the file into RAM.

    Accepts ``.npy`` (via ``np.load(mmap_mode='r')``) or a raw binary of
    ``dtype`` tokens.  ``stride`` < ``seq_len`` yields overlapping rows;
    rows are materialized as small int32 copies only when indexed, so the
    loader's shuffle/shard/prefetch machinery works unchanged on files far
    larger than host memory.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        dtype: Any = np.uint16,
        stride: Optional[int] = None,
        key: str = "tokens",
        vocab_size: Optional[int] = None,
    ) -> None:
        if str(path).endswith(".npy"):
            arr = np.load(path, mmap_mode="r")
        else:
            arr = np.memmap(path, dtype=dtype, mode="r")
        if arr.ndim != 1:
            arr = arr.reshape(-1)
        self._arr = arr
        self._seq = int(seq_len)
        self._stride = int(stride) if stride is not None else self._seq
        if self._seq < 2 or self._stride < 1:
            raise ValueError(f"bad seq_len={seq_len} / stride={stride}")
        n = (len(arr) - self._seq) // self._stride + 1
        self._length = max(0, int(n))
        self._key = key
        if vocab_size is not None:
            # Fail fast on tokenizer mismatch (out-of-range ids would be
            # silently clipped by the embedding gather): scan bounded
            # samples — full files can be many GB.  Head + tail + a strided
            # middle sample catch corrupt/mismatched regions that start
            # anywhere, not just in the first chunk.
            chunk = 1_000_000
            total = len(self._arr)
            if total <= 8 * chunk:
                # Small enough to scan exhaustively (<=16MB of sequential
                # reads for uint16) — no blind spots.
                spans = [(s, min(s + chunk, total))
                         for s in range(0, total, chunk)]
            else:
                # Huge file: bound I/O at ~10MB of contiguous sequential
                # windows (head, tail, quartiles).  Contiguous windows, not
                # a strided scan — a stride faults one page per element
                # (~GBs of random I/O on a cold 20GB memmap).
                spans = [(0, chunk), (total - chunk, total)]
                spans += [(int(total * f), int(total * f) + chunk)
                          for f in (0.25, 0.5, 0.75)]
            for start, stop in spans:
                sample = np.asarray(self._arr[start:stop])
                if sample.size and int(sample.max()) >= int(vocab_size):
                    raise ValueError(
                        f"token id {int(sample.max())} >= vocab_size "
                        f"{vocab_size} in {path!s} "
                        f"(offset range [{start}, {stop}))"
                    )

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, index: int) -> Any:
        if index < 0:
            index += self._length
        if not 0 <= index < self._length:
            raise IndexError(index)
        start = index * self._stride
        row = np.asarray(self._arr[start:start + self._seq], dtype=np.int32)
        return {self._key: row}
